"""Unified configuration registry.

The reference spreads configuration across three channels (SURVEY §5):
argv options parsed by getopt_long (``-w/-r/-a/-m/-g/-t/-s``, reference
src/CommUtils/C2JNexus.cc:43-137), positional INIT-message params
(reference src/Merger/reducer.cc:56-99), and a pull-based ``getConfData``
up-call for late-bound keys (reference src/UdaBridge.cc:419-438). This
module unifies all three behind one registry:

- every known flag is declared once with its reference key, type and
  default (the full inventory from the reference is reproduced below);
- ``Config.from_argv`` accepts the same short options the reference's
  ``parse_options`` does;
- a ``conf_source`` callable can be attached to serve late-bound lookups
  (the getConfData channel).

TPU-specific knobs (mesh shape, HBM arena sizes, device record widths)
live in the same registry so there is exactly one way to configure the
framework.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

from uda_tpu.utils.errors import ConfigError

__all__ = ["Flag", "Config", "FLAGS"]


@dataclasses.dataclass(frozen=True)
class Flag:
    key: str                 # dotted config key (reference JobConf key where one exists)
    default: Any
    type: type
    help: str
    short: Optional[str] = None  # reference getopt short option, if any


# Full flag inventory. Reference keys keep their original names for
# compatibility with Hadoop-side configs; uda.tpu.* keys are new.
_FLAG_LIST = [
    # --- reference argv channel (C2JNexus.cc:43-137) ---
    Flag("mapred.rdma.wqe.per.conn", 256, int,
         "in-flight fetch window per peer (reference WQEs per connection)", "w"),
    Flag("mapred.rdma.cma.port", 9011, int,
         "control-plane port (reference rdma_cm port)", "r"),
    Flag("mapred.netmerger.merge.approach", 1, int,
         "1=online in-memory merge, 2=hybrid LPQ/RPQ merge, 0=auto "
         "(hybrid when the transport's size estimate is under "
         "uda.tpu.auto.approach.threshold.mb, bounded-memory streaming "
         "online otherwise or when the size is unknown)", "a"),
    Flag("uda.log.dir", "", str, "private log directory", "g"),
    Flag("uda.log.level", 4, int, "log severity 0..6 (lsNONE..lsTRACE)", "t"),
    Flag("mapred.rdma.buf.size", 1024, int,
         "staging buffer size in KB (reference RDMA buffer size)", "s"),
    # --- reference INIT/getConfData channel (reducer.cc, UdaPlugin.java) ---
    Flag("mapred.rdma.buf.size.min", 16, int, "minimum staging buffer KB"),
    Flag("mapred.rdma.shuffle.total.size", 0, int,
         "total shuffle memory budget in bytes (0 = derive from percent)"),
    Flag("mapred.job.shuffle.input.buffer.percent", 0.7, float,
         "fraction of available memory for shuffle when total.size unset"),
    Flag("mapred.netmerger.hybrid.lpq.size", 0, int,
         "segments per LPQ in hybrid merge (0 = sqrt(num_maps))"),
    Flag("mapred.rdma.num.parallel.lpqs", 0, int,
         "concurrent LPQs in hybrid merge (0 -> 3)"),
    Flag("mapred.rdma.compression.buffer.ratio", 0.20, float,
         "fraction of each buffer pair used for compressed data"),
    Flag("mapred.uda.log.to.unique.file", "", str,
         "when set, log to a private file instead of the up-call sink"),
    Flag("mapred.uda.provider.blocked.threads.per.disk", 1, int,
         "reader threads per local dir in the supplier data engine"),
    Flag("mapred.local.dir", "", str,
         "comma-separated task-local dirs (the Hadoop key); the bridge "
         "resolves spill directories from it when uda.tpu.spill.dirs "
         "is unset (reference LocalDirAllocator rotation)"),
    Flag("mapred.rdma.developer.mode", False, bool,
         "abort on failure instead of falling back to vanilla"),
    Flag("mapred.compress.map.output", False, bool, "map outputs are compressed"),
    Flag("mapred.map.output.compression.codec", "", str,
         "codec class name (Lzo/Snappy accepted, like reference createInputClient)"),
    Flag("io.compression.codec.snappy.buffersize", 256 * 1024, int,
         "snappy block size"),
    Flag("io.compression.codec.lzo.buffersize", 256 * 1024, int,
         "lzo block size"),
    # --- TPU-native knobs (new in this framework) ---
    Flag("uda.tpu.mesh.shape", "", str,
         "device mesh as 'dp:N,sh:M' axis list; empty = 1D over all devices"),
    Flag("uda.tpu.key.width", 16, int,
         "normalized key bytes carried in device sort columns (multiple of 4)"),
    Flag("uda.tpu.run.records", 1 << 20, int,
         "records per HBM-resident sorted run before spilling"),
    Flag("uda.tpu.fetch.retries", 3, int,
         "whole-segment re-fetch attempts after a transport error (the "
         "reference retries its RDMA connect dance 5x, RDMAClient.cc:41)"),
    Flag("uda.tpu.arena.slots", 16, int,
         "buffer-pair slots in the HBM staging arena"),
    Flag("uda.tpu.exchange.chunk.records", 1 << 18, int,
         "records per all-to-all exchange round (windowing, replaces the "
         "reference's 1000-chunk server pool)"),
    Flag("uda.tpu.use.native", True, bool,
         "use the C++ native codec/reader library when built"),
    Flag("uda.tpu.merge.overlap", True, bool,
         "overlap device merge with fetching (the network-levitated "
         "property); off = merge once after all fetches complete"),
    Flag("uda.tpu.spill.dirs", "", str,
         "comma-separated local dirs for LPQ spill files (round-robin, "
         "like the reference's local-dir rotation); empty = system tmp"),
    Flag("uda.tpu.online.streaming", False, bool,
         "online merge spools per-segment sorted runs to local disk and "
         "streams a permutation-driven interleave at emit, bounding host "
         "memory to the fetch window (the reference's 1 MB staging-loop "
         "memory model, StreamRW.cc:151-225); off = keep every segment "
         "host-resident through emission"),
    Flag("uda.tpu.online.stagers", 0, int,
         "overlap staging worker threads (pack+sort+spool per segment); "
         "0 = single merge thread (serial mode; with "
         "uda.tpu.stage.pipeline this is superseded by uda.tpu.stage.pool)"),
    # --- staged fetch->decompress->pack->stage pipeline (merger/overlap) ---
    Flag("uda.tpu.stage.pipeline", True, bool,
         "pipelined staging: a bounded stage-worker pool (decompress + "
         "vint-decode/pack + row build + spool, concurrent across "
         "segments, reusable pre-allocated host buffers) feeds ONE "
         "merge consumer that overlaps jax.device_put of the next run "
         "with the device merge of the current one. off = the serial "
         "stage-one-segment-at-a-time loop (the byte-identical "
         "correctness twin, scripts/bench_pipeline.py A/Bs the two)"),
    Flag("uda.tpu.stage.pool", 0, int,
         "stage-pipeline worker count; 0 = auto (a few workers, "
         "~min(4, cores) — staging is numpy-heavy and releases the "
         "GIL). Ignored when uda.tpu.stage.pipeline is off"),
    Flag("uda.tpu.stage.inflight.mb", 0, int,
         "in-flight staging budget in MB: bytes fed to the overlap "
         "merger but not yet merged/spooled; feed() blocks past it "
         "(transport backpressure, counted in "
         "stage.backpressure_events). 0 = auto: max(256 MB, 2x the "
         "fetch window), capped to half the host budget when one is "
         "already built (utils.budget.stage_inflight_cap)"),
    Flag("uda.tpu.merge.two_phase", "auto", str,
         "non-overlapped merge routing: 'on' = two-phase device sort "
         "(per-run partial sort + HBM-resident pairwise merge tree, "
         "ops.merge.merge_batches_two_phase), 'off' = whole-shuffle "
         "re-sort of the concatenation, 'auto' = two-phase on TPU "
         "backends / re-sort on CPU (the small-batch take-ramp datum, "
         "BENCH_NOTES_r05). Byte-identical either way"),
    # --- failure-domain knobs (failpoints + retrying fetch path) ---
    Flag("mapred.rdma.fetch.retry.backoff.ms", 0, int,
         "base exponential backoff between fetch retries in ms, doubling "
         "per attempt (0 = immediate retry, the reference's behavior)"),
    Flag("mapred.rdma.fetch.retry.backoff.max.ms", 2000, int,
         "exponential backoff cap in ms"),
    Flag("mapred.rdma.fetch.retry.jitter", 0.2, float,
         "+/- fraction of jitter applied to each backoff so failed "
         "segments do not re-issue in lockstep"),
    Flag("mapred.rdma.fetch.attempt.timeout.ms", 0, int,
         "per-attempt chunk fetch timeout in ms; a fetch the transport "
         "never completes is failed and retried (0 = wait forever)"),
    Flag("mapred.rdma.fetch.deadline.ms", 0, int,
         "overall per-segment fetch deadline in ms across all retries "
         "and backoffs (0 = none)"),
    Flag("uda.tpu.fetch.crc", False, bool,
         "supplier stamps each chunk with a CRC32 computed before any "
         "fault can mangle it; Segment validates and re-fetches a "
         "mismatched chunk once per offset before failing (compressed "
         "fetches validate the wire chunk inside DecompressingClient "
         "and recover via whole-segment retry)"),
    Flag("uda.tpu.fetch.penalty.threshold", 2, int,
         "transport faults before a supplier enters the penalty box "
         "(its remaining fetches are deprioritized in the schedule)"),
    Flag("uda.tpu.fetch.penalty.ms", 1000, int,
         "how long a penalized supplier stays deprioritized before it "
         "gets another chance"),
    Flag("uda.tpu.failpoints", "", str,
         "failpoint arming spec, same syntax as UDA_FAILPOINTS: "
         "comma-separated site=action[:arg][:trigger...] entries "
         "(uda_tpu.utils.failpoints)"),
    # --- survivable shuffle: speculation / resume / erasure coding ---
    Flag("uda.tpu.fetch.speculate.pn", 0, int,
         "straggler-detector percentile (pN) of the observed "
         "fetch.latency_ms histogram: an in-flight chunk fetch older "
         "than max(floor, pN) gets a speculative duplicate issued to "
         "the best PenaltyBox-ranked alternate source; first "
         "completion wins, the loser is discarded as a stale epoch "
         "(0 = speculation off)"),
    Flag("uda.tpu.fetch.speculate.floor.ms", 50, int,
         "minimum in-flight milliseconds before a fetch may be "
         "speculated, and the whole threshold while the latency "
         "histogram is empty (stats off or cold start)"),
    Flag("uda.tpu.fetch.resume", False, bool,
         "warm-resume on transport retry: keep the segment's offset "
         "ledger (fetched batches + carry) across a connection loss "
         "and continue mid-partition instead of refetching from zero, "
         "when the transport reports the source resumable "
         "(InputClient.resume_ok — warm supplier restart, immutable "
         "MOF); the first resumed chunk revalidates the partition's "
         "identity (raw_length) and falls back to a full restart on "
         "mismatch. off = the seed behavior (whole-segment re-fetch)"),
    Flag("uda.tpu.coding.scheme", "", str,
         "k-of-n erasure coding of map outputs as 'rs:k:n' "
         "(systematic Reed-Solomon over GF(2^8), uda_tpu.coding): "
         "map-side emit writes n-k parity chunks per partition stripe "
         "(parity section + v2 index) and the reduce side rebuilds a "
         "partition from ANY k of the n stripe chunks when its "
         "primary supplier is dead or penalized. empty = coding off; "
         "rs:k:k = chunked layout with zero parity (byte-identical "
         "data path)"),
    Flag("uda.tpu.coding.domains", "", str,
         "failure-domain map for stripe shard placement, "
         "'host=domain,host=domain,...'. The reduce side keys by "
         "canonical supplier HOST names and the writer by supplier "
         "ROOTS — declare BOTH namespaces in this one spec (extra "
         "keys are harmless; a spec matching neither side warns "
         "loudly and degrades to rotation). Declared domains spread "
         "each stripe's n shards "
         "round-robin ACROSS domains (no rack/power domain "
         "accumulates enough shards to make a stripe unrecoverable); "
         "undeclared hosts count as their own singleton domain; empty "
         "= the positional rotation over the sorted supplier list "
         "(the PR 8 placement, unchanged)"),
    Flag("uda.tpu.coding.scrub.s", 0, int,
         "background stripe-scrub interval in seconds: a low-priority "
         "daemon pass (one in flight per process, the "
         "tuncache.ensure_fresh idiom) re-verifies each coded map "
         "output's parity section against its data region and checks "
         "peer shard MOFs, counting coding.scrub.stripes / "
         "coding.scrub.repairs. 0 = scrub off (explicit scrub_roots "
         "calls still work)"),
    Flag("uda.tpu.coding.scrub.repair", False, bool,
         "let the scrub REBUILD lost or corrupt peer stripe shards "
         "from the primary's data+parity (proactive repair). Default "
         "off = dump-only: mismatches are counted and logged, bytes "
         "on disk are never touched"),
    Flag("uda.tpu.net.handoff.path", "", str,
         "supplier warm-restart handoff record: stop(drain=True) "
         "persists {generation, served-offset watermarks} to this "
         "path and the next start() advertises generation+1 with the "
         "warm flag in its accept banner, so reduce-side fetches "
         "resume from their own offset ledgers instead of refetching "
         "(uda.tpu.fetch.resume). empty = no persistence (every start "
         "mints a fresh cold generation)"),
    # --- network shuffle data plane (uda_tpu/net/) ---
    Flag("uda.tpu.net.listen", False, bool,
         "start a ShuffleServer (the TCP shuffle data plane, the "
         "reference's RDMAServer role) next to the role's DataEngine at "
         "INIT; stopped with the engine at EXIT/teardown"),
    Flag("uda.tpu.net.port", 9012, int,
         "shuffle data-plane TCP port: the server's bind port (0 = "
         "ephemeral) and the default port the socket fetch factory "
         "dials when a supplier host carries no ':port' suffix (one "
         "above the reference's 9011 control-plane rdma_cm port)"),
    Flag("uda.tpu.net.bind", "0.0.0.0", str,
         "listen address for the shuffle server"),
    Flag("uda.tpu.net.fetch", False, bool,
         "route reduce-side fetches over the socket data plane: INIT "
         "builds a HostRoutingClient whose default factory dials each "
         "supplier host's ShuffleServer (host[:port], one multiplexed "
         "connection per host) instead of a local in-process client"),
    Flag("uda.tpu.net.connect.timeout.s", 10.0, float,
         "TCP connect timeout per dial; a failed/timed-out dial "
         "completes the fetch with TransportError and the Segment's "
         "RetryPolicy paces the reconnect attempts"),
    Flag("uda.tpu.net.drain.s", 5.0, float,
         "graceful server stop: how long stop() lets in-flight "
         "responses flush before closing connections"),
    Flag("uda.tpu.net.sockbuf.kb", 0, int,
         "SO_SNDBUF/SO_RCVBUF for every data-plane socket in KB "
         "(server and client); 0 = leave the OS autotuned "
         "defaults. TCP_NODELAY is always set regardless — small "
         "REQ/SIZE frames must not eat Nagle delays"),
    Flag("uda.tpu.net.zerocopy", True, bool,
         "serve fd-cache-backed DATA chunks zero-copy so chunk bytes "
         "never transit the Python heap (event-loop core only); the "
         "byte path (sendmsg scatter-gather) is taken per-chunk "
         "whenever the chunk is not fd-backed: CRC stamping on, "
         "data_engine.pread failpoint armed, or a sendfile-refusing "
         "fd. off = always serve bytes"),
    Flag("uda.tpu.net.zerocopy.mode", "auto", str,
         "zero-copy mechanism: 'sendfile' (splice from the MOF fd), "
         "'mmap' (sendmsg memoryviews of the MOF's page-cache "
         "mapping — faster on kernels that emulate sendfile, e.g. "
         "sandboxed runtimes), or 'auto' (one-time per-process probe "
         "picks the faster; sendfile wins ties)"),
    # --- batched host-I/O plane (mofserver/data_engine.py) --------------
    Flag("uda.tpu.read.batch", "auto", str,
         "batched supplier reads: 'on'/'auto' = the event-loop serve "
         "path feeds byte-path request bursts to DataEngine."
         "submit_batch (per-fd grouping, range coalescing, one vectored "
         "read + one completion dispatch per batch — the RDMAbox "
         "batched-submission lesson); 'off' = today's one-pool-handoff-"
         "one-pread-per-chunk path, kept as the byte-identity "
         "correctness oracle (scripts/io_bench.py A/Bs the two). "
         "'auto' additionally lets the tuning cache "
         "(uda.tpu.tune.cache.path) refine the batch parameters"),
    Flag("uda.tpu.read.coalesce.gap.kb", 64, int,
         "coalescing gap threshold in KB: two queued reads of the same "
         "MOF whose ranges are closer than this merge into ONE "
         "vectored read (the gap bytes are read into scratch and "
         "discarded — a small waste that buys a syscall; "
         "io.coalesce.gap.bytes counts the waste). 0 = only strictly "
         "adjacent ranges coalesce"),
    Flag("uda.tpu.read.batch.max", 256, int,
         "max requests per submitted batch (the server flushes a "
         "burst at this bound); also caps one coalesced run at "
         "max*64 KB so scratch buffers stay bounded"),
    Flag("uda.tpu.read.backend", "auto", str,
         "batch read mechanism: 'io_uring' (native reader pool with "
         "the kernel ring, when compiled in AND the running kernel "
         "supports it), 'preadv' (one os.preadv per coalesced run), "
         "'pread' (per-request os.pread on the batch worker — still "
         "one pool handoff per batch). 'auto' walks that ladder "
         "downward; the selected rung is recorded as the io.backend "
         "metric label"),
    # --- online tuning cache (utils/tuncache.py) ------------------------
    Flag("uda.tpu.tune.cache.path", "", str,
         "persisted per-(key-shape, platform, backend) fly-off winner "
         "table (JSON) consulted by ops.sort.route_engine and the "
         "batched-I/O plane's parameters; populated by "
         "scripts/tune_probe.py. Corrupt/truncated/version-bumped "
         "files are ignored (tune.cache.invalid), never fatal; "
         "env-var winners (UDA_TPU_SORT_PATH) still override the "
         "cache. Setting this explicitly also installs the path as "
         "the PROCESS-default cache (tuncache.set_default_cache) so "
         "config-less consumers like route_engine consult the same "
         "table — unless UDA_TPU_TUNE_CACHE is set, which always "
         "wins. empty = UDA_TPU_TUNE_CACHE env, else no cache "
         "(today's built-in defaults)"),
    Flag("uda.tpu.tune.reprobe.s", 0.0, float,
         "tuning-cache staleness horizon in seconds: an entry older "
         "than this is re-measured by the background re-probe rung "
         "(tune_probe.py --reprobe-age, or a registered in-process "
         "probe via tuncache.ensure_fresh). 0 = winners never expire"),
    # --- multi-tenant service plane (uda_tpu/tenant/) -------------------
    Flag("uda.tpu.tenant.enable", False, bool,
         "run the ShuffleServer as a multi-job daemon: HELLO "
         "advertises CAP_TENANT, MSG_JOB registrations land in a "
         "TenantRegistry, every bound REQ is epoch-validated, and the "
         "per-conn credit cap is replaced by the weighted-fair "
         "CreditScheduler (uda.tpu.tenant.wqe.total). Off = the "
         "single-job data plane, bit for bit"),
    Flag("uda.tpu.tenant.id", "", str,
         "this process's tenant identity (reduce side): clients send "
         "MSG_JOB binding (tenant, job, epoch) before each job's "
         "first fetch, and hot-path metrics gain tenant labels. "
         "Empty = untenanted"),
    Flag("uda.tpu.tenant.epoch", 1, int,
         "this job attempt's epoch: a restarted attempt registers "
         "epoch+1, fencing the predecessor — its connections draw "
         "typed TenantError instead of reading the successor's "
         "chunks"),
    Flag("uda.tpu.tenant.weight", 1, int,
         "this tenant's weighted-fair share: scheduler grants and "
         "supplier read-budget partitions are proportional to weight "
         "over the sum of active tenants' weights"),
    Flag("uda.tpu.tenant.secret", "", str,
         "shared HMAC-SHA256 secret authenticating MSG_JOB frames "
         "(tenant/registry.sign_job); empty = unauthenticated (the "
         "trusted-fabric default, like the reference's rdma_cm "
         "plane). Both sides must agree"),
    Flag("uda.tpu.tenant.quantum.kb", 64, int,
         "byte quantum of the weighted-deficit round robin: each "
         "tenant's deficit EARNS quantum.kb x weight KB per turn and "
         "is CHARGED each granted request's requested bytes "
         "(chunk_size), so mixed chunk sizes stay byte-fair — a "
         "tenant fetching 1 MB chunks no longer out-draws one "
         "fetching 64 KB chunks at equal weight. A head request "
         "larger than one turn's earning accumulates deficit across "
         "turns (and the sweep force-serves the most-indebted head "
         "rather than idle credits). 0 = request-count quanta (the "
         "PR 14 behavior)"),
    Flag("uda.tpu.tenant.wqe.total", 0, int,
         "the daemon-wide credit pool the CreditScheduler grants by "
         "weighted deficit round-robin (requests in flight across ALL "
         "connections and tenants); 0 = mapred.rdma.wqe.per.conn — "
         "the bound the single-job knob provided, now weighted-fair"),
    Flag("uda.tpu.tenant.strict", False, bool,
         "refuse REQs for jobs never registered via MSG_JOB (typed "
         "TenantError); off = unbound jobs ride the default tenant "
         "(old clients stay compatible)"),
    Flag("uda.tpu.tenant.ttl.s", 0.0, float,
         "idle-job expiry horizon: a registered job with no "
         "register/validate/heartbeat activity for this long is "
         "dropped from the registry (retired tombstones are collected "
         "on the same clock). 0 = jobs never expire"),
    Flag("uda.tpu.tenant.penalty.threshold", 4, int,
         "abusive-tenant events (admission rejections, faulted "
         "requests) before the tenant enters the scheduler's penalty "
         "box — its parked requests yield to unboxed tenants (never "
         "starved: served when nothing competes)"),
    Flag("uda.tpu.tenant.penalty.ms", 1000, int,
         "how long a penalty-boxed tenant stays deprioritized"),
    Flag("uda.tpu.tenant.budget.share", 0.0, float,
         "reduce-side MemoryBudget partition: scale this job's host + "
         "HBM budgets to the fraction of the machine its tenant owns "
         "(several reducers of different tenants sharing one host "
         "must not each claim the whole MemAvailable). 0 = whole-"
         "machine budgets (the single-job default)"),
    # --- memory admission / pressure-response knobs (utils/budget.py) ---
    Flag("uda.tpu.hbm.budget.mb", 0, int,
         "per-chip HBM budget for the device row matrix + merge working "
         "set in MB; 0 = detect the platform (v5e 16 GB, v5p 95 GB, ...) "
         "and reserve 90% of it (CPU backends use the host budget — the "
         "'device' rows are host RSS there)"),
    Flag("uda.tpu.host.budget.mb", 0, int,
         "host-RSS budget for fetch-window + staging working sets in MB; "
         "0 = MemAvailable x mapred.job.shuffle.input.buffer.percent"),
    Flag("uda.tpu.budget.hard.mb", 0, int,
         "hard admission ceiling on the partition estimate in MB: above "
         "it the merge refuses the task with FallbackSignal before any "
         "allocation (0 = no ceiling; the degraded streaming path is "
         "bounded-memory at any size)"),
    Flag("uda.tpu.budget.enforce", "reroute", str,
         "INIT over-budget behavior: 'reroute' shrinks the fetch window "
         "to fit the host budget with a warning (the reference's buffer-"
         "shrink, reducer.cc:100-119); 'reject' raises -> fallback"),
    Flag("uda.tpu.supplier.read.budget.mb", 0, int,
         "supplier read-pool admission budget in MB: ShuffleRequests "
         "whose queued+in-flight bytes would exceed it are rejected "
         "(non-blocking; the reduce side's retry/backoff absorbs the "
         "push-back — the occupy_chunk pool bound, IndexInfo.cc:276-292)."
         " 0 = 256 MB floor scaled by the reader thread count"),
    Flag("uda.tpu.watchdog.stall.s", 0.0, float,
         "stall watchdog deadline in seconds: no fetch/merge/emit "
         "progress for this long dumps all thread stacks + the span "
         "tree and fails the task into the fallback path (0 = off)"),
    Flag("uda.tpu.watchdog.fallback", True, bool,
         "when the watchdog fires, fail in-flight segments so the task "
         "terminates via FallbackSignal (true) or only dump diagnostics "
         "and keep waiting (false)"),
    Flag("uda.tpu.arena.pressure.s", 1.0, float,
         "staging-arena soft-pressure threshold: an acquire that waits "
         "longer than this fires the arena's pressure callback and "
         "counts arena.pressure_events"),
    # --- observability knobs (metrics / tracing / stats reporter) ---
    Flag("uda.tpu.stats.enable", False, bool,
         "turn on the optional observability layers (histograms, span "
         "tracing, the StatsReporter thread); UDA_TPU_STATS=1 is the "
         "env equivalent"),
    Flag("uda.tpu.stats.interval.ms", 1000, int,
         "StatsReporter snapshot/report interval in ms"),
    Flag("uda.tpu.stats.jsonl", "", str,
         "path for the JSON-lines stats stream (appended); empty = "
         "UDA_TPU_STATS_JSONL env, else stderr"),
    Flag("uda.tpu.flightrec.enable", True, bool,
         "the flight recorder (utils/flightrec.py): an always-on "
         "bounded ring of structured events (segment transitions, "
         "admission causes, recovery events, failpoint fires, watchdog "
         "samples) dumped automatically on FallbackSignal, stall or "
         "resledger leak. UDA_TPU_FLIGHTREC=0 is the env kill switch "
         "(both must say on)"),
    Flag("uda.tpu.flightrec.events", 4096, int,
         "flight-recorder ring capacity in events (the black box's "
         "whole memory bound; oldest events roll off)"),
    Flag("uda.tpu.profile.hz", 0, int,
         "span-attributed sampling profiler rate in Hz "
         "(utils/profiler.py): a daemon thread walks every thread's "
         "stack at this rate and attributes samples to the thread's "
         "active span; summaries land in Metrics.snapshot counters "
         "(profile.samples), stats records, MSG_STATS, span exports "
         "and stall/flightrec dumps. 0 = off (no sampling thread, one "
         "enabled-check elsewhere); UDA_TPU_PROFILE=<hz> is the env "
         "equivalent (bare '1' = the 97 Hz default). Span attribution "
         "needs the span layer on (UDA_TPU_STATS=1)"),
    Flag("uda.tpu.flightrec.dir", "", str,
         "directory for flight-recorder dump files "
         "(flightrec_<pid>_<seq>_<cause>.json); empty = "
         "UDA_TPU_FLIGHTREC_DIR env, else dumps stay in-memory only "
         "(FlightRecorder.reports)"),
    # --- the live telemetry plane (ISSUE 17: rollups / SLO / anomaly) ---
    Flag("uda.tpu.ts.enable", True, bool,
         "the in-process time-series rollup ring (utils/timeseries.py):"
         " one timer folds per-interval counter deltas, gauge levels "
         "and histogram percentiles into a bounded recent-history ring "
         "— armed only when the stats plane is on (uda.tpu.stats."
         "enable / UDA_TPU_STATS=1); false keeps even an armed stats "
         "plane ring-less"),
    Flag("uda.tpu.ts.interval.s", 1.0, float,
         "rollup sampling interval in seconds (the one timer the "
         "anomaly detectors and the per-tenant SLI book also ride)"),
    Flag("uda.tpu.ts.window", 120, int,
         "rollup ring capacity in intervals (oldest roll off); also "
         "the SLO attainment / fairness-audit window"),
    Flag("uda.tpu.anomaly.enable", True, bool,
         "online anomaly detectors over the rollup ring (utils/"
         "anomaly.py): throughput collapse, p99 inflation, gauge "
         "leak-slope, tenant starvation — each fires anomaly.* "
         "counters and flight-recorder events (armed with the ring)"),
    Flag("uda.tpu.anomaly.dump", False, bool,
         "proactive flight-recorder dumps on detection (cause="
         "anomaly, BEFORE anything fails); false = detect-only (the "
         "default: counters + events, no files). UDA_TPU_ANOMALY_DUMP"
         "=1 is the env equivalent"),
    Flag("uda.tpu.anomaly.dump.interval.s", 300.0, float,
         "minimum seconds between proactive anomaly dumps (a flapping "
         "detector must not fill a disk)"),
    Flag("uda.tpu.anomaly.warmup", 5, int,
         "intervals of baseline history a detector needs before it may "
         "judge (EWMA warm-up)"),
    Flag("uda.tpu.anomaly.zscore", 4.0, float,
         "z-score threshold for the p99-inflation detector"),
    Flag("uda.tpu.anomaly.consec", 3, int,
         "consecutive breaching intervals before an anomaly fires "
         "(hysteresis against single-interval noise)"),
    Flag("uda.tpu.anomaly.collapse.frac", 0.25, float,
         "throughput-collapse threshold: per-interval rate below this "
         "fraction of its EWMA while the plane was moving"),
    Flag("uda.tpu.anomaly.collapse.floor.mb_s", 1.0, float,
         "absolute guard for the collapse detector: the EWMA rate in "
         "MB/s a counter must sustain before a collapse is judgeable "
         "(an idle process is not an outage)"),
    Flag("uda.tpu.anomaly.p99.floor.ms", 50.0, float,
         "absolute guard for the p99-inflation detector: interval p99 "
         "below this never alarms regardless of z-score"),
    Flag("uda.tpu.anomaly.leak.gauges", "fetch.on_air", str,
         "comma-separated gauges watched by the leak-slope detector "
         "(monotone rise across the whole window = leak shape)"),
    Flag("uda.tpu.anomaly.leak.rise", 64.0, float,
         "minimum whole-window rise of a watched gauge before the "
         "leak-slope detector fires"),
    Flag("uda.tpu.anomaly.starve.s", 5.0, float,
         "continuous seconds a tenant may sit with backlog and zero "
         "scheduled bytes before the starvation detector fires"),
    Flag("uda.tpu.slo.fetch.p99.ms", 0.0, float,
         "per-tenant SLO target on interval fetch p99 latency in ms "
         "(0 = SLI tracked, no target/burn accounting)"),
    Flag("uda.tpu.slo.serve.p99.ms", 0.0, float,
         "per-tenant SLO target on interval supplier-read p99 latency "
         "in ms (0 = no target)"),
    Flag("uda.tpu.slo.share.frac", 0.5, float,
         "fairness SLO: an interval complies when a tenant with demand "
         "received at least this fraction of its weight-entitled "
         "scheduled-byte share (the WDRR audit threshold)"),
    Flag("uda.tpu.slo.objective", 0.99, float,
         "the SLO objective (fraction of intervals that must comply); "
         "burn rate = (1-attainment)/(1-objective)"),
    Flag("uda.tpu.metrics.http.port", 0, int,
         "OpenMetrics/Prometheus text exposition port (utils/"
         "openmetrics.py GET /metrics) for standard scrapers; 0 = off"),
    Flag("uda.tpu.auto.approach.threshold.mb", 2048, int,
         "auto merge-approach crossover: partitions at most this many "
         "MB take the hybrid LPQ/RPQ path (fastest at small/mid scale), "
         "larger or unknown sizes take bounded-memory streaming online "
         "(measured crossover between the 1 GB and 10 GB regression "
         "rungs, REGRESSION_cpu_x{,x}large_r05.json)"),
    Flag("uda.tpu.ckpt.dir", "", str,
         "crash-consistent checkpoint root (merger/checkpoint.py): "
         "non-empty arms periodic snapshots of each running reduce — "
         "sorted run files spool under <dir>/<job>.r<reduce>/runs/ and "
         "an atomic versioned UCKP manifest records run CRCs, in-flight "
         "fetch offset ledgers, the recovery journal and penalty-box "
         "state; a restarted attempt resumes instead of refetching. "
         "Also steers the auto merge approach to the streaming path "
         "(hybrid has no durable run spool). Empty = off (the seed "
         "behavior: a reducer death loses all fetched bytes)"),
    Flag("uda.tpu.ckpt.interval.s", 30.0, float,
         "minimum seconds between checkpoint snapshots; saves trigger "
         "at run-spool boundaries and are rate-limited by this "
         "interval (0 = snapshot at every spool boundary — the chaos "
         "and resume tests run there)"),
    Flag("uda.tpu.ckpt.keep", 2, int,
         "checkpoint manifest generations retained after a save: a "
         "torn newest manifest (kill mid-snapshot) falls back to the "
         "previous one, and consumed-on-load walks backward across "
         "crash-retry loops (min 1)"),
    Flag("uda.tpu.store.blob.root", "", str,
         "blob-tier root directory of the elastic disaggregated MOF "
         "store (mofserver/store.py): non-empty arms the StoreManager "
         "— spilled/migrated partitions live here and the path joins "
         "the DirIndexResolver search roots. Empty = off (the seed "
         "behavior: supplier-local storage only)"),
    Flag("uda.tpu.store.spill.watermark.mb", 0, int,
         "supplier local-retention watermark in MB: retained MOF "
         "bytes above it migrate oldest-first to the blob tier "
         "(CRC-verified, store.spilled.bytes ledgered). 0 = derive "
         "from uda.tpu.store.spill.frac of the host memory budget"),
    Flag("uda.tpu.store.spill.frac", 0.0, float,
         "watermark as a fraction of the MemoryBudget host budget "
         "when the explicit MB knob is 0 (0 = spill ladder off)"),
    Flag("uda.tpu.store.shadow", False, bool,
         "keep the local file.out as a failover twin after a spill "
         "cut-over (blob primary, local shadow): a dying blob "
         "backend then re-routes reads to the surviving local copy "
         "instead of the k-of-n reconstruction rung"),
    Flag("uda.tpu.store.health.threshold", 2, int,
         "store-backend faults before the tier is penalty-boxed and "
         "twin-holding reads proactively re-route (BackendHealth)"),
    Flag("uda.tpu.store.health.penalty.ms", 1000.0, float,
         "how long a boxed store backend stays deprioritized before "
         "parole (one more fault re-boxes it)"),
    Flag("uda.tpu.push.enable", False, bool,
         "push-based pipelined shuffle (uda_tpu/net/push.py): the "
         "server advertises CAP_PUSH and pushes committed partitions "
         "to subscribed reduce connections; the MergeManager arms "
         "reduce-side staging and adopts pushed prefixes as resumed "
         "fetches. Off = the pull-only plane, frame for frame"),
    Flag("uda.tpu.push.window", 8, int,
         "per-connection cap of un-ACKed MSG_PUSH chunks (the push "
         "plane's credit discipline — receivers pace suppliers via "
         "PUSH_ACK; the effective window is the min of both peers')"),
    Flag("uda.tpu.push.eager.mb", 0.0, float,
         "reduce-side staging bytes held IN MEMORY before pushes "
         "spill to a staging run file (0 = an eighth of the "
         "MemoryBudget host budget — pushes must not crowd out the "
         "fetch pipeline's own admission)"),
    Flag("uda.tpu.push.staged.mb", 0.0, float,
         "total reduce-side staged bytes (memory + spill) per task "
         "before further pushes draw PUSH_NACK(BUDGET) and convert "
         "to ordinary pull (0 = 4x the eager cap)"),
    Flag("uda.tpu.push.spill", True, bool,
         "allow the staging spill tier (uda.tpu.spill.dirs): pushes "
         "over the eager cap land in a run file instead of being "
         "refused; off = memory-only staging, earlier NACKs"),
]

FLAGS: Dict[str, Flag] = {f.key: f for f in _FLAG_LIST}
_SHORT: Dict[str, Flag] = {f.short: f for f in _FLAG_LIST if f.short}


def _coerce(flag: Flag, value: Any) -> Any:
    if isinstance(value, flag.type):
        return value
    if flag.type is bool:
        if isinstance(value, str):
            return value.strip().lower() in ("1", "true", "yes", "on")
        return bool(value)
    try:
        return flag.type(value)
    except (TypeError, ValueError) as e:
        raise ConfigError(f"bad value {value!r} for {flag.key}: {e}") from e


class Config:
    """Layered config: explicit overrides > conf_source pulls > defaults."""

    def __init__(self, overrides: Optional[Dict[str, Any]] = None,
                 conf_source: Optional[Callable[[str, str], str]] = None):
        self._values: Dict[str, Any] = {}
        self.conf_source = conf_source
        for k, v in (overrides or {}).items():
            self.set(k, v)

    def set(self, key: str, value: Any) -> None:
        flag = FLAGS.get(key)
        self._values[key] = _coerce(flag, value) if flag else value

    def is_set(self, key: str) -> bool:
        """True when the key was explicitly set (override or pull), as
        opposed to falling through to its declared default."""
        return key in self._values

    def get(self, key: str, default: Any = None) -> Any:
        if key in self._values:
            return self._values[key]
        if self.conf_source is not None:
            flag = FLAGS.get(key)
            fallback = default if default is not None else (flag.default if flag else "")
            pulled = self.conf_source(key, str(fallback))
            if pulled is not None and pulled != "":
                value = _coerce(flag, pulled) if flag else pulled
                self._values[key] = value
                return value
        if default is not None:
            return default
        flag = FLAGS.get(key)
        if flag is None:
            raise ConfigError(f"unknown config key {key!r} and no default given")
        return flag.default

    @classmethod
    def from_argv(cls, argv: list[str]) -> "Config":
        """Parse the reference's short-option argv (C2JNexus.cc:43-137).

        Accepts ``["-w","256","-r","9011","-a","1","-m","0","-g",dir,
        "-t","4","-s","1024"]`` style lists; ``-m`` (standalone mode) is
        accepted and ignored, like the reference's mostly-vestigial mode
        flag.
        """
        cfg = cls()
        i = 0
        while i < len(argv):
            tok = argv[i]
            if not tok.startswith("-") or len(tok) != 2:
                raise ConfigError(f"bad option token {tok!r}")
            opt = tok[1]
            if i + 1 >= len(argv):
                raise ConfigError(f"option -{opt} missing value")
            val = argv[i + 1]
            i += 2
            if opt == "m":
                continue
            flag = _SHORT.get(opt)
            if flag is None:
                raise ConfigError(f"unknown option -{opt}")
            cfg.set(flag.key, val)
        return cfg

    def as_dict(self) -> Dict[str, Any]:
        out = {f.key: f.default for f in _FLAG_LIST}
        out.update(self._values)
        return out
