"""Stall watchdog: no-progress detection with a diagnostic dump.

The reference could hang forever when a supplier stopped answering —
the RDMA completion never arrived and the merge thread sat in a
cond-wait with nothing watching it (the failure mode SURVEY §4.5 calls
out: no liveness layer existed at all). Here a :class:`StallWatchdog`
thread samples a *progress token* (any monotonically-advancing value:
the sum of fetch/merge/emit counters, a queue depth, a file offset).
When the token stops changing for ``stall_s`` seconds it

1. dumps the live diagnosis to the engine log: every thread's current
   stack (``sys._current_frames``) plus the recorded span tree and the
   non-zero counters — the post-mortem a wedged production job never
   gets to write;
2. fires ``on_stall(StallError)`` exactly once (configurable off), the
   hook the MergeManager uses to fail in-flight segments so its waiters
   wake and the failure flows through the normal ``FallbackSignal`` ->
   ``failure_in_uda`` fallback contract instead of hanging forever.

Knobs: ``uda.tpu.watchdog.stall.s`` (0 = watchdog off),
``uda.tpu.watchdog.fallback`` (dump-only when false). The poll period is
``stall_s / 4`` clamped to [0.05 s, 5 s] — detection latency is at most
``stall_s + poll``.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Callable, Optional

from uda_tpu.utils.errors import UdaError
from uda_tpu.utils.flightrec import flightrec
from uda_tpu.utils.locks import lockdep
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["StallError", "StallWatchdog", "dump_diagnostics"]

log = get_logger()


class StallError(UdaError):
    """No observable progress for the configured stall deadline."""


def dump_diagnostics(reason: str = "") -> str:
    """The stall dump: all thread stacks + the recorded span tree +
    non-zero counters, as one log-ready string. Also usable standalone
    (e.g. from a signal handler or a debug command)."""
    lines = [f"=== stall diagnostics{': ' + reason if reason else ''} ==="]
    # thread stacks (the py-spy a wedged job can't run on itself)
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    lines.append(f"--- {len(frames)} thread stacks ---")
    for tid, frame in frames.items():
        lines.append(f"thread {names.get(tid, '?')} (ident {tid}):")
        lines.extend("  " + ln.rstrip("\n").replace("\n", "\n  ")
                     for ln in traceback.format_stack(frame))
    # the lockdep view (UDA_TPU_LOCKDEP=1): who holds which tracked
    # locks right now, and any order cycles seen so far — a wedged
    # thread's stack says WHERE it sits, the lock table says WHAT it
    # sits on
    if lockdep.enabled:
        held = lockdep.held_by_thread()
        lines.append(f"--- tracked locks held ({len(held)} threads) ---")
        lines.extend(f"  {who}: {' -> '.join(classes)}"
                     for who, classes in sorted(held.items()))
        if lockdep.cycles:
            lines.append(f"--- lockdep cycles "
                         f"({len(lockdep.cycles)} reported) ---")
            lines.extend(f"  [{c['kind']}] {c['note']}"
                         for c in lockdep.cycles)
    # the span tree: completed spans, rendered parent->child (the live
    # subtree is whatever has not ended yet — its absence under a parent
    # with children is itself the wedge signature)
    spans = list(metrics.spans)
    if spans:
        lines.append(f"--- span tree ({len(spans)} recorded spans) ---")
        children: dict = {}
        known = {s["id"] for s in spans}
        for s in spans:
            parent = s.get("parent")
            # a parent id this process never recorded is a REMOTE
            # parent (wire-carried trace context) or an un-ended span:
            # render the child as a local root rather than dropping the
            # whole subtree from the dump
            if parent is not None and parent not in known:
                parent = None
            children.setdefault(parent, []).append(s)

        def walk(parent_id, depth):
            for s in children.get(parent_id, []):
                attrs = s.get("attrs") or {}
                a = (" " + ",".join(f"{k}={v}" for k, v in attrs.items())
                     if attrs else "")
                lines.append(f"{'  ' * depth}{s['name']} "
                             f"dur={s['dur'] * 1e3:.1f}ms{a}")
                walk(s["id"], depth + 1)

        walk(None, 1)
    # the last-30-seconds span-attributed profile: WHAT the threads
    # were executing as progress flatlined, next to WHERE they sit now
    # (the stacks above). Armed profiler only — a dump never arms it —
    # and total: any profiler error degrades to omission, because this
    # renders inside a failure path
    try:
        from uda_tpu.utils.profiler import profiler

        if profiler.armed:
            recent = profiler.recent_summary(30.0)
            lines.append(f"--- sampling profile (last "
                         f"{recent['window_s']:g}s, "
                         f"{recent['samples']} samples) ---")
            lines.extend(f"  {name}: {n}"
                         for name, n in recent["spans"].items())
    except Exception:  # udalint: disable=UDA006 - dump must stay total
        pass
    # where the wall went so far (span-derived; spans on only)
    try:
        from uda_tpu.utils.critpath import time_accounting_block

        ta = time_accounting_block()
        if ta is not None:
            lines.append(f"--- time accounting (wall "
                         f"{ta['wall_s']:.3f}s, root "
                         f"{ta['root'] or 'none'}) ---")
            lines.extend(
                f"  {b}: critical {rec['critical_s']:.3f}s "
                f"({rec['share'] * 100:.1f}%), busy {rec['busy_s']:.3f}s"
                for b, rec in ta["buckets"].items() if rec["busy_s"])
            lines.append(f"  idle: {ta['idle_s']:.3f}s")
    except Exception:  # udalint: disable=UDA006 - dump must stay total
        pass
    counters = {k: v for k, v in metrics.snapshot().items() if v}
    if counters:
        lines.append("--- non-zero counters ---")
        lines.extend(f"  {k} = {v:g}" for k, v in sorted(counters.items()))
    gauges = {k: v for k, v in metrics.gauges_snapshot().items() if v}
    if gauges:
        lines.append("--- gauges ---")
        lines.extend(f"  {k} = {v:g}" for k, v in sorted(gauges.items()))
    return "\n".join(lines)


class StallWatchdog:
    """One watcher thread per guarded task. ``progress`` is called from
    the watchdog thread and must be cheap and non-blocking (counter
    reads); any value supporting ``==`` works as the token."""

    def __init__(self, stall_s: float, progress: Callable[[], object],
                 on_stall: Optional[Callable[[StallError], None]] = None,
                 name: str = "uda-watchdog"):
        if stall_s <= 0:
            raise UdaError("watchdog needs a positive stall deadline")
        self.stall_s = float(stall_s)
        self.progress = progress
        self.on_stall = on_stall
        self.poll_s = min(5.0, max(0.05, self.stall_s / 4.0))
        self.fired = False
        self.last_dump: Optional[str] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name=name)

    def start(self) -> "StallWatchdog":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # never join from the watchdog's own thread (an on_stall hook
        # that tears its manager down would deadlock on self-join)
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=2.0)

    def _watch(self) -> None:
        token = self.progress()
        last_change = time.monotonic()
        while not self._stop.wait(self.poll_s):
            try:
                now_token = self.progress()
            except Exception as e:  # noqa: BLE001 - a broken probe must
                log.warn(f"watchdog progress probe failed: {e}")  # not
                continue                                          # kill us
            now = time.monotonic()
            changed = now_token != token
            # every sample lands in the black box: a post-mortem dump
            # shows exactly when progress flatlined, not just that it
            # eventually did (bounded rate — poll_s >= 0.05 s)
            flightrec.record("watchdog", changed=changed,
                             idle_s=round(0.0 if changed
                                          else now - last_change, 3))
            if changed:
                token, last_change = now_token, now
                continue
            if now - last_change < self.stall_s:
                continue
            self._fire(now - last_change)
            return

    def _fire(self, stalled_for: float) -> None:
        metrics.add("watchdog.stalls")
        err = StallError(
            f"no fetch/merge progress for {stalled_for:.1f} s "
            f"(stall deadline {self.stall_s:g} s)")
        self.last_dump = dump_diagnostics(str(err))
        log.error(self.last_dump)
        # the stall IS a black-box trigger: the ring holds the
        # flatlining watchdog samples and whatever faults preceded them
        flightrec.dump("stall", extra={"stalled_s": round(stalled_for, 3),
                                       "deadline_s": self.stall_s})
        hook = self.on_stall
        if hook is not None:
            try:
                hook(err)
            except Exception as e:  # noqa: BLE001 - the hook is rescue
                log.error(f"watchdog on_stall hook failed: {e}")  # code
        # set LAST: an observer seeing fired=True may rely on the dump
        # being written and the rescue hook having run
        self.fired = True
