"""Memory admission control: budgets, the device-bytes model, routing.

The reference validated every buffer budget at INIT and failed into the
vanilla path when the pool could not fit (handle_init_msg, reference
src/Merger/reducer.cc:56-133) and *blocked* on chunk-pool exhaustion
instead of dying (occupy_chunk, reference
src/MOFServer/IndexInfo.cc:276-292). This engine's equivalent exposure
is the device row matrix: the global sort holds ~27 uint32 words per
record device-resident (~108 B/record at the TeraSort shape, ≈1.08x the
shuffle bytes — VERDICT.md Missing #4), so a >10 GB per-chip partition
OOMs a 16 GB v5e with no graceful route, and on CPU the same rows are
host RSS (the 9.3 GB xxlarge symptom).

:class:`MemoryBudget` is the front door: per-chip HBM and host-RSS
budgets (``uda.tpu.hbm.budget.mb`` / ``uda.tpu.host.budget.mb``,
defaults derived from the detected platform), an estimator that converts
the transport's on-disk partition estimate into row-matrix +
working-set bytes, and two admission points:

- :meth:`validate_init` — the INIT-time buffer-budget check (the
  reducer.cc:56-133 mirror): the fetch window + staging arena working
  set must fit the host budget; over-budget either shrinks the window
  (``uda.tpu.budget.enforce=reroute``, warn like the reference's
  buffer shrink) or raises (``=reject``, the fallback path);
- :meth:`route` — the merge-approach decision (consumed by
  ``MergeManager._run``'s auto policy): in-budget partitions keep the
  fast hybrid/in-memory path, partitions whose device estimate exceeds
  the HBM budget are rerouted to bounded-memory streaming, and
  partitions above the hard ceiling (``uda.tpu.budget.hard.mb``) are
  rejected *before any allocation* — the caller raises
  ``FallbackSignal``. Unknown estimates route to streaming (bounded
  memory is the only safe default for an unbounded input).

Every decision is logged and counted (``budget.admitted`` /
``budget.rerouted`` / ``budget.rejected``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

from uda_tpu.utils.errors import UdaError
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["MemoryBudget", "Admission", "device_bytes_estimate",
           "stage_inflight_cap", "ROW_OVERHEAD_WORDS",
           "WORKING_SET_FACTOR", "HBM_RESERVE_FRACTION",
           "PLATFORM_HBM_MB", "STAGE_INFLIGHT_FLOOR_MB"]

log = get_logger()

MB = 1 << 20

# floor for the auto-derived staging-pipeline in-flight byte budget
STAGE_INFLIGHT_FLOOR_MB = 256


def stage_inflight_cap(cfg, window: int, chunk_size: int,
                       budget: Optional["MemoryBudget"] = None) -> int:
    """In-flight byte budget for the staging pipeline (bytes fed to the
    overlap merger but not yet merged/spooled — uda_tpu.merger.overlap
    charges/releases them; the gauge is ``stage.inflight.bytes``).

    ``uda.tpu.stage.inflight.mb`` wins when set; the auto default is
    max(STAGE_INFLIGHT_FLOOR_MB, 2x the fetch window's wire bytes) —
    enough that staging never throttles a healthy fetch window, small
    enough that a stalled device consumer cannot pile the whole shuffle
    into host RSS. When a MemoryBudget has ALREADY been built (the auto
    merge-approach path), the cap additionally clamps to half its host
    budget; a budget is deliberately NOT constructed here — platform
    detection must not run for explicitly-configured approaches (the
    same laziness MergeManager.budget() preserves)."""
    mb = int(cfg.get("uda.tpu.stage.inflight.mb"))
    if mb > 0:
        return mb * MB
    cap = max(STAGE_INFLIGHT_FLOOR_MB * MB,
              2 * max(1, int(window)) * max(1, int(chunk_size)))
    if budget is not None:
        cap = min(cap, max(MB, budget.host_budget_bytes // 2))
    return cap

# -- the device-bytes model (VERDICT.md Missing #4) -------------------------
#
# Per record the engine holds one uint32 row of (key words, content
# length, segment index, row index) = key_width/4 + ROW_OVERHEAD_WORDS
# words. At the TeraSort shape the *sort-network* ladder carries ~27
# words/record (~108 B, ≈1.08x shuffle bytes): key + payload surrogate
# columns ride along on the fully device-resident sort path. The
# admission model uses the larger of the two (row matrix vs the 1.08x
# sort ladder) so it is conservative for both the forest-merge and the
# whole-run-sort engines.
ROW_OVERHEAD_WORDS = 3        # length, segment index, row index columns
SORT_LADDER_RATIO = 1.08      # device bytes / shuffle bytes, TeraSort shape
RECORD_BYTES_DEFAULT = 100    # TeraSort record (10 B key + 90 B value)

# Transient working set: a pairwise merge holds both operands plus the
# output simultaneously, and binary-counter runs pad to a power of two —
# 2x the resident matrix bounds both.
WORKING_SET_FACTOR = 2.0

# Fraction of physical HBM the budget may claim by default (the rest is
# XLA scratch, compiled executables, and the exchange path's buffers).
HBM_RESERVE_FRACTION = 0.9

# Known per-chip HBM sizes by TPU device-kind substring, FIRST MATCH
# WINS (VERDICT.md ask #3 names v5e and v5p; the rest are the published
# per-chip figures). Order matters: every v5e/lite spelling (libtpu
# reports e.g. "TPU v5 lite") must match before "v5p", and a BARE "v5"
# resolves to the small end — over-budgeting a 16 GB chip as 95 GB
# would silently re-open the exact OOM this layer exists to prevent.
PLATFORM_HBM_MB = (
    ("v5litepod", 16 * 1024),   # v5e: 16 GB/chip
    ("v5 lite", 16 * 1024),
    ("v5lite", 16 * 1024),
    ("v5e", 16 * 1024),
    ("v5p", 95 * 1024),         # v5p: 95 GB/chip
    ("v6e", 32 * 1024),
    ("v6", 32 * 1024),
    ("v4", 32 * 1024),
    ("v3", 16 * 1024),
    ("v2", 8 * 1024),
    ("v5", 16 * 1024),          # bare v5: assume the small end
)
DEFAULT_HBM_MB = 16 * 1024      # unknown accelerator: assume the small end


def _host_available_mb() -> int:
    """Best-effort available host memory (MemAvailable, else MemTotal,
    else a conservative 4 GB)."""
    try:
        with open("/proc/meminfo") as f:
            text = f.read()
        for key in ("MemAvailable", "MemTotal"):
            m = re.search(rf"^{key}:\s+(\d+)\s*kB", text, re.M)
            if m:
                return int(m.group(1)) // 1024
    except OSError:
        pass
    return 4 * 1024


def _detect_hbm_mb() -> int:
    """Per-chip HBM of the ambient backend. On CPU backends the 'device'
    rows live in host RSS, so the HBM budget IS the host budget (the
    xxlarge-rung reality). jax import stays lazy: admission must not
    drag a backend up in processes that never touch the device."""
    try:
        import jax

        backend = jax.default_backend()
        if backend == "cpu":
            return _host_available_mb()
        kind = str(jax.devices()[0].device_kind).lower()
        for sub, mb in PLATFORM_HBM_MB:
            if sub in kind:
                return mb
    except Exception as e:  # noqa: BLE001 - detection is best effort
        log.warn(f"HBM budget autodetect failed ({e}); "
                 f"assuming {DEFAULT_HBM_MB} MB")
    return DEFAULT_HBM_MB


def device_bytes_estimate(partition_bytes: int, key_width: int,
                          record_bytes: int = RECORD_BYTES_DEFAULT) -> int:
    """Device-resident bytes the merge would hold for a partition of
    ``partition_bytes`` on-disk bytes: max(row matrix, sort ladder) x
    the transient working-set factor. Conservative by construction —
    admission errs toward the bounded path."""
    if partition_bytes <= 0:
        return 0
    row_bytes = 4 * (max(4, key_width) // 4 + ROW_OVERHEAD_WORDS)
    records = max(1, partition_bytes // max(1, record_bytes))
    row_matrix = records * row_bytes
    ladder = int(partition_bytes * SORT_LADDER_RATIO)
    return int(max(row_matrix, ladder) * WORKING_SET_FACTOR)


@dataclasses.dataclass(frozen=True)
class Admission:
    """One routing decision: which path the partition was admitted to
    and why — the logged/counted record of the budget layer."""

    decision: str                 # "in_memory" | "hybrid" | "streaming"
    #                             | "reject"
    reason: str                   # human-readable (logs only — never
    #                             branch on this string)
    estimate_bytes: Optional[int]   # transport estimate (None = unknown)
    device_bytes: Optional[int]     # modeled device working set
    hbm_budget_bytes: int
    host_budget_bytes: int
    # structured decision basis — what callers branch on: which budget
    # forced the decision ("hbm" | "host" | "hard" | "init", "ckpt" for
    # the checkpoint-steered streaming route, or "", the empty string
    # meaning no budget was binding)
    cause: str = ""
    rerouted: bool = False

    @property
    def rejected(self) -> bool:
        return self.decision == "reject"


class MemoryBudget:
    """Per-chip HBM + host-RSS budgets with lazy platform detection.

    Budgets resolve in this order: explicit config knob > platform
    default (detected HBM x HBM_RESERVE_FRACTION; available host memory
    x ``mapred.job.shuffle.input.buffer.percent``). Detection runs at
    most once per instance and only when a budget is actually read.
    """

    def __init__(self, hbm_budget_mb: int = 0, host_budget_mb: int = 0,
                 hard_ceiling_mb: int = 0, key_width: int = 16,
                 host_fraction: float = 0.7, enforce: str = "reroute",
                 tenant_share: float = 0.0):
        self._hbm_mb = int(hbm_budget_mb)
        self._host_mb = int(host_budget_mb)
        self.hard_ceiling_mb = int(hard_ceiling_mb)
        self.key_width = int(key_width)
        self.host_fraction = float(host_fraction)
        if enforce not in ("reroute", "reject"):
            raise UdaError(f"uda.tpu.budget.enforce must be 'reroute' or "
                           f"'reject', got {enforce!r}")
        self.enforce = enforce
        # the multi-tenant partition (uda.tpu.tenant.budget.share):
        # several reducers of different tenants sharing one host must
        # not each budget against the whole machine — every budget
        # read below is scaled to this job's slice. 0/1 = whole
        # machine (the single-job default). Applied to EXPLICIT knob
        # values too: the knob states the machine's capacity, the
        # share states this tenant's entitlement.
        if tenant_share < 0.0 or tenant_share > 1.0:
            raise UdaError(f"uda.tpu.tenant.budget.share must be in "
                           f"[0, 1], got {tenant_share!r}")
        self.tenant_share = float(tenant_share) or 1.0

    @classmethod
    def from_config(cls, cfg) -> "MemoryBudget":
        return cls(
            hbm_budget_mb=cfg.get("uda.tpu.hbm.budget.mb"),
            host_budget_mb=cfg.get("uda.tpu.host.budget.mb"),
            hard_ceiling_mb=cfg.get("uda.tpu.budget.hard.mb"),
            key_width=cfg.get("uda.tpu.key.width"),
            host_fraction=cfg.get(
                "mapred.job.shuffle.input.buffer.percent"),
            enforce=cfg.get("uda.tpu.budget.enforce"),
            tenant_share=cfg.get("uda.tpu.tenant.budget.share"))

    def _share(self, nbytes: int) -> int:
        # never below 1 MB: a pathological share must degrade to the
        # reroute/reject ladder, not to a zero budget that rejects the
        # arena itself with a confusing arithmetic message
        return max(MB, int(nbytes * self.tenant_share))

    @property
    def hbm_budget_bytes(self) -> int:
        if self._hbm_mb <= 0:
            self._hbm_mb = max(
                1, int(_detect_hbm_mb() * HBM_RESERVE_FRACTION))
        return self._share(self._hbm_mb * MB)

    @property
    def host_budget_bytes(self) -> int:
        if self._host_mb <= 0:
            self._host_mb = max(
                1, int(_host_available_mb() * self.host_fraction))
        return self._share(self._host_mb * MB)

    @property
    def hard_ceiling_bytes(self) -> int:
        """Estimate above which even the degraded paths are refused
        (0 = no ceiling): spool disk, emit wall-clock and the consumer
        side all scale with the partition, and past this point the
        embedder's vanilla path is the better failure mode."""
        return self.hard_ceiling_mb * MB

    def device_bytes(self, partition_bytes: int) -> int:
        return device_bytes_estimate(partition_bytes, self.key_width)

    # -- admission point 1: INIT buffer validation --------------------------

    def validate_init(self, cfg) -> Admission:
        """The reducer.cc:56-133 mirror: the fetch-window + staging-
        arena working set (window x chunk in-flight fetch bytes, arena
        slots, the emitter's double buffer) must fit the host budget.
        Over budget: ``enforce=reroute`` shrinks the window to fit and
        warns (the reference's buffer-shrink path); ``enforce=reject``
        raises ``UdaError`` (-> the fallback contract). A chunk that
        cannot fit even at window 1 always raises (the reference's
        "RDMA Buffer is too small" hard failure). Mutates ``cfg`` when
        it shrinks the window; returns the decision record."""
        chunk = max(1, cfg.get("mapred.rdma.buf.size")) * 1024
        window = max(1, cfg.get("mapred.rdma.wqe.per.conn"))
        slots = max(1, cfg.get("uda.tpu.arena.slots"))
        fixed = (slots + 2) * chunk           # arena + emitter pair
        budget = self.host_budget_bytes
        # the HBM side is not consulted at INIT (no partition known yet)
        # and must not force backend detection in host-only processes
        hbm = self._hbm_mb * MB if self._hbm_mb > 0 else 0
        need = window * chunk + fixed
        if need <= budget:
            adm = Admission("in_memory", "init-working-set-in-budget",
                            need, None, hbm, budget)
            self._record(adm, "budget.admitted")
            return adm
        max_window = (budget - fixed) // chunk
        if max_window < 1:
            adm = Admission(
                "reject",
                f"chunk {chunk} B + {slots}-slot arena cannot fit host "
                f"budget {budget} B at any window", need, None,
                hbm, budget, cause="init")
            self._record(adm, "budget.rejected")
            raise UdaError(
                f"Not enough memory for the fetch working set: "
                f"host budget {budget} B < one {chunk} B chunk plus the "
                f"{slots}-slot staging arena (reduce the buffer size or "
                f"raise uda.tpu.host.budget.mb)")
        if self.enforce == "reject":
            adm = Admission(
                "reject",
                f"window {window} x {chunk} B exceeds host budget "
                f"{budget} B (enforce=reject)", need, None,
                hbm, budget, cause="init")
            self._record(adm, "budget.rejected")
            raise UdaError(
                f"fetch window over budget: {window} x {chunk} B + "
                f"{fixed} B fixed > host budget {budget} B")
        cfg.set("mapred.rdma.wqe.per.conn", int(max_window))
        log.warn(f"shrinking fetch window {window} -> {int(max_window)} "
                 f"to fit host budget {budget} B "
                 f"(chunk {chunk} B, arena {slots} slots)")
        adm = Admission("in_memory",
                        f"over-host-budget: window shrunk to "
                        f"{int(max_window)}", need, None,
                        hbm, budget, cause="host", rerouted=True)
        self._record(adm, "budget.rerouted")
        return adm

    # -- admission point 2: merge-approach routing --------------------------

    def route(self, estimate_bytes: Optional[int],
              threshold_bytes: int,
              prefer_streaming: bool = False) -> Admission:
        """The budget-aware auto merge-approach decision.

        - unknown estimate -> streaming (bounded memory for unbounded
          input);
        - over the hard ceiling -> reject (caller raises
          ``FallbackSignal`` before any allocation);
        - device estimate over the HBM budget, or host-resident bytes
          over the host budget -> streaming with bounded device runs;
        - small (within the measured hybrid crossover AND in budget) ->
          hybrid; in-budget above the crossover -> streaming (the
          measured-fastest large-scale path, which is also bounded).

        ``prefer_streaming`` (checkpointing armed, ``uda.tpu.ckpt.dir``)
        steers the in-budget-small case to streaming too: the hybrid
        LPQ/RPQ path has no durable run spool to snapshot, so
        crash-consistent resume needs the streaming path (cause
        ``"ckpt"``). Budget-forced decisions are unaffected.
        """
        hbm = self.hbm_budget_bytes
        host = self.host_budget_bytes
        if estimate_bytes is None:
            adm = Admission("streaming", "unknown-estimate", None, None,
                            hbm, host)
            self._record(adm, "budget.admitted")
            return adm
        dev = self.device_bytes(estimate_bytes)
        hard = self.hard_ceiling_bytes
        if hard and estimate_bytes > hard:
            adm = Admission(
                "reject", f"over-hard-ceiling: estimate "
                f"{estimate_bytes} B > {hard} B", estimate_bytes, dev,
                hbm, host, cause="hard")
            self._record(adm, "budget.rejected")
            return adm
        if dev > hbm:
            adm = Admission(
                "streaming", f"over-hbm-budget: device working set "
                f"{dev} B > {hbm} B", estimate_bytes, dev, hbm, host,
                cause="hbm", rerouted=True)
            self._record(adm, "budget.rerouted")
            return adm
        # hybrid/in-memory additionally hold the fetched bytes host-
        # resident through the LPQ spill; gate that on the host budget
        if estimate_bytes > host:
            adm = Admission(
                "streaming", f"over-host-budget: partition "
                f"{estimate_bytes} B > {host} B", estimate_bytes, dev,
                hbm, host, cause="host", rerouted=True)
            self._record(adm, "budget.rerouted")
            return adm
        if estimate_bytes <= threshold_bytes and prefer_streaming:
            adm = Admission(
                "streaming", "in-budget-small-ckpt: checkpoint/resume "
                "needs the run-spool (streaming) path", estimate_bytes,
                dev, hbm, host, cause="ckpt")
        elif estimate_bytes <= threshold_bytes:
            adm = Admission("hybrid", "in-budget-small", estimate_bytes,
                            dev, hbm, host)
        else:
            adm = Admission("streaming", "in-budget-large",
                            estimate_bytes, dev, hbm, host)
        self._record(adm, "budget.admitted")
        return adm

    # -- bookkeeping --------------------------------------------------------

    @staticmethod
    def _record(adm: Admission, counter: str) -> None:
        # literal names only: the metrics linter audits call sites
        if counter == "budget.admitted":
            metrics.add("budget.admitted")
        elif counter == "budget.rerouted":
            metrics.add("budget.rerouted")
        else:
            metrics.add("budget.rejected")
        line = (f"budget {adm.decision}: {adm.reason} "
                f"(estimate={adm.estimate_bytes}, "
                f"device={adm.device_bytes}, "
                f"hbm_budget={adm.hbm_budget_bytes}, "
                f"host_budget={adm.host_budget_bytes})")
        if counter == "budget.admitted":
            log.info(line)
        else:
            log.warn(line)
