"""Runtime lock-order validation (lockdep) for the shuffle threads.

19 modules spawn threads and take locks with no deadlock tooling at
all — the failure class that cost PR 4 its first version (a reader
blocked in ``recv()`` holding state another thread needed to close the
socket). udalint's **UDA007** rule is the static half (no unbounded
blocking call under a lock); this module is the dynamic half, modeled
on the kernel's lockdep: locks are grouped into *classes* by name, and
every acquisition while other locks are held records a directed edge
``held-class -> acquired-class`` in a process-global order graph. An
acquisition that would close a cycle in that graph is a potential
deadlock — two threads CAN interleave the two orders — and is reported
at acquire time with both stacks (the current one and the stack that
established the reverse path), long before the unlucky scheduling that
would actually wedge.

Usage::

    self._lock = TrackedLock("segment.state")
    self._cv = TrackedCondition(self._lock)       # or its own name
    with self._lock: ...

Zero-overhead-when-off contract: with ``UDA_TPU_LOCKDEP`` unset the
wrappers delegate straight to the underlying primitive (one attribute
check per acquire). Enabled (``UDA_TPU_LOCKDEP=1``), every tracked
acquire/release maintains a per-thread held stack and the global edge
graph. ``scripts/run_chaos.sh`` runs the whole faults tier under
lockdep; detected cycles count ``lockdep.cycles`` and the reports land
in ``CHAOS_TELEMETRY.json``. The stall watchdog's diagnostic dump
(:func:`uda_tpu.utils.watchdog.dump_diagnostics`) includes the held-
lock table when lockdep is on.

Same-class nesting (two INSTANCES of one class held together) is
deliberately not an edge — like lockdep's nesting annotations, class-
level self-edges would false-positive on legitimate instance
hierarchies; re-acquiring the SAME non-reentrant instance, however, is
reported immediately as a self-deadlock (it will wedge this very
thread).
"""

from __future__ import annotations

import json
import os
import threading
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = ["LockDep", "TrackedLock", "TrackedCondition", "lockdep",
           "lockdep_enabled_from_env", "RaceDetector", "racedet",
           "race_instrument", "racedet_enabled_from_env",
           "RACE_INSTRUMENTED"]


def _env_flag(var: str) -> bool:
    return os.environ.get(var, "").strip().lower() in (
        "1", "true", "yes", "on")


def lockdep_enabled_from_env() -> bool:
    """UDA_TPU_LOCKDEP=1 (or true/yes/on) arms the validator for the
    whole process."""
    return _env_flag("UDA_TPU_LOCKDEP")


def racedet_enabled_from_env() -> bool:
    """UDA_TPU_RACEDET=1 (or true/yes/on) arms the Eraser state machine
    for the whole process."""
    return _env_flag("UDA_TPU_RACEDET")


class LockDep:
    """The order graph + per-thread held stacks. One global instance
    (:data:`lockdep`) serves every TrackedLock by default; tests that
    SEED inversions use private instances so fixture cycles never
    pollute the real code's zero-cycle invariant (or its metrics)."""

    def __init__(self, enabled: Optional[bool] = None,
                 emit_metrics: bool = False):
        self.enabled = (lockdep_enabled_from_env() if enabled is None
                        else bool(enabled))
        self.emit_metrics = emit_metrics
        self._mu = threading.Lock()   # guards the graph (deliberately a
        # raw lock: the validator must not validate itself)
        self._tls = threading.local()
        # edge (held_class, acquired_class) -> stack where first seen,
        # plus the incremental adjacency the cycle DFS walks (a cycle
        # can only APPEAR when a new edge is inserted, so the check —
        # and the stack capture feeding it — run only then)
        self._edges: Dict[Tuple[str, str], str] = {}
        self._adj: Dict[str, List[str]] = {}
        self._reported: set = set()   # cycle keys already reported
        self.cycles: List[dict] = []  # cycle reports (see _report)
        # thread ident -> (thread name, held classes): the cross-thread
        # mirror of the per-thread held stacks (tls is invisible from
        # other threads, and the watchdog dumps from its own)
        self._held_all: Dict[int, Tuple[str, List[str]]] = {}

    # -- per-thread held stack ----------------------------------------------

    def _held(self) -> List["TrackedLock"]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_by_thread(self) -> Dict[str, List[str]]:
        """thread label -> held lock classes, every thread that holds
        anything (the watchdog dump's lock table). Best-effort: the
        table mutates concurrently, but a wedged thread's entry is
        static — which is exactly the one a stall dump needs."""
        with self._mu:
            snap = dict(self._held_all)
        return {f"{name} (ident {tid})": list(classes)
                for tid, (name, classes) in snap.items() if classes}

    def _publish_held(self, held: List["TrackedLock"]) -> None:
        """Mirror this thread's held stack into the global table the
        watchdog can read from another thread."""
        t = threading.current_thread()
        with self._mu:
            if held:
                self._held_all[t.ident] = (t.name,
                                           [lk.name for lk in held])
            else:
                self._held_all.pop(t.ident, None)

    # -- events --------------------------------------------------------------

    def before_acquire(self, lock: "TrackedLock") -> None:
        """Pre-acquire check: re-acquiring the same non-reentrant
        instance is a self-deadlock — report BEFORE blocking on it, or
        the report would never be written."""
        held = self._held()
        if any(lk is lock for lk in held):
            self._report(
                kind="self-deadlock", path=[lock.name, lock.name],
                stacks={"acquire": "".join(traceback.format_stack()[:-2])},
                note=f"thread re-acquires non-reentrant lock "
                     f"{lock.name!r} it already holds")

    def note_acquire(self, lock: "TrackedLock") -> None:
        held = self._held()
        if not getattr(self._tls, "reporting", False):
            cur_stack: Optional[str] = None
            for h in held:
                if h.name == lock.name:
                    continue  # same-class nesting: see module docstring
                edge = (h.name, lock.name)
                # unlocked membership probe: a steady-state nested
                # acquire (edge already recorded) must not pay stack
                # capture + DFS on every pass through a hot path; the
                # rare lost race just re-checks under _mu in _add_edge
                if edge in self._edges:
                    continue
                if cur_stack is None:
                    cur_stack = "".join(traceback.format_stack()[:-2])
                self._add_edge(edge, cur_stack)
        held.append(lock)
        self._publish_held(held)

    def note_release(self, lock: "TrackedLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                break
        self._publish_held(held)

    # -- the graph -----------------------------------------------------------

    def _add_edge(self, edge: Tuple[str, str], stack: str) -> None:
        a, b = edge
        with self._mu:
            if edge in self._edges:
                return  # raced with another thread: already analyzed
            self._edges[edge] = stack
            self._adj.setdefault(a, []).append(b)
            # a cycle exists iff b already reaches a — and only a NEW
            # edge can create one, so this DFS runs once per edge ever
            path = self._find_path(b, a)
        if path is not None:
            stacks = {f"{x}->{y}": self._edges.get((x, y), "")
                      for x, y in zip(path, path[1:])}
            stacks[f"{a}->{b} (now)"] = stack
            self._report(kind="order-inversion",
                         path=[a, b] + path[1:],
                         stacks=stacks,
                         note=f"acquiring {b!r} while holding {a!r}, "
                              f"but {b!r} already reaches {a!r} via "
                              f"{' -> '.join(path)}")

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src -> dst over recorded edges (caller holds _mu)."""
        stack = [(src, [src])]
        seen = {src}
        adj = self._adj
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- reporting -----------------------------------------------------------

    def _report(self, kind: str, path: List[str], stacks: Dict[str, str],
                note: str) -> None:
        key = (kind, tuple(sorted(set(path))))
        with self._mu:
            if key in self._reported:
                return
            self._reported.add(key)
            rep = {"kind": kind, "path": path, "note": note,
                   "stacks": stacks}
            self.cycles.append(rep)
        # everything below may take tracked locks (metrics, the
        # logger): the reporting flag keeps the recursion out of the
        # graph without breaking held-stack symmetry
        self._tls.reporting = True
        try:
            lines = [f"LOCKDEP: potential deadlock ({kind}): {note}"]
            for label, stk in stacks.items():
                if stk:
                    lines.append(f"-- first seen {label} --\n{stk}")
            text = "\n".join(lines)
            try:
                from uda_tpu.utils.logging import get_logger
                get_logger().error(text)
            except Exception:  # noqa: BLE001 - the report must survive
                print(text)    # a half-imported logging module
            if self.emit_metrics:
                try:
                    from uda_tpu.utils.metrics import metrics
                    metrics.add("lockdep.cycles")
                except Exception as e:  # noqa: BLE001
                    print(f"lockdep: metrics unavailable: {e}")
                out = os.environ.get("UDA_TPU_LOCKDEP_JSON")
                if out:
                    try:
                        with open(out, "a") as f:
                            f.write(json.dumps(
                                {"kind": kind, "path": path,
                                 "note": note}) + "\n")
                    except OSError as e:
                        print(f"lockdep: cannot append {out}: {e}")
        finally:
            self._tls.reporting = False

    def reset(self) -> None:
        """Forget edges, cycles and dedup state (tests). Held stacks
        are per-thread and survive — they describe reality, not
        history."""
        with self._mu:
            self._edges.clear()
            self._adj.clear()
            self._reported.clear()
            self.cycles.clear()


lockdep = LockDep(emit_metrics=True)


class TrackedLock:
    """``threading.Lock`` with lockdep class tracking. The ``name`` is
    the lock CLASS (shared by every instance guarding the same kind of
    state — 'segment.state', 'net.conn', ...), exactly like lockdep
    keys classes, not instances."""

    __slots__ = ("_lock", "name", "_dep")

    def __init__(self, name: str, dep: Optional[LockDep] = None):
        self._lock = threading.Lock()
        self.name = name
        self._dep = dep if dep is not None else lockdep

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        dep = self._dep
        if dep.enabled and blocking:
            # self-deadlock pre-check only for acquires that would WAIT:
            # a non-blocking try-acquire of a held lock just returns
            # False — a legitimate pattern, not a wedge
            dep.before_acquire(self)
        got = self._lock.acquire(blocking, timeout)
        if got and dep.enabled:
            dep.note_acquire(self)
        if got and _race_tracking.on:
            _race_tracking.note_acquire(self)
        return got

    def release(self) -> None:
        self._lock.release()
        if self._dep.enabled:
            self._dep.note_release(self)
        if _race_tracking.on:
            _race_tracking.note_release(self)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"TrackedLock({self.name!r})"


class TrackedCondition:
    """``threading.Condition`` over a :class:`TrackedLock`. ``wait``
    really releases the lock, so the held stack drops the entry for the
    duration — a waiter parked in ``cv.wait`` does NOT order-constrain
    locks acquired by the threads that will wake it."""

    def __init__(self, lock: Optional[TrackedLock] = None,
                 name: str = "cond", dep: Optional[LockDep] = None):
        self._tlock = lock if lock is not None else TrackedLock(name, dep)
        self._cond = threading.Condition(self._tlock._lock)

    @property
    def name(self) -> str:
        return self._tlock.name

    def acquire(self, *args, **kwargs) -> bool:
        return self._tlock.acquire(*args, **kwargs)

    def release(self) -> None:
        self._tlock.release()

    def __enter__(self) -> "TrackedCondition":
        self._tlock.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._tlock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        dep = self._tlock._dep
        if dep.enabled:
            dep.note_release(self._tlock)
        if _race_tracking.on:
            _race_tracking.note_release(self._tlock)
        try:
            return self._cond.wait(timeout)
        finally:
            if dep.enabled:
                dep.note_acquire(self._tlock)
            if _race_tracking.on:
                _race_tracking.note_acquire(self._tlock)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        dep = self._tlock._dep
        if dep.enabled:
            dep.note_release(self._tlock)
        if _race_tracking.on:
            _race_tracking.note_release(self._tlock)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            if dep.enabled:
                dep.note_acquire(self._tlock)
            if _race_tracking.on:
                _race_tracking.note_acquire(self._tlock)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:
        return f"TrackedCondition({self._tlock.name!r})"


# ---------------------------------------------------------------------------
# The runtime race detector (udarace, the dynamic half of UDA201-203):
# a sampling Eraser lockset state machine over the attributes
# race_instrument() hooks. Per (object, attr) the machine walks
# virgin -> exclusive -> shared -> shared-modified exactly like Eraser
# (Savage et al.): the first thread owns the field without lockset
# constraints (init-then-publish is legal); the moment a SECOND thread
# touches it, the candidate lockset starts as the locks that thread
# holds and every later access intersects it; an empty candidate set on
# a shared-modified field is a data race, reported once per
# (class, attr) with BOTH stacks — the current access and the most
# recent access from the other thread — like lockdep's cycle reports.
# ---------------------------------------------------------------------------

_EXCLUSIVE, _SHARED, _SHARED_MOD = 0, 1, 2


class _RaceState:
    """Per-(object, attr) machine state. ``lockset`` is None while the
    field is still thread-exclusive (the Eraser 'universe' — no
    constraint yet) and a set of lock ids once shared."""

    __slots__ = ("state", "owner", "lockset", "prev", "prev_cross")

    def __init__(self, owner: int):
        self.state = _EXCLUSIVE
        self.owner = owner
        self.lockset: Optional[frozenset] = None
        # (thread ident, thread name, op, stack) of the last sampled
        # access, and of the last one from a DIFFERENT thread than the
        # current accessor — the "other side" of a race report
        self.prev: Optional[Tuple[int, str, str, str]] = None
        self.prev_cross: Optional[Tuple[int, str, str, str]] = None


class _RaceTracking:
    """Shared held-lock bookkeeping: per-thread held sets are a fact
    about THREADS, not about any one detector, so every RaceDetector
    (the global one and the private test instances) reads the same
    table. TrackedLock feeds it whenever any enabled detector exists —
    one attribute check (``_race_tracking.on``) on the disabled path."""

    def __init__(self):
        self.on = False
        self._tls = threading.local()

    def held(self) -> Dict[int, str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = {}
        return held

    def note_acquire(self, lock: "TrackedLock") -> None:
        self.held()[id(lock)] = lock.name

    def note_release(self, lock: "TrackedLock") -> None:
        self.held().pop(id(lock), None)


_race_tracking = _RaceTracking()


class RaceDetector:
    """The Eraser machine. One global instance (:data:`racedet`) serves
    every race_instrument() hook; tests that SEED races use private
    instances so fixture races never pollute the real code's zero-race
    invariant (mirroring LockDep's private-instance discipline). All
    instances share the per-thread held-lock table TrackedLock feeds
    (:class:`_RaceTracking`)."""

    def __init__(self, enabled: Optional[bool] = None,
                 emit_metrics: bool = False,
                 sample: Optional[int] = None):
        self.enabled = (racedet_enabled_from_env() if enabled is None
                        else bool(enabled))
        self.emit_metrics = emit_metrics
        if sample is None:
            sample = int(os.environ.get("UDA_TPU_RACEDET_SAMPLE", "1")
                         or "1")
        self.sample = max(1, sample)
        self._mu = threading.Lock()   # raw: must not validate itself
        self._tls = threading.local()
        self._state: Dict[Tuple[int, str], _RaceState] = {}
        self._reported: set = set()
        self.races: List[dict] = []
        if self.enabled:
            _race_tracking.on = True

    # -- per-thread held-lock set (fed by TrackedLock) -----------------------

    def _held(self) -> Dict[int, str]:
        return _race_tracking.held()

    def note_acquire(self, lock: "TrackedLock") -> None:
        _race_tracking.note_acquire(lock)

    def note_release(self, lock: "TrackedLock") -> None:
        _race_tracking.note_release(lock)

    # -- the machine ---------------------------------------------------------

    def access(self, obj, attr: str, is_write: bool) -> None:
        """One sampled access to an instrumented attribute. The caller
        (the race_instrument property) already checked ``enabled``."""
        if getattr(self._tls, "busy", False):
            return  # a report in progress touches instrumented state
        if self.sample > 1:
            n = getattr(self._tls, "n", 0) + 1
            self._tls.n = n
            if n % self.sample:
                return
        tid = threading.get_ident()
        held = frozenset(self._held())
        key = (id(obj), attr)
        race_note = None
        with self._mu:
            st = self._state.get(key)
            if st is None:
                st = self._state[key] = _RaceState(tid)
                # record the birth access: it is the "other side" of
                # the first cross-thread race report (usually the
                # init-then-publish write in __init__)
                st.prev = (tid, threading.current_thread().name,
                           "write" if is_write else "read",
                           "".join(traceback.format_stack()[:-2]))
                return
            op = "write" if is_write else "read"
            stack = "".join(traceback.format_stack()[:-2])
            rec = (tid, threading.current_thread().name, op, stack)
            if st.prev is not None and st.prev[0] != tid:
                st.prev_cross = st.prev
            if st.state == _EXCLUSIVE and tid == st.owner:
                # still single-threaded: no lockset constraint, but
                # remember the stack — it is the "other side" the first
                # cross-thread race report needs
                st.prev = rec
                return
            # second thread (or already shared): intersect candidates
            st.lockset = (held if st.lockset is None
                          else st.lockset & held)
            if is_write or st.state == _SHARED_MOD:
                st.state = _SHARED_MOD
            else:
                st.state = _SHARED
            st.prev = rec
            if st.state == _SHARED_MOD and not st.lockset:
                race_note = (type(obj).__name__, rec, st.prev_cross)
        if race_note is not None:
            cls_name, rec, cross = race_note
            stacks = {f"{rec[2]} on {rec[1]} (now)": rec[3]}
            if cross is not None:
                stacks[f"{cross[2]} on {cross[1]}"] = cross[3]
            self._report(cls_name, attr, stacks)

    # -- reporting -----------------------------------------------------------

    def _report(self, cls_name: str, attr: str,
                stacks: Dict[str, str]) -> None:
        key = (cls_name, attr)
        with self._mu:
            if key in self._reported:
                return
            self._reported.add(key)
            note = (f"{cls_name}.{attr} is written from multiple "
                    f"threads with no consistently held lock")
            rep = {"class": cls_name, "attr": attr, "note": note,
                   "stacks": stacks}
            self.races.append(rep)
        self._tls.busy = True
        try:
            lines = [f"RACEDET: data race: {note}"]
            for label, stk in stacks.items():
                if stk:
                    lines.append(f"-- {label} --\n{stk}")
            text = "\n".join(lines)
            try:
                from uda_tpu.utils.logging import get_logger
                get_logger().error(text)
            except Exception:  # noqa: BLE001 - the report must survive
                print(text)    # a half-imported logging module
            if self.emit_metrics:
                try:
                    from uda_tpu.utils.metrics import metrics
                    metrics.add("racedet.races")
                except Exception as e:  # noqa: BLE001
                    print(f"racedet: metrics unavailable: {e}")
                out = os.environ.get("UDA_TPU_RACEDET_JSON")
                if out:
                    try:
                        # one compact line per race: the chaos ladder
                        # greps these; stacks stay in the log/report
                        with open(out, "a") as f:
                            f.write(json.dumps(
                                {"class": cls_name, "attr": attr,
                                 "note": note}) + "\n")
                    except OSError as e:
                        print(f"racedet: cannot append {out}: {e}")
        finally:
            self._tls.busy = False

    def reset(self) -> None:
        """Forget machine state, reports and dedup keys (tests). Held
        sets are per-thread reality and survive."""
        with self._mu:
            self._state.clear()
            self._reported.clear()
            self.races.clear()


racedet = RaceDetector(emit_metrics=True)


# module qualname -> instrumented attrs: ALWAYS recorded (armed or
# not) so the static<->runtime lockstep test can compare this registry
# against analysis/threads.py RUNTIME_INSTRUMENTED without re-importing
# the world under UDA_TPU_RACEDET=1
RACE_INSTRUMENTED: Dict[str, Tuple[str, ...]] = {}


def race_instrument(*attrs: str, det: Optional[RaceDetector] = None):
    """Class decorator hooking ``attrs`` into the race detector.

    Zero-overhead-when-off contract, stricter than lockdep's: with
    ``UDA_TPU_RACEDET`` unset the class is returned UNTOUCHED — plain
    attributes, no descriptor in the lookup path — so the hot tables
    (conn maps, staging ladders, credit ledgers) pay nothing. Armed,
    each attr becomes a property whose fast path is one ``enabled``
    check before the instance-dict access; every read/write feeds
    :meth:`RaceDetector.access`. Incompatible with ``__slots__`` on
    the decorated class (the hooks store through the instance dict)."""

    def deco(cls):
        d = det if det is not None else racedet
        RACE_INSTRUMENTED[f"{cls.__module__}.{cls.__qualname__}"] = attrs
        if not d.enabled:
            return cls
        if "__slots__" in cls.__dict__:
            raise TypeError(
                f"race_instrument: {cls.__name__} declares __slots__; "
                f"the hooks need an instance dict")
        for name in attrs:
            def _mk(name=name):
                def _get(self):
                    if d.enabled:
                        d.access(self, name, False)
                    return self.__dict__[name]

                def _set(self, value):
                    if d.enabled:
                        d.access(self, name, True)
                    self.__dict__[name] = value

                def _del(self):
                    if d.enabled:
                        d.access(self, name, True)
                    del self.__dict__[name]

                return property(_get, _set, _del)
            setattr(cls, name, _mk())
        return cls

    return deco
