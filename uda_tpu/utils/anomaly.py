"""Online anomaly detection over the time-series rollup ring.

The flight recorder (utils/flightrec.py) dumps its event ring *after*
something failed; perfwatch catches regressions *offline* in CI. This
module closes the gap in between: cheap online detectors run on every
rollup the :class:`~uda_tpu.utils.timeseries.TimeSeries` timer
produces, and when a live degradation is recognized the black box is
dumped **proactively** — cause ``anomaly``, before any FallbackSignal —
so the minutes leading up to a failure are on disk even when the
process later dies uncleanly.

Detectors (each EWMA/z-score based with an absolute guard so a noisy
idle process cannot alarm):

- **throughput collapse** — a counter's per-interval rate falls below
  ``uda.tpu.anomaly.collapse.frac`` of its EWMA while the EWMA says the
  plane was moving (floor ``uda.tpu.anomaly.collapse.floor.mb_s``);
- **p99 inflation** — a latency histogram's per-interval p99 z-scores
  above ``uda.tpu.anomaly.zscore`` and clears the absolute floor
  ``uda.tpu.anomaly.p99.floor.ms`` (per-interval percentiles, so one
  bad minute is not averaged away by a long healthy history);
- **gauge leak-slope** — a watched gauge (``uda.tpu.anomaly.leak.
  gauges``) rises monotonically across the whole window by at least
  ``uda.tpu.anomaly.leak.rise`` — the on-air/obligation shape of a
  leak, caught while the process is still healthy;
- **tenant starvation** — the SLI book (tenant/sli.py) reports a
  tenant with backlog and zero scheduled bytes for
  ``uda.tpu.anomaly.starve.s`` — the WDRR fairness audit's alarm.

Every firing advances ``anomaly.<kind>`` (labeled with the offending
series/tenant) and records an ``anomaly`` flight-recorder event;
dumping is **detect-only by default** (``uda.tpu.anomaly.dump`` /
``UDA_TPU_ANOMALY_DUMP=1``) and rate-limited
(``uda.tpu.anomaly.dump.interval.s``) so a flapping detector cannot
fill a disk. All detectors need ``uda.tpu.anomaly.consec`` consecutive
breaching intervals (hysteresis) and ``uda.tpu.anomaly.warmup``
intervals of history before they may fire.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Dict, List, Optional

from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["AnomalyEngine", "anomaly_engine"]

log = get_logger()

# clean intervals after which an active anomaly is considered resolved
_CLEAR_AFTER = 3


class _Ewma:
    """Exponentially-weighted mean/variance (West's update) — the
    per-series baseline every detector scores against."""

    __slots__ = ("alpha", "n", "mean", "var")

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, x: float) -> None:
        self.n += 1
        if self.n == 1:
            self.mean = x
            return
        d = x - self.mean
        incr = self.alpha * d
        self.mean += incr
        self.var = (1.0 - self.alpha) * (self.var + d * incr)

    def zscore(self, x: float) -> float:
        if self.n < 2:
            return 0.0
        return (x - self.mean) / math.sqrt(self.var + 1e-12)


class _Detector:
    """One detector = per-key baselines + a consecutive-breach counter
    (the hysteresis that keeps a single noisy interval silent)."""

    kind = "generic"

    def __init__(self, engine: "AnomalyEngine"):
        self.engine = engine
        self._ewma: Dict[str, _Ewma] = {}
        self._breach: Dict[str, int] = {}

    def baseline(self, key: str) -> _Ewma:
        b = self._ewma.get(key)
        if b is None:
            b = self._ewma[key] = _Ewma(self.engine.alpha)
        return b

    def judge(self, key: str, breaching: bool, detail: Dict) -> None:
        """Count consecutive breaches; hand a sustained one to the
        engine (which dedupes active anomalies and rate-limits dumps)."""
        n = self._breach.get(key, 0) + 1 if breaching else 0
        self._breach[key] = n
        if breaching and n >= self.engine.consec:
            self.engine.fire(self.kind, key, detail)
        elif not breaching:
            self.engine.clear_tick(self.kind, key)


class _ThroughputCollapse(_Detector):
    kind = "throughput"

    COUNTERS = ("fetch.bytes", "supplier.bytes", "emit.bytes")

    def observe(self, roll: Dict) -> None:
        eng = self.engine
        for name in self.COUNTERS:
            rate = roll["counters"].get(name, 0.0) / roll["dt"]
            b = self.baseline(name)
            moving = b.n >= eng.warmup and b.mean >= eng.collapse_floor
            breaching = moving and rate < eng.collapse_frac * b.mean
            self.judge(name, breaching, {
                "series": name, "rate": round(rate, 1),
                "ewma": round(b.mean, 1)})
            # a collapsed interval must not drag the baseline down to
            # the collapsed level (self-normalizing outage): only
            # healthy intervals teach the EWMA
            if not breaching:
                b.update(rate)


class _P99Inflation(_Detector):
    kind = "p99"

    HISTS = ("fetch.latency_ms", "supplier.read.latency_ms")

    def observe(self, roll: Dict) -> None:
        eng = self.engine
        for name in self.HISTS:
            s = roll["percentiles"].get(name)
            if s is None:
                continue  # idle interval: no latency evidence either way
            p99 = s["p99"]
            b = self.baseline(name)
            breaching = (b.n >= eng.warmup
                         and p99 >= eng.p99_floor_ms
                         and b.zscore(p99) >= eng.zscore)
            self.judge(name, breaching, {
                "series": name, "p99_ms": round(p99, 3),
                "ewma_ms": round(b.mean, 3),
                "z": round(b.zscore(p99), 2)})
            if not breaching:
                b.update(p99)


class _GaugeLeak(_Detector):
    kind = "leak"

    def observe(self, roll: Dict) -> None:
        eng = self.engine
        ts = eng.timeseries
        if ts is None:
            return
        for name in eng.leak_gauges:
            series = ts.gauge_series(name)
            if len(series) < max(eng.warmup, 4):
                self._breach[name] = 0
                continue
            rise = series[-1] - series[0]
            monotone = all(b >= a for a, b in zip(series, series[1:]))
            breaching = monotone and rise >= eng.leak_rise
            self.judge(name, breaching, {
                "gauge": name, "rise": round(rise, 1),
                "over_intervals": len(series)})


class _TenantStarvation(_Detector):
    kind = "starvation"

    def observe(self, roll: Dict) -> None:
        from uda_tpu.tenant.sli import sli_book

        eng = self.engine
        starving = sli_book.starving_tenants(eng.starve_s)
        seen = set()
        for tenant, starved_s in starving.items():
            seen.add(tenant)
            self.judge(tenant, True, {
                "tenant": tenant, "starved_s": round(starved_s, 3)})
        for tenant in list(self._breach):
            if tenant not in seen:
                self.judge(tenant, False, {})


class AnomalyEngine:
    """The detector host: subscribes to the TimeSeries listener feed,
    keeps the active-anomaly table the wire/fleet layer exports, and
    owns the proactive-dump policy."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.timeseries = None
        self.armed = False
        # policy knobs (re-pointed by arm_from_config)
        self.alpha = 0.3
        self.zscore = 4.0
        self.warmup = 5
        self.consec = 3
        self.collapse_frac = 0.25
        self.collapse_floor = 1e6  # bytes/s the EWMA must show before
        # a collapse is judgeable (the absolute guard)
        self.p99_floor_ms = 50.0
        self.leak_gauges: tuple = ("fetch.on_air",)
        self.leak_rise = 64.0
        self.starve_s = 5.0
        self.dump_enabled = False
        self.dump_interval_s = 300.0
        self._detectors: List[_Detector] = []
        self._active: Dict[str, Dict] = {}   # (kind|key) -> anomaly
        self._clean: Dict[str, int] = {}     # (kind|key) -> clean ticks
        self.fired = 0
        self.dumps = 0
        self._last_dump_t = 0.0

    # -- lifecycle -----------------------------------------------------------

    def arm_from_config(self, config, ts) -> bool:
        """Configure + subscribe to ``ts``'s rollup feed. Idempotent;
        returns armed state. Detect-only unless ``uda.tpu.anomaly.dump``
        (or UDA_TPU_ANOMALY_DUMP=1) asks for proactive capture."""
        if not config.get("uda.tpu.anomaly.enable"):
            return False
        with self._lock:
            self.zscore = float(config.get("uda.tpu.anomaly.zscore"))
            self.warmup = int(config.get("uda.tpu.anomaly.warmup"))
            self.consec = int(config.get("uda.tpu.anomaly.consec"))
            self.collapse_frac = float(
                config.get("uda.tpu.anomaly.collapse.frac"))
            self.collapse_floor = 1e6 * float(
                config.get("uda.tpu.anomaly.collapse.floor.mb_s"))
            self.p99_floor_ms = float(
                config.get("uda.tpu.anomaly.p99.floor.ms"))
            self.leak_gauges = tuple(
                g.strip() for g in
                str(config.get("uda.tpu.anomaly.leak.gauges")).split(",")
                if g.strip())
            self.leak_rise = float(config.get("uda.tpu.anomaly.leak.rise"))
            self.starve_s = float(config.get("uda.tpu.anomaly.starve.s"))
            self.dump_enabled = (
                bool(config.get("uda.tpu.anomaly.dump"))
                or os.environ.get("UDA_TPU_ANOMALY_DUMP", "") == "1")
            self.dump_interval_s = float(
                config.get("uda.tpu.anomaly.dump.interval.s"))
            if not self.armed:
                self._detectors = [_ThroughputCollapse(self),
                                   _P99Inflation(self),
                                   _GaugeLeak(self),
                                   _TenantStarvation(self)]
                self.timeseries = ts
                ts.add_listener(self.on_rollup)
                self.armed = True
        return True

    def reset(self) -> None:
        """Disarm and clear all state (conftest hygiene)."""
        with self._lock:
            ts, self.timeseries = self.timeseries, None
            self.armed = False
            self._detectors = []
            self._active.clear()
            self._clean.clear()
            self.fired = 0
            self.dumps = 0
            self._last_dump_t = 0.0
            self.dump_enabled = False
        if ts is not None:
            ts.remove_listener(self.on_rollup)

    # -- the per-rollup pass -------------------------------------------------

    def on_rollup(self, roll: Dict) -> None:
        for det in list(self._detectors):
            det.observe(roll)

    # -- firing / clearing ---------------------------------------------------

    def fire(self, kind: str, key: str, detail: Dict) -> None:
        """A sustained breach. Transition-edge counting: an anomaly
        already active only refreshes its detail — counters and dumps
        fire on the inactive->active edge."""
        akey = f"{kind}|{key}"
        with self._lock:
            self._clean.pop(akey, None)
            known = self._active.get(akey)
            if known is not None:
                known.update(detail)
                known["last_ts"] = round(time.time(), 3)
                return
            self._active[akey] = dict(
                detail, kind=kind, key=key,
                since_ts=round(time.time(), 3),
                last_ts=round(time.time(), 3))
            self.fired += 1
        metrics.add(f"anomaly.{kind}", key=key)
        metrics.add("anomaly.fired")
        log.warn(f"anomaly detected: {kind} on {key!r} {detail}")
        from uda_tpu.utils.flightrec import flightrec

        flightrec.record("anomaly", anomaly=kind, key=key, **detail)
        self._maybe_dump(kind, key, detail)

    def clear_tick(self, kind: str, key: str) -> None:
        """One clean interval for this (kind, key); after
        ``_CLEAR_AFTER`` of them the anomaly leaves the active table."""
        akey = f"{kind}|{key}"
        with self._lock:
            if akey not in self._active:
                return
            n = self._clean.get(akey, 0) + 1
            if n >= _CLEAR_AFTER:
                self._active.pop(akey, None)
                self._clean.pop(akey, None)
            else:
                self._clean[akey] = n

    def _maybe_dump(self, kind: str, key: str, detail: Dict) -> None:
        """The proactive capture: rate-limited flight-recorder dump
        BEFORE anything fails (cause=anomaly). Detect-only default."""
        if not self.dump_enabled:
            return
        now = time.monotonic()
        with self._lock:
            if self._last_dump_t and \
                    now - self._last_dump_t < self.dump_interval_s:
                return
            self._last_dump_t = now
            self.dumps += 1
        from uda_tpu.utils.flightrec import flightrec

        metrics.add("anomaly.dumps")
        flightrec.dump("anomaly", extra={
            "anomaly": dict(detail, kind=kind, key=key),
            "active": self.active()})

    # -- export --------------------------------------------------------------

    def active(self) -> List[Dict]:
        with self._lock:
            return sorted((dict(a) for a in self._active.values()),
                          key=lambda a: (a["kind"], a["key"]))

    def snapshot(self) -> Dict:
        """The provider / MSG_STATS block."""
        with self._lock:
            active = sorted((dict(a) for a in self._active.values()),
                            key=lambda a: (a["kind"], a["key"]))
            return {"armed": self.armed, "fired": self.fired,
                    "dumps": self.dumps,
                    "dump_enabled": self.dump_enabled,
                    "active": active}


anomaly_engine = AnomalyEngine()
