"""Runtime resource-obligation ledger (the dynamic half of udaflow).

udalint's **UDA101** proves statically that every registered acquire is
balanced on every CFG path; this module is the runtime mirror, modeled
on lockdep (:mod:`uda_tpu.utils.locks`): under ``UDA_TPU_RESLEDGER=1``
every registered acquire — a RowBufferPool lease, a DataEngine fd-cache
pin, an admission-byte charge, a paired-gauge increment, a scoped
failpoint arming — records an *outstanding obligation* with the stack
that opened it, and the paired release settles it. Drain points
(OverlappedMerger finish/abort, DataEngine stop, bridge EXIT) then
assert the books are empty: anything still open is a
leak, reported ONCE with its allocation stack — the exact diagnostic
the historical bugs (PR 6's ``try_plan`` charge leak, the PR 5
cancel-while-queued leak, PR 9's stranded ``stage.inflight.bytes``)
each cost a review round to reconstruct by hand.

The obligation inventory is kept in deliberate lockstep with the static
registry (:data:`uda_tpu.analysis.flow.DEFAULT_PAIRS`); pair ids match
so a UDA101 finding and a runtime leak report name the same discipline
(``tests/test_udaflow.py`` asserts the two inventories agree).

Zero-overhead-when-off contract (same as lockdep): with
``UDA_TPU_RESLEDGER`` unset every hook is one attribute check. Enabled,
each acquire pays a stack capture — chaos-tier pricing, not production
pricing. ``scripts/run_chaos.sh`` arms the ledger on the pipeline,
network and completion rungs and FAILS the run on a non-empty leak
report; leaks count ``resledger.leaks`` and append JSON lines to
``UDA_TPU_RESLEDGER_JSON`` when set.

Settlement is by ``(pair, owner, key)``: the key is whatever identity
the call site can cheaply reproduce on both sides — the buffer's data
pointer for pool leases, the MOF path for fd pins, the gauge name for
paired gauges — and ``owner`` scopes an instance's books (``id(self)``
of the pool/cache/engine) so one DataEngine's drain point cannot
confiscate a concurrently-live engine's legitimately-open obligations
(the killed-supplier chaos shape: one supplier stops while its peers
still serve). Amount-bearing pairs (gauges, admission bytes) settle
greedily: a release of N bytes consumes open records oldest-first,
splitting the last one — exactly how a gauge decrement relates to
prior increments. An amount-bearing settle that finds nothing (or not
enough) open records the shortfall as a transient *deficit* the next
acquire under the same key cancels first: the gauge hot paths bump
their paired gauges OUTSIDE the state locks that order the underlying
attempts, so a decrement can legitimately reach the books an instant
before its matching increment (e.g. a watchdog-rescue ``fail()``
racing ``_try_issue``'s +1) — without the deficit, that inversion
would fabricate a phantom obligation and a false leak at the next
drain. A deficit never survives a drain point (drains clear it; at a
quiescent boundary a residual deficit is a plain gauge imbalance, and
the conftest gauge-balance check owns that class). Unit settles with
no record stay ignored entirely: arming the ledger mid-process must
not turn pre-arming acquires into phantom double-releases.
"""

from __future__ import annotations

import json
import os
import threading
import traceback
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["ResourceLedger", "resledger", "PAIRED_GAUGES",
           "resledger_enabled_from_env"]


def resledger_enabled_from_env() -> bool:
    """UDA_TPU_RESLEDGER=1 (or true/yes/on) arms the ledger for the
    whole process."""
    return os.environ.get("UDA_TPU_RESLEDGER", "").strip().lower() in (
        "1", "true", "yes", "on")


# gauge name -> ledger pair id, the paired (increment must meet
# decrement) gauges. Pair ids mirror uda_tpu.analysis.flow.DEFAULT_PAIRS
# — the static and runtime inventories are the same table on purpose.
PAIRED_GAUGES: Dict[str, str] = {
    "fetch.on_air": "gauge.fetch.on_air",
    "stage.inflight.bytes": "gauge.stage.inflight",
    "arena.slots_in_use": "gauge.arena.slots",
    "supplier.reads.on_air": "gauge.reads.on_air",
    "supplier.read.bytes.on_air": "gauge.read.bytes",
    "io.batch.inflight": "gauge.io.batch",
    "tenant.read.bytes.on_air": "gauge.tenant.read.bytes",
    "store.migrate.bytes.on_air": "gauge.store.migrate",
    "push.on_air": "gauge.push.on_air",
    "push.staged.bytes": "gauge.push.staged",
}


class _Rec:
    """One open obligation: how much, who opened it, where."""

    __slots__ = ("amount", "detail", "stack", "seq")

    def __init__(self, amount: float, detail: str, stack: str, seq: int):
        self.amount = amount
        self.detail = detail
        self.stack = stack
        self.seq = seq


class ResourceLedger:
    """The obligation books. One global instance (:data:`resledger`)
    serves every instrumented site by default; tests that SEED leaks
    use private instances so fixture leaks never pollute the real
    code's zero-outstanding invariant (the LockDep pattern)."""

    def __init__(self, enabled: Optional[bool] = None,
                 emit_metrics: bool = False, emit_json: bool = False):
        self.enabled = (resledger_enabled_from_env() if enabled is None
                        else bool(enabled))
        # only the process-global instance feeds the resledger.leaks
        # counter and the UDA_TPU_RESLEDGER_JSON report file: a private
        # fixture ledger SEEDING a leak on purpose must never fail the
        # chaos rung's zero-leaks-on-real-code gate (the LockDep rule)
        self.emit_metrics = emit_metrics
        self.emit_json = emit_json
        # a raw lock, not a TrackedLock: the ledger must not ledger
        # itself (and must stay importable before utils.locks)
        self._mu = threading.Lock()
        self._recs: Dict[Tuple[str, Any, Any], List[_Rec]] = {}
        # transient settle-before-acquire shortfalls (see module
        # docstring); consumed by the next acquire under the same key,
        # cleared at every drain point
        self._deficits: Dict[Tuple[str, Any, Any], float] = {}
        self._seq = 0
        self.leak_reports: List[dict] = []  # every drain's findings

    # -- events --------------------------------------------------------------

    def acquire(self, pair: str, key: Any = None, amount: float = 1,
                detail: str = "", owner: Any = None) -> None:
        """Open one obligation under ``(pair, owner, key)``. No-op
        when off."""
        if not self.enabled:
            return
        # [:-1] drops this frame; the acquire site is the tail
        stack = "".join(traceback.format_stack()[:-1])
        with self._mu:
            k = (pair, owner, key)
            deficit = self._deficits.get(k, 0.0)
            if deficit > 0:
                # a racing settle got here first (see module
                # docstring): this acquire is the one it paid for
                take = min(deficit, float(amount))
                if deficit - take <= 0:
                    self._deficits.pop(k, None)
                else:
                    self._deficits[k] = deficit - take
                amount = float(amount) - take
                if amount <= 0:
                    return
            self._seq += 1
            self._recs.setdefault(k, []).append(
                _Rec(float(amount), detail, stack, self._seq))

    def settle(self, pair: str, key: Any = None,
               amount: Optional[float] = None, owner: Any = None) -> None:
        """Close obligations under ``(pair, key)``: the newest single
        record when ``amount`` is None (the unit acquire/release idiom:
        fd pins, leases), else ``amount`` worth oldest-first (the
        byte-accounting idiom: gauges, admission charges — a release
        of N bytes retires the N longest-open bytes, splitting the
        last record). An unmatched unit settle is ignored (mid-process
        arming); an unmatched amount becomes a transient deficit the
        next acquire cancels (the settle-before-acquire inversion —
        see the module docstring)."""
        if not self.enabled:
            return
        with self._mu:
            k = (pair, owner, key)
            recs = self._recs.get(k)
            if amount is None:
                if recs:
                    recs.pop()
            else:
                left = float(amount)
                while recs and left > 0:
                    if recs[0].amount <= left:
                        left -= recs[0].amount
                        recs.pop(0)
                    else:
                        recs[0].amount -= left
                        left = 0
                if left > 0:
                    self._deficits[k] = self._deficits.get(k, 0.0) + left
            if not recs:
                self._recs.pop(k, None)

    def note_gauge(self, name: str, delta: float) -> None:
        """The central paired-gauge hook (called by
        :meth:`uda_tpu.utils.metrics.Metrics.gauge_add`): a positive
        delta opens ``delta`` worth of obligation, a negative one
        settles it."""
        pair = PAIRED_GAUGES.get(name)
        if pair is None:
            return
        if delta > 0:
            self.acquire(pair, key=name, amount=delta)
        elif delta < 0:
            self.settle(pair, key=name, amount=-delta)

    # -- inspection / drains -------------------------------------------------

    _ANY = object()  # drain/outstanding: no owner filter

    def outstanding(self, pairs: Optional[Iterable[str]] = None,
                    owner: Any = _ANY) -> List[dict]:
        """Snapshot of open obligations (optionally only ``pairs`` /
        one ``owner``'s books)."""
        want = set(pairs) if pairs is not None else None
        out = []
        with self._mu:
            for (pair, own, key), recs in self._recs.items():
                if want is not None and pair not in want:
                    continue
                if owner is not self._ANY and own != owner:
                    continue
                for rec in recs:
                    out.append({"pair": pair, "owner": own, "key": key,
                                "amount": rec.amount,
                                "detail": rec.detail,
                                "stack": rec.stack, "seq": rec.seq})
        out.sort(key=lambda r: r["seq"])
        return out

    def drain(self, point: str, pairs: Optional[Iterable[str]] = None,
              owner: Any = _ANY) -> List[dict]:
        """Assert the books are empty at a lifecycle boundary:
        anything still open (optionally restricted to ``pairs`` and to
        one instance's ``owner`` scope) is a LEAK — popped from the
        books (so each obligation is reported exactly once, even
        across overlapping drain points), logged with its allocation
        stack, counted (``resledger.leaks``) and appended to
        ``UDA_TPU_RESLEDGER_JSON``. Returns the reports."""
        if not self.enabled:
            return []
        want = set(pairs) if pairs is not None else None
        leaked: List[Tuple[str, Any, _Rec]] = []
        with self._mu:
            for pk in list(self._recs):
                if want is not None and pk[0] not in want:
                    continue
                if owner is not self._ANY and pk[1] != owner:
                    continue
                for rec in self._recs.pop(pk):
                    leaked.append((pk[0], pk[2], rec))
            # deficits are transient by contract: at a quiescent
            # boundary a residual one is a plain gauge imbalance (the
            # gauge-balance teardown's class), never carried forward
            for pk in list(self._deficits):
                if want is not None and pk[0] not in want:
                    continue
                if owner is not self._ANY and pk[1] != owner:
                    continue
                del self._deficits[pk]
        if not leaked:
            return []
        leaked.sort(key=lambda t: t[2].seq)
        reports = []
        for pair, key, rec in leaked:
            reports.append({"point": point, "pair": pair,
                            "key": repr(key), "amount": rec.amount,
                            "detail": rec.detail, "stack": rec.stack})
        with self._mu:
            self.leak_reports.extend(reports)
        self._emit(point, reports)
        return reports

    def _emit(self, point: str, reports: List[dict]) -> None:
        lines = [f"RESLEDGER: {len(reports)} leaked obligation(s) at "
                 f"drain point {point!r}:"]
        for r in reports:
            lines.append(
                f"-- {r['pair']} key={r['key']} amount={r['amount']:g}"
                f"{' (' + r['detail'] + ')' if r['detail'] else ''}, "
                f"acquired at --\n{r['stack']}")
        text = "\n".join(lines)
        try:
            from uda_tpu.utils.logging import get_logger
            get_logger().error(text)
        except Exception:  # noqa: BLE001 - the report must survive a
            print(text)    # half-imported logging module
        if self.emit_metrics:
            try:
                from uda_tpu.utils.metrics import metrics
                metrics.add("resledger.leaks", len(reports))
            except Exception as e:  # noqa: BLE001
                print(f"resledger: metrics unavailable: {e}")
            # a leak on the PROCESS-GLOBAL books is a black-box trigger
            # (private fixture ledgers seeding leaks on purpose stay
            # out — the emit_metrics flag is the global-instance mark):
            # dump the event stream that surrounded the unmatched
            # acquire, with the leak summary as the cause
            try:
                from uda_tpu.utils.flightrec import flightrec
                flightrec.dump("resledger_leak", extra={
                    "point": point, "leaks": len(reports),
                    "pairs": sorted({r["pair"] for r in reports})})
            except Exception as e:  # noqa: BLE001 - interpreter teardown
                print(f"resledger: flightrec unavailable: {e}")
        out = (os.environ.get("UDA_TPU_RESLEDGER_JSON")
               if self.emit_json else None)
        if out:
            try:
                with open(out, "a") as f:
                    for r in reports:
                        f.write(json.dumps(r) + "\n")
            except OSError as e:
                print(f"resledger: cannot append {out}: {e}")

    def reset(self) -> None:
        """Forget open obligations and past reports (tests)."""
        with self._mu:
            self._recs.clear()
            self._deficits.clear()
            self.leak_reports.clear()
            self._seq = 0


resledger = ResourceLedger(emit_metrics=True, emit_json=True)
