"""Online tuning cache: persisted per-(key-shape, platform, backend)
fly-off winners that make routing self-service.

The repo's tuned constants were all hand-deployed sweep results:
``UDA_TPU_SORT_PATH``/``UDA_TPU_CHUNK_COLS`` carry a fly-off winner to
every call site via the environment, and thresholds like
``SMALL_BATCH_ROWS`` or the ``CC_LADDER`` crossovers are literals from
one measured host. This module is the Exoshuffle posture applied to
that machinery (arXiv:2203.05072 — shuffle policy should adapt
per-workload, not be baked in): a small persisted winner table

- **written** by seeded fly-off probes (``scripts/tune_probe.py``,
  riding the bench_pipeline/net_bench harness pattern; any in-process
  probe can call :meth:`TuneCache.record` too),
- **consulted** by ``ops.sort.route_engine`` (engine choice per
  (backend, row-bucket, lanes-capability)) and by the batched host-I/O
  plane (``mofserver/data_engine.py``: batch on/off, coalesce gap,
  backend rung),
- **refreshed** by a background re-probe rung: entries older than
  ``uda.tpu.tune.reprobe.s`` are re-measured by a registered probe on
  a daemon thread (:func:`ensure_fresh`) or by
  ``tune_probe.py --reprobe-age``.

Precedence is strict and tested: **explicit env/config winner > cached
winner > built-in default**. A cold cache is byte-for-byte today's
defaults; a corrupt, truncated or version-bumped cache file is ignored
(counted ``tune.cache.invalid``), never fatal — losing the cache must
only ever cost performance, not a job.

File format (JSON, atomic tmp+rename writes)::

    {"schema": 1, "entries": {
        "<domain>|<key>": {"winner": {...}, "metric": <float|null>,
                           "probed_unix": <float>, "probe": "<name>"}}}

``domain`` names the consumer contract (``sort.engine``, ``io.read``);
``key`` encodes the shape/platform/backend coordinates the consumer
can cheaply reproduce at lookup time (e.g.
``cpu|rows20|lanes1``). ``winner`` is an opaque dict the consumer
validates — a cache can never force an invalid engine name or knob
value onto a caller (validation failures count as misses).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["TuneCache", "tune_cache", "cache_path_from_env",
           "register_probe", "ensure_fresh", "rows_bucket",
           "SCHEMA_VERSION"]

log = get_logger()

SCHEMA_VERSION = 1


def cache_path_from_env() -> str:
    """The process-default cache location: UDA_TPU_TUNE_CACHE (the
    ``uda.tpu.tune.cache.path`` config key wins where a Config is in
    hand — consumers pass the resolved path in). Empty = no cache."""
    return os.environ.get("UDA_TPU_TUNE_CACHE", "").strip()


def rows_bucket(n_rows: int) -> int:
    """Shape-class key for row counts: the power-of-two bucket
    (bit_length), so one probed winner covers its whole size class
    instead of one exact row count."""
    return max(0, int(n_rows)).bit_length()


class TuneCache:
    """One winner table bound to one file path (``path=''`` = a purely
    in-memory table: lookups miss until something records).

    Reads are cached per (path, mtime): route_engine sits on production
    sort surfaces, so a lookup is a dict access, not a file parse —
    the file is re-read only when another process replaced it."""

    def __init__(self, path: str = ""):
        self.path = path or ""
        self._mu = threading.Lock()
        self._entries: Dict[str, dict] = {}
        self._loaded_mtime: Optional[float] = None
        self._invalid_warned = False

    # -- persistence ---------------------------------------------------------

    def _load_locked(self) -> None:
        """Refresh the in-memory table from the file when it changed.
        Every failure mode — missing file, torn JSON, wrong schema,
        non-dict entries — degrades to an empty table (built-in
        defaults), counted once per observation, never raised."""
        if not self.path:
            return
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            self._entries = {}
            self._loaded_mtime = None
            return
        if mtime == self._loaded_mtime:
            return
        try:
            with open(self.path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict) \
                    or doc.get("schema") != SCHEMA_VERSION \
                    or not isinstance(doc.get("entries"), dict):
                raise ValueError(
                    f"schema {doc.get('schema') if isinstance(doc, dict) else '?'}"
                    f" != {SCHEMA_VERSION} or malformed shape")
            entries = {k: v for k, v in doc["entries"].items()
                       if isinstance(v, dict) and "winner" in v}
        except (OSError, ValueError) as e:
            metrics.add("tune.cache.invalid")
            if not self._invalid_warned:
                self._invalid_warned = True
                log.warn(f"tune cache {self.path} ignored ({e}); "
                         f"using built-in defaults")
            self._entries = {}
            self._loaded_mtime = mtime  # don't re-parse a bad file per lookup
            return
        self._entries = entries
        self._loaded_mtime = mtime

    def _save_locked(self) -> None:
        if not self.path:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"schema": SCHEMA_VERSION,
                           "entries": self._entries}, f, indent=1,
                          sort_keys=True)
                f.write("\n")
            os.replace(tmp, self.path)
            try:
                self._loaded_mtime = os.stat(self.path).st_mtime
            except OSError:
                self._loaded_mtime = None
            metrics.add("tune.cache.writes")
        except OSError as e:
            # a read-only dir / full disk must not fail the probe (or
            # the job that ran it): the winner just isn't persisted
            metrics.add("errors.swallowed")
            log.warn(f"tune cache {self.path} not persisted ({e})")
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # -- the consumer surface ------------------------------------------------

    def lookup(self, domain: str, key: str) -> Optional[dict]:
        """The persisted winner record for ``domain|key``, or None
        (cold cache / unreadable file / no such entry). Counts
        ``tune.cache.hits``/``tune.cache.misses`` labeled by domain —
        the lifecycle tests key on these."""
        with self._mu:
            self._load_locked()
            rec = self._entries.get(f"{domain}|{key}")
        if rec is None:
            metrics.add("tune.cache.misses", domain=domain)
            return None
        metrics.add("tune.cache.hits", domain=domain)
        return dict(rec)

    def record(self, domain: str, key: str, winner: dict,
               metric: Optional[float] = None,
               probe: str = "") -> None:
        """Persist one fly-off winner (atomic write; merges with the
        entries already on disk so concurrent probes of different
        domains don't clobber each other)."""
        rec = {"winner": dict(winner), "metric": metric,
               "probed_unix": time.time(), "probe": probe}
        with self._mu:
            self._load_locked()
            self._entries[f"{domain}|{key}"] = rec
            self._save_locked()

    def age_s(self, domain: str, key: str) -> Optional[float]:
        """Seconds since the entry was probed; None when absent (or
        the record carries no timestamp — treated as infinitely
        stale by re-probe consumers)."""
        with self._mu:
            self._load_locked()
            rec = self._entries.get(f"{domain}|{key}")
        if rec is None:
            return None
        probed = rec.get("probed_unix")
        if not isinstance(probed, (int, float)):
            return float("inf")
        return max(0.0, time.time() - float(probed))

    def entries(self) -> Dict[str, dict]:
        """Snapshot of the table (diagnostics / tune_probe --list)."""
        with self._mu:
            self._load_locked()
            return {k: dict(v) for k, v in self._entries.items()}


# The process-default cache (UDA_TPU_TUNE_CACHE): what config-less
# consumers (ops.sort.route_engine) consult. Consumers holding a
# Config with uda.tpu.tune.cache.path set read their own instance AND
# install the path as the process default via set_default_cache, so
# one explicitly-configured engine makes the whole process
# self-service — the env var always wins.
tune_cache = TuneCache(cache_path_from_env())


def set_default_cache(path: str) -> TuneCache:
    """Install ``path`` as the process-default cache — unless
    UDA_TPU_TUNE_CACHE is set (the env channel outranks config, like
    every deploy override). Called by DataEngine when
    ``uda.tpu.tune.cache.path`` is explicitly configured, so
    route_engine (which has no Config in scope) consults the same
    table. Returns the instance now serving the path (consumers that
    read the module attribute at call time pick it up immediately)."""
    global tune_cache
    if not path or cache_path_from_env():
        return tune_cache
    if path != tune_cache.path:
        tune_cache = TuneCache(path)
    return tune_cache


# -- background re-probe rung -------------------------------------------------
# A consumer that wants its winner tracked against hardware drift
# registers a probe callable; ensure_fresh() then re-measures a stale
# entry on a single daemon thread (at most one re-probe in flight per
# process — routing hot paths must never block on a fly-off).

_PROBES: Dict[str, Callable[[str], None]] = {}
_REPROBE_MU = threading.Lock()
_REPROBE_ACTIVE = False


def register_probe(domain: str, fn: Callable[[str], None]) -> None:
    """Register the re-probe implementation for ``domain``: called as
    ``fn(key)`` on the background thread; it should measure and
    ``record()`` the fresh winner."""
    _PROBES[domain] = fn


def ensure_fresh(cache: TuneCache, domain: str, key: str,
                 max_age_s: float) -> None:
    """Kick a background re-probe when the entry exists but is older
    than ``max_age_s`` (0/negative = never re-probe). Non-blocking;
    the CURRENT lookup keeps the stale winner — the refreshed one
    lands for later consumers (the fly-off generalized into an online
    autotuner, ROADMAP item 5)."""
    global _REPROBE_ACTIVE
    if max_age_s <= 0:
        return
    fn = _PROBES.get(domain)
    if fn is None:
        return
    age = cache.age_s(domain, key)
    if age is None or age <= max_age_s:
        return
    with _REPROBE_MU:
        if _REPROBE_ACTIVE:
            return
        _REPROBE_ACTIVE = True

    def _run() -> None:
        global _REPROBE_ACTIVE
        try:
            metrics.add("tune.reprobes")
            fn(key)
        except Exception as e:  # noqa: BLE001 - a failed re-probe must
            # never surface into the routing caller; the stale winner
            # keeps serving
            metrics.add("errors.swallowed")
            log.warn(f"tune re-probe of {domain}|{key} failed: {e}")
        finally:
            with _REPROBE_MU:
                _REPROBE_ACTIVE = False

    threading.Thread(target=_run, daemon=True,
                     name="uda-tune-reprobe").start()
