"""IFile record streams: Hadoop map-output segment format.

Byte-exact implementation of the record framing the reference reads and
writes (reference src/Merger/StreamRW.cc): each record is
``VInt(keyLen) VInt(valLen) key value``; end-of-stream is the marker pair
``(-1, -1)`` (two 0xFF bytes), detected by the reference's ``nextKV``
(StreamRW.cc:334-449) and appended by ``write_kv_to_stream``
(StreamRW.cc:151-225).

Two access styles:

- streaming reader/writer (``IFileReader``/``IFileWriter``) matching the
  reference's record-at-a-time iterators;
- bulk *columnar cracking* (``crack``): one pass converts a whole segment
  buffer into offset/length arrays over the raw bytes — the host-side
  preparation step for staging records into device-resident columns.
  This replaces the reference's per-record VInt parse in the merge hot
  loop with a single vectorizable pass (natively accelerated by
  uda_tpu/native when built).

Checksum note: Hadoop's IFile wraps streams in IFileOutputStream (CRC32
trailer). The reference's native merger consumes the *decompressed,
checksum-stripped* record stream handed over by the Java side, so the
framing here deliberately matches that inner stream, not the on-disk
CRC-wrapped one. An optional CRC32 trailer is supported for our own
spill files.
"""

from __future__ import annotations

import dataclasses
import io
import zlib
from typing import BinaryIO, Iterable, Iterator, Optional, Tuple

import numpy as np

from uda_tpu.utils import vint
from uda_tpu.utils.errors import StorageError

__all__ = ["IFileWriter", "IFileReader", "RecordBatch", "crack",
           "crack_partial", "iter_file_records", "write_records",
           "set_native_enabled", "native_enabled"]

EOF_MARKER = b"\xff\xff"  # VInt(-1) VInt(-1)

# native codec dispatch: the C++ library (uda_tpu/native) takes over the
# bulk scan for buffers past this size; the Python implementation below
# remains the semantic reference it is parity-tested against
_NATIVE_THRESHOLD = 4096
_native_enabled = True


def set_native_enabled(enabled: bool) -> None:
    """Toggle the native codec (the ``uda.tpu.use.native`` flag's hook)."""
    global _native_enabled
    _native_enabled = enabled


def native_enabled() -> bool:
    """Whether native dispatch is allowed (the kill switch state; says
    nothing about whether the library is built)."""
    return _native_enabled


def _native_mod():
    if not _native_enabled:
        return None
    try:
        from uda_tpu import native
    except ImportError:
        return None
    return native if native.available() else None


class IFileWriter:
    """Sequential record writer with EOF marker on close.

    Mirrors ``write_kv_to_stream`` framing (reference StreamRW.cc:151-225).
    """

    def __init__(self, out: BinaryIO, with_crc: bool = False):
        self._out = out
        self._crc = zlib.crc32(b"") if with_crc else None
        self.records = 0
        self.bytes_written = 0
        self._closed = False

    def append(self, key: bytes, value: bytes) -> None:
        rec = (vint.encode_vlong(len(key)) + vint.encode_vlong(len(value))
               + key + value)
        self._out.write(rec)
        if self._crc is not None:
            self._crc = zlib.crc32(rec, self._crc)
        self.records += 1
        self.bytes_written += len(rec)

    def close(self) -> None:
        if self._closed:
            return
        self._out.write(EOF_MARKER)
        self.bytes_written += len(EOF_MARKER)
        if self._crc is not None:
            self._crc = zlib.crc32(EOF_MARKER, self._crc)
            self._out.write(self._crc.to_bytes(4, "big"))
            self.bytes_written += 4
        self._closed = True

    def __enter__(self) -> "IFileWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class IFileReader:
    """Record-at-a-time reader (reference BaseSegment::nextKV semantics,
    StreamRW.cc:334-449): yields (key, value) until the EOF marker."""

    def __init__(self, src: BinaryIO):
        self._buf = src.read()
        self._pos = 0

    def __iter__(self) -> Iterator[Tuple[bytes, bytes]]:
        buf = self._buf
        pos = self._pos
        while True:
            try:
                klen, pos = vint.decode_vlong(buf, pos)
                vlen, pos = vint.decode_vlong(buf, pos)
            except IndexError as e:
                raise StorageError(f"truncated IFile stream at offset {pos}: {e}") from e
            if klen == -1 and vlen == -1:
                return
            if klen < 0 or vlen < 0:
                raise StorageError(f"corrupt IFile record lengths {klen}/{vlen}")
            key = buf[pos:pos + klen]
            pos += klen
            val = buf[pos:pos + vlen]
            pos += vlen
            if len(key) != klen or len(val) != vlen:
                raise StorageError("truncated IFile record")
            yield bytes(key), bytes(val)


@dataclasses.dataclass
class RecordBatch:
    """Columnar view of one segment: raw bytes + per-record offsets.

    ``data`` holds the segment bytes; keys/values are addressed by
    (offset, length) int64 arrays. This is the host-side currency between
    the supplier, the staging arena and the device packing step.
    """

    data: np.ndarray        # uint8, the full segment buffer (records are
                            # addressed by offset; any EOF marker / CRC
                            # trailer bytes at the tail are never addressed)
    key_off: np.ndarray     # int64 [n]
    key_len: np.ndarray     # int64 [n]
    val_off: np.ndarray     # int64 [n]
    val_len: np.ndarray     # int64 [n]

    @property
    def num_records(self) -> int:
        return int(self.key_off.shape[0])

    def key(self, i: int) -> bytes:
        o, n = int(self.key_off[i]), int(self.key_len[i])
        return self.data[o:o + n].tobytes()

    def value(self, i: int) -> bytes:
        o, n = int(self.val_off[i]), int(self.val_len[i])
        return self.data[o:o + n].tobytes()

    def iter_records(self) -> Iterator[Tuple[bytes, bytes]]:
        for i in range(self.num_records):
            yield self.key(i), self.value(i)

    def take(self, order: np.ndarray) -> "RecordBatch":
        """Reorder records (used to materialize a device-computed sort
        permutation back into record order)."""
        return RecordBatch(self.data, self.key_off[order], self.key_len[order],
                           self.val_off[order], self.val_len[order])

    @staticmethod
    def concat(batches: list["RecordBatch"]) -> "RecordBatch":
        """Concatenate batches into one (rebases offsets into one buffer)."""
        if not batches:
            return RecordBatch(np.zeros(0, np.uint8), *([np.zeros(0, np.int64)] * 4))
        datas, kos, kls, vos, vls = [], [], [], [], []
        base = 0
        for b in batches:
            datas.append(b.data)
            kos.append(b.key_off + base)
            kls.append(b.key_len)
            vos.append(b.val_off + base)
            vls.append(b.val_len)
            base += len(b.data)
        return RecordBatch(np.concatenate(datas), np.concatenate(kos),
                           np.concatenate(kls), np.concatenate(vos),
                           np.concatenate(vls))


def crack(buf: bytes | np.ndarray, expect_eof: bool = True,
          verify_crc: bool = False) -> RecordBatch:
    """One-pass columnar crack of an IFile segment buffer.

    Replaces per-record parsing in the merge hot loop (reference
    StreamRW.cc:334-449) with a single host pass producing offset/length
    columns. With ``verify_crc`` the 4 bytes after the EOF marker are
    checked as a big-endian CRC32 of everything before them (the trailer
    ``IFileWriter(with_crc=True)`` writes). The native library
    (uda_tpu.native.lib) overrides this with a C++ implementation when
    available; this is the pure-Python reference.
    """
    arr = np.frombuffer(buf, dtype=np.uint8) if not isinstance(buf, np.ndarray) else buf
    nat = _native_mod() if len(arr) >= _NATIVE_THRESHOLD else None
    if nat is not None:
        batch, consumed, saw = nat.crack_partial_native(arr)
        if expect_eof and not saw:
            raise StorageError("IFile segment missing EOF marker")
        if not saw and consumed != len(arr):
            raise StorageError(f"truncated IFile segment at offset {consumed}")
        if verify_crc:
            _check_crc_trailer(arr, consumed, saw)
        return batch
    mem = memoryview(arr)
    n = len(arr)
    key_off, key_len, val_off, val_len = [], [], [], []
    pos = 0
    saw_eof = False
    while pos < n:
        try:
            klen, p = vint.decode_vlong(mem, pos)
            vlen, p = vint.decode_vlong(mem, p)
        except IndexError as e:
            raise StorageError(f"truncated IFile segment at offset {pos}: {e}") from e
        if klen == -1 and vlen == -1:
            saw_eof = True
            pos = p
            break
        if klen < 0 or vlen < 0 or p + klen + vlen > n:
            raise StorageError(f"corrupt IFile segment at offset {pos}")
        key_off.append(p)
        key_len.append(klen)
        val_off.append(p + klen)
        val_len.append(vlen)
        pos = p + klen + vlen
    if expect_eof and not saw_eof:
        raise StorageError("IFile segment missing EOF marker")
    if verify_crc:
        _check_crc_trailer(arr, pos, saw_eof)
    return RecordBatch(
        arr,
        np.asarray(key_off, dtype=np.int64),
        np.asarray(key_len, dtype=np.int64),
        np.asarray(val_off, dtype=np.int64),
        np.asarray(val_len, dtype=np.int64),
    )


def _check_crc_trailer(arr: np.ndarray, pos: int, saw_eof: bool) -> None:
    """Verify the 4-byte big-endian CRC32 trailer after the EOF marker."""
    n = len(arr)
    if not saw_eof or pos + 4 > n:
        raise StorageError("IFile segment missing CRC trailer")
    mem = memoryview(arr)
    want = int.from_bytes(mem[pos:pos + 4], "big")
    got = zlib.crc32(mem[:pos])
    if want != got:
        raise StorageError(f"IFile CRC mismatch: trailer {want:#010x}, "
                           f"computed {got:#010x}")


def crack_partial(data: bytes, expect_eof: bool = False
                  ) -> Tuple[RecordBatch, int, bool]:
    """Crack the longest prefix of complete records; returns ``(batch,
    bytes_consumed, saw_eof)``.

    The incremental sibling of ``crack`` for chunked streams: a record
    split across a chunk boundary is left unconsumed so the caller can
    carry its bytes into the next chunk (the reference's temp_kv join
    across buffers, StreamRW.cc:542-590). With ``expect_eof`` the buffer
    must be a complete segment and everything is consumed.
    """
    if expect_eof:
        batch = crack(data, expect_eof=True)
        return batch, len(data), True
    arr = np.frombuffer(data, np.uint8) if not isinstance(data, np.ndarray) else data
    nat = _native_mod() if len(arr) >= _NATIVE_THRESHOLD else None
    if nat is not None:
        return nat.crack_partial_native(arr)
    mem = memoryview(arr)
    n = len(arr)
    key_off, key_len, val_off, val_len = [], [], [], []
    pos = 0
    saw_eof = False
    while pos < n:
        start = pos
        try:
            klen, p = vint.decode_vlong(mem, pos)
            vlen, p = vint.decode_vlong(mem, p)
        except IndexError:
            pos = start
            break
        if klen == -1 and vlen == -1:
            pos = p
            saw_eof = True
            break
        if klen < 0 or vlen < 0:
            raise StorageError(f"corrupt record framing at offset {start}")
        if p + klen + vlen > n:
            pos = start
            break
        key_off.append(p)
        key_len.append(klen)
        val_off.append(p + klen)
        val_len.append(vlen)
        pos = p + klen + vlen
    batch = RecordBatch(
        arr,
        np.asarray(key_off, dtype=np.int64),
        np.asarray(key_len, dtype=np.int64),
        np.asarray(val_off, dtype=np.int64),
        np.asarray(val_len, dtype=np.int64),
    )
    return batch, pos, saw_eof


def iter_file_records(path: str, buffer_size: int = 1 << 20
                      ) -> Iterator[Tuple[bytes, bytes]]:
    """Stream records from an IFile on disk with bounded memory.

    Reads ``buffer_size`` chunks, cracks complete records, carries the
    partial tail — the file-backed analogue of the reference's
    SuperSegment cursor (StreamRW.cc:813-861), used by the RPQ phase so
    spill files never need to be memory-resident.
    """
    carry = b""
    with open(path, "rb") as f:
        while True:
            chunk = f.read(buffer_size)
            if not chunk:
                raise StorageError(f"IFile {path} missing EOF marker")
            data = carry + chunk
            batch, consumed, saw_eof = crack_partial(data)
            for i in range(batch.num_records):
                yield batch.key(i), batch.value(i)
            if saw_eof:
                return
            carry = data[consumed:]


def write_records(records: Iterable[Tuple[bytes, bytes]],
                  out: Optional[BinaryIO] = None) -> bytes:
    """Serialize records into IFile framing; returns the bytes when no
    stream is given."""
    own = out is None
    stream = out or io.BytesIO()
    w = IFileWriter(stream)
    for k, v in records:
        w.append(k, v)
    w.close()
    return stream.getvalue() if own else b""
