"""Retry policy for the fetch path: backoff, attempt timeout, deadline.

The reference hard-coded its recovery numbers (connect dance retried 5x,
RDMAClient.cc:41; RNR retry 7, RDMAComm.h:29) and waited forever on a
stuck supplier. Here the same decisions are one declarative object,
built from ``mapred.rdma.fetch.*`` config knobs and applied by
``uda_tpu.merger.segment.Segment`` at the InputClient.start_fetch
boundary:

- ``retries``: whole-segment re-fetch attempts after a transport error
  (``uda.tpu.fetch.retries``, the pre-existing knob);
- ``backoff_ms``/``backoff_max_ms``/``jitter``: exponential backoff
  between attempts, doubling from the base and capped, with a
  symmetric +/-``jitter`` fraction so a burst of failed segments does
  not re-issue in lockstep (0 base = immediate retry, the seed
  behavior);
- ``attempt_timeout_ms``: per-attempt chunk fetch timeout — a fetch the
  transport never completes is failed and retried instead of wedging
  the merge (0 = wait forever);
- ``deadline_ms``: overall per-segment budget across every retry and
  backoff; once passed, the segment fails with the last transport error
  even if retries remain (0 = none).

Defaults keep every knob off, so a default-config engine behaves
exactly like the seed: N immediate retries, no timers.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional

__all__ = ["RetryPolicy", "SpeculationPolicy"]


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    retries: int = 3
    backoff_ms: float = 0.0
    backoff_max_ms: float = 2000.0
    jitter: float = 0.2
    attempt_timeout_ms: float = 0.0
    deadline_ms: float = 0.0
    seed: Optional[int] = None

    def backoff(self, attempt: int,
                rng: Optional[random.Random] = None) -> float:
        """Seconds to wait before retry ``attempt`` (1-based):
        ``backoff_ms * 2^(attempt-1)`` capped at ``backoff_max_ms``,
        then jittered by a uniform +/-``jitter`` fraction from ``rng``
        (deterministic for a seeded rng)."""
        if self.backoff_ms <= 0:
            return 0.0
        base = min(self.backoff_ms * (2.0 ** max(0, attempt - 1)),
                   self.backoff_max_ms)
        if self.jitter and rng is not None:
            base *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, base) / 1000.0

    @classmethod
    def from_config(cls, cfg) -> "RetryPolicy":
        return cls(
            retries=max(0, cfg.get("uda.tpu.fetch.retries")),
            backoff_ms=float(cfg.get("mapred.rdma.fetch.retry.backoff.ms")),
            backoff_max_ms=float(
                cfg.get("mapred.rdma.fetch.retry.backoff.max.ms")),
            jitter=float(cfg.get("mapred.rdma.fetch.retry.jitter")),
            attempt_timeout_ms=float(
                cfg.get("mapred.rdma.fetch.attempt.timeout.ms")),
            deadline_ms=float(cfg.get("mapred.rdma.fetch.deadline.ms")),
        )


@dataclasses.dataclass(frozen=True)
class SpeculationPolicy:
    """The straggler detector's knobs (speculative dual-source fetch,
    uda_tpu.merger.segment): an in-flight chunk fetch that outlives
    ``max(floor_ms, pN of the observed fetch.latency_ms histogram)``
    gets a duplicate issued to an alternate source. ``pn == 0`` (the
    default) disables speculation; with stats off (no histogram) the
    floor alone is the threshold."""

    pn: int = 0           # latency percentile (e.g. 95); 0 = off
    floor_ms: float = 50.0

    @property
    def enabled(self) -> bool:
        return self.pn > 0

    def threshold_ms(self) -> float:
        from uda_tpu.utils.metrics import metrics

        q = metrics.percentile("fetch.latency_ms", float(self.pn))
        return max(self.floor_ms, q or 0.0)

    @classmethod
    def from_config(cls, cfg) -> "SpeculationPolicy":
        return cls(
            pn=max(0, min(100, int(cfg.get("uda.tpu.fetch.speculate.pn")))),
            floor_ms=max(0.0, float(
                cfg.get("uda.tpu.fetch.speculate.floor.ms"))),
        )
