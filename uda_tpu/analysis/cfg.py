"""Per-function control-flow graphs for the udaflow dataflow tier.

udalint's original rules (UDA001-UDA008) are *syntactic*: one node, one
verdict. The leak class that cost three consecutive PRs a review round
— a resource charged on one path and never released on an exception or
early-return path (PR 6's ``try_plan`` admission-byte leak, the PR 5
cancel-while-queued leak, PR 9's ``feed()``/``abort()`` race) — is a
*path* property: the bug is not any single statement but the existence
of a route from the acquire to function exit that skips the release.
This module builds the graph those rules reason over.

Shape of the graph
------------------

One :class:`CFG` per function (``FunctionDef`` / ``AsyncFunctionDef``;
nested defs are opaque single statements of the enclosing function —
deferred code runs on its own CFG). Nodes are statement *headers*: a
compound statement contributes one node carrying only its header
expressions (``if``/``while`` tests, ``for`` iterables, ``with`` items)
— bodies become their own nodes — so a node's effect set never double
counts a nested statement. Two synthetic terminals:

- ``EXIT`` — normal completion (fall off the end, ``return``);
- ``RAISE`` — exceptional exit (an uncaught exception propagates).

Edges:

- **normal**: statement order, branch arms, loop back-edges,
  ``break``/``continue`` to their loop targets;
- **exception**: any node that *can raise* (it contains a ``Call``, is
  a ``raise``/``assert``, or is a ``with`` header — ``__enter__`` runs
  there) gets an edge to the innermost enclosing handler dispatch, or
  to ``RAISE`` when none encloses it. Handler dispatch fans out to
  every ``except`` body and, unless some handler is broad (bare /
  ``Exception`` / ``BaseException``), onward to the next outer target
  (the not-caught-here path);
- **finally routing**: ``finally`` bodies are *copied per
  continuation* — the normal path, the exception path and each
  ``return``/``break``/``continue`` that crosses the ``try`` get their
  own copy of the finally subgraph wired to their own continuation, so
  "the release lives in a finally" is visible as "every path to EXIT
  passes a release node" without merging normal and exceptional
  contexts (a single shared finally block would manufacture paths that
  do not exist, e.g. normal completion -> exceptional exit).

``with`` headers do not suppress exceptions (true for every context
manager in this tree — locks, scoped failpoints, spans); body
exceptions propagate past them to the enclosing target.

The graph is deliberately an over-approximation in one direction only:
it may contain a path the program cannot take (any call "can" raise),
never the reverse — so a dataflow verdict of "no path leaks" is sound,
and a finding is a path the runtime *could* plausibly walk.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple

__all__ = ["CFG", "CFGNode", "build_cfg"]

# broad handler type names: a `try` with one of these catches everything
# we model (the graph drops the propagate edge past it)
_BROAD = {"Exception", "BaseException"}

# 3.12 `type X = ...` statements (absent on 3.10/3.11 — gate, don't touch)
_TYPE_ALIAS = getattr(ast, "TypeAlias", None)


@dataclasses.dataclass
class CFGNode:
    """One CFG node: a statement header (or synthetic terminal).

    ``exprs`` holds exactly the AST fragments evaluated *at this node*
    (a compound statement's bodies live in their own nodes); effect
    extraction (acquire/release matching) scans these and nothing else.
    ``kind`` tags synthetics ("exit", "raise") and headers ("with",
    "return", ...) the analysis treats specially.
    """

    index: int
    kind: str                      # "stmt" | "with" | "return" | "exit" | ...
    stmt: Optional[ast.AST]        # the owning statement (None: synthetic)
    exprs: Tuple[ast.AST, ...]     # fragments evaluated at this node
    # normal-completion vs exception successors are SEPARATE: a
    # dataflow client must know which state leaves on which edge (an
    # acquire that raises did not acquire — its own exception edge
    # carries the pre-acquire state)
    norm_succs: List[int] = dataclasses.field(default_factory=list)
    exc_succs: List[int] = dataclasses.field(default_factory=list)

    def add(self, target: int, exc: bool = False) -> None:
        lst = self.exc_succs if exc else self.norm_succs
        if target not in lst:
            lst.append(target)

    @property
    def succs(self) -> List[int]:
        """All successors (normal first), deduplicated."""
        out = list(self.norm_succs)
        out.extend(t for t in self.exc_succs if t not in out)
        return out

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)


class CFG:
    """The per-function graph: ``nodes[entry]`` starts the body,
    ``nodes[exit_id]`` / ``nodes[raise_id]`` are the two terminals."""

    def __init__(self, func: ast.AST):
        self.func = func
        self.nodes: List[CFGNode] = []
        self.exit_id = self._new("exit", None, ())
        self.raise_id = self._new("raise", None, ())
        self.entry = self.exit_id  # replaced by build()

    def _new(self, kind: str, stmt: Optional[ast.AST],
             exprs: Tuple[ast.AST, ...]) -> int:
        node = CFGNode(len(self.nodes), kind, stmt, tuple(exprs))
        self.nodes.append(node)
        return node.index

    def node(self, idx: int) -> CFGNode:
        return self.nodes[idx]

    def preds(self) -> Dict[int, List[Tuple[int, bool]]]:
        """target -> [(pred index, is_exception_edge), ...]."""
        out: Dict[int, List[Tuple[int, bool]]] = {
            n.index: [] for n in self.nodes}
        for n in self.nodes:
            for s in n.norm_succs:
                out[s].append((n.index, False))
            for s in n.exc_succs:
                out[s].append((n.index, True))
        return out

    # -- debug/tests ---------------------------------------------------------

    def render(self) -> str:
        lines = []
        for n in self.nodes:
            label = n.kind
            if n.stmt is not None:
                label += f"@{n.line}"
            succ = ",".join([str(s) for s in n.norm_succs]
                            + [f"{s}!" for s in n.exc_succs])
            lines.append(f"{n.index}:{label} -> [{succ}]")
        return "\n".join(lines)


# Callees whose failure modes the graph does NOT model: observability
# (metrics counters/gauges are dict writes under a leaf lock; loggers
# absorb their own failures) and the infallible release wrappers of the
# obligation registry (settle-then-nothing bodies). Without this set,
# every `metrics.add` between an acquire and its release manufactures a
# cleanup-code-raised leak path — the classic false-positive source of
# path checkers. Extendable per-build via ``build_cfg(no_raise=...)``.
DEFAULT_NO_RAISE = frozenset({
    # metrics hub
    "add", "gauge", "gauge_add", "observe",
    # loggers / stdout
    "debug", "info", "warn", "warning", "error", "exception", "print",
    # infallible releases (pair-registry release wrappers + primitives)
    "release", "_unadmit", "_release_charge", "close_hard",
    "notify", "notify_all", "append",
})


def _can_raise(exprs: Tuple[ast.AST, ...],
               no_raise: frozenset = DEFAULT_NO_RAISE) -> bool:
    """Conservative can-this-node-raise: it evaluates a call (or is an
    explicit raise/assert — handled by the builder). Attribute access
    and arithmetic are deliberately not counted: in this tree they do
    not fail in practice, and counting them would manufacture leak
    paths out of every statement. Calls whose callee's last segment is
    in ``no_raise`` are likewise exempt (see DEFAULT_NO_RAISE)."""
    for e in exprs:
        for sub in ast.walk(e):
            if isinstance(sub, ast.Call):
                func = sub.func
                name = None
                if isinstance(func, ast.Attribute):
                    name = func.attr
                elif isinstance(func, ast.Name):
                    name = func.id
                if name not in no_raise:
                    return True
    return False


class _Ctx:
    """Where non-local control transfers go from the current position:
    raise -> ``exc``, return -> ``ret``, break/continue -> ``brk`` /
    ``cont`` (None outside a loop). try/finally rebinds all four
    through finally copies."""

    __slots__ = ("exc", "ret", "brk", "cont")

    def __init__(self, exc: int, ret: int, brk: Optional[int],
                 cont: Optional[int]):
        self.exc = exc
        self.ret = ret
        self.brk = brk
        self.cont = cont

    def replace(self, **kw) -> "_Ctx":
        new = _Ctx(self.exc, self.ret, self.brk, self.cont)
        for k, v in kw.items():
            setattr(new, k, v)
        return new


class _Builder:
    def __init__(self, cfg: CFG):
        self.cfg = cfg

    # Each _build_* returns the ENTRY node id of the construct, wired so
    # that normal completion continues at `nxt`.

    def build_block(self, stmts: List[ast.stmt], nxt: int,
                    ctx: _Ctx) -> int:
        entry = nxt
        for stmt in reversed(stmts):
            entry = self.build_stmt(stmt, entry, ctx)
        return entry

    def build_stmt(self, stmt: ast.stmt, nxt: int, ctx: _Ctx) -> int:
        cfg = self.cfg
        if isinstance(stmt, ast.Return):
            exprs = (stmt.value,) if stmt.value is not None else ()
            idx = cfg._new("return", stmt, exprs)
            node = cfg.node(idx)
            node.add(ctx.ret)
            if _can_raise(exprs):
                node.add(ctx.exc, exc=True)
            return idx
        if isinstance(stmt, ast.Raise):
            exprs = tuple(e for e in (stmt.exc, stmt.cause)
                          if e is not None)
            idx = cfg._new("raise_stmt", stmt, exprs)
            cfg.node(idx).add(ctx.exc, exc=True)
            return idx
        if isinstance(stmt, ast.Break):
            idx = cfg._new("break", stmt, ())
            cfg.node(idx).add(ctx.brk if ctx.brk is not None else nxt)
            return idx
        if isinstance(stmt, ast.Continue):
            idx = cfg._new("continue", stmt, ())
            cfg.node(idx).add(ctx.cont if ctx.cont is not None else nxt)
            return idx
        if isinstance(stmt, ast.If):
            body = self.build_block(stmt.body, nxt, ctx)
            orelse = self.build_block(stmt.orelse, nxt, ctx) \
                if stmt.orelse else nxt
            idx = cfg._new("if", stmt, (stmt.test,))
            node = cfg.node(idx)
            node.add(body)
            node.add(orelse)
            if _can_raise((stmt.test,)):
                node.add(ctx.exc, exc=True)
            return idx
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._build_loop(stmt, nxt, ctx)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._build_with(stmt, nxt, ctx)
        if isinstance(stmt, ast.Try):
            return self._build_try(stmt, nxt, ctx)
        if isinstance(stmt, ast.Assert):
            # a failing assert raises; the test itself may call
            exprs = tuple(e for e in (stmt.test, stmt.msg) if e is not None)
            idx = cfg._new("assert", stmt, exprs)
            node = cfg.node(idx)
            node.add(nxt)
            node.add(ctx.exc, exc=True)
            return idx
        if isinstance(stmt, ast.Match):
            return self._build_match(stmt, nxt, ctx)
        if _TYPE_ALIAS is not None and isinstance(stmt, _TYPE_ALIAS):
            # 3.12 `type X = ...`: the value is lazily evaluated, so
            # the statement itself cannot raise — a plain no-effect node
            idx = cfg._new("stmt", stmt, ())
            cfg.node(idx).add(nxt)
            return idx
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # nested defs are opaque: their bodies run later (or never),
            # on their own CFG; only decorators/defaults evaluate here
            exprs = tuple(stmt.decorator_list)
            idx = cfg._new("def", stmt, exprs)
            node = cfg.node(idx)
            node.add(nxt)
            if _can_raise(exprs):
                node.add(ctx.exc, exc=True)
            return idx
        # simple statement: Expr/Assign/AugAssign/AnnAssign/Delete/
        # Global/Import/Pass/...
        idx = cfg._new("stmt", stmt, (stmt,))
        node = cfg.node(idx)
        node.add(nxt)
        if _can_raise((stmt,)):
            node.add(ctx.exc, exc=True)
        return idx

    def _build_loop(self, stmt, nxt: int, ctx: _Ctx) -> int:
        cfg = self.cfg
        if isinstance(stmt, ast.While):
            exprs: Tuple[ast.AST, ...] = (stmt.test,)
        else:
            exprs = (stmt.target, stmt.iter)
        header = cfg._new("loop", stmt, exprs)
        after = self.build_block(stmt.orelse, nxt, ctx) \
            if stmt.orelse else nxt
        body_ctx = ctx.replace(brk=nxt, cont=header)
        body = self.build_block(stmt.body, header, body_ctx)
        node = cfg.node(header)
        node.add(body)
        node.add(after)
        if _can_raise(exprs):
            node.add(ctx.exc, exc=True)
        return header

    def _build_match(self, stmt: "ast.Match", nxt: int, ctx: _Ctx) -> int:
        """3.10+ ``match``: one header node evaluates the subject and
        every case guard; each case body is its own subgraph (so a
        ``return``/``raise`` inside a case is a real exit, not a
        swallowed side effect of one opaque mega-node). The header
        keeps a fall-through edge to ``nxt`` — the statement is not
        required to be exhaustive — which over-approximates only in
        the sound direction (paths that may not exist, never fewer)."""
        cfg = self.cfg
        exprs: Tuple[ast.AST, ...] = (stmt.subject,) + tuple(
            c.guard for c in stmt.cases if c.guard is not None)
        idx = cfg._new("match", stmt, exprs)
        node = cfg.node(idx)
        for case in stmt.cases:
            node.add(self.build_block(case.body, nxt, ctx))
        node.add(nxt)  # no case matched
        if _can_raise(exprs):
            node.add(ctx.exc, exc=True)
        return idx

    def _build_with(self, stmt, nxt: int, ctx: _Ctx) -> int:
        cfg = self.cfg
        # one header node evaluates every item's context expression
        # (__enter__ runs here and can raise BEFORE the body is
        # guarded); the body's own exceptions propagate to the same
        # enclosing target — our context managers never suppress
        exprs = tuple(item.context_expr for item in stmt.items)
        idx = cfg._new("with", stmt, exprs)
        body = self.build_block(stmt.body, nxt, ctx)
        node = cfg.node(idx)
        node.add(body)
        node.add(ctx.exc, exc=True)  # __enter__ may raise
        return idx

    def _build_try(self, stmt: ast.Try, nxt: int, ctx: _Ctx) -> int:
        cfg = self.cfg
        if stmt.finalbody:
            # route EVERY way out of the try through its own copy of
            # the finally body (see module docstring); cache one copy
            # per distinct continuation
            copies: Dict[Tuple[int, bool], int] = {}

            def through_finally(cont: int, exceptional: bool = False) -> int:
                key = (cont, exceptional)
                if key not in copies:
                    # the finally body itself runs under the OUTER
                    # context (its own raise replaces the in-flight one)
                    copies[key] = self.build_block(
                        list(stmt.finalbody), cont, ctx)
                return copies[key]

            inner_ctx = ctx.replace(
                exc=through_finally(ctx.exc, exceptional=True),
                ret=through_finally(ctx.ret))
            if ctx.brk is not None:
                inner_ctx = inner_ctx.replace(
                    brk=through_finally(ctx.brk))
            if ctx.cont is not None:
                inner_ctx = inner_ctx.replace(
                    cont=through_finally(ctx.cont))
            inner_nxt = through_finally(nxt)
            return self._build_try_core(stmt, inner_nxt, inner_ctx)
        return self._build_try_core(stmt, nxt, ctx)

    def _build_try_core(self, stmt: ast.Try, nxt: int, ctx: _Ctx) -> int:
        """The handlers half (callers have already wrapped ``nxt``/
        ``ctx`` in finally routing when a finalbody exists)."""
        cfg = self.cfg
        if not stmt.handlers:
            body_entry = self.build_block(
                stmt.body + list(stmt.orelse), nxt, ctx)
            return body_entry
        dispatch = cfg._new("except_dispatch", stmt, ())
        broad = False
        for handler in stmt.handlers:
            t = handler.type
            if t is None:
                broad = True
            elif isinstance(t, ast.Name) and t.id in _BROAD:
                broad = True
            elif isinstance(t, ast.Tuple) and any(
                    isinstance(e, ast.Name) and e.id in _BROAD
                    for e in t.elts):
                broad = True
            h_entry = self.build_block(handler.body, nxt, ctx)
            cfg.node(dispatch).add(h_entry)
        if not broad:
            # no handler is broad: the exception may not match any and
            # keeps propagating
            cfg.node(dispatch).add(ctx.exc, exc=True)
        body_ctx = ctx.replace(exc=dispatch)
        orelse_entry = self.build_block(stmt.orelse, nxt, ctx) \
            if stmt.orelse else nxt
        return self.build_block(stmt.body, orelse_entry, body_ctx)


def build_cfg(func: ast.AST) -> CFG:
    """Build the CFG of one ``FunctionDef`` / ``AsyncFunctionDef`` (or
    any object with a ``body`` list of statements)."""
    cfg = CFG(func)
    ctx = _Ctx(exc=cfg.raise_id, ret=cfg.exit_id, brk=None, cont=None)
    cfg.entry = _Builder(cfg).build_block(list(func.body),
                                          cfg.exit_id, ctx)
    return cfg
