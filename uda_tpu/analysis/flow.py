"""udaflow: the dataflow rule tier (UDA101-UDA103).

udalint's first eight rules are per-statement; the bug class that kept
resurfacing in review (PR 6's ``try_plan`` admission-byte leak, the
PR 5 cancel-while-queued leak, PR 9's stranded ``stage.inflight.bytes``)
is a *path* property: a resource acquired on one path and never released
on an exception/early-exit path. This module makes balance a
machine-checked property over :mod:`uda_tpu.analysis.cfg` graphs:

====== ==============================================================
UDA101 resource-balance: an acquire (per the obligation-pair registry,
       :data:`DEFAULT_PAIRS`) from which some CFG path — exception
       edges included — reaches function exit without the paired
       release, a declared transfer, or a ``with`` guard
UDA102 transitive blocking: an unbounded blocking call reached through
       a *helper function* inside ``with <lock>:`` (the hop that
       defeats UDA007) or inside an ``@loop_callback`` body (the hop
       that defeats UDA008), via a lightweight intra-package call
       graph resolved by function name
UDA103 static lock order: ``with``-nesting pairs of TrackedLock/
       TrackedCondition *classes* collected tree-wide must form an
       acyclic order graph — the compile-time complement of the
       runtime lockdep validator (uda_tpu/utils/locks.py)
====== ==============================================================

The obligation model (UDA101)
-----------------------------

Obligations come from a declared acquire->release pair registry — the
same inventory the runtime :class:`~uda_tpu.utils.resledger
.ResourceLedger` arms. Three pair kinds:

- **method pairs**: ``acquire``/``release``/``transfer`` callee names
  (optionally receiver-filtered), e.g. DataEngine ``_admit_bytes`` /
  ``_unadmit`` with the charge transferable into an FdSlice;
- **gauge pairs**: ``metrics.gauge_add(<name>, +d)`` opens and
  ``gauge_add(<name>, -d)`` closes an obligation for the registered
  paired gauges (``fetch.on_air``, ``stage.inflight.bytes``, ...);
- **context pairs**: calls that return a context manager and are only
  balanced when entered (``failpoints.scoped``) — using one outside a
  ``with`` item (or ``enter_context``) is itself the finding.

A forward worklist ("may be open") analysis propagates the set of open
acquire sites; any site still open at a terminal is reported at its
acquire line. Settling events: the paired release, a declared transfer
call, a ``with`` guard (the acquire *is* a context expression), or a
``return`` of a non-constant value — the obligation may ride the
returned object to the caller (the FdSlice/BufferSlot/charge-int
hand-off idiom), so escaping values are the caller's problem, exactly
like the runtime ledger holds whoever ends up with the handle
responsible. What can NEVER settle silently is an exception edge: that
is the historical leak shape, and the rule exists for it.

All three rules keep the engine contract: constructor-injectable
registries for fixtures, findings on the line the developer must fix,
suppressions via ``# udalint: disable=...`` with a justification.
UDA102/UDA103 are tree-wide (they accumulate per-file state and report
from ``finalize()`` after the last file).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from uda_tpu.analysis.cfg import CFG, build_cfg
from uda_tpu.analysis.core import FileContext, Finding, Rule

__all__ = ["ObligationPair", "DEFAULT_PAIRS", "ResourceBalanceRule",
           "TransitiveBlockingRule", "StaticLockOrderRule"]


def _last_segment(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _call_has_timeout(call: ast.Call) -> bool:
    if call.args:
        return True
    return any(kw.arg == "timeout" for kw in call.keywords)


# -- the obligation-pair registry --------------------------------------------


@dataclasses.dataclass(frozen=True)
class ObligationPair:
    """One declared acquire->release discipline.

    ``kind``: "method" (call-name pair), "gauge" (paired gauge_add
    increments), or "context" (must be entered via ``with``).
    ``recv`` is an optional regex the receiver's last segment must
    match (keeps generic names like ``lease``/``acquire`` scoped to
    the objects that own the discipline). ``transfer`` names calls
    that take the obligation over (ownership hand-off, e.g. the pool
    submit that carries an admission charge to the worker's finally).
    """

    pair_id: str
    kind: str = "method"
    acquire: Tuple[str, ...] = ()
    release: Tuple[str, ...] = ()
    transfer: Tuple[str, ...] = ()
    recv: str = ""                 # regex on the receiver's last segment
    gauge: str = ""                # gauge name (kind == "gauge")
    description: str = ""

    def recv_ok(self, call: ast.Call) -> bool:
        if not self.recv:
            return True
        func = call.func
        if not isinstance(func, ast.Attribute):
            return False
        seg = _last_segment(func.value)
        return seg is not None and re.fullmatch(self.recv, seg) is not None


# The live registry: every runtime discipline the ResourceLedger arms
# (uda_tpu/utils/resledger.py) has its static mirror here — the two
# inventories are kept in lockstep deliberately (README table).
DEFAULT_PAIRS: Tuple[ObligationPair, ...] = (
    ObligationPair(
        "engine.admit", acquire=("_admit_bytes",), release=("_unadmit",),
        description="DataEngine read-budget admission bytes "
                    "(mofserver/data_engine.py)"),
    ObligationPair(
        "engine.fd", acquire=("acquire",), release=("release",),
        recv=r".*fds.*",
        description="DataEngine fd-cache references (_FdCache)"),
    ObligationPair(
        "pool.lease", acquire=("lease",), release=("release",),
        recv=r".*(pool|bufs).*",
        description="RowBufferPool host-buffer leases (ops/merge.py)"),
    ObligationPair(
        "gauge.fetch.on_air", kind="gauge", gauge="fetch.on_air",
        description="in-flight fetch attempts (merger/segment.py)"),
    ObligationPair(
        "gauge.stage.inflight", kind="gauge", gauge="stage.inflight.bytes",
        description="fed-but-unmerged staging bytes (merger/overlap.py)"),
    ObligationPair(
        "gauge.arena.slots", kind="gauge", gauge="arena.slots_in_use",
        description="staging-arena slot occupancy (merger/arena.py)"),
    ObligationPair(
        "gauge.reads.on_air", kind="gauge", gauge="supplier.reads.on_air",
        description="DataEngine reads queued or executing"),
    ObligationPair(
        "gauge.read.bytes", kind="gauge", gauge="supplier.read.bytes.on_air",
        description="admitted supplier read bytes"),
    ObligationPair(
        "gauge.io.batch", kind="gauge", gauge="io.batch.inflight",
        description="requests inside the batched read plane "
                    "(mofserver/data_engine.py submit_batch)"),
    ObligationPair(
        "gauge.tenant.read.bytes", kind="gauge",
        gauge="tenant.read.bytes.on_air",
        description="tenant-stamped supplier admission bytes (the "
                    "per-tenant partition level; the tenant.admit "
                    "attribution pair rides the same charge with "
                    "key=tenant — mofserver/data_engine.py)"),
    ObligationPair(
        "ctx.failpoints.scoped", kind="context", acquire=("scoped",),
        recv=r".*failpoints.*", transfer=("enter_context",),
        description="scoped failpoint arming must be entered "
                    "(utils/failpoints.py)"),
    ObligationPair(
        "store.fd", acquire=("acquire_fd",), release=("release_fd",),
        description="MOF-store backend handles (mofserver/store.py "
                    "MOFStore.acquire_fd/release_fd)"),
    ObligationPair(
        "gauge.store.migrate", kind="gauge",
        gauge="store.migrate.bytes.on_air",
        description="bytes mid-migration between store tiers "
                    "(mofserver/store.py StoreManager.migrate)"),
    ObligationPair(
        "gauge.push.on_air", kind="gauge", gauge="push.on_air",
        description="in-flight MSG_PUSH chunks awaiting ACK/NACK "
                    "(net/push.py PushScheduler)"),
    ObligationPair(
        "gauge.push.staged", kind="gauge", gauge="push.staged.bytes",
        description="pushed bytes staged reduce-side but not yet "
                    "adopted or discarded (net/push.py PushStaging)"),
)


# -- UDA101 ------------------------------------------------------------------


class _Events:
    """Per-CFG-node obligation effects."""

    __slots__ = ("acquires", "kills", "ret_value", "ret_names",
                 "ret_has_call")

    def __init__(self) -> None:
        # (pair id, bound variable name or None) opened here
        self.acquires: List[Tuple[str, Optional[str]]] = []
        self.kills: Set[str] = set()    # pair ids settled here
        # return-of-value escape data (see _ret_settles): names the
        # return expression references, and whether it contains a call
        # (a constructed object may carry a handle-less obligation)
        self.ret_value = False
        self.ret_names: Set[str] = set()
        self.ret_has_call = False


class ResourceBalanceRule(Rule):
    """UDA101: every acquire must be balanced on every CFG path.

    See the module docstring for the obligation model. Findings anchor
    on the acquire line (that is where the fix goes: a try/finally, a
    ``with``, or an exception-path release)."""

    rule_id = "UDA101"
    description = ("acquire/release balance on every CFG path "
                   "(exception edges included)")
    hint = ("guard the acquire with try/finally (or `with`), release "
            "on the exception path, or hand the obligation off "
            "explicitly and suppress with a justification")
    node_types = (ast.FunctionDef, ast.AsyncFunctionDef)

    def __init__(self, pairs: Optional[Iterable[ObligationPair]] = None):
        self.pairs = tuple(DEFAULT_PAIRS if pairs is None else pairs)
        self._by_kind = {
            "method": [p for p in self.pairs if p.kind == "method"],
            "gauge": [p for p in self.pairs if p.kind == "gauge"],
            "context": [p for p in self.pairs if p.kind == "context"],
        }
        # a function NAMED like a pair's acquire/release/transfer IS the
        # pair's implementation: its body performs the raw state moves
        # (the paired gauge bump inside _admit_bytes, the free-list push
        # inside release) that the registry models at its CALLERS —
        # charging the wrapper's own body would double count every pair
        self._impl_names: Set[str] = set()
        for p in self.pairs:
            self._impl_names.update(p.acquire)
            self._impl_names.update(p.release)
            self._impl_names.update(p.transfer)

    # -- event extraction ----------------------------------------------------

    @staticmethod
    def _gauge_delta_sign(call: ast.Call) -> Optional[int]:
        """+1 / -1 for the gauge_add delta argument's static sign,
        None when indeterminate (no delta argument)."""
        arg: Optional[ast.AST] = None
        if len(call.args) >= 2:
            arg = call.args[1]
        else:
            for kw in call.keywords:
                if kw.arg == "delta":
                    arg = kw.value
        if arg is None:
            return None
        if isinstance(arg, ast.UnaryOp) and isinstance(arg.op, ast.USub):
            return -1
        if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                        (int, float)):
            return -1 if arg.value < 0 else 1
        return 1  # bare name/expression: the idiom charges positively

    def _call_events(self, call: ast.Call, guarded: bool,
                     ev: _Events) -> None:
        seg = _last_segment(call.func)
        if seg is None:
            return
        if seg == "gauge_add":
            name_arg = call.args[0] if call.args else None
            if isinstance(name_arg, ast.Constant) \
                    and isinstance(name_arg.value, str):
                for pair in self._by_kind["gauge"]:
                    if pair.gauge != name_arg.value:
                        continue
                    sign = self._gauge_delta_sign(call)
                    if sign is not None and sign < 0:
                        ev.kills.add(pair.pair_id)
                    elif not guarded:
                        ev.acquires.append((pair.pair_id, None))
            return
        for pair in self._by_kind["method"] + self._by_kind["context"]:
            if seg in pair.release and pair.recv_ok(call):
                ev.kills.add(pair.pair_id)
            if seg in pair.transfer:
                ev.kills.add(pair.pair_id)
            if seg in pair.acquire and pair.recv_ok(call) and not guarded:
                ev.acquires.append((pair.pair_id, None))

    @staticmethod
    def _bound_target(node) -> Tuple[Optional[str], bool]:
        """(variable name the node's statement binds, escapes-to-
        attribute): ``x = <acquire>`` binds ``x``; ``self.x =
        <acquire>`` escapes the function scope immediately (the object
        owns the obligation now, like a returned handle)."""
        stmt = node.stmt
        if node.kind == "stmt" and isinstance(stmt, ast.Assign) \
                and len(stmt.targets) == 1:
            tgt = stmt.targets[0]
            if isinstance(tgt, ast.Name):
                return tgt.id, False
            if isinstance(tgt, ast.Attribute):
                return None, True
        return None, False

    def _node_events(self, node) -> _Events:
        """Extract obligation effects from one CFG node's expressions.
        Calls inside nested defs/lambdas are deferred code and do not
        count; a call that IS a ``with`` item's context expression is
        guarded (the with statement owns its balance); a call directly
        inside ``enter_context(...)`` likewise."""
        ev = _Events()
        guarded_calls: Set[int] = set()
        if node.kind == "with" and node.stmt is not None:
            for item in node.stmt.items:
                if isinstance(item.context_expr, ast.Call):
                    guarded_calls.add(id(item.context_expr))
        var, escapes = self._bound_target(node)
        for expr in node.exprs:
            if expr is None:
                continue
            stack = [expr]
            while stack:
                cur = stack.pop()
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    continue
                if isinstance(cur, ast.Call):
                    guarded = id(cur) in guarded_calls or escapes
                    if not guarded:
                        seg = _last_segment(cur.func)
                        if seg in ("enter_context",):
                            for arg in cur.args:
                                if isinstance(arg, ast.Call):
                                    guarded_calls.add(id(arg))
                    before = len(ev.acquires)
                    self._call_events(cur, guarded, ev)
                    if var is not None:
                        # the handle the statement binds carries every
                        # obligation this call opened
                        ev.acquires[before:] = [
                            (pid, var) for pid, _ in ev.acquires[before:]]
                stack.extend(ast.iter_child_nodes(cur))
        if node.kind == "return" and node.stmt is not None:
            value = node.stmt.value
            if value is not None and not (
                    isinstance(value, ast.Constant)):
                ev.ret_value = True
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Name):
                        ev.ret_names.add(sub.id)
                    elif isinstance(sub, ast.Call):
                        ev.ret_has_call = True
        return ev

    @staticmethod
    def _ret_settles(pair: ObligationPair, var: Optional[str],
                     ev: _Events) -> bool:
        """Does a ``return <non-constant>`` settle this open site? Only
        when the obligation can plausibly ride the returned value: the
        bound handle is referenced in the return expression, or the
        acquire bound no handle and the value is built by a call (the
        FdSlice idiom — the constructed object carries the charge).
        A paired-GAUGE increment can never ride a return value."""
        if pair.kind == "gauge":
            return False
        if var is not None:
            return var in ev.ret_names
        return ev.ret_has_call

    # -- the worklist --------------------------------------------------------

    def _analyze(self, cfg: CFG,
                 ctx: FileContext) -> List[Finding]:
        events = [self._node_events(n) for n in cfg.nodes]
        if not any(ev.acquires for ev in events):
            return []  # nothing acquired in this function
        # site = (pair_id, node_index, bound var or None); state = set
        # of open sites. TWO out-states per node: the normal edge
        # carries (IN - kills - ret_settled) | gens, the node's own
        # exception edge carries IN - kills only — an acquire that
        # raises did not acquire (and a release that raises is still
        # credited: release implementations settle before any failure
        # can surface). The return-of-value escape (_ret_settles)
        # applies to the normal edge only — a raising return never
        # produced the value.
        pair_by_id = {p.pair_id: p for p in self.pairs}
        Site = Tuple[str, int, Optional[str]]
        n_nodes = len(cfg.nodes)
        state_in: List[Set[Site]] = [set() for _ in range(n_nodes)]
        out_norm: List[Set[Site]] = [set() for _ in range(n_nodes)]
        out_exc: List[Set[Site]] = [set() for _ in range(n_nodes)]
        preds = cfg.preds()

        # standard forward may-analysis worklist: seed with every node
        # (gens self-seed), re-queue successors on any OUT change;
        # union join is monotone over finite site sets, so this
        # terminates at the least fixpoint
        work = list(range(n_nodes))
        queued = set(work)
        while work:
            idx = work.pop()
            queued.discard(idx)
            incoming: Set[Site] = set()
            for p, via_exc in preds[idx]:
                incoming |= out_exc[p] if via_exc else out_norm[p]
            state_in[idx] = incoming
            ev = events[idx]
            survived = ({s for s in incoming if s[0] not in ev.kills}
                        if ev.kills else set(incoming))
            norm = set(survived)
            if ev.ret_value:
                norm = {s for s in norm if not self._ret_settles(
                    pair_by_id[s[0]], s[2], ev)}
            norm.update((pid, idx, var) for pid, var in ev.acquires)
            if norm != out_norm[idx] or survived != out_exc[idx]:
                out_norm[idx] = norm
                out_exc[idx] = survived
                for s in cfg.nodes[idx].succs:
                    if s not in queued:
                        queued.add(s)
                        work.append(s)
        leaks_exit = state_in[cfg.exit_id]
        leaks_raise = state_in[cfg.raise_id]
        findings: List[Finding] = []
        reported: Set[Tuple[str, int]] = set()
        for site in sorted(leaks_exit | leaks_raise,
                           key=lambda s: (cfg.nodes[s[1]].line, s[0])):
            pid, node_idx, _var = site
            if (pid, node_idx) in reported:
                continue
            reported.add((pid, node_idx))
            node = cfg.nodes[node_idx]
            pair = pair_by_id[pid]
            if pair.kind == "context":
                msg = (f"{pid}: {pair.acquire[0]}() returns a context "
                       f"obligation but is not entered (`with ...:`) — "
                       f"the scope never closes")
            else:
                how = []
                if site in leaks_raise:
                    how.append("an exception path")
                if site in leaks_exit:
                    how.append("a normal path")
                msg = (f"{pid}: acquired here but "
                       f"{' and '.join(how)} reaches function exit "
                       f"without the paired release "
                       f"({'/'.join(pair.release) or 'with-guard'})")
            findings.append(Finding(
                ctx.rel, node.line,
                getattr(node.stmt, "col_offset", 0), self.rule_id, msg,
                self.hint, data={"pair": pid}))
        return findings

    def visit(self, node, ctx: FileContext) -> Iterable[Finding]:
        if node.name in self._impl_names:
            return ()  # the pair's own implementation (see __init__)
        try:
            cfg = build_cfg(node)
        except RecursionError:  # pathological nesting: skip, don't die
            return ()
        return self._analyze(cfg, ctx)


# -- UDA102 ------------------------------------------------------------------

_LOCK_RE = re.compile(r"_?(?:[a-z0-9_]*lock|cv|cond(?:ition)?|mu(?:tex)?)")
_QUEUE_RE = re.compile(r"_?(?:[a-z0-9_]*queue|(?:in|out|work)?q)")
_RECV = {"recv", "recv_into", "recvfrom", "recvmsg"}

# names that never resolve to a project def worth chasing (cheap noise
# filter; anything not defined in the linted tree is skipped anyway)
_SKIP_CALLEES = {"len", "int", "str", "float", "bool", "list", "dict",
                 "set", "tuple", "print", "isinstance", "getattr",
                 "setattr", "hasattr", "range", "min", "max", "sorted"}


def _direct_blocking(call: ast.Call) -> Optional[str]:
    """The shared unbounded-blocking-call detector (UDA007's notion,
    plus no-arg ``.join()`` and ``time.sleep``-style delays): what a
    function must contain to seed the transitive `blocks` set."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    if attr == "result" and not _call_has_timeout(call):
        return "Future.result()"
    if attr in ("wait", "wait_for") and not _call_has_timeout(call):
        return f".{attr}()"
    if attr == "get" and not _call_has_timeout(call):
        seg = _last_segment(func.value)
        if seg is not None and _QUEUE_RE.fullmatch(seg):
            return f"{seg}.get()"
        return None
    if attr == "join" and not call.args and not call.keywords:
        seg = _last_segment(func.value)
        if seg is not None and not isinstance(func.value, ast.Constant):
            return f"{seg}.join()"
        return None
    if attr == "sendall":
        return ".sendall()"
    if attr in _RECV:
        return f"socket .{attr}()"
    return None


@dataclasses.dataclass
class _DefInfo:
    file: str
    line: int
    blocking: Optional[str]          # direct blocking description
    calls: Set[str]                  # callee last-segments


@dataclasses.dataclass
class _GuardedCall:
    file: str
    line: int
    col: int
    callee: str
    guard: str                       # "with <lock>:" | "@loop_callback"
    owner: str                       # guarding function / lock name


class TransitiveBlockingRule(Rule):
    """UDA102: blocking through a helper hop. UDA007/UDA008 catch a
    blocking call written directly under a lock / in a loop callback;
    one helper function defeats them (``with lock: self._drain()``
    where ``_drain`` joins threads). This rule builds a lightweight
    intra-package call graph — functions keyed by NAME, calls resolved
    to project-defined names only — seeds it with the directly-blocking
    defs, propagates to a fixpoint, and reports guarded calls whose
    callee lands in the transitive `blocks` set. Name-keyed resolution
    over-approximates (two defs sharing a name share a verdict), which
    is the right direction for a linter: the finding names the witness
    chain so a false hit is a one-line justified suppression."""

    rule_id = "UDA102"
    description = ("no transitively-blocking helper calls under a lock "
                   "or in an event-loop callback")
    hint = ("bound the wait inside the helper (timeout=...), move the "
            "helper call outside the lock/callback, or suppress with "
            "the justification that this name's blocking twin is "
            "never the one called here")
    node_types = (ast.FunctionDef, ast.AsyncFunctionDef, ast.With)

    def __init__(self, marker: str = "loop_callback"):
        self.marker = marker
        self._defs: Dict[str, List[_DefInfo]] = {}
        self._guarded: List[_GuardedCall] = []

    def begin_file(self, ctx: FileContext) -> None:
        self._ctx = ctx

    # -- collection ----------------------------------------------------------

    @staticmethod
    def _lock_names(node: ast.With) -> List[str]:
        names = []
        for item in node.items:
            seg = _last_segment(item.context_expr)
            if seg is not None and _LOCK_RE.fullmatch(seg):
                names.append(seg)
        return names

    def _scan_calls(self, body, skip_lock_withs: bool):
        """(callee, line, col, direct_blocking) for every call in
        ``body``, excluding nested defs/lambdas (deferred) and — when
        asked — nested lock-with bodies (they get their own site)."""
        out = []
        stack = list(body)
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if skip_lock_withs and isinstance(cur, ast.With) \
                    and self._lock_names(cur):
                continue
            if isinstance(cur, ast.Call):
                seg = _last_segment(cur.func)
                if seg:
                    out.append((seg, cur.lineno, cur.col_offset,
                                _direct_blocking(cur)))
            stack.extend(ast.iter_child_nodes(cur))
        return out

    def _is_marked(self, node) -> bool:
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _last_segment(target) == self.marker:
                return True
        return False

    def visit(self, node, ctx: FileContext) -> Iterable[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            calls = self._scan_calls(node.body, skip_lock_withs=False)
            blocking = next((d for _, _, _, d in calls if d), None)
            self._defs.setdefault(node.name, []).append(_DefInfo(
                ctx.rel, node.lineno, blocking,
                {c for c, _, _, _ in calls}))
            if ctx.in_net and self._is_marked(node):
                for callee, line, col, direct in self._scan_calls(
                        node.body, skip_lock_withs=False):
                    if direct:
                        continue  # UDA008's finding, not ours
                    self._guarded.append(_GuardedCall(
                        ctx.rel, line, col, callee,
                        "@loop_callback", node.name))
            return ()
        # ast.With
        locks = self._lock_names(node)
        if not locks:
            return ()
        for callee, line, col, direct in self._scan_calls(
                node.body, skip_lock_withs=True):
            if direct:
                continue  # UDA007's finding, not ours
            self._guarded.append(_GuardedCall(
                ctx.rel, line, col, callee, f"with {locks[0]}:",
                locks[0]))
        return ()

    # -- the fixpoint + report -----------------------------------------------

    def _blocking_closure(self) -> Dict[str, str]:
        """name -> witness chain ("a -> b -> .result()") for every
        project-defined name that blocks. Resolution is by NAME, so a
        name with several defs is only convicted when EVERY def blocks
        (directly or via its calls) — a name whose blocking twin lives
        in an unrelated module must not poison every caller of the
        benign homonyms (the generic-name problem: release/close/run).
        Monotone: adding a convicted name only ever flips more defs, so
        the loop reaches a least fixpoint."""
        blocks: Dict[str, str] = {}
        changed = True
        while changed:
            changed = False
            for name, infos in self._defs.items():
                if name in blocks:
                    continue
                witness: Optional[str] = None
                for info in infos:
                    if info.blocking:
                        witness = witness or info.blocking
                        continue
                    hit = next((c for c in info.calls
                                if c != name and c in blocks), None)
                    if hit is None:
                        witness = None
                        break
                    witness = witness or f"{hit} -> {blocks[hit]}"
                if witness is not None:
                    blocks[name] = witness
                    changed = True
        return blocks

    def finalize(self) -> Iterable[Finding]:
        blocks = self._blocking_closure()
        findings = []
        for g in self._guarded:
            if g.callee in _SKIP_CALLEES or g.callee not in self._defs:
                continue
            tail = blocks.get(g.callee)
            if tail is None:
                continue
            chain = f"{g.callee} -> {tail}"
            findings.append(Finding(
                g.file, g.line, g.col, self.rule_id,
                f"call to {g.callee!r} inside `{g.guard}` blocks "
                f"transitively ({chain})",
                self.hint, data={"callee": g.callee, "guard": g.guard}))
        findings.sort(key=lambda f: (f.file, f.line))
        return findings


# -- UDA103 ------------------------------------------------------------------

_TRACKED = {"TrackedLock", "TrackedCondition"}


class StaticLockOrderRule(Rule):
    """UDA103: the ``with``-nesting order of TrackedLock *classes*,
    collected tree-wide, must be acyclic. The runtime lockdep validator
    only sees orders a test actually exercised; this is the
    compile-time sweep over every lexically-nested pair, so an AB/BA
    inversion is a build failure even when no test interleaves the two
    orders. Same-class nesting is not an edge (lockdep's rule: class-
    level self-edges false-positive on instance hierarchies)."""

    rule_id = "UDA103"
    description = ("static TrackedLock with-nesting order must be "
                   "acyclic tree-wide")
    hint = ("pick ONE global order for the two lock classes and "
            "restructure the inverted site (or drop one lock scope)")
    node_types = (ast.Assign, ast.With)

    def __init__(self) -> None:
        # (file, enclosing class name or "", attr/var name) -> class
        self._lock_vars: Dict[Tuple[str, str, str], str] = {}
        # attr/var name -> set of classes (global fallback)
        self._by_name: Dict[str, Set[str]] = {}
        # raw nesting observations, resolved at finalize
        self._nestings: List[Tuple[str, int, int, Tuple[Tuple[str, str],
                                                        ...]]] = []

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _enclosing_class(node: ast.AST) -> str:
        cur = getattr(node, "parent", None)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = getattr(cur, "parent", None)
        return ""

    def _lock_class_of_ctor(self, call: ast.Call,
                            scope: Tuple[str, str]) -> Optional[str]:
        """The lock class a TrackedLock(...)/TrackedCondition(...)
        constructor creates, or None when indeterminate."""
        seg = _last_segment(call.func)
        if seg == "TrackedLock":
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str):
                return call.args[0].value
            for kw in call.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    return str(kw.value.value)
            return None
        if seg == "TrackedCondition":
            arg0 = call.args[0] if call.args else None
            if isinstance(arg0, ast.Call):
                return self._lock_class_of_ctor(arg0, scope)
            if arg0 is not None:
                ref = _last_segment(arg0)
                if ref is not None:
                    got = self._lock_vars.get((scope[0], scope[1], ref))
                    if got:
                        return got
            for kw in call.keywords:
                if kw.arg == "lock" and isinstance(kw.value, ast.Call):
                    return self._lock_class_of_ctor(kw.value, scope)
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    return str(kw.value.value)
            return "cond"  # TrackedCondition() default name
        return None

    def visit(self, node, ctx: FileContext) -> Iterable[Finding]:
        if isinstance(node, ast.Assign):
            if not isinstance(node.value, ast.Call):
                return ()
            seg = _last_segment(node.value.func)
            if seg not in _TRACKED:
                return ()
            scope = (ctx.rel, self._enclosing_class(node))
            cls = self._lock_class_of_ctor(node.value, scope)
            if cls is None:
                return ()
            for tgt in node.targets:
                name = _last_segment(tgt)
                if name:
                    self._lock_vars[(ctx.rel, scope[1], name)] = cls
                    self._by_name.setdefault(name, set()).add(cls)
            return ()
        # ast.With: record this with's lock refs + those of enclosing
        # withs (innermost last); resolution happens at finalize when
        # the variable table is complete
        refs = self._with_lock_refs(node)
        if not refs:
            return ()
        chain: List[Tuple[str, str]] = []
        cur = getattr(node, "parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break  # a `with` in an enclosing def is not held here
            if isinstance(cur, ast.With):
                outer = self._with_lock_refs(cur)
                chain = outer + chain
            cur = getattr(cur, "parent", None)
        scope_cls = self._enclosing_class(node)
        self._nestings.append(
            (ctx.rel, node.lineno, node.col_offset,
             tuple((scope_cls, r) for r in chain + refs)))
        return ()

    @staticmethod
    def _with_lock_refs(node: ast.With) -> List[str]:
        refs = []
        for item in node.items:
            seg = _last_segment(item.context_expr)
            if seg is not None and not isinstance(item.context_expr,
                                                  ast.Call):
                refs.append(seg)
        return refs

    # -- the order graph -----------------------------------------------------

    def _resolve(self, file: str, scope_cls: str,
                 name: str) -> Optional[str]:
        got = self._lock_vars.get((file, scope_cls, name))
        if got:
            return got
        classes = self._by_name.get(name, set())
        if len(classes) == 1:
            return next(iter(classes))
        return None  # unknown or ambiguous: no edge

    def finalize(self) -> Iterable[Finding]:
        edges: Dict[Tuple[str, str], Tuple[str, int, int]] = {}
        for file, line, col, chain in sorted(self._nestings):
            resolved = [c for c in
                        (self._resolve(file, sc, r) for sc, r in chain)
                        if c is not None]
            for i in range(len(resolved) - 1):
                a, b = resolved[i], resolved[i + 1]
                if a != b and (a, b) not in edges:
                    edges[(a, b)] = (file, line, col)
        adj: Dict[str, List[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)

        def path(src: str, dst: str) -> Optional[List[str]]:
            stack, seen = [(src, [src])], {src}
            while stack:
                node, p = stack.pop()
                if node == dst:
                    return p
                for nxt in adj.get(node, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append((nxt, p + [nxt]))
            return None

        findings = []
        reported: Set[Tuple[str, ...]] = set()
        for (a, b), (file, line, col) in sorted(edges.items(),
                                                key=lambda kv: kv[1]):
            p = path(b, a)
            if p is None:
                continue
            key = tuple(sorted(set([a] + p)))
            if key in reported:
                continue
            reported.add(key)
            other = edges.get((p[0], p[1]))
            where = (f" (reverse order at {other[0]}:{other[1]})"
                     if other else "")
            findings.append(Finding(
                file, line, col, self.rule_id,
                f"static lock-order cycle: `with` nesting takes "
                f"{a!r} -> {b!r} here, but {b!r} already reaches "
                f"{a!r} via {' -> '.join(p)}{where}",
                self.hint, data={"cycle": [a] + p}))
        return findings
