"""The udalint rule suite: the invariants PRs 1-4 established, encoded.

====== ==============================================================
UDA001 config-key strings (``uda.tpu.*`` / ``mapred.*``) must be
       declared in the ``FLAGS`` registry (uda_tpu/utils/config.py)
UDA002 metrics names must resolve against ``METRICS_REGISTRY`` (the
       AST port of the old check_metrics_names regex, including
       f-string prefixes and aliased receivers)
UDA003 failpoint site names must be registered sites
       (uda_tpu/utils/failpoints.py ``KNOWN_SITES``)
UDA004 no raw ``sock.close()`` in uda_tpu/net/ outside wire.py —
       ``wire.close_hard`` (shutdown-then-close) is the only legal
       teardown (the PR 4 deadlock lesson)
UDA005 never branch on exception/admission reason *strings*: compare
       structured ``cause`` fields, not ``str(e)`` or ``.reason``
UDA006 ``except Exception`` must log, count, re-raise, or forward the
       exception — silent swallows are findings
UDA007 no unbounded blocking call (``.result()``, ``Queue.get()``,
       ``Condition.wait()`` without timeout, socket ``recv``) inside a
       ``with <lock>:`` body — the static half of deadlock prevention
       (the dynamic half is uda_tpu/utils/locks.py lockdep)
UDA008 no blocking call (``recv``/``sendall``/unbounded ``.result()``/
       unbounded ``Queue.get()``) inside an event-loop callback body
       in uda_tpu/net/ — registered callbacks are the functions marked
       ``@loop_callback`` (uda_tpu/net/evloop.py); the loop thread's
       own run loop is exempt (parking in select() is its job)
UDA009 span names passed to ``start_span``/``span`` must belong to the
       declared ``SPAN_REGISTRY`` (uda_tpu/utils/metrics.py) — the
       UDA002 contract for the trace plane: span names are
       cross-process identifiers (REQ frames carry them as trace
       context, trace_merge.py stitches on them), so a typo'd name is
       a broken trace, not just an ugly one
UDA101 resource balance over the per-function CFG: every registered
       acquire (uda_tpu/analysis/flow.py DEFAULT_PAIRS) must reach a
       release/transfer/with-guard on EVERY path, exception edges
       included (the udaflow dataflow tier, uda_tpu/analysis/cfg.py)
UDA102 transitive blocking-under-lock / blocking-in-loop-callback via
       the intra-package call graph (the helper hop UDA007/UDA008
       cannot see)
UDA103 static TrackedLock with-nesting order must be acyclic tree-wide
       (the compile-time complement of runtime lockdep)
====== ==============================================================

Every rule is constructor-injectable (registry/sites/flags overrides)
so the fixture tests can prove firing without depending on the live
tables.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set, Tuple

from uda_tpu.analysis.core import FileContext, Finding, Rule
from uda_tpu.analysis.flow import (ResourceBalanceRule, StaticLockOrderRule,
                                   TransitiveBlockingRule)
from uda_tpu.analysis.race import RaceLocksetRule, WireExhaustivenessRule

__all__ = ["ALL_RULES", "default_engine",
           "ConfigKeyRule", "MetricsNameRule", "FailpointSiteRule",
           "RawSocketCloseRule", "ReasonStringBranchRule",
           "SwallowedExceptionRule", "BlockingInLockRule",
           "EventLoopBlockingRule", "SpanNameRule",
           "ResourceBalanceRule",
           "TransitiveBlockingRule", "StaticLockOrderRule"]


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_segment(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _call_has_timeout(call: ast.Call) -> bool:
    """True when the call passes any positional arg or a ``timeout=``
    keyword (the static signature of a bounded wait)."""
    if call.args:
        return True
    return any(kw.arg == "timeout" for kw in call.keywords)


# -- UDA001 ------------------------------------------------------------------

_KEY_RE = re.compile(r"(?:uda\.tpu|mapred)(?:\.[a-z0-9_]+)+")


class ConfigKeyRule(Rule):
    """Config-key string literals must be declared in ``FLAGS``."""

    rule_id = "UDA001"
    description = "uda.tpu.* / mapred.* key strings must be in FLAGS"
    hint = "declare the key in uda_tpu/utils/config.py FLAGS (or fix the typo)"
    node_types = (ast.Constant,)

    def __init__(self, flags: Optional[Set[str]] = None):
        if flags is None:
            from uda_tpu.utils.config import FLAGS
            flags = set(FLAGS)
        self.flags = flags

    def visit(self, node: ast.Constant,
              ctx: FileContext) -> Iterable[Finding]:
        v = node.value
        if not isinstance(v, str) or not _KEY_RE.fullmatch(v):
            return ()
        if v in self.flags or ctx.is_docstring(node):
            return ()
        return (self.finding(
            ctx, node,
            f"config key {v!r} is not declared in the FLAGS registry"),)


# -- UDA002 ------------------------------------------------------------------

_METRIC_METHODS = ("add", "gauge", "gauge_add", "observe")


class MetricsNameRule(Rule):
    """Metric names at ``metrics.add/gauge/gauge_add/observe`` call
    sites must be static and resolve against ``METRICS_REGISTRY``
    (f-string families against ``REGISTRY_PREFIXES``). Receivers are
    resolved through per-file aliases (``from ... import metrics as m``,
    ``m = metrics``, ``self.metrics``), which the old regex missed."""

    rule_id = "UDA002"
    description = "metrics names must be registered in METRICS_REGISTRY"
    hint = ("register the name in uda_tpu/utils/metrics.py "
            "METRICS_REGISTRY (schema doc included)")
    node_types = (ast.Call, ast.ImportFrom, ast.Assign)

    def __init__(self, registry: Optional[Set[str]] = None,
                 prefixes: Optional[Tuple[str, ...]] = None,
                 name_re: Optional[str] = None):
        if registry is None or prefixes is None or name_re is None:
            from uda_tpu.utils.metrics import (METRICS_REGISTRY, NAME_RE,
                                               REGISTRY_PREFIXES)
            registry = set(METRICS_REGISTRY) if registry is None else registry
            prefixes = REGISTRY_PREFIXES if prefixes is None else prefixes
            name_re = NAME_RE if name_re is None else name_re
        self.registry = registry
        self.prefixes = tuple(prefixes)
        self.name_re = re.compile(name_re + r"\Z")
        self._aliases: Set[str] = set()

    def begin_file(self, ctx: FileContext) -> None:
        # "metrics" counts as the hub even without a visible import:
        # fixtures and generated code still get checked
        self._aliases = {"metrics"}

    def _is_metrics_receiver(self, recv: ast.AST) -> bool:
        seg = _last_segment(recv)
        return seg is not None and seg in self._aliases

    def visit(self, node: ast.AST, ctx: FileContext) -> Iterable[Finding]:
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.endswith("metrics"):
                for alias in node.names:
                    if alias.name == "metrics":
                        self._aliases.add(alias.asname or alias.name)
            return ()
        if isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Name) \
                    and node.value.id in self._aliases:
                for tgt in node.targets:
                    seg = _last_segment(tgt)
                    if seg:
                        self._aliases.add(seg)
            return ()
        # ast.Call
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _METRIC_METHODS
                and self._is_metrics_receiver(func.value)):
            return ()
        name_arg = node.args[0] if node.args else None
        if name_arg is None:
            for kw in node.keywords:
                if kw.arg == "name":
                    name_arg = kw.value
        if name_arg is None:
            return (self._bad(ctx, node, ast.unparse(node)[:60],
                              "metric name must be a string literal"),)
        if isinstance(name_arg, ast.Constant) \
                and isinstance(name_arg.value, str):
            name = name_arg.value
            if not self.name_re.match(name):
                return (self._bad(ctx, name_arg, name,
                                  "not dotted domain.metric namespace"),)
            if name not in self.registry:
                return (self._bad(ctx, name_arg, name,
                                  "not listed in METRICS_REGISTRY"),)
            return ()
        if isinstance(name_arg, ast.JoinedStr):
            prefix = ""
            for part in name_arg.values:
                if isinstance(part, ast.Constant) \
                        and isinstance(part.value, str):
                    prefix += part.value
                else:
                    break
            if not any(prefix.startswith(p) for p in self.prefixes):
                return (self._bad(
                    ctx, name_arg, ast.unparse(name_arg),
                    f"f-string prefix {prefix!r} not in "
                    f"REGISTRY_PREFIXES {self.prefixes}"),)
            return ()
        return (self._bad(ctx, name_arg, ast.unparse(name_arg)[:60],
                          "metric name must be a string literal"),)

    def _bad(self, ctx: FileContext, node: ast.AST, name: str,
             reason: str) -> Finding:
        return self.finding(ctx, node, f"metric {name!r}: {reason}",
                            data={"name": name, "reason": reason})


# -- UDA003 ------------------------------------------------------------------


class FailpointSiteRule(Rule):
    """``failpoint("<site>")`` must name a registered site — a typo'd
    site is a failpoint that can never fire (and a chaos schedule that
    silently tests nothing)."""

    rule_id = "UDA003"
    description = "failpoint() sites must be registered"
    hint = ("register the site in uda_tpu/utils/failpoints.py "
            "_SITE_ERRORS (and document it in the module docstring)")
    node_types = (ast.Call,)

    def __init__(self, sites: Optional[Set[str]] = None):
        if sites is None:
            from uda_tpu.utils.failpoints import KNOWN_SITES
            sites = set(KNOWN_SITES)
        self.sites = sites

    def visit(self, node: ast.Call, ctx: FileContext) -> Iterable[Finding]:
        if not (isinstance(node.func, ast.Name)
                and node.func.id == "failpoint"):
            return ()
        site_arg = node.args[0] if node.args else None
        if not (isinstance(site_arg, ast.Constant)
                and isinstance(site_arg.value, str)):
            return (self.finding(
                ctx, node, "failpoint site must be a string literal "
                           "(sites are a static, auditable inventory)"),)
        if site_arg.value in self.sites:
            return ()
        return (self.finding(
            ctx, site_arg,
            f"failpoint site {site_arg.value!r} is not a registered "
            f"site"),)


# -- UDA004 ------------------------------------------------------------------

_SOCK_RE = re.compile(r"_?(?:[a-z_]*sock(?:et)?|listener|ls)")


class RawSocketCloseRule(Rule):
    """In uda_tpu/net/ every socket teardown must go through
    ``wire.close_hard`` — ``close()`` alone neither wakes a blocked
    ``recv()`` nor sends FIN while a reader's syscall pins the fd (the
    deadlock that cost PR 4 its first version)."""

    rule_id = "UDA004"
    description = "net/ sockets close via wire.close_hard only"
    hint = "call wire.close_hard(sock) (shutdown-then-close)"
    node_types = (ast.Call,)

    def visit(self, node: ast.Call, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.in_net or ctx.basename == "wire.py":
            return ()
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "close"):
            return ()
        seg = _last_segment(func.value)
        if seg is None or not _SOCK_RE.fullmatch(seg):
            return ()
        return (self.finding(
            ctx, node,
            f"raw {seg}.close() in uda_tpu/net/ — close() neither wakes "
            f"a blocked recv() nor forces the FIN out"),)


# -- UDA005 ------------------------------------------------------------------

_CMP_OPS = (ast.Eq, ast.NotEq, ast.In, ast.NotIn)


def _is_str_of_exception(node: ast.AST) -> bool:
    """``str(e)`` where ``e`` is bound by an enclosing except handler."""
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "str" and len(node.args) == 1
            and isinstance(node.args[0], ast.Name)):
        return False
    exc_name = node.args[0].id
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, ast.ExceptHandler) and cur.name == exc_name:
            return True
        cur = getattr(cur, "parent", None)
    return False


def _is_str_const(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, str)


class ReasonStringBranchRule(Rule):
    """Control flow must branch on structured ``cause`` fields, never on
    human-readable reason strings (``str(e)``, ``.reason``) — messages
    get reworded, causes are API (the PR 3 admission contract)."""

    rule_id = "UDA005"
    description = "branch on cause enums, not reason strings"
    hint = ("compare the structured `cause` field (e.g. adm.cause == "
            "'hbm') or the exception type, never its message text")
    node_types = (ast.Compare, ast.Call)

    def visit(self, node: ast.AST, ctx: FileContext) -> Iterable[Finding]:
        if isinstance(node, ast.Call):
            # str(e).startswith("...") and friends
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in ("startswith", "endswith")
                    and _is_str_of_exception(func.value)):
                return (self.finding(
                    ctx, node, "branching on the exception's message "
                               "text via str(e)." + func.attr),)
            return ()
        if len(node.ops) != 1 or not isinstance(node.ops[0], _CMP_OPS):
            return ()
        left, right = node.left, node.comparators[0]
        for a, b in ((left, right), (right, left)):
            if _is_str_of_exception(a) and _is_str_const(b):
                return (self.finding(
                    ctx, node, "comparing str(<exception>) against a "
                               "string literal"),)
            if (isinstance(a, ast.Attribute) and a.attr == "reason"
                    and _is_str_const(b)):
                return (self.finding(
                    ctx, node, "comparing a .reason string against a "
                               "literal"),)
        return ()


# -- UDA006 ------------------------------------------------------------------

_LOG_METHODS = {"debug", "info", "warn", "warning", "error", "exception",
                "fatal", "critical", "trace", "log"}
_BROAD = {"Exception", "BaseException"}


class SwallowedExceptionRule(Rule):
    """A broad ``except Exception`` handler must log, count
    (``metrics.*``), re-raise, or at least forward the bound exception
    somewhere — a handler that does none of these erases the error."""

    rule_id = "UDA006"
    description = "except Exception must log, count, or re-raise"
    hint = ("log it (log.warn/error), count it "
            "(metrics.add('errors.swallowed')), re-raise, or forward "
            "the exception object")
    node_types = (ast.ExceptHandler,)

    @staticmethod
    def _is_broad(handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        if isinstance(t, ast.Name):
            return t.id in _BROAD
        if isinstance(t, ast.Tuple):
            return any(isinstance(e, ast.Name) and e.id in _BROAD
                       for e in t.elts)
        return False

    def visit(self, node: ast.ExceptHandler,
              ctx: FileContext) -> Iterable[Finding]:
        if not self._is_broad(node):
            return ()
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Raise):
                    return ()
                if isinstance(sub, ast.Name) and node.name \
                        and sub.id == node.name:
                    return ()  # the exception object is being used
                if isinstance(sub, ast.Call):
                    f = sub.func
                    if isinstance(f, ast.Name) and f.id == "print":
                        return ()
                    if isinstance(f, ast.Attribute):
                        if f.attr in _LOG_METHODS:
                            return ()
                        if f.attr in _METRIC_METHODS \
                                and _last_segment(f.value) == "metrics":
                            return ()
        what = ("bare except" if node.type is None
                else ast.unparse(node.type))
        return (self.finding(
            ctx, node, f"`except {what}` silently swallows the error"),)


# -- UDA007 ------------------------------------------------------------------

_LOCK_RE = re.compile(r"_?(?:[a-z0-9_]*lock|cv|cond(?:ition)?|mu(?:tex)?)")
_QUEUE_RE = re.compile(r"_?(?:[a-z0-9_]*queue|(?:in|out|work)?q)")
_RECV = {"recv", "recv_into", "recvfrom", "recvmsg"}


class BlockingInLockRule(Rule):
    """No unbounded blocking call inside a ``with <lock>:`` body: a
    wait that can never time out while holding a lock is half a
    deadlock already (the other half is whoever needs that lock to
    produce the completion). Bounded waits — any positional arg or
    ``timeout=`` keyword — pass."""

    rule_id = "UDA007"
    description = "no unbounded blocking calls while holding a lock"
    hint = ("move the wait outside the lock, or bound it with a "
            "timeout= and handle the timeout")
    node_types = (ast.With,)

    @staticmethod
    def _lock_names(node: ast.With) -> List[str]:
        names = []
        for item in node.items:
            seg = _last_segment(item.context_expr)
            if seg is not None and _LOCK_RE.fullmatch(seg):
                names.append(seg)
        return names

    def visit(self, node: ast.With, ctx: FileContext) -> Iterable[Finding]:
        locks = self._lock_names(node)
        if not locks:
            return ()
        findings: List[Finding] = []
        # walk the body, but not into nested lock-withs (they get their
        # own dispatch) nor into nested function bodies (deferred code
        # does not run while this lock is held)
        stack: List[ast.AST] = list(node.body)
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if isinstance(cur, ast.With) and self._lock_names(cur):
                continue
            if isinstance(cur, ast.Call):
                bad = self._blocking(cur)
                if bad:
                    findings.append(self.finding(
                        ctx, cur,
                        f"unbounded {bad} inside `with {locks[0]}:`"))
            stack.extend(ast.iter_child_nodes(cur))
        return findings

    @staticmethod
    def _blocking(call: ast.Call) -> Optional[str]:
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        if attr == "result" and not _call_has_timeout(call):
            return "Future.result()"
        if attr in ("wait", "wait_for") and not _call_has_timeout(call):
            return f".{attr}()"
        if attr == "get" and not _call_has_timeout(call):
            seg = _last_segment(func.value)
            if seg is not None and _QUEUE_RE.fullmatch(seg):
                return f"{seg}.get()"
            return None
        if attr in _RECV:
            return f"socket .{attr}()"
        return None


# -- UDA008 ------------------------------------------------------------------


class EventLoopBlockingRule(Rule):
    """No blocking call inside an event-loop callback body in
    ``uda_tpu/net/``: one parked callback stalls EVERY connection the
    shared loop multiplexes (and, transitively, every fetch in the
    process) — the failure mode the event-loop refactor exists to make
    impossible. Registered callbacks are the functions marked with
    ``@loop_callback`` (the declarative contract from
    uda_tpu/net/evloop.py); the loop thread's own run loop is exempt —
    parking in ``select()`` is its job. Banned forms: blocking socket
    ``recv``/``sendall`` (use ``recv_into``/``send``/``sendmsg`` on
    the non-blocking fd), unbounded ``Future.result()``, unbounded
    queue ``get()``. Deferred code (nested defs, lambdas) is skipped —
    it does not run on the loop. Potentially-blocking completion
    upcalls belong on ``EventLoop.dispatch()``."""

    rule_id = "UDA008"
    description = "no blocking calls in event-loop callbacks in net/"
    hint = ("use the non-blocking form (recv_into/send/sendmsg, "
            "result(timeout=...), get(timeout=...)), or move the work "
            "to EventLoop.dispatch()")
    node_types = (ast.FunctionDef, ast.AsyncFunctionDef)

    def __init__(self, marker: str = "loop_callback"):
        self.marker = marker

    def _is_marked(self, node) -> bool:
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _last_segment(target) == self.marker:
                return True
        return False

    def visit(self, node, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.in_net or not self._is_marked(node):
            return ()
        findings: List[Finding] = []
        stack: List[ast.AST] = list(node.body)
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue  # deferred code does not run on the loop
            if isinstance(cur, ast.Call):
                bad = self._blocking(cur)
                if bad:
                    findings.append(self.finding(
                        ctx, cur,
                        f"{bad} inside event-loop callback "
                        f"{node.name!r}"))
            stack.extend(ast.iter_child_nodes(cur))
        return findings

    @staticmethod
    def _blocking(call: ast.Call) -> Optional[str]:
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        if attr == "sendall":
            return "blocking .sendall()"
        if attr == "recv":
            return "blocking .recv()"
        if attr == "result" and not _call_has_timeout(call):
            return "unbounded Future.result()"
        if attr == "get" and not _call_has_timeout(call):
            seg = _last_segment(func.value)
            if seg is not None and _QUEUE_RE.fullmatch(seg):
                return f"unbounded {seg}.get()"
        return None


# -- UDA009 ------------------------------------------------------------------

_SPAN_METHODS = ("start_span", "span")


class SpanNameRule(Rule):
    """Span names at ``metrics.start_span``/``metrics.span`` call sites
    must be string literals registered in ``SPAN_REGISTRY`` — the
    UDA002 contract extended to the trace plane. Span names are
    cross-process identifiers (the wire carries their ids as trace
    context; scripts/trace_merge.py and every trace dashboard key on
    the inventory), so they are a static, auditable table like metrics
    names and failpoint sites. Receivers resolve through the same
    per-file alias tracking as UDA002 (``from ... import metrics as
    m``, ``m = metrics``); ``metrics.timer(name)`` spans are named by
    their timer counter and deliberately out of scope."""

    rule_id = "UDA009"
    description = "span names must be registered in SPAN_REGISTRY"
    hint = ("register the name in uda_tpu/utils/metrics.py "
            "SPAN_REGISTRY (description included) or fix the typo")
    node_types = (ast.Call, ast.ImportFrom, ast.Assign)

    def __init__(self, registry: Optional[Set[str]] = None):
        if registry is None:
            from uda_tpu.utils.metrics import SPAN_REGISTRY
            registry = set(SPAN_REGISTRY)
        self.registry = registry
        self._aliases: Set[str] = set()

    def begin_file(self, ctx: FileContext) -> None:
        self._aliases = {"metrics"}

    def visit(self, node: ast.AST, ctx: FileContext) -> Iterable[Finding]:
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.endswith("metrics"):
                for alias in node.names:
                    if alias.name == "metrics":
                        self._aliases.add(alias.asname or alias.name)
            return ()
        if isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Name) \
                    and node.value.id in self._aliases:
                for tgt in node.targets:
                    seg = _last_segment(tgt)
                    if seg:
                        self._aliases.add(seg)
            return ()
        # ast.Call
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _SPAN_METHODS
                and _last_segment(func.value) in self._aliases):
            return ()
        name_arg = node.args[0] if node.args else None
        if name_arg is None:
            for kw in node.keywords:
                if kw.arg == "name":
                    name_arg = kw.value
        if not (isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)):
            return (self.finding(
                ctx, node,
                "span name must be a string literal (span names are a "
                "static, cross-process-auditable inventory)"),)
        if name_arg.value in self.registry:
            return ()
        return (self.finding(
            ctx, name_arg,
            f"span name {name_arg.value!r} is not declared in "
            f"SPAN_REGISTRY"),)


ALL_RULES = (ConfigKeyRule, MetricsNameRule, FailpointSiteRule,
             RawSocketCloseRule, ReasonStringBranchRule,
             SwallowedExceptionRule, BlockingInLockRule,
             EventLoopBlockingRule, SpanNameRule,
             # the udaflow dataflow tier (uda_tpu/analysis/flow.py)
             ResourceBalanceRule, TransitiveBlockingRule,
             StaticLockOrderRule,
             # the udarace lockset tier (uda_tpu/analysis/race.py):
             # UDA201/202/203 from the one collector + UDA204
             RaceLocksetRule, WireExhaustivenessRule)


def default_engine(root: Optional[str] = None):
    """The full-suite engine (lazy import keeps core importable without
    the live registries)."""
    from uda_tpu.analysis.core import Engine
    return Engine([cls() for cls in ALL_RULES], root=root)
