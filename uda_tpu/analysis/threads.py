"""The thread-root registry for the udarace lockset tier.

Eraser-style lockset inference (uda_tpu/analysis/race.py) is only as
good as its model of WHICH code runs on which thread. This module is
that model, in one auditable place: every thread entry point the
package spawns — the event-loop/dispatcher pair, the MOF writer router,
the merge pool workers, the overlap stage pool, the push scheduler's
completion callbacks, the spill ladder, and the daemon herd (watchdog,
profiler, StatsReporter, time-series rollup, scrub, tuncache,
openmetrics) — is DECLARED here as a :class:`ThreadRoot`, keyed by the
defining file and function name, exactly like the reference annotated
its pthread entry points in RDMAComm.cc comment blocks (only here the
table is machine-read, not prose).

The static tier walks the intra-package call graph from these roots
(plus the roots it auto-detects: ``Thread(target=...)`` spawn sites,
``@loop_callback`` bodies, ``call_soon``/``submit``/
``add_done_callback`` marshalling) and marks every function with the
set of roots that reach it. A ``self.<attr>`` touched from two or more
distinct roots is cross-thread shared state and must carry a
consistent lockset — or a justified ``# udarace: lockfree=`` waiver.

The runtime half mirrors the static one: :data:`RUNTIME_INSTRUMENTED`
declares, per hot class, the attributes ``utils/locks.py`` hooks with
its sampling Eraser state machine under ``UDA_TPU_RACEDET=1``. The
static↔runtime lockstep test (tests/test_udarace.py) fails the build
when the runtime instruments a class this table does not declare — the
two inventories must never drift.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

__all__ = ["ThreadRoot", "THREAD_ROOTS", "LOOP_ROOT", "POOL_ROOT",
           "RUNTIME_INSTRUMENTED", "declared_root"]


@dataclasses.dataclass(frozen=True)
class ThreadRoot:
    """One declared thread entry point.

    ``root`` is the thread identity the race tier reasons about (two
    accesses race only when their reaching-root sets differ); ``file``
    is a repo-relative path suffix and ``func`` the entry function's
    name — together they pin the declaration to one def without
    hardcoding line numbers.
    """

    root: str   # thread identity, e.g. "net.loop"
    file: str   # path suffix of the defining module
    func: str   # entry function name (bare, no class qualifier)
    note: str   # what runs here (documentation, lint report context)


# The shared event-loop thread identity: `@loop_callback` bodies and
# everything marshalled onto the loop via `call_soon` runs here.
LOOP_ROOT = "net.loop"
# The engine/executor pool identity: `submit()` fns and
# `add_done_callback` completions run on some pool worker.
POOL_ROOT = "pool"

THREAD_ROOTS: Tuple[ThreadRoot, ...] = (
    # -- the data-plane event loop + its dispatcher (PR 6) ---------------
    ThreadRoot(LOOP_ROOT, "net/evloop.py", "_run",
               "the selectors event-loop thread (all @loop_callback "
               "bodies and call_soon thunks run here)"),
    ThreadRoot("net.dispatcher", "net/evloop.py", "_dispatch_loop",
               "the completion dispatcher thread (potentially-blocking "
               "upcalls marshalled off the loop)"),
    ThreadRoot("net.drain", "net/server.py", "drain",
               "per-connection drain helper thread (warm handoff)"),
    # -- supplier storage / MOF plane ------------------------------------
    ThreadRoot("engine.router", "mofserver/data_engine.py", "_route",
               "the native-read completion router thread "
               "(_NativeReads: wakes submitters by tag)"),
    ThreadRoot("app.producer", "mofserver/writer.py", "write",
               "map-task producer thread(s): MOFWriter.write -> "
               "account_write -> spill ladder runs on each concurrent "
               "writer's own thread (bench/chaos drivers spawn several)"),
    ThreadRoot("app.control", "net/server.py", "announce_drain",
               "operator control-plane entry: the elastic drain API is "
               "invoked from the application main thread, concurrent "
               "with the data plane it drains"),
    # -- merge/overlap pools ---------------------------------------------
    ThreadRoot(POOL_ROOT, "ops/merge.py", "_part",
               "merge pool worker threads"),
    ThreadRoot("merge.overlap.worker", "merger/overlap.py",
               "_worker_loop", "overlap stage pool workers"),
    ThreadRoot("merge.overlap.consumer", "merger/overlap.py",
               "_consumer_loop", "overlap stage consumer thread"),
    ThreadRoot("merge.overlap.feeder", "merger/overlap.py", "_loop",
               "overlap feeder thread"),
    ThreadRoot("bridge.merge", "bridge/bridge.py", "_merge_main",
               "bridge-side merge thread"),
    # -- daemons ---------------------------------------------------------
    ThreadRoot("coding.scrub", "coding/scrub.py", "_run",
               "background parity scrub daemon"),
    ThreadRoot("watchdog", "utils/watchdog.py", "_watch",
               "stall watchdog daemon"),
    ThreadRoot("obs.timeseries", "utils/timeseries.py", "_loop",
               "time-series rollup daemon"),
    ThreadRoot("obs.stats", "utils/stats.py", "_loop",
               "StatsReporter daemon"),
    ThreadRoot("obs.openmetrics", "utils/openmetrics.py", "do_GET",
               "openmetrics exporter: ThreadingHTTPServer runs stdlib "
               "serve_forever; the in-tree code on those per-request "
               "threads is the handler's do_GET"),
    ThreadRoot("profiler", "utils/profiler.py", "_run",
               "sampling profiler daemon"),
    ThreadRoot("tuncache", "utils/tuncache.py", "_run",
               "tuning-cache writeback daemon"),
)


def declared_root(file_rel: str, func: str) -> Optional[ThreadRoot]:
    """The declared root whose (file suffix, function name) matches, or
    None. Path separators are normalized by the caller (the lint engine
    hands repo-relative forward-slash paths)."""
    for tr in THREAD_ROOTS:
        if func == tr.func and file_rel.endswith(tr.file):
            return tr
    return None


# -- the static <-> runtime lockstep inventory -------------------------------
#
# Per hot class (dotted module path -> class -> instrumented attrs):
# the EXACT attributes utils/locks.py race_instrument() hooks when
# UDA_TPU_RACEDET=1 is armed. The conn tables, staging ladders and
# credit ledgers here are the attributes the static tier convicted (or
# proved guarded) in this tree — the runtime machine re-checks the same
# state under chaos scheduling, and tests/test_udarace.py fails when
# the runtime hooks a class/attr this table does not declare.
RUNTIME_INSTRUMENTED: Dict[str, Tuple[str, ...]] = {
    # supplier push plane: subscription/commit/inflight tables mutated
    # by the loop thread, the MOFWriter thread and pool completions
    "uda_tpu.net.push.PushScheduler": ("_subs", "_commits", "_inflight"),
    # reduce-side staging ladder: loop-thread offers vs merge-side takes
    "uda_tpu.net.push.PushStaging": ("_maps",),
    # MOF store: migration log appended by the spill ladder (writer
    # thread) and drain/validate paths, read by snapshot/stats threads
    "uda_tpu.mofserver.store.StoreManager": ("_migrations",),
    # WDRR credit ledger: loop-thread-confined BY DESIGN (no locks) —
    # instrumented so the runtime machine PROVES the confinement under
    # chaos instead of trusting the docstring
    "uda_tpu.tenant.sched.CreditScheduler": ("_tenants",),
}
