"""udalint: AST invariant linter for the shuffle stack.

Four PRs accreted project invariants that lived only as prose or
fragile regexes (metrics registry membership, config-key declaration,
shutdown-before-close, structured-cause branching). This package makes
them machine-enforced: :mod:`uda_tpu.analysis.core` is a small rule
engine (one parented AST walk per file, ``# udalint: disable=<rule>``
suppressions, findings with file:line + rule id + fix hint) and
:mod:`uda_tpu.analysis.rules` the rule suite encoding the invariants.
``scripts/udalint.py`` is the CLI; ``scripts/build/ci.sh`` gates on it
before the test tiers.

The dynamic half of the same program — the runtime lock-order validator
— lives in :mod:`uda_tpu.utils.locks` (``UDA_TPU_LOCKDEP=1``).
"""

from uda_tpu.analysis.core import Engine, Finding, Rule, lint_paths
from uda_tpu.analysis.rules import ALL_RULES, default_engine

__all__ = ["Engine", "Finding", "Rule", "lint_paths", "ALL_RULES",
           "default_engine"]
