"""The udalint rule engine.

One engine pass per file: parse, attach parent pointers, collect
``# udalint: disable=...`` suppression comments (via tokenize, so a
comment anywhere on a physical line works), then walk the tree ONCE in
document order dispatching each node to every rule that registered
interest in its type. Rules are small classes; per-file state (alias
tables, path predicates) lives in the rule between ``begin_file`` and
``end_file``, shared read-only context (path, source lines) in the
:class:`FileContext`.

Suppression syntax (the rule id is case-insensitive)::

    sock.close()  # udalint: disable=UDA004        one rule
    ...           # udalint: disable=UDA004,UDA006 several
    ...           # udalint: disable=all           every rule

A suppression silences findings REPORTED on its physical line, so for a
multi-line statement the comment goes on the line the finding names
(the node's ``lineno`` — for an ``except`` handler, the ``except``
line; for a call, the line the call starts on).

Design notes: rules never re-walk the tree (the engine's single walk is
the contract — a rule that needs ancestry walks ``node.parent``
pointers up, never the tree down), and findings are plain data so the
CLI, the test fixtures and the check_metrics_names wrapper all consume
the same objects.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "FileContext", "Rule", "Engine", "lint_paths",
           "iter_py_files", "PARSE_RULE_ID"]

# a file that does not parse is itself a finding (the tree gate must
# fail loudly, not skip silently)
PARSE_RULE_ID = "UDA000"

_SUPPRESS_RE = re.compile(
    r"#\s*udalint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    file: str          # repo-relative path
    line: int          # 1-based
    col: int           # 0-based (ast convention)
    rule: str          # rule id, e.g. "UDA004"
    message: str       # what is wrong, specifically
    hint: str = ""     # how to fix it (the rule's standing advice)
    data: Optional[dict] = None  # rule-specific extras (wrappers use it)

    def render(self) -> str:
        out = f"{self.file}:{self.line}:{self.col}: {self.rule} {self.message}"
        if self.hint:
            out += f" [fix: {self.hint}]"
        return out


class FileContext:
    """Read-only per-file context handed to every rule callback."""

    def __init__(self, rel: str, source: str, tree: ast.AST):
        self.rel = rel
        self.source = source
        self.tree = tree
        self.in_net = "uda_tpu/net/" in rel.replace(os.sep, "/")
        self.basename = os.path.basename(rel)

    def is_docstring(self, node: ast.Constant) -> bool:
        """True when ``node`` is a module/class/function docstring (the
        first statement's bare constant)."""
        expr = getattr(node, "parent", None)
        if not isinstance(expr, ast.Expr):
            return False
        owner = getattr(expr, "parent", None)
        if not isinstance(owner, (ast.Module, ast.ClassDef,
                                  ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        body = owner.body
        return bool(body) and body[0] is expr


class Rule:
    """Base rule. Subclasses set ``rule_id``, ``hint`` and
    ``node_types`` and implement ``visit`` (and optionally
    ``begin_file``/``end_file`` for per-file state)."""

    rule_id: str = ""
    hint: str = ""
    description: str = ""
    node_types: Tuple[type, ...] = ()

    def begin_file(self, ctx: FileContext) -> None:
        pass

    def visit(self, node: ast.AST, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def end_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        """Tree-wide findings, reported once after the LAST file (rules
        that accumulate cross-file state: call graphs, lock-order
        edges). The engine applies each finding's own file's
        suppressions, same as per-file findings."""
        return ()

    def finding(self, ctx: FileContext, node: ast.AST, message: str,
                data: Optional[dict] = None) -> Finding:
        return Finding(ctx.rel, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0),
                       self.rule_id, message, self.hint, data)


def _suppressions(source: str) -> Dict[int, Set[str]]:
    """line (1-based) -> set of suppressed rule ids ("ALL" = every)."""
    out: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group(1).split(",")
                     if r.strip()}
            out.setdefault(tok.start[0], set()).update(rules)
    except tokenize.TokenError:
        pass  # the parse-error finding covers broken files
    return out


def _iter_parented(tree: ast.AST) -> Iterable[ast.AST]:
    """Document-order (preorder) walk that stamps ``node.parent``."""
    # stamp the WHOLE tree first: a rule visiting a node may walk
    # parent pointers up from anywhere in that node's subtree (e.g.
    # UDA005 resolving which except-handler bound the name inside a
    # nested str(e) call)
    tree.parent = None  # type: ignore[attr-defined]
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]
    stack = [tree]
    while stack:
        node = stack.pop()
        yield node
        stack.extend(reversed(list(ast.iter_child_nodes(node))))


def iter_py_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories to a sorted list of ``.py`` files
    (``__pycache__`` pruned)."""
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            files.extend(os.path.join(dirpath, fn)
                         for fn in filenames if fn.endswith(".py"))
    return sorted(files)


class Engine:
    """Runs a rule set over sources; one parented walk per file."""

    def __init__(self, rules: Sequence[Rule], root: Optional[str] = None):
        self.rules = list(rules)
        self.root = root  # rel-path anchor; None = leave paths as given
        self._dispatch: Dict[type, List[Rule]] = {}
        # per-file suppression tables, kept so finalize() findings (the
        # tree-wide rules) honor the same disable= comments
        self._suppressed: Dict[str, Dict[int, Set[str]]] = {}
        for rule in self.rules:
            for t in rule.node_types:
                self._dispatch.setdefault(t, []).append(rule)

    def _rel(self, path: str) -> str:
        if self.root:
            try:
                return os.path.relpath(path, self.root)
            except ValueError:
                pass
        return path

    def lint_source(self, source: str, rel: str) -> List[Finding]:
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            return [Finding(rel, e.lineno or 0, e.offset or 0,
                            PARSE_RULE_ID, f"file does not parse: {e.msg}",
                            "fix the syntax error")]
        ctx = FileContext(rel, source, tree)
        suppressed = _suppressions(source)
        self._suppressed[rel] = suppressed
        findings: List[Finding] = []
        for rule in self.rules:
            rule.begin_file(ctx)
        for node in _iter_parented(tree):
            for rule in self._dispatch.get(type(node), ()):
                findings.extend(rule.visit(node, ctx))
        for rule in self.rules:
            findings.extend(rule.end_file(ctx))
        if suppressed:
            findings = [
                f for f in findings
                if not (f.line in suppressed
                        and ("ALL" in suppressed[f.line]
                             or f.rule.upper() in suppressed[f.line]))]
        return findings

    def lint_file(self, path: str) -> List[Finding]:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        return self.lint_source(source, self._rel(path))

    def finish(self) -> List[Finding]:
        """Run every rule's tree-wide ``finalize`` hook (after all
        files have been linted) and filter the results through each
        finding's own file's suppression table."""
        findings: List[Finding] = []
        for rule in self.rules:
            findings.extend(rule.finalize())
        out = []
        for f in findings:
            supp = self._suppressed.get(f.file, {}).get(f.line)
            if supp and ("ALL" in supp or f.rule.upper() in supp):
                continue
            out.append(f)
        return out

    def lint_paths(self, paths: Sequence[str]) -> List[Finding]:
        findings: List[Finding] = []
        for path in iter_py_files(paths):
            findings.extend(self.lint_file(path))
        findings.extend(self.finish())
        findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
        return findings


def lint_paths(paths: Sequence[str], rules: Optional[Sequence[Rule]] = None,
               root: Optional[str] = None) -> List[Finding]:
    """Convenience entry point: lint ``paths`` with ``rules`` (default:
    the full suite from :mod:`uda_tpu.analysis.rules`)."""
    if rules is None:
        from uda_tpu.analysis.rules import ALL_RULES
        rules = [cls() for cls in ALL_RULES]
    return Engine(rules, root=root).lint_paths(paths)
