"""udarace — Eraser-style lockset inference over the udaflow CFG.

The third static-analysis tier. udalint (UDA001-009) checks single
nodes, udaflow (UDA101-103) checks paths *within* one function; this
module checks the property neither can see: that shared state is
touched with the right lock held *at all*. The bug class is the one
behind the PR 10 "gauge stuck at -1" double-settle and the PR 6
parked-request recursion — a ``self.<attr>`` mutated from two threads
where one access path skips the lock — which runtime gates only catch
when the unlucky interleaving actually happens in CI.

The analysis, per Eraser (Savage et al.) adapted to lexical Python:

1. **Thread roots** (uda_tpu/analysis/threads.py): every declared
   thread entry point, plus auto-detected ones — ``Thread(target=f)``
   spawn sites, ``@loop_callback`` bodies (the event-loop thread),
   ``call_soon(f)`` marshalling (also the loop thread), ``submit(f)`` /
   ``add_done_callback(f)`` (pool workers). A call-graph walk (name-
   keyed like UDA102, but ``self.m()`` calls resolve within the class)
   marks every function with the set of roots that reach it.

2. **Locksets**: for every ``self.<attr>`` access in a root-reachable
   method, the set of locks held — the lexical ``with <lock>:``
   ancestors (sound: ``with`` release is the finally-copy discipline
   made syntax) plus a CFG must-hold dataflow over explicit
   ``.acquire()``/``.release()`` pairs (finally copies from
   :mod:`uda_tpu.analysis.cfg` make a release-in-finally kill the
   obligation on BOTH continuations).

3. **Verdicts**, per (class, attribute) with accesses from >= 2
   distinct roots and at least one write:

   - every access lockset empty -> **UDA201** (unguarded shared
     attribute) unless waived by ``# udarace: lockfree=<attr>[,...]``
     with a justification;
   - a consistent lock exists but some access skips it -> **UDA202**
     (the check-then-act escape), anchored on the unguarded write;
   - every access holds SOME lock but no lock is common -> **UDA203**
     (mixed guards: two locks protect nothing).

   Findings carry one witness access per conflicting thread root, so
   the report reads like a runtime race report with line numbers
   instead of stacks.

Single-threaded state needs no annotation: a method no declared or
detected root reaches is owner-thread-confined (construction, main
test thread) and never convicts an attribute. That makes the
loop-thread-confined idioms (CreditScheduler, the evloop's parked
table) clean BY MODEL rather than by waiver — only genuinely
multi-root lock-free idioms (GIL-atomic deques, bool flags) need the
``# udarace: lockfree=`` comment, and each one must say why::

    # udarace: lockfree=_closed - bool flip, GIL-atomic, racing
    #     readers see the old value for at most one extra iteration

UDA204 (``WireExhaustivenessRule``) rides in the same module: the
``MSG_*`` inventory of net/wire.py must be total — every frame type
carries a ``WIRE_CODECS`` entry naming its encoder + strict decoder
(``None`` only with an on-line justification comment) and a dispatch
arm in net/server.py or net/client.py — so the next PR-19-style frame
family cannot land half-wired.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from uda_tpu.analysis.cfg import build_cfg
from uda_tpu.analysis.core import FileContext, Finding, Rule
from uda_tpu.analysis.flow import _LOCK_RE, _last_segment
from uda_tpu.analysis.threads import (LOOP_ROOT, POOL_ROOT,
                                      RUNTIME_INSTRUMENTED, declared_root)

# class names declared shared at runtime (threads.py) participate in
# the static tier even when they hold no lock (the loop-confined
# no-lock-by-design classes the runtime machine watches)
_DECLARED_SHARED = {key.rsplit(".", 1)[1] for key in RUNTIME_INSTRUMENTED}

__all__ = ["RaceLocksetRule", "WireExhaustivenessRule"]

# `# udarace: lockfree=_a,_b - why` — the waiver for deliberate
# GIL-atomic idioms. The justification after the dash is REQUIRED; a
# bare waiver is itself a finding (suppressions must carry their why).
_LOCKFREE_RE = re.compile(
    r"#\s*udarace:\s*lockfree=([A-Za-z0-9_,\s]*[A-Za-z0-9_])"
    r"(?:\s*[-–—]\s*(\S.*))?")

# container-mutating method calls: `self._tab.append(x)` is a WRITE of
# the shared table, not a read of the attribute binding
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "add",
             "insert", "remove", "discard", "pop", "popleft", "popitem",
             "clear", "update", "setdefault", "sort", "reverse",
             "put", "put_nowait"}

# dunders + teardown: pre-publication / owner-finalized, never
# contribute accesses (Eraser's virgin state, decided lexically)
_CONFINED_METHODS = {"__init__", "__new__", "__del__", "__repr__"}


@dataclasses.dataclass
class _Access:
    attr: str
    write: bool
    line: int
    col: int
    locks: FrozenSet[str]


@dataclasses.dataclass
class _Def:
    file: str
    cls: str                      # enclosing class name, "" at module level
    name: str                     # function name
    line: int
    accesses: List[_Access]
    calls: List[Tuple[str, str]]  # ("self", m) -> same class; ("", m) -> any
    roots: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class _ClassInfo:
    file: str
    name: str
    line: int
    end_line: int
    # attr -> (waiver line, justification or None)
    lockfree: Dict[str, Tuple[int, Optional[str]]] = \
        dataclasses.field(default_factory=dict)


def _expr_key(node: ast.AST) -> Optional[str]:
    """Dotted source form of a lock reference ('self._lock', 'mu'), or
    None when it is not a plain name/attribute chain."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


# Broader than flow.py's _LOCK_RE: the lockset tier must also accept
# suffixed names (`_inflight_cv`, `_state_lock`, `_forest_lock`) —
# missing one turns a correctly guarded access into a false UDA201.
_LOCK_SUFFIX_RE = re.compile(
    r"[a-z0-9_]*(?:lock|cv|cond(?:ition)?|mu(?:tex)?|sem(?:aphore)?)")


def _is_lock_ref(node: ast.AST) -> Optional[str]:
    """The lock key when ``node`` looks like a lock reference (its last
    segment matches the shared lock-name shape), else None."""
    seg = _last_segment(node)
    if seg is not None and (_LOCK_RE.fullmatch(seg)
                            or _LOCK_SUFFIX_RE.fullmatch(seg)):
        return _expr_key(node)
    return None


class RaceLocksetRule(Rule):
    """UDA201/202/203: guarded-field lockset analysis (see the module
    docstring). One collector emits all three verdicts — they are one
    analysis with three failure shapes, like UDA101's leak kinds."""

    rule_id = "UDA201"
    description = ("udarace lockset tier: shared attributes reachable "
                   "from >= 2 thread roots must hold one consistent "
                   "TrackedLock on every access (UDA201 unguarded / "
                   "UDA202 lock-skipping access / UDA203 mixed locks)")
    hint = ("guard every access with the class's lock, or — for a "
            "deliberate GIL-atomic idiom — waive the attribute with "
            "`# udarace: lockfree=<attr> - <why>` inside the class")
    node_types = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                  ast.Assign)

    def __init__(self) -> None:
        self._defs: List[_Def] = []
        self._classes: Dict[Tuple[str, str], _ClassInfo] = {}
        # seed roots: (root id, callee ref, enclosing class) resolved
        # like call edges
        self._spawned: List[Tuple[str, Tuple[str, str], str]] = []
        # classes that DECLARE lock discipline (own a TrackedLock /
        # TrackedCondition attr): the static tier's conviction scope.
        # Function-level reachability cannot see instance confinement,
        # so lock-less helper classes (per-request cursors, histogram
        # cells) must not convict — a class enters the tier by holding
        # a lock or by being declared shared in analysis/threads.py.
        self._locked_classes: Set[str] = set()
        # variable/attr name -> ctor class names seen assigned to it
        # (`self.store = StoreManager(...)`): receiver-informed call
        # resolution, the UDA103 lock-var-table idiom
        self._ctor_vars: Dict[str, Set[str]] = {}

    # -- collection ----------------------------------------------------------

    def begin_file(self, ctx: FileContext) -> None:
        self._lines = ctx.source.splitlines()

    def visit(self, node, ctx: FileContext) -> Iterable[Finding]:
        if isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Call):
                ctor = _last_segment(node.value.func)
                if ctor is not None and ctor[:1].isupper():
                    for tgt in node.targets:
                        name = _last_segment(tgt)
                        if name:
                            self._ctor_vars.setdefault(
                                name, set()).add(ctor)
            return ()
        if isinstance(node, ast.ClassDef):
            info = _ClassInfo(ctx.rel, node.name, node.lineno,
                              getattr(node, "end_lineno", node.lineno))
            for lno in range(info.line, info.end_line + 1):
                if lno > len(self._lines):
                    break
                m = _LOCKFREE_RE.search(self._lines[lno - 1])
                if m:
                    just = m.group(2)
                    for attr in m.group(1).split(","):
                        attr = attr.strip()
                        if attr:
                            info.lockfree[attr] = (lno, just)
            self._classes[(ctx.rel, node.name)] = info
            return ()
        # FunctionDef / AsyncFunctionDef: one def record; nested defs
        # get their own visit (and their accesses stay out of ours)
        cls = self._enclosing_class(node)
        d = _Def(ctx.rel, cls, node.name, node.lineno, [], [])
        if self._is_loop_callback(node):
            d.roots.add(LOOP_ROOT)
        tr = declared_root(ctx.rel.replace("\\", "/"), node.name)
        if tr is not None:
            d.roots.add(tr.root)
        self._scan(node, d, ctx)
        self._defs.append(d)
        return ()

    @staticmethod
    def _enclosing_class(node: ast.AST) -> str:
        cur = getattr(node, "parent", None)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return ""  # a def nested in a method is not a method
            cur = getattr(cur, "parent", None)
        return ""

    @staticmethod
    def _is_loop_callback(node) -> bool:
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _last_segment(target) == "loop_callback":
                return True
        return False

    @staticmethod
    def _callee_ref(func_expr: ast.AST) -> Optional[Tuple[str, str]]:
        """A call-edge reference: ('self', m) for self.m,
        ('recv:<name>', m) for <something>.<name>.m — the receiver name
        feeds the ctor-var table — and ('', m) for bare names."""
        if isinstance(func_expr, ast.Attribute):
            if isinstance(func_expr.value, ast.Name) \
                    and func_expr.value.id == "self":
                return ("self", func_expr.attr)
            recv = _last_segment(func_expr.value)
            if recv is not None and recv != "self":
                return (f"recv:{recv}", func_expr.attr)
            return ("", func_expr.attr)
        if isinstance(func_expr, ast.Name):
            return ("", func_expr.id)
        return None

    def _scan(self, func, d: _Def, ctx: FileContext) -> None:
        """One pass over the method body: attribute accesses with their
        lexical lock context, call edges, and spawn/marshal sites."""
        must_hold = _cfg_must_hold(func)
        stack: List[ast.AST] = list(func.body)
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue  # deferred code: its own def record / opaque
            if isinstance(cur, ast.Call):
                self._scan_call(cur, d)
            elif isinstance(cur, ast.Attribute) \
                    and isinstance(cur.value, ast.Name) \
                    and cur.value.id == "self" \
                    and not _LOCK_RE.fullmatch(cur.attr) \
                    and not _LOCK_SUFFIX_RE.fullmatch(cur.attr):
                write = self._is_write(cur)
                if write is not None:
                    locks = self._held_at(cur, func, must_hold)
                    d.accesses.append(_Access(
                        cur.attr, write, cur.lineno, cur.col_offset,
                        locks))
            stack.extend(ast.iter_child_nodes(cur))

    def _scan_call(self, call: ast.Call, d: _Def) -> None:
        seg = _last_segment(call.func)
        if seg is None:
            return
        if seg in ("TrackedLock", "TrackedCondition"):
            parent = getattr(call, "parent", None)
            if isinstance(parent, ast.Assign) and d.cls:
                self._locked_classes.add(d.cls)
            return
        if seg == "Thread":
            # spawns in driver/benchmark scripts are not data-plane
            # roots: a script thread exercises one private instance,
            # and counting it would manufacture multi-rootness for
            # whatever pipeline the benchmark drives
            if "uda_tpu" not in d.file.replace("\\", "/"):
                return
            for kw in call.keywords:
                if kw.arg == "target":
                    ref = self._callee_ref(kw.value)
                    if ref is not None:
                        tr = declared_root(d.file.replace("\\", "/"),
                                           ref[1])
                        root = tr.root if tr is not None else \
                            f"thread:{d.file}:{call.lineno}"
                        self._spawned.append((root, ref, d.cls))
            return
        marshal = {"call_soon": LOOP_ROOT, "submit": POOL_ROOT,
                   "add_done_callback": POOL_ROOT}.get(seg)
        if marshal is not None and call.args:
            ref = self._callee_ref(call.args[0])
            if ref is not None:
                self._spawned.append((marshal, ref, d.cls))
        ref = self._callee_ref(call.func)
        if ref is not None:
            d.calls.append(ref)

    @staticmethod
    def _is_write(attr: ast.Attribute) -> Optional[bool]:
        """True write / False read / None not-an-access (the attribute
        is itself a method being called: self.m() is a call edge)."""
        if isinstance(attr.ctx, (ast.Store, ast.Del)):
            return True
        parent = getattr(attr, "parent", None)
        if isinstance(parent, ast.Call) and parent.func is attr:
            return None  # self.m(...): the call edge covers it
        if isinstance(parent, ast.Attribute) \
                and parent.attr in _MUTATORS:
            grand = getattr(parent, "parent", None)
            if isinstance(grand, ast.Call) and grand.func is parent:
                return True  # self._tab.append(...): container write
        if isinstance(parent, ast.Subscript) \
                and isinstance(parent.ctx, (ast.Store, ast.Del)) \
                and parent.value is attr:
            return True      # self._tab[k] = ...: container write
        if isinstance(parent, ast.AugAssign) and parent.target is attr:
            return True
        return False

    @staticmethod
    def _held_at(node: ast.AST, func, must_hold) -> FrozenSet[str]:
        """Locks held at ``node``: lexical `with <lock>:` ancestors
        inside ``func`` + the CFG must-hold set of the enclosing
        statement (explicit acquire/release pairs)."""
        held: Set[str] = set()
        stmt = None
        cur = getattr(node, "parent", None)
        prev: ast.AST = node
        while cur is not None and cur is not func:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                # held only when we came from the BODY (the header's
                # context expressions evaluate before __enter__)
                if prev in cur.body:
                    for item in cur.items:
                        key = _is_lock_ref(item.context_expr)
                        if key is not None:
                            held.add(key)
            if isinstance(cur, ast.stmt):
                stmt = cur
            prev = cur
            cur = getattr(cur, "parent", None)
        if stmt is not None:
            held |= must_hold.get(id(stmt), frozenset())
        return frozenset(held)

    # -- the verdicts --------------------------------------------------------

    def _resolve(self, ref: Tuple[str, str], cls: str) -> List[int]:
        """Call-edge resolution: indexes of the defs a reference can
        mean. ``self.m`` binds strictly within the class; a bare name
        binds ONLY when the tree defines it exactly once — resolving a
        generic name (`set`, `close`, `run`) to every same-named def
        would smear thread roots across unrelated subsystems (the
        UDA102 generic-name problem, solved here by abstention: a
        missed edge costs a missed finding, never a false one)."""
        kind, name = ref
        hits = [i for i, d in enumerate(self._defs) if d.name == name]
        if kind == "self":
            return [i for i in hits if self._defs[i].cls == cls]
        if kind.startswith("recv:"):
            # receiver-informed: `self.store.drain()` resolves into the
            # one class ever constructed into a var/attr named `store`
            classes = {c for c in self._ctor_vars.get(kind[5:], ())
                       if any(self._defs[i].cls == c for i in hits)}
            if len(classes) == 1:
                tgt = next(iter(classes))
                return [i for i in hits if self._defs[i].cls == tgt]
        return hits if len(hits) == 1 else []

    def _propagate_roots(self) -> None:
        for root, ref, cls in self._spawned:
            for i in self._resolve(ref, cls):
                self._defs[i].roots.add(root)
        work = [i for i, d in enumerate(self._defs) if d.roots]
        while work:
            i = work.pop()
            d = self._defs[i]
            for ref in d.calls:
                for j in self._resolve(ref, d.cls):
                    tgt = self._defs[j]
                    if not d.roots <= tgt.roots:
                        tgt.roots |= d.roots
                        work.append(j)

    def finalize(self) -> Iterable[Finding]:
        self._propagate_roots()
        in_scope = self._locked_classes | _DECLARED_SHARED
        by_class: Dict[Tuple[str, str], List[Tuple[_Def, _Access]]] = {}
        for d in self._defs:
            if not d.cls or d.cls not in in_scope or not d.roots \
                    or d.name in _CONFINED_METHODS:
                continue
            for a in d.accesses:
                by_class.setdefault((d.file, d.cls), []).append((d, a))
        findings: List[Finding] = []
        for (file, cls), pairs in sorted(by_class.items()):
            info = self._classes.get((file, cls))
            by_attr: Dict[str, List[Tuple[_Def, _Access]]] = {}
            for d, a in pairs:
                by_attr.setdefault(a.attr, []).append((d, a))
            for attr, acc in sorted(by_attr.items()):
                findings.extend(self._judge(file, cls, attr, acc, info))
        # bare waivers: a lockfree= with no justification is itself a
        # finding — every suppression carries its why
        for (file, cls), info in sorted(self._classes.items()):
            for attr, (lno, just) in sorted(info.lockfree.items()):
                if just is None or not just.strip():
                    findings.append(Finding(
                        file, lno, 0, "UDA201",
                        f"lockfree waiver for {cls}.{attr} carries no "
                        f"justification",
                        "append ` - <why this is GIL-atomic/confined>` "
                        "to the waiver comment"))
        findings.sort(key=lambda f: (f.file, f.line, f.rule))
        return findings

    def _judge(self, file: str, cls: str, attr: str,
               acc: List[Tuple[_Def, _Access]],
               info: Optional[_ClassInfo]) -> Iterable[Finding]:
        roots: Set[str] = set()
        for d, _ in acc:
            roots |= d.roots
        writes = [(d, a) for d, a in acc if a.write]
        if len(roots) < 2 or not writes:
            return ()
        if info is not None and attr in info.lockfree:
            return ()  # waived (bare waivers are reported separately)
        common = frozenset.intersection(*[a.locks for _, a in acc])
        if common:
            return ()  # consistently guarded
        witnesses = {}
        for root in sorted(roots):
            for d, a in acc:
                if root in d.roots:
                    witnesses[root] = (f"{d.file}:{a.line} "
                                       f"({'write' if a.write else 'read'}"
                                       f" in {cls}.{d.name}, locks="
                                       f"{sorted(a.locks) or '[]'})")
                    break
        data = {"class": cls, "attr": attr,
                "roots": sorted(roots), "witnesses": witnesses}
        held_sets = {a.locks for _, a in acc}
        d0, a0 = writes[0]
        if all(not s for s in held_sets):
            return (Finding(
                file, a0.line, a0.col, "UDA201",
                f"{cls}.{attr} is written with NO lock held but is "
                f"reachable from {len(roots)} thread roots "
                f"({', '.join(sorted(roots))}); witnesses: "
                f"{'; '.join(f'{r}: {w}' for r, w in witnesses.items())}",
                self.hint, data),)
        # some accesses hold a lock: either an escape (empty lockset
        # somewhere) or mixed guards (all non-empty, no intersection)
        bare = [(d, a) for d, a in acc if not a.locks]
        if bare:
            tally: Dict[str, int] = {}
            for _, a in acc:
                for lk in a.locks:
                    tally[lk] = tally.get(lk, 0) + 1
            inferred = max(tally, key=lambda k: tally[k])
            d1, a1 = next(((d, a) for d, a in bare if a.write), bare[0])
            return (Finding(
                file, a1.line, a1.col, "UDA202",
                f"{cls}.{attr} is guarded by {inferred!r} elsewhere but "
                f"this {'write' if a1.write else 'read'} "
                f"(in {d1.name}) holds no lock — the check-then-act "
                f"escape; witnesses: "
                f"{'; '.join(f'{r}: {w}' for r, w in witnesses.items())}",
                f"move the access under `with {inferred}:` (or waive "
                f"with `# udarace: lockfree={attr} - <why>`)", data),)
        locksets = sorted({tuple(sorted(s)) for s in held_sets})
        return (Finding(
            file, a0.line, a0.col, "UDA203",
            f"{cls}.{attr} is guarded by DIFFERENT locks on different "
            f"paths ({' vs '.join(str(list(s)) for s in locksets)}) — "
            f"no common lock, mutual exclusion protects nothing; "
            f"witnesses: "
            f"{'; '.join(f'{r}: {w}' for r, w in witnesses.items())}",
            "pick ONE lock for this attribute and use it on every "
            "access", data),)


def _cfg_must_hold(func) -> Dict[int, FrozenSet[str]]:
    """Forward must-hold dataflow over explicit ``X.acquire()`` /
    ``X.release()`` pairs: id(stmt ast) -> locks held ON ENTRY to that
    statement on EVERY path. `with` blocks are handled lexically by the
    caller (the CFG has no with-exit node); this pass exists for the
    manual-pair shape, where the finally-copy discipline of
    :func:`build_cfg` is what makes `release()` in a finally kill the
    obligation on both the normal and exceptional continuation."""
    acquires: Set[str] = set()
    for sub in ast.walk(func):
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in ("acquire", "release"):
            key = _expr_key(sub.func.value)
            if key is not None and not key.startswith("self.__"):
                acquires.add(key)
    if not acquires:
        return {}
    try:
        cfg = build_cfg(func)
    except RecursionError:
        return {}
    universe = frozenset(acquires)

    def transfer(node, state: FrozenSet[str]) -> FrozenSet[str]:
        out = set(state)
        for e in node.exprs:
            for sub in ast.walk(e):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute):
                    key = _expr_key(sub.func.value)
                    if key is None:
                        continue
                    if sub.func.attr == "acquire":
                        out.add(key)
                    elif sub.func.attr == "release":
                        out.discard(key)
        return frozenset(out)

    n = len(cfg.nodes)
    in_state: List[FrozenSet[str]] = [universe] * n
    in_state[cfg.entry] = frozenset()
    work = [cfg.entry]
    while work:
        i = work.pop()
        node = cfg.nodes[i]
        out_norm = transfer(node, in_state[i])
        out_exc = in_state[i]  # an acquire that raised did not acquire
        for succs, out in ((node.norm_succs, out_norm),
                           (node.exc_succs, out_exc)):
            for s in succs:
                met = in_state[s] & out
                if met != in_state[s]:
                    in_state[s] = met
                    work.append(s)
    result: Dict[int, FrozenSet[str]] = {}
    for node in cfg.nodes:
        if node.stmt is None:
            continue
        key = id(node.stmt)
        # finally copies: the same stmt can appear on several nodes —
        # must-hold means the intersection over every copy
        result[key] = result.get(key, universe) & in_state[node.index]
    return result


# -- UDA204 ------------------------------------------------------------------

class WireExhaustivenessRule(Rule):
    """UDA204: the MSG_* frame inventory must be total (see the module
    docstring). Tree-wide: wire.py declares the constants and the
    ``WIRE_CODECS`` encoder/decoder table; server.py/client.py provide
    the dispatch arms; finalize() joins the three."""

    rule_id = "UDA204"
    description = ("every MSG_* frame type carries a WIRE_CODECS "
                   "encoder/decoder entry and a dispatch arm in "
                   "net/server.py or net/client.py")
    hint = ("add the WIRE_CODECS entry (decoder None needs an on-line "
            "justification comment) and wire the dispatch arm, or "
            "remove the dead constant")
    node_types = (ast.Assign, ast.FunctionDef, ast.Compare)

    def __init__(self) -> None:
        # constant name -> (file, line)
        self._consts: Dict[str, Tuple[str, int]] = {}
        # constant name -> (encoder, decoder-or-None, line, has_comment)
        self._codecs: Dict[str, Tuple[Optional[str], Optional[str],
                                      int, bool]] = {}
        self._codecs_file: Optional[str] = None
        self._wire_funcs: Set[str] = set()
        self._dispatched: Set[str] = set()
        self._saw_dispatch_file = False

    def begin_file(self, ctx: FileContext) -> None:
        self._in_wire = ctx.basename == "wire.py" and ctx.in_net
        self._in_dispatch = (ctx.basename in ("server.py", "client.py")
                             and ctx.in_net)
        if self._in_dispatch:
            self._saw_dispatch_file = True

    def visit(self, node, ctx: FileContext) -> Iterable[Finding]:
        if isinstance(node, ast.Compare):
            if self._in_dispatch:
                for sub in ast.walk(node):
                    seg = _last_segment(sub) \
                        if isinstance(sub, (ast.Name, ast.Attribute)) \
                        else None
                    if seg and seg.startswith("MSG_"):
                        self._dispatched.add(seg)
            return ()
        if not self._in_wire:
            return ()
        if isinstance(node, ast.FunctionDef):
            self._wire_funcs.add(node.name)
            return ()
        # ast.Assign in wire.py
        for tgt in node.targets:
            if not isinstance(tgt, ast.Name):
                continue
            if tgt.id.startswith("MSG_") \
                    and isinstance(node.value, ast.Constant):
                self._consts[tgt.id] = (ctx.rel, node.lineno)
            elif tgt.id == "WIRE_CODECS" \
                    and isinstance(node.value, ast.Dict):
                self._codecs_file = ctx.rel
                self._take_codecs(node.value, ctx)
        return ()

    def _take_codecs(self, d: ast.Dict, ctx: FileContext) -> None:
        lines = ctx.source.splitlines()
        for key, val in zip(d.keys, d.values):
            seg = _last_segment(key) if key is not None else None
            if seg is None or not seg.startswith("MSG_"):
                continue
            enc = dec = None
            if isinstance(val, (ast.Tuple, ast.List)) \
                    and len(val.elts) == 2:
                e0, e1 = val.elts
                if isinstance(e0, ast.Constant) \
                        and isinstance(e0.value, str):
                    enc = e0.value
                if isinstance(e1, ast.Constant) \
                        and isinstance(e1.value, str):
                    dec = e1.value
            line = getattr(val, "lineno", d.lineno)
            end = getattr(val, "end_lineno", line)
            has_comment = any("#" in lines[ln - 1]
                              for ln in range(line, end + 1)
                              if ln <= len(lines))
            self._codecs[seg] = (enc, dec, line, has_comment)

    def finalize(self) -> Iterable[Finding]:
        if not self._consts:
            return ()
        findings: List[Finding] = []
        for const, (file, line) in sorted(self._consts.items()):
            entry = self._codecs.get(const)
            if entry is None:
                findings.append(Finding(
                    file, line, 0, self.rule_id,
                    f"{const} has no WIRE_CODECS entry — the frame "
                    f"family is half-wired (no declared encoder/strict "
                    f"decoder)", self.hint))
                continue
            enc, dec, eline, has_comment = entry
            efile = self._codecs_file or file
            if enc is None or enc not in self._wire_funcs:
                findings.append(Finding(
                    efile, eline, 0, self.rule_id,
                    f"{const}: declared encoder "
                    f"{enc!r} is not defined in wire.py", self.hint))
            if dec is None:
                if not has_comment:
                    findings.append(Finding(
                        efile, eline, 0, self.rule_id,
                        f"{const}: decoder is None without an on-line "
                        f"justification comment (empty-payload / "
                        f"reserved frames must say so)", self.hint))
            elif dec not in self._wire_funcs:
                findings.append(Finding(
                    efile, eline, 0, self.rule_id,
                    f"{const}: declared decoder "
                    f"{dec!r} is not defined in wire.py", self.hint))
            if self._saw_dispatch_file \
                    and const not in self._dispatched:
                findings.append(Finding(
                    file, line, 0, self.rule_id,
                    f"{const} has no dispatch arm in net/server.py or "
                    f"net/client.py — a peer sending it gets silence "
                    f"or a generic unsupported-frame error",
                    self.hint))
        return findings
