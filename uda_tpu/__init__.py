"""uda_tpu — a TPU-native shuffle/merge framework.

A ground-up rebuild of the capabilities of Mellanox/Auburn UDA (the Hadoop
MapReduce shuffle accelerator: RDMA data plane + network-levitated k-way
merge) designed for TPU hardware:

- XLA collectives (``all_to_all``/``ppermute``) over ICI/DCN replace the
  ibverbs RDMAClient/RDMAServer queue-pair transport (reference
  src/DataNet/).
- Map-output IFile segments are staged into HBM arenas instead of
  registered, pinned host memory (reference src/MOFServer/IndexInfo.cc).
- The reduce-side priority-queue merge (reference src/Merger/MergeQueue.h,
  StreamRW.cc) becomes device-resident sort/merge over fixed-stride
  normalized key columns, with a host fallback for correctness diffing.
- The UdaBridge control surface (startNative/doCommand + 6 up-calls,
  reference src/UdaBridge.cc) is preserved as a Python/C control plane.

Byte-level compatibility: Hadoop zero-compressed VInt/VLong, IFile record
framing (VInt klen, VInt vlen, key, value, EOF = -1/-1), and RawComparator
ordering semantics are preserved exactly (see uda_tpu.utils).
"""

from uda_tpu.version import __version__

__all__ = ["__version__"]
