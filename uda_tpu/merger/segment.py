"""Segments: streaming views of one map-output partition.

Equivalent of the reference's Segment/BaseSegment (reference
src/Merger/StreamRW.cc:334-590): a segment pulls its partition's bytes
chunk by chunk through an InputClient, handling records that break across
chunk boundaries. The reference does this with double-buffered RDMA
fetches and a cond-wait ``switch_mem`` that ``join``s the split record
into ``temp_kv`` (StreamRW.cc:462-590); here the same contract is a
*carry buffer*: each chunk is columnar-cracked up to its last complete
record and the partial tail is prepended to the next chunk.

``InputClient`` is the transport abstraction of reference
src/Merger/InputClient.h:30-56 (``start_fetch_req``/``comp_fetch_req``):
implementations are LocalFetchClient (single host, over the DataEngine)
and the mesh exchange client (uda_tpu.parallel).
"""

from __future__ import annotations

import abc
import random
import threading
import time
import zlib
from typing import Optional

from uda_tpu.mofserver.data_engine import DataEngine, FetchResult, ShuffleRequest
from uda_tpu.tenant import current_tenant
from uda_tpu.utils.errors import (MergeError, StorageError, TenantError,
                                  TransportError, attribute_supplier)
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.flightrec import flightrec
from uda_tpu.utils.ifile import RecordBatch, crack_partial
from uda_tpu.utils.locks import TrackedLock
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics
from uda_tpu.utils.retry import RetryPolicy, SpeculationPolicy

log = get_logger()

__all__ = ["InputClient", "LocalFetchClient", "HostRoutingClient",
           "Segment"]


class InputClient(abc.ABC):
    """Transport abstraction (reference InputClient.h:30-56)."""

    @abc.abstractmethod
    def start_fetch(self, req: ShuffleRequest, on_complete) -> None:
        """Async fetch; ``on_complete(FetchResult | Exception)``."""

    def estimate_partition_bytes(self, job_id: str, map_ids,
                                 reduce_id: int):
        """Best-effort on-disk size of this reduce partition across
        ``map_ids``, or None when the transport cannot know it without
        fetching (the auto merge-approach policy then defaults to the
        bounded-memory path — see MergeManager.run)."""
        return None

    def resume_ok(self, host: str = "") -> bool:
        """May a retrying Segment keep its offset ledger and resume
        mid-partition instead of refetching from zero
        (``uda.tpu.fetch.resume``)? True by default — MOFs are
        immutable files, so a byte range re-read after a transport
        blip is the same bytes. Transports with per-stream state
        (DecompressingClient) or evidence of a cold supplier restart
        (RemoteFetchClient's generation tracking) answer False; the
        Segment then restarts the whole fetch."""
        return True

    def speculate_ok(self) -> bool:
        """May the straggler detector issue a DUPLICATE in-flight fetch
        for the same (job, map, reduce) through this transport? True by
        default — stateless transports serve concurrent duplicates
        independently. Transports with per-stream state keyed on the
        partition (DecompressingClient's sequential stream claim)
        answer False: a duplicate would steal the stream token and turn
        the healthy primary's completion into a fabricated fault."""
        return True

    def generation(self, host: str = "") -> Optional[int]:
        """The supplier's observed restart generation for ``host`` (the
        HELLO banner's counter), or None when the transport has no
        generation concept or has not connected yet. The checkpoint
        resume path (merger/checkpoint.py) compares a manifest's
        recorded generation against this: a changed generation means
        the supplier restarted since the ledger was written, so the
        offset ledger is dropped and that segment re-fetches from zero
        (its run files, being self-contained, are kept)."""
        return None

    def recover_partition(self, req: ShuffleRequest, ctx,
                          on_complete) -> bool:
        """k-of-n stripe reconstruction (uda_tpu.coding): rebuild
        ``req``'s whole partition from any k of its n stripe chunks,
        delivering a full-partition FetchResult (or an Exception) to
        ``on_complete``. Returns False when unsupported (no stripe
        context) — the Segment then fails terminally as before. The
        default implementation drives the generic recovery over THIS
        transport's ``start_fetch`` (shard pseudo-maps route per host
        like any other fetch); wrappers that transform the byte domain
        (DecompressingClient) override to re-wrap the result."""
        if ctx is None:
            return False
        from uda_tpu.coding.recovery import start_recovery

        start_recovery(self, req, ctx, on_complete)
        return True

    def stop(self) -> None:
        pass


class LocalFetchClient(InputClient):
    """Single-host client: fetches straight from a DataEngine (the
    minimum end-to-end slice of SURVEY §7.3)."""

    def __init__(self, engine: DataEngine):
        self.engine = engine

    def start_fetch(self, req: ShuffleRequest, on_complete) -> None:
        fut = self.engine.submit(req)

        def _done(f):
            err = f.exception()
            on_complete(err if err is not None else f.result())

        fut.add_done_callback(_done)

    def estimate_partition_bytes(self, job_id: str, map_ids,
                                 reduce_id: int):
        """Sum of raw_length over the map outputs (the spill-index
        triples the supplier serves from; resolution is cached by the
        engine's resolver). raw_length — the UNCOMPRESSED record bytes
        — is what the merge will actually hold, so the estimate stays
        correct through a DecompressingClient wrap (for uncompressed
        jobs raw == part). Exact-or-unknown: ANY unresolvable map makes
        the whole estimate None — a partial sum is a lower bound, and a
        lower bound could steer the auto policy onto the host-resident
        path for a partition that is actually huge. Fetch itself still
        fails loudly on a truly missing MOF."""
        total = 0
        for mid in map_ids:
            try:
                total += int(self.engine.resolver.resolve(
                    job_id, mid, reduce_id).raw_length)
            except Exception as e:  # noqa: BLE001 - exact-or-unknown:
                # the estimate degrades to None, but never silently —
                # a perpetually-unresolvable index would otherwise hide
                # behind "the auto policy just picked streaming again"
                metrics.add("errors.swallowed")
                log.debug(f"size estimate: {mid} unresolvable ({e}); "
                          f"partition size unknown")
                return None
        return total


class HostRoutingClient(InputClient):
    """Per-supplier-host transport table with lazy connect.

    The reference's reduce-side client opens one RDMA connection per
    supplier host ON FIRST USE and caches it (connect-per-host with DNS
    cache, reference src/DataNet/RDMAClient.cc:498-527, 602-629). Here
    ``connect(host)`` builds the host's transport (e.g. a
    LocalFetchClient over that host's DataEngine, or a remote client)
    the first time a fetch addresses it; every later fetch for the host
    reuses the cached transport. A failed connect surfaces through the
    fetch's completion callback like any transport error (the
    reference's connect-retry-then-fail path, RDMAClient.cc:215-356).

    With no ``connect`` callable the router defaults to the socket data
    plane: each host dials that supplier's ShuffleServer as
    ``host[:port]`` (port defaulting to ``uda.tpu.net.port``) through a
    :class:`~uda_tpu.net.client.RemoteFetchClient` — one multiplexed
    connection per supplier host, the deployed-service wiring.
    """

    def __init__(self, connect=None, config=None):
        self._connect = (connect if connect is not None
                         else self._socket_factory(config))
        self._clients: dict[str, InputClient] = {}
        self._stopped = False
        # elastic membership (ISSUE 18): joiners announced mid-job via
        # notify_join and leavers via notify_drain. Membership is
        # ADVISORY routing state — fetches still address whatever host
        # the entry names; the sets steer candidate ranking and let
        # MergeManager.notify_join widen in-flight segments.
        self._members: set[str] = set()
        self._draining: set[str] = set()
        # push plane (ISSUE 19): (job, reduce) -> staging, applied to
        # every transport the router builds — including transports
        # created (or re-dialed after refresh()) AFTER registration,
        # so a joiner/bounced supplier gets subscribed too
        self._push_regs: dict = {}
        self._lock = TrackedLock("host_router")

    @staticmethod
    def _socket_factory(config):
        """The default connect: dial ``host[:port]`` over TCP. Imported
        lazily (uda_tpu.net imports this module)."""
        def connect(host: str) -> InputClient:
            from uda_tpu.net.client import RemoteFetchClient
            from uda_tpu.utils.config import Config

            # accepted shapes: "name", "name:port", "[v6addr]:port",
            # and a bare IPv6 literal (2+ colons, no brackets)
            name, port = host, ""
            if host.startswith("["):
                name, bracket, rest = host[1:].partition("]")
                if not bracket or (rest and not rest.startswith(":")):
                    raise TransportError(
                        f"malformed supplier address {host!r}")
                port = rest[1:]
            elif host.count(":") == 1:
                name, _, port = host.partition(":")
            if not name:
                # an empty host would resolve to localhost and
                # misdirect the fetch to whatever listens there; fail
                # loudly instead (the entry was built without a
                # supplier host — a wiring bug, not a transport fault)
                raise TransportError(
                    "socket fetch routing needs a supplier host per "
                    "map entry; got an empty host")
            if port and not port.isdigit():
                raise TransportError(
                    f"malformed supplier port in {host!r}")
            cfg = config or Config()
            return RemoteFetchClient(
                name, int(port) if port else None, config=cfg)
        return connect

    def _client_for(self, host: str) -> InputClient:
        with self._lock:
            if self._stopped:
                raise MergeError("HostRoutingClient is stopped")
            client = self._clients.get(host)
        if client is None:
            client = self._connect(host)
            with self._lock:
                if self._stopped:
                    loser = client  # connected after stop(): tear down
                else:
                    # a concurrent connect for the same host may have
                    # won; the loser must be torn down, not leaked
                    winner = self._clients.setdefault(host, client)
                    loser = None if winner is client else client
                    client = winner
            if loser is not None:
                loser.stop()
            with self._lock:
                if self._stopped:
                    raise MergeError("HostRoutingClient is stopped")
                regs = list(self._push_regs.items())
            self._apply_push_regs(client, regs)
        return client

    @staticmethod
    def _apply_push_regs(client: InputClient, regs) -> None:
        """Subscribe an armed push registration on one transport.
        Duck-typed: transports without a push plane (LocalFetchClient,
        custom connects) simply stay pull-only."""
        reg = getattr(client, "push_register", None)
        if not callable(reg):
            return
        for (job_id, reduce_id), staging in regs:
            reg(job_id, reduce_id, staging)

    # -- push plane (ISSUE 19) -----------------------------------------------

    def push_register(self, job_id: str, reduce_id: int, staging,
                      hosts=None) -> None:
        """Register reduce-side staging across the supplier fleet:
        every cached transport subscribes now, every FUTURE transport
        (lazy first-fetch dial, join, post-refresh re-dial) subscribes
        at build time. ``hosts`` eagerly dials the named suppliers so
        pushes can arrive before the first fetch exists; dial failures
        are best-effort (those hosts stay pull-only until fetched)."""
        with self._lock:
            if self._stopped:
                return
            self._push_regs[(job_id, int(reduce_id))] = staging
            cached = list(self._clients.values())
        regs = [((job_id, int(reduce_id)), staging)]
        for client in cached:
            self._apply_push_regs(client, regs)
        for host in set(hosts or ()) | set(self.members()):
            try:
                self._client_for(host)  # _apply_push_regs rides the build
            except Exception:  # noqa: BLE001 - eager dial is advisory
                metrics.add("push.dial.failures", supplier=host)

    def push_unregister(self, job_id: str, reduce_id: int) -> None:
        with self._lock:
            self._push_regs.pop((job_id, int(reduce_id)), None)
            cached = list(self._clients.values())
        for client in cached:
            unreg = getattr(client, "push_unregister", None)
            if callable(unreg):
                unreg(job_id, reduce_id)

    def start_fetch(self, req: ShuffleRequest, on_complete) -> None:
        try:
            client = self._client_for(req.host)
        except Exception as e:  # noqa: BLE001 - connect failure ->
            on_complete(e)      # completion error, like the reference
            return
        client.start_fetch(req, on_complete)

    def resume_ok(self, host: str = "") -> bool:
        """Delegate to the host's transport (a RemoteFetchClient may
        have observed a cold supplier restart); an unconnected host is
        resumable by default — the reconnect itself revalidates."""
        with self._lock:
            client = self._clients.get(host)
        return True if client is None else client.resume_ok(host)

    def generation(self, host: str = "") -> Optional[int]:
        """Delegate to the host's transport; an unconnected host has no
        observed generation yet (None — the checkpoint resume path then
        accepts optimistically and lets the first resumed chunk's
        identity check revalidate)."""
        with self._lock:
            client = self._clients.get(host)
        return None if client is None else client.generation(host)

    # -- elastic membership (ISSUE 18) ---------------------------------------

    def notify_join(self, host: str) -> None:
        """A supplier registered mid-job (its banner carries
        CAP_ELASTIC): fold it into the membership ring and refresh any
        stale cached transport so the next fetch re-dials and observes
        the joiner's current generation."""
        with self._lock:
            already = host in self._members
            self._members.add(host)
            self._draining.discard(host)
        if not already:
            metrics.add("elastic.joins", supplier=host)
        self.refresh(host)

    def notify_drain(self, host: str) -> None:
        """A supplier announced departure (CAP_DRAINING): keep its
        transport — in-flight fetches complete against it — but mark it
        so candidate ranking demotes it and no new placement lands
        there."""
        with self._lock:
            self._members.discard(host)
            self._draining.add(host)

    def refresh(self, host: str) -> None:
        """Drop the host's cached transport (stopping it) so the next
        fetch re-dials; a no-op for unconnected hosts. Used after a
        join/restart to pick up the fresh HELLO banner."""
        with self._lock:
            client = self._clients.pop(host, None)
        if client is not None:
            client.stop()

    def members(self) -> list[str]:
        """The advisory elastic membership (joiners announced via
        notify_join, minus announced leavers), sorted for deterministic
        placement."""
        with self._lock:
            return sorted(self._members)

    def is_draining(self, host: str) -> bool:
        """Has this host announced drain — either via notify_drain or
        through a CAP_DRAINING banner its live transport observed?"""
        with self._lock:
            if host in self._draining:
                return True
            client = self._clients.get(host)
        probe = getattr(client, "peer_draining", None)
        return bool(probe(host)) if callable(probe) else False

    def estimate_partition_bytes(self, job_id: str, map_ids,
                                 reduce_id: int):
        """Per-host fan-out of the size estimate: entries group by
        supplier host and each host's transport answers for its own
        maps (RemoteFetchClient probes over the wire, LocalFetchClient
        sums its spill index). Exact-or-unknown like LocalFetchClient:
        ANY host that cannot answer (unknown size, failed connect)
        makes the whole estimate None — a partial sum is a lower bound
        and would steer the auto merge-approach policy wrong (see
        LocalFetchClient.estimate_partition_bytes). Replicated entries
        (a host LIST per map) are estimated against their first
        (primary) host."""
        by_host: dict[str, list[str]] = {}
        for entry in map_ids:
            host, mid = entry if isinstance(entry, tuple) else ("", entry)
            if isinstance(host, (list, tuple)):
                host = host[0] if host else ""
            by_host.setdefault(host, []).append(mid)

        def probe(host: str, mids: list[str]):
            try:
                return self._client_for(host).estimate_partition_bytes(
                    job_id, mids, reduce_id)
            except Exception as e:  # noqa: BLE001 - estimate is best-
                # effort (fetch itself will fail loudly later), but the
                # degradation is counted and logged, never silent
                metrics.add("errors.swallowed")
                log.debug(f"size estimate: probe of host {host!r} "
                          f"failed ({e}); partition size unknown")
                return None

        if len(by_host) == 1:  # the common case, no thread overhead
            host, mids = next(iter(by_host.items()))
            return probe(host, mids)
        # many hosts: probe concurrently — serially, one slow or dead
        # supplier's connect+probe timeout would stack per host and
        # stall the auto merge-approach decision for minutes
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
                max_workers=min(16, len(by_host)),
                thread_name_prefix="uda-size-probe") as pool:
            estimates = list(pool.map(lambda kv: probe(*kv),
                                      by_host.items()))
        if any(est is None for est in estimates):
            return None
        return sum(estimates)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.stop()


class Segment:
    """One partition's record stream, fetched chunk-wise with a carry
    buffer for records split across chunk boundaries.

    Drives ``chunk_size``-byte fetches at increasing offsets until
    ``raw_length`` bytes have arrived (the reference's send_request /
    switch_mem loop, StreamRW.cc:462-590). Completed chunks are cracked
    into RecordBatches immediately so bytes can be packed/shipped to
    device while later chunks are still in flight.

    Survivable-shuffle ladder (ISSUE 8; every rung shares the task's
    :class:`~uda_tpu.merger.recovery.RecoveryLedger`):

    - **speculation** (``uda.tpu.fetch.speculate.pn``): an in-flight
      chunk that outlives max(floor, pN of the observed
      ``fetch.latency_ms`` histogram) gets a DUPLICATE fetch issued to
      the best-ranked alternate source (PenaltyBox rank over
      ``hosts``; the same source when no alternate exists).
      First-completion-wins rides the attempt-epoch machinery — the
      loser's completion is discarded as stale — and a speculation win
      switches the segment to the faster source for its remaining
      chunks;
    - **resume** (``uda.tpu.fetch.resume``): a transport-level retry
      against a resumable source (InputClient.resume_ok — warm
      supplier restart, immutable MOFs) keeps the offset ledger
      (batches + carry + next offset) and continues mid-partition
      instead of refetching from zero; the first resumed chunk's
      ``raw_length`` must match the pre-fault identity or the segment
      falls back to a full restart;
    - **reconstruction** (``uda.tpu.coding.scheme``): once retries are
      exhausted, the partition is rebuilt from any k of its n erasure
      stripe chunks on the surviving suppliers
      (InputClient.recover_partition) — the rung that turns a dead
      supplier from a FallbackSignal into a completed task.
    """

    def __init__(self, client: InputClient, job_id: str, map_id: str,
                 reduce_id: int, chunk_size: int, host: str = "",
                 retries: int = 3, policy: Optional[RetryPolicy] = None,
                 *, hosts=None, ledger=None,
                 speculation: Optional[SpeculationPolicy] = None,
                 resume: bool = False, stripe=None):
        self.client = client
        self.job_id = job_id
        self.map_id = map_id
        self.reduce_id = reduce_id
        self.chunk_size = chunk_size
        # candidate sources: ``hosts`` are suppliers known to hold this
        # map output (replicas); the primary is (re)picked by ledger
        # rank, speculation duplicates to the best alternate
        self.hosts: list[str] = [h for h in (hosts or ([host] if host
                                                       else [""]))]
        self.host = host or self.hosts[0]
        self.ledger = ledger
        self.speculation = speculation
        self.resume_enabled = bool(resume)
        self.stripe = stripe  # StripeContext when k-of-n coding is on
        self.batches: list[RecordBatch] = []
        self.num_records = 0  # monotone fetch-side record count
        self.raw_length: Optional[int] = None
        self.on_done = None  # callback fired once when fetch finishes
        self.on_fault = None  # callback fired on EVERY transport fault
        # (retried or terminal) — the penalty-box feedback channel
        self.policy = policy or RetryPolicy(retries=max(0, retries))
        self.trace_span = None
        self._issue_t0 = 0.0
        self._released = False
        self._carry = b""
        self._next_offset = 0
        self._retries_left = max(0, self.policy.retries)
        self._deadline: Optional[float] = None
        self._crc_refetched: set[int] = set()  # offsets re-fetched once
        self._rng = random.Random((self.policy.seed or 0)
                                  ^ zlib.crc32(map_id.encode()))
        self._issuing = False
        self._inline = self._PENDING
        self._next_epoch = 0     # attempt-id allocator (monotone)
        self._epoch = 0          # id of the outstanding PRIMARY attempt
        self._spec: Optional[tuple] = None  # (epoch, host) of the live
        # speculative duplicate, if any — the `speculative` epoch flag
        self._epoch_settled = True  # the attempt group has completed
        self._open_attempts = 0  # live attempts (on-air accounting)
        self._attempt_hosts: dict[int, str] = {}
        self._resume_check = False   # next chunk must revalidate identity
        self._recover_tried = False  # the reconstruction rung is one-shot
        self._timeout_timer: Optional[threading.Timer] = None
        self._spec_timer: Optional[threading.Timer] = None
        self._done = threading.Event()
        self._error: Optional[Exception] = None
        # lockdep-tracked: the segment state machine is driven from
        # transport completion threads, retry timers AND the merge
        # thread — the widest thread fan-in in the tree
        self._lock = TrackedLock("segment.state")

    @property
    def supplier(self) -> str:
        """The metric/penalty label of the CURRENT source (host when
        routed per host, else the map id); tracks speculation wins."""
        return self.host or self.map_id

    def add_host(self, host: str) -> bool:
        """Mid-job joiner pickup (ISSUE 18): widen the candidate list
        of an IN-FLIGHT segment so the existing ledger-ranked paths —
        retry re-pick, speculation alternate, reconstruction anchors —
        can elect the joiner. No attempt is re-routed eagerly; the
        joiner only matters at the next decision point. Returns True
        when the host was actually added (unknown and not done)."""
        if not host:
            return False
        with self._lock:
            if self._done.is_set() or host in self.hosts:
                return False
            self.hosts.append(host)
        return True

    def _notify_done(self) -> None:
        span = self.trace_span
        if span is not None:
            err = self._error
            span.end(**({"error": type(err).__name__} if err else {}))
        cb = self.on_done
        if cb is not None:
            cb(self)

    def _finish(self, error: Optional[Exception]) -> bool:
        """The ONLY terminal transition: first caller wins, every other
        (a concurrent fail() racing the drive loop's own terminal path)
        is a no-op — on_done must fire exactly once."""
        with self._lock:
            if self._done.is_set():
                return False
            self._error = error
            self._done.set()
        # black-box state transition (per segment, off the chunk path)
        flightrec.record("segment.done", map=self.map_id,
                         supplier=self.supplier,
                         error=type(error).__name__ if error else None)
        self._notify_done()
        return True

    # -- fetch driving ------------------------------------------------------

    _PENDING = object()  # sentinel: no inline completion delivered

    def start(self) -> None:
        if self.policy.deadline_ms > 0:
            self._deadline = time.monotonic() + self.policy.deadline_ms / 1e3
        # consult box rank BEFORE the primary pick, not only on fault:
        # a replicated segment opens against the healthiest source
        if len(self.hosts) > 1 and self.ledger is not None:
            self.host = self.ledger.rank(self.hosts)[0]
        # child of the caller's current span (the fetch phase of the
        # reduce-task trace); ended by _notify_done on ANY terminal path
        self.trace_span = metrics.start_span(
            "fetch.segment", map=self.map_id, supplier=self.supplier,
            reduce=self.reduce_id)
        flightrec.record("segment.start", map=self.map_id,
                         supplier=self.supplier)
        with self._lock:
            resume_at = self._next_offset
        if resume_at > 0:
            # checkpoint-preloaded offset ledger (ckpt_preload): the
            # fetch continues mid-partition; the bytes below the offset
            # are never refetched, and the first chunk revalidates the
            # partition identity through the _resume_check ladder
            metrics.add("fetch.resumed", supplier=self.supplier)
            metrics.add("fetch.resumed.bytes", resume_at)
            flightrec.record("segment.ckpt_resume", map=self.map_id,
                             supplier=self.supplier, offset=resume_at)
            log.info(f"fetch of {self.map_id} resuming at offset "
                     f"{resume_at} from a checkpointed ledger")
        self._drive(self._try_issue(resume_at))

    def _try_issue(self, offset: int):
        """Issue one fetch. Returns None when the transport took it
        asynchronously (the completion callback will fire later), or
        the RESULT (FetchResult or Exception) when the transport raised
        synchronously / invoked the callback inline — the caller's
        _drive loop then processes it WITHOUT recursing, so a transport
        that fails inline (e.g. a router's connect error) cannot
        overflow the stack however large the retry budget is.

        Each issue opens a new attempt epoch; completions (real,
        injected, or timeout-generated) carry their epoch and only the
        FIRST one for the current epoch is accepted — a late completion
        racing its own attempt timeout is dropped as stale instead of
        double-driving the state machine."""
        with self._lock:
            if self._done.is_set():
                # administratively failed (fail()) while a retry backoff
                # timer was pending: the segment is finished — issuing
                # would open a fresh epoch on a dead segment and fire
                # on_done twice when it completed
                return None
            self._inline = self._PENDING
            self._issuing = True
            self._next_epoch += 1
            self._epoch = self._next_epoch
            self._epoch_settled = False
            self._open_attempts += 1
            self._issue_t0 = time.perf_counter()
            epoch = self._epoch
            host = self.host
            self._attempt_hosts[epoch] = host
        req = ShuffleRequest(self.job_id, self.map_id, self.reduce_id,
                             offset, self.chunk_size, host=host)
        # on-air accounting (reference AIOHandler on-air counters):
        # +1 per attempt epoch, -1 when that epoch settles (accepted
        # completion, timeout-generated completion, sync raise, or
        # abandonment of a speculation loser)
        # the +1 hands off to the attempt epoch: _on_complete (accepted
        # or timeout-generated completion) owns the -1; only the sync
        # raise below settles it here
        metrics.gauge_add("fetch.on_air", 1)  # udalint: disable=UDA101
        try:
            # the failpoint is inside the try: an injected raise takes
            # the same sync-failure path as a stopped transport. The
            # key carries map AND source so chaos schedules can target
            # one supplier of a replicated segment (match:@host)
            failpoint("segment.fetch", key=f"{self.map_id}@{host}")
            # the segment's span is the transport's parent for this
            # issue: spans a transport opens (e.g. net.fetch) join the
            # fetch span tree even when the issue happens on a
            # completion thread with no ambient context
            with metrics.use_span(self.trace_span):
                self.client.start_fetch(
                    req, lambda res, e=epoch: self._on_complete(res, e))
        except Exception as e:  # noqa: BLE001 - a sync raise must fail
            # the segment, never escape into the transport's thread
            with self._lock:
                self._issuing = False
                # settle only a LIVE attempt: fail() (watchdog rescue /
                # stop drain) may have settled this epoch's on-air
                # charge while we were wedged inside the issue — a
                # second decrement here would push the gauge negative
                # forever (found by the ResourceLedger teardown gate)
                live = epoch in self._attempt_hosts
                if live:
                    self._epoch_settled = True
                    self._open_attempts -= 1
                    self._attempt_hosts.pop(epoch, None)
            if live:
                metrics.gauge_add("fetch.on_air", -1)
            return e
        with self._lock:
            self._issuing = False
            r = self._inline
            self._inline = self._PENDING
            if r is self._PENDING and not self._epoch_settled:
                self._arm_timeout(epoch)  # only for an async in-flight fetch
                self._arm_speculation(epoch, offset)
        return None if r is self._PENDING else r

    def _arm_timeout(self, epoch: int) -> None:
        """Arm the per-attempt timeout (caller holds self._lock)."""
        timeout = self.policy.attempt_timeout_ms
        if timeout <= 0:
            return
        t = threading.Timer(timeout / 1e3, self._on_timeout, args=(epoch,))
        t.daemon = True
        self._timeout_timer = t
        t.start()

    def _cancel_timeout(self) -> None:
        with self._lock:
            t, self._timeout_timer = self._timeout_timer, None
            s, self._spec_timer = self._spec_timer, None
        if t is not None:
            t.cancel()
        if s is not None:
            s.cancel()

    def _on_timeout(self, epoch: int) -> None:
        with self._lock:
            spec_epoch = self._spec[0] if self._spec else None
            if epoch not in (self._epoch, spec_epoch) \
                    or self._epoch_settled:
                return  # the attempt completed first
        tenant = current_tenant()
        if tenant:
            metrics.add("fetch.timeouts", supplier=self.supplier,
                        tenant=tenant)
        else:
            metrics.add("fetch.timeouts", supplier=self.supplier)
        self._on_complete(TransportError(
            f"fetch of {self.map_id} attempt timed out after "
            f"{self.policy.attempt_timeout_ms:g} ms"), epoch)

    # -- speculation (the straggler detector) -------------------------------

    def _arm_speculation(self, epoch: int, offset: int) -> None:
        """Arm the straggler timer for one in-flight attempt (caller
        holds self._lock): fires at max(floor, pN of the observed
        fetch.latency_ms histogram)."""
        sp = self.speculation
        if sp is None or not sp.enabled or self._spec is not None \
                or not self.client.speculate_ok():
            return
        t = threading.Timer(sp.threshold_ms() / 1e3,
                            self._maybe_speculate, args=(epoch, offset))
        t.daemon = True
        self._spec_timer = t
        t.start()

    def _pick_alt(self) -> str:
        """The speculation target: best PenaltyBox-ranked candidate
        that is not the current source; the current source itself when
        the segment has no alternates (a duplicate fetch still races a
        per-request stall)."""
        ranked = (self.ledger.rank(self.hosts) if self.ledger is not None
                  else list(self.hosts))
        for h in ranked:
            if h != self.host:
                return h
        return self.host

    def _maybe_speculate(self, epoch: int, offset: int) -> None:
        """Straggler-timer body: issue the speculative duplicate. Runs
        on the timer thread; a speculative attempt that fails (sync or
        async) is simply dropped — it must never fail the segment while
        the primary race is still open."""
        with self._lock:
            if self._done.is_set() or self._epoch_settled \
                    or epoch != self._epoch or self._spec is not None:
                return
            alt = self._pick_alt()
            self._next_epoch += 1
            spec_epoch = self._next_epoch
            self._spec = (spec_epoch, alt)
            self._attempt_hosts[spec_epoch] = alt
            self._open_attempts += 1
        metrics.add("fetch.speculated", supplier=alt or self.map_id)
        flightrec.record("segment.speculate", map=self.map_id,
                         primary=self.host, alternate=alt)
        # hands off to the speculative epoch: _on_complete settles the
        # winner, _drop_attempt the loser (and the sync-raise path)
        metrics.gauge_add("fetch.on_air", 1)  # udalint: disable=UDA101
        log.warn(f"fetch of {self.map_id} chunk at {offset} is a "
                 f"straggler; speculating against "
                 f"{alt or 'the same source'}")
        req = ShuffleRequest(self.job_id, self.map_id, self.reduce_id,
                             offset, self.chunk_size, host=alt)
        try:
            failpoint("segment.fetch", key=f"{self.map_id}@{alt}#spec")
            with metrics.use_span(self.trace_span):
                self.client.start_fetch(
                    req, lambda res, e=spec_epoch: self._on_complete(res, e))
        except Exception as e:  # noqa: BLE001 - a failed spec issue is
            # a dropped duplicate, not a segment failure
            self._drop_attempt(spec_epoch, e)

    def _drop_attempt(self, epoch: int, exc: Optional[Exception]) -> None:
        """Close ONE of two live attempts (a speculation loser that
        errored): the race continues on the surviving attempt.

        Racing failures: when BOTH attempts fail concurrently, the
        first drop leaves one live attempt (possibly by promotion) and
        the second drop finds ``_spec`` already None — that second
        failure now belongs to the SOLE live attempt, so it settles
        the group and drives the ordinary retry ladder instead of
        being discarded (discarding it would strand the segment with
        zero attempts in flight and nothing left to wake it)."""
        promoted = False
        sole_failure = False
        with self._lock:
            if self._epoch_settled:
                return
            spec = self._spec
            host = self._attempt_hosts.pop(epoch, self.host)
            if spec is not None and epoch == spec[0]:
                self._spec = None
            elif spec is not None and epoch == self._epoch:
                # the PRIMARY died while a speculative duplicate is in
                # flight: promote the duplicate — it is now the fetch
                self._epoch = spec[0]
                self._spec = None
                self.host = spec[1]
                promoted = True
                old_t, self._timeout_timer = self._timeout_timer, None
            elif spec is None and epoch == self._epoch:
                # the other attempt was dropped/promoted first: this
                # failure is the last live attempt's — settle and retry
                sole_failure = True
                self._epoch_settled = True
                settled_n = self._open_attempts
                self._open_attempts = 0
            else:
                return  # neither live attempt: stale
            if not sole_failure:
                self._open_attempts -= 1
            if promoted:
                self._arm_timeout(self._epoch)
        if sole_failure:
            metrics.gauge_add("fetch.on_air", -settled_n)
            self._cancel_timeout()
            if exc is None:
                exc = TransportError(
                    f"fetch of {self.map_id}: both racing attempts "
                    f"failed")
            attribute_supplier(exc, host or self.map_id)
            self._drive(exc)
            return
        metrics.gauge_add("fetch.on_air", -1)
        if promoted and old_t is not None:
            old_t.cancel()
        if exc is not None:
            attribute_supplier(exc, host or self.map_id)
            self._notify_fault(exc)

    def _on_complete(self, result, epoch: int) -> None:
        with self._lock:
            spec = self._spec
            spec_epoch = spec[0] if spec else None
            if self._epoch_settled or \
                    epoch not in (self._epoch, spec_epoch):
                metrics.add("fetch.stale_completions")
                return  # superseded attempt (timed out or re-issued)
            two_live = spec_epoch is not None
            drop_loser = isinstance(result, Exception) and two_live
            if not drop_loser:
                # accepted: this completion settles the attempt GROUP;
                # the loser of a speculation race is abandoned now (its
                # own completion, if it ever lands, is stale)
                self._epoch_settled = True
                won_spec = two_live and epoch == spec_epoch
                if won_spec:
                    self.host = spec[1]  # sticky: the faster source
                    # serves this segment's remaining chunks too
                self._spec = None
                self._attempt_hosts.clear()
                settled = self._open_attempts
                self._open_attempts = 0
                inline = self._issuing
                if inline:  # inline completion: hand back to _drive
                    self._inline = result
        if drop_loser:
            # one of TWO racing attempts failed: close it and keep
            # racing on the survivor (a failed primary promotes the
            # speculative duplicate)
            self._drop_attempt(epoch, result)
            return
        metrics.gauge_add("fetch.on_air", -settled)
        if two_live:
            if won_spec:
                metrics.add("fetch.speculation.won",
                            supplier=self.supplier)
            else:
                metrics.add("fetch.speculation.lost")
        if inline:
            return
        self._cancel_timeout()
        self._drive(result)

    def _notify_fault(self, exc: Exception) -> None:
        """Fire the on_fault hook (penalty-box feedback). The hook must
        never decide the segment's fate: its own errors are logged and
        swallowed."""
        hook = self.on_fault
        if hook is not None:
            try:
                hook(self, exc)
            except Exception as e:  # noqa: BLE001
                log.warn(f"on_fault hook failed for {self.map_id}: {e}")

    def _drive(self, result) -> None:
        """Iterative fetch state machine (one outstanding fetch at a
        time; runs on whichever thread delivered the completion)."""
        while result is not None:
            if isinstance(result, TenantError):
                # the service plane's refusal is TERMINAL: a fenced
                # epoch / retired job / failed registration cannot be
                # retried into legality — burning the retry+backoff
                # budget against the registry would only delay the
                # fallback (and churn the penalty box against a
                # healthy supplier)
                self._notify_fault(result)
                self._finish(result)
                return
            if isinstance(result, Exception):
                # transport-level retry (the reference retries its
                # connect dance 5x and RNR-retries sends,
                # RDMAClient.cc:41, 235-344; RDMAComm.h:29). Default:
                # restart the WHOLE segment from offset 0 —
                # re-fetch-the-MOF granularity, which also resets any
                # decompressing wrapper's stream state cleanly. With
                # uda.tpu.fetch.resume on and a resumable source
                # (warm-restarted supplier, immutable MOF), keep the
                # offset ledger and continue mid-partition instead —
                # already-served bytes are never refetched.
                deadline_hit = False
                # transport capability probed OUTSIDE self._lock (the
                # client has locks of its own; no order edge wanted).
                # Resumable failures: a disconnect (TransportError), or
                # a REMOTE StorageError (structured remote_kind stamp,
                # net/wire.py) — the supplier answered with a typed ERR
                # frame on a healthy stream, so every chunk ingested
                # before it is valid and a transient pread failure must
                # not cost a full refetch (a per-call fault probability
                # compounds over a partition's chunk count, so refetch-
                # from-zero retries lose ground they never recover —
                # the chaos error-schedule livelock shape). A LOCAL
                # StorageError (no remote_kind) still restarts from
                # zero: that class includes the resume-identity
                # invalidation below, which exists to force exactly
                # that restart.
                remote_storage = (isinstance(result, StorageError)
                                  and getattr(result, "remote_kind",
                                              None) is not None)
                resumable = (self.resume_enabled
                             and (isinstance(result, TransportError)
                                  or remote_storage)
                             and self.client.resume_ok(self.host))
                with self._lock:
                    if self._done.is_set():
                        # administratively failed (fail()) while this
                        # attempt was in flight: the segment's fate is
                        # sealed — retrying into a dead job would only
                        # burn backoff timers and churn the penalty box
                        return
                    retry = self._retries_left > 0
                    if retry and self._deadline is not None \
                            and time.monotonic() >= self._deadline:
                        retry, deadline_hit = False, True
                    resume = retry and resumable and self._next_offset > 0
                    if retry and not resume:
                        self._retries_left -= 1
                        self.batches = []
                        self.num_records = 0
                        self._carry = b""
                        self._next_offset = 0
                        self._crc_refetched.clear()
                        self._resume_check = False
                    elif resume:
                        self._retries_left -= 1
                        self._resume_check = True  # revalidate identity
                    offset = self._next_offset if resume else 0
                    attempt = self.policy.retries - self._retries_left
                    cands = list(self.hosts)
                self._notify_fault(result)
                if retry and not resume and len(cands) > 1 \
                        and self.ledger is not None:
                    # restart-from-zero retries re-rank the candidate
                    # list (which mid-job joiners may have WIDENED via
                    # add_host): a punished primary falls behind a
                    # healthy replica or joiner. Resumed retries must
                    # stay put — the offset ledger is only valid
                    # against the host that served it.
                    self.host = self.ledger.rank(cands)[0]
                if not retry:
                    if deadline_hit:
                        metrics.add("fetch.deadline_exceeded")
                        log.warn(f"fetch of {self.map_id} gave up: "
                                 f"deadline passed with retries left")
                    if self._try_recover(result):
                        return  # the reconstruction rung owns the
                        # segment now (completes it via _on_recovered)
                    self._finish(result)
                    return
                if resume:
                    metrics.add("fetch.resumed", supplier=self.supplier)
                    metrics.add("fetch.resumed.bytes", offset)
                    log.warn(f"fetch of {self.map_id} failed ({result}); "
                             f"resuming at offset {offset} "
                             f"({self._retries_left} retries left)")
                else:
                    log.warn(f"fetch of {self.map_id} failed ({result}); "
                             f"retrying ({self._retries_left} left)")
                tenant = current_tenant()
                if tenant:
                    metrics.add("fetch.retries", supplier=self.supplier,
                                tenant=tenant)
                else:
                    metrics.add("fetch.retries", supplier=self.supplier)
                flightrec.record("segment.retry", map=self.map_id,
                                 supplier=self.supplier,
                                 error=type(result).__name__,
                                 resume=resume, left=self._retries_left)
                delay = self.policy.backoff(attempt, self._rng)
                if self._deadline is not None:
                    delay = min(delay,
                                max(0.0, self._deadline - time.monotonic()))
                if delay > 0:
                    # back off without blocking the completion thread
                    # (it may be a transport worker the retry needs)
                    metrics.add("fetch.backoff_seconds", delay)
                    t = threading.Timer(
                        delay,
                        lambda o=offset: self._drive(self._try_issue(o)))
                    t.daemon = True
                    t.start()
                    return
                result = self._try_issue(offset)
                continue
            if self._resume_check:
                # first chunk after a resumed retry: the partition's
                # identity must match what the ledger was built from —
                # a supplier restarted onto a DIFFERENT map attempt
                # must not splice two attempts' bytes together. The
                # StorageError (not a TransportError) forces the next
                # retry to restart from zero.
                with self._lock:
                    prev = self.raw_length
                    self._resume_check = False
                if prev is not None and result.raw_length != prev:
                    metrics.add("fetch.resume.invalidated")
                    result = StorageError(
                        f"partition {self.map_id} changed identity "
                        f"across the supplier restart (raw_length "
                        f"{result.raw_length} != {prev}); restarting "
                        f"the fetch from zero")
                    continue
            crc = getattr(result, "crc", None)
            if crc is not None and \
                    zlib.crc32(result.data) & 0xFFFFFFFF != crc:
                # integrity layer (uda.tpu.fetch.crc): one re-fetch per
                # offset; a second mismatch at the same offset becomes a
                # transport-level error and consumes the retry budget
                metrics.add("fetch.crc_mismatch")
                off = result.offset
                if off not in self._crc_refetched:
                    self._crc_refetched.add(off)
                    metrics.add("fetch.crc_refetch")
                    log.warn(f"chunk CRC mismatch at {self.map_id}:{off}; "
                             f"re-fetching once")
                    result = self._try_issue(off)
                    continue
                result = StorageError(
                    f"chunk CRC mismatch at {self.map_id}:{off} persists "
                    f"after re-fetch")
                continue
            try:
                last = self._ingest(result)
            except Exception as e:  # crack errors -> surfaced to waiter
                self._finish(e)
                return
            # notify exactly once, outside _ingest's try scope: an
            # exception thrown by the on_done callback itself must NOT
            # re-enter the error path above and fire on_done a second
            # time (double credit release / double progress count)
            if last:
                self._finish(None)
                return
            result = self._try_issue(self._next_offset)

    def _ingest(self, res: FetchResult) -> bool:
        """Absorb one chunk; returns True when the segment is complete.
        Never calls callbacks and never touches them under self._lock —
        the completion callback may call record_batch(), which takes the
        same (non-reentrant) lock on this same thread."""
        with self._lock:
            self.raw_length = res.raw_length
            data = self._carry + res.data
            last = res.is_last
            if last and not data:
                # legitimately empty partition (raw_length == 0: a byte
                # range with no records and no EOF marker, as foreign
                # writers may produce for empty reducers)
                self._carry = b""
            else:
                # crack up to the last complete record; keep the tail
                batch, consumed, _ = crack_partial(data, expect_eof=last)
                if batch.num_records:
                    self.batches.append(batch)
                    self.num_records += batch.num_records
                self._carry = data[consumed:] if not last else b""
                self._next_offset = res.offset + len(res.data)
            issue_t0 = self._issue_t0
        tenant = current_tenant()
        if tenant:
            # tenanted reduce tasks label the hot-path fetch counters
            # (one module-global read per chunk; untenanted jobs keep
            # the exact two-series shape of PRs 2-13)
            metrics.add("fetch.bytes", len(res.data),
                        supplier=self.supplier, tenant=tenant)
            metrics.add("fetch.chunks", supplier=self.supplier,
                        tenant=tenant)
        else:
            metrics.add("fetch.bytes", len(res.data),
                        supplier=self.supplier)
            metrics.add("fetch.chunks", supplier=self.supplier)
        if tenant:
            metrics.observe("fetch.latency_ms",
                            (time.perf_counter() - issue_t0) * 1e3,
                            supplier=self.supplier, tenant=tenant)
            metrics.observe("fetch.chunk.bytes", len(res.data),
                            tenant=tenant)
        else:
            metrics.observe("fetch.latency_ms",
                            (time.perf_counter() - issue_t0) * 1e3,
                            supplier=self.supplier)
            metrics.observe("fetch.chunk.bytes", len(res.data))
        return last

    def _try_recover(self, cause: Exception) -> bool:
        """The post-retry reconstruction rung: rebuild the partition
        from any k of its n stripe chunks (uda_tpu.coding). One-shot;
        returns False when coding is off or the transport cannot
        recover — the caller then finishes the segment with ``cause``
        exactly as before."""
        if self.stripe is None or self._recover_tried:
            return False
        self._recover_tried = True
        with self._lock:
            # the recovery replaces the whole partition: drop whatever
            # partial state the failed attempts left behind
            self.batches = []
            self.num_records = 0
            self._carry = b""
            self._next_offset = 0
            self._resume_check = False
            self._issue_t0 = time.perf_counter()
        # anchor placement at the WRITER's primary (hosts[0] — the map
        # entry's first host), never the current source: rank-picks and
        # speculation wins move self.host, but the stripe was placed by
        # rotation from where the map was written
        req = ShuffleRequest(self.job_id, self.map_id, self.reduce_id,
                             0, self.chunk_size, host=self.hosts[0])
        metrics.add("coding.recover.attempts", supplier=self.supplier)
        log.warn(f"fetch of {self.map_id} exhausted retries ({cause}); "
                 f"attempting k-of-n stripe reconstruction")
        try:
            with metrics.use_span(self.trace_span):
                return bool(self.client.recover_partition(
                    req, self.stripe, self._on_recovered))
        except Exception as e:  # noqa: BLE001 - a recovery that cannot
            # even start must fall through to the terminal path, not
            # escape into the completion thread
            metrics.add("coding.recover.failures")
            log.warn(f"stripe reconstruction of {self.map_id} could "
                     f"not start: {e}")
            return False

    def _on_recovered(self, result) -> None:
        """Reconstruction completion: a full-partition FetchResult (the
        decoded on-disk bytes, decompressed by any wrapper on the way
        up) or the reconstruction's terminal error."""
        if isinstance(result, Exception):
            metrics.add("coding.recover.failures")
            self._finish(result)
            return
        try:
            last = self._ingest(result)
        except Exception as e:  # noqa: BLE001 - crack errors surface to
            # the waiter like any fetched chunk's would
            self._finish(e)
            return
        self._finish(None if last else MergeError(
            f"stripe reconstruction of {self.map_id} delivered a "
            f"non-final chunk"))

    def fail(self, exc: Exception) -> bool:
        """Administratively terminate the fetch (watchdog rescue / stop-
        path drain): the segment completes NOW with ``exc`` and every
        waiter wakes. The outstanding attempts' epochs are invalidated,
        so a transport completion that eventually arrives (e.g. a wedged
        worker finishing minutes later) is dropped as stale instead of
        double-driving the state machine. Returns False when the segment
        had already finished (success or error) — fail() never rewrites
        history. Safe from any thread; fires on_done (credit release)
        exactly once like every other terminal path.

        The failing supplier rides the STRUCTURED cause: ``exc`` gains
        a ``supplier`` attribute (first unset wins — a shared stop-path
        error keeps its first attribution) and the recovery ledger gets
        an exact per-segment record, so downstream consumers never
        parse reason strings (UDA005)."""
        with self._lock:
            if self._done.is_set():
                return False
            open_attempts = self._open_attempts
            self._open_attempts = 0
            self._next_epoch += 1     # outstanding completions -> stale
            self._epoch = self._next_epoch
            self._spec = None
            self._attempt_hosts.clear()
            self._epoch_settled = True
        if open_attempts:
            # settle the abandoned attempts' on-air accounting (their
            # own completions, if they ever land, see a stale epoch and
            # must not decrement a second time)
            metrics.gauge_add("fetch.on_air", -open_attempts)
        self._cancel_timeout()
        attribute_supplier(exc, self.supplier)
        if self.ledger is not None:
            self.ledger.record("admin_fail", supplier=self.supplier,
                               map_id=self.map_id, error=exc)
        if not self._finish(exc):
            return False  # a real terminal path won the race
        metrics.add("fetch.failed_admin")
        flightrec.record("segment.admin_fail", map=self.map_id,
                         supplier=self.supplier,
                         error=type(exc).__name__)
        return True

    # -- consumption --------------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> None:
        if not self._done.wait(timeout=timeout):
            raise MergeError(f"segment {self.map_id} fetch timed out")
        if self._error is not None:
            raise self._error

    @property
    def ready(self) -> bool:
        return self._done.is_set() and self._error is None

    def record_batch(self) -> RecordBatch:
        """All records of the partition as one batch (fetch must be
        done). The concat is cached: callers on different threads (the
        overlap staging thread, then the finish pass) pay for it once."""
        self.wait()
        with self._lock:
            if self._released:
                raise MergeError(
                    f"segment {self.map_id} bytes were released "
                    f"(streaming mode spooled them to a sorted run)")
            if len(self.batches) == 1:
                return self.batches[0]
            cat = RecordBatch.concat(self.batches)
            self.batches = [cat]
            return cat

    def release(self) -> None:
        """Drop the fetched bytes (streaming online mode: the sorted run
        file is now the source of truth; ``num_records`` survives for
        accounting). record_batch() raises after this."""
        with self._lock:
            self.batches = []
            self._released = True

    # -- checkpoint (merger/checkpoint.py) ----------------------------------

    def ckpt_export(self) -> Optional[dict]:
        """Snapshot this segment's fetch offset ledger for a checkpoint
        manifest: the cracked batches re-framed (IFile framing, no EOF)
        plus the carry tail, with the offsets that make the state
        resumable. None when there is nothing worth persisting — the
        segment is done/released (its run file carries the records) or
        has fetched nothing yet (a fresh fetch costs the same).

        Crash-consistent by construction: state is copied under the
        segment lock (batches are immutable once appended and
        ``_next_offset`` advances in the same critical section as the
        append, so the copy is internally consistent); the re-framing
        runs outside the lock."""
        with self._lock:
            if self._done.is_set() or self._released \
                    or self._next_offset <= 0:
                return None
            batches = list(self.batches)
            carry = self._carry
            state = {"next_offset": self._next_offset,
                     "raw_length": self.raw_length,
                     "num_records": self.num_records,
                     "carry_len": len(carry)}
        from uda_tpu import native

        framed = b"".join(native.frame_batch(b, write_eof=False)
                          for b in batches)
        state["data"] = framed + bytes(carry)
        return state

    def ckpt_preload(self, *, data: bytes, carry_len: int,
                     next_offset: int, raw_length, num_records: int) -> None:
        """Restore a checkpointed offset ledger BEFORE start(): re-crack
        the persisted framed bytes, verify they account for exactly the
        recorded records, and arm the resume (start() then issues at
        ``next_offset`` and the first chunk revalidates identity).
        Raises :class:`StorageError` on any mismatch — the caller drops
        the ledger and the segment fetches from zero."""
        framed_len = len(data) - int(carry_len)
        if framed_len < 0:
            raise StorageError(
                f"checkpoint ledger of {self.map_id}: carry "
                f"{carry_len} B exceeds payload {len(data)} B")
        batch, consumed, _ = crack_partial(bytes(data[:framed_len]),
                                           expect_eof=False)
        if consumed != framed_len or batch.num_records != int(num_records):
            raise StorageError(
                f"checkpoint ledger of {self.map_id} re-cracked to "
                f"{batch.num_records} records/{consumed} B, manifest "
                f"says {num_records}/{framed_len}")
        with self._lock:
            if self._next_epoch:
                raise StorageError(
                    f"ckpt_preload of {self.map_id} after start()")
            self.batches = [batch] if batch.num_records else []
            self.num_records = int(num_records)
            self._carry = bytes(data[framed_len:])
            self._next_offset = int(next_offset)
            self.raw_length = (int(raw_length) if raw_length is not None
                               else None)
            self._resume_check = True  # first chunk revalidates identity


