"""The recovery ledger: one shared record of everything the
survivable-shuffle layer did for a reduce task.

The three fault-tolerance rungs above plain retry — speculative
dual-source fetch, k-of-n stripe reconstruction, and warm-restart
resume (ISSUE 8) — all need the same two things: a structured,
string-parse-free record of WHO failed and WHAT recovered (the penalty
box and the watchdog diagnostics key on it), and a shared source
ranking so every rung prefers the same healthy suppliers. The ledger
is that shared state: a bounded event log plus a rank() view over the
task's :class:`~uda_tpu.merger.merge_manager.PenaltyBox`.

Events are structured dicts (kind, supplier, map_id, error class) —
never reason strings (udalint UDA005). The monotone ``version``
feeds the stall watchdog's progress token: a reconstruction fetching
shards IS progress even while the segment's own counters stand still.
"""

from __future__ import annotations

from collections import deque
from typing import Optional, Sequence

from uda_tpu.utils.flightrec import flightrec
from uda_tpu.utils.locks import TrackedLock

__all__ = ["RecoveryLedger"]

_MAX_EVENTS = 256


class RecoveryLedger:
    """Bounded per-task recovery journal + supplier health ranking."""

    def __init__(self, box=None):
        self._box = box  # PenaltyBox (rank source); optional for tests
        self._lock = TrackedLock("recovery.ledger")
        self._events: deque = deque(maxlen=_MAX_EVENTS)
        self.version = 0  # monotone event counter (watchdog progress)

    def record(self, kind: str, supplier: str = "", map_id: str = "",
               error: Optional[BaseException] = None) -> None:
        """Append one structured event. ``error`` is recorded by CLASS
        NAME only — the ledger is for keying and diagnostics, not for
        re-raising."""
        event = {"kind": kind, "supplier": supplier, "map_id": map_id,
                 "error": type(error).__name__ if error is not None
                 else None}
        with self._lock:
            self._events.append(event)
            self.version += 1
        # recovery events are exactly what a post-mortem wants in
        # sequence with the faults that caused them — mirror into the
        # process black box (utils/flightrec.py) under a
        # recovery.<kind> event kind
        flightrec.record(f"recovery.{kind}", supplier=supplier,
                         map_id=map_id, error=event["error"])

    def rank(self, hosts: Sequence[str]) -> list:
        """``hosts`` ordered healthiest-first by PenaltyBox state
        (unboxed before boxed, fewer faults before more; stable within
        a tier, so the caller's preference order breaks ties). The
        shared source-choice primitive: the scheduler's primary pick,
        speculation's alternate pick and reconstruction's shard
        fan-out all rank through here."""
        box = self._box
        if box is None:
            return list(hosts)
        return box.rank(hosts)

    def events(self, kind: Optional[str] = None) -> list:
        with self._lock:
            evs = list(self._events)
        return evs if kind is None else [e for e in evs
                                         if e["kind"] == kind]

    def restore(self, events: Sequence[dict]) -> None:
        """Re-seed the journal from a checkpoint manifest (resume path).
        Only the structured keys are taken — a manifest is outside
        input, so unknown keys are dropped rather than trusted. Bumps
        ``version`` once so the watchdog sees the load as progress."""
        with self._lock:
            for e in events:
                self._events.append(
                    {"kind": str(e.get("kind", "")),
                     "supplier": str(e.get("supplier", "")),
                     "map_id": str(e.get("map_id", "")),
                     "error": (str(e["error"])
                               if e.get("error") is not None else None)})
            self.version += 1

    def snapshot(self) -> dict:
        """Diagnostics view (watchdog dumps, tests)."""
        with self._lock:
            evs = list(self._events)
            version = self.version
        counts: dict = {}
        for e in evs:
            counts[e["kind"]] = counts.get(e["kind"], 0) + 1
        return {"version": version, "counts": counts, "events": evs}
