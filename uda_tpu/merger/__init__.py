"""Reduce-side merge engine (the Merger/ layer of SURVEY §1): staging
arena, streaming segments, merge manager, hybrid LPQ/RPQ merge."""

from uda_tpu.merger.arena import BufferArena, BufferSlot, SlotState
from uda_tpu.merger.merge_manager import MergeManager, PenaltyBox
from uda_tpu.merger.recovery import RecoveryLedger
from uda_tpu.merger.segment import (HostRoutingClient, InputClient,
                                    LocalFetchClient, Segment)

__all__ = ["BufferArena", "BufferSlot", "SlotState", "MergeManager",
           "PenaltyBox", "RecoveryLedger", "InputClient",
           "LocalFetchClient", "HostRoutingClient", "Segment"]
