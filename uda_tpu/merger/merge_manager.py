"""Merge manager: fetch scheduling + merge orchestration.

Equivalent of the reference's MergeManager (reference
src/Merger/MergeManager.cc): the fetch phase issues per-map fetch
requests in randomized order with a bounded in-flight window (the
reference shuffles its fetch list to spread load across supplier hosts,
MergeManager.cc:58-63 / UdaUtil.h:99-103, and bounds in-flight fetches
with RDMA credits); the merge phase produces the globally sorted stream
and hands it to the consumer in staging-buffer-sized IFile-framed blocks
(the reference fills 2 x 1 MB DirectByteBuffers and up-calls
``dataFromUda`` per block, MergeManager.cc:155-182, NetlevComm.h:33).

Differences by design (TPU-first):

- no priority queue: whole runs are sorted/merged on device
  (uda_tpu.ops); the "network-levitated" property — merge overlapping
  fetch — survives as: segments crack+pack while later fetches are in
  flight, and device sorts of earlier runs overlap later fetching.
- progress: the reference reports every 20 merged segments
  (``fetchOverMessage``, MergeManager.cc:44, 124-130); we keep the same
  cadence through the ``progress`` callback.

Online mode (everything HBM/host-memory resident) is implemented here;
hybrid LPQ/RPQ spilling lives in uda_tpu.merger.hybrid.
"""

from __future__ import annotations

import functools
import random
import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

from uda_tpu.coding import parse_scheme
from uda_tpu.merger.emitter import FramedEmitter
from uda_tpu.merger.recovery import RecoveryLedger
from uda_tpu.merger.segment import InputClient, Segment
from uda_tpu.ops import merge as merge_ops
from uda_tpu.utils.budget import MemoryBudget, stage_inflight_cap
from uda_tpu.utils.comparators import KeyType, get_key_type
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import (FallbackSignal, MergeError, StorageError,
                                  UdaError)
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.flightrec import flightrec
from uda_tpu.utils.locks import TrackedLock
from uda_tpu.tenant import current_tenant
from uda_tpu.utils.ifile import RecordBatch
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics
from uda_tpu.utils.retry import RetryPolicy, SpeculationPolicy
from uda_tpu.utils.watchdog import StallError, StallWatchdog

__all__ = ["MergeManager", "PenaltyBox", "PROGRESS_INTERVAL"]

log = get_logger()

PROGRESS_INTERVAL = 20  # segments per progress report (MergeManager.cc:44)


class PenaltyBox:
    """Per-supplier fault tracker: a supplier whose fetches keep failing
    is *deprioritized* — its remaining maps rotate to the back of the
    fetch schedule instead of burning the window on a sick host (the
    dynamic counterpart of the reference's randomized fetch list, which
    only spread load statically, MergeManager.cc:58-63). Suppliers leave
    the box when the penalty expires or through forgiveness; boxing is
    never exclusion — when every pending supplier is boxed the scheduler
    proceeds anyway (progress beats politeness).

    Forgiveness DECAYS rather than resets: one success takes one fault
    off the record; only ``reset_successes`` CONSECUTIVE successes (a
    fault restarts the streak) clear it outright — a flapping supplier
    that alternates success and fault can no longer oscillate out of
    the box on every lucky fetch."""

    def __init__(self, threshold: int = 2, penalty_s: float = 1.0,
                 reset_successes: int = 3):
        self.threshold = max(1, threshold)
        self.penalty_s = penalty_s
        self.reset_successes = max(1, reset_successes)
        self._lock = TrackedLock("penalty_box")
        self._faults: dict[str, int] = {}
        self._until: dict[str, float] = {}
        self._streak: dict[str, int] = {}  # consecutive successes

    def punish(self, key: str) -> bool:
        """Record one fault; returns True when this fault boxed the
        supplier (crossing the threshold, or extending an active box)."""
        with self._lock:
            self._streak.pop(key, None)  # a fault breaks the streak
            n = self._faults.get(key, 0) + 1
            self._faults[key] = n
            if n < self.threshold:
                return False
            self._until[key] = time.monotonic() + self.penalty_s
        tenant = current_tenant()
        if tenant:
            metrics.add("fetch.penalties", supplier=key, tenant=tenant)
        else:
            metrics.add("fetch.penalties", supplier=key)
        return True

    def forgive(self, key: str) -> None:
        """One success decays the fault record one step (and unboxes a
        supplier that dropped below the threshold); the record clears
        entirely only after ``reset_successes`` consecutive
        successes."""
        with self._lock:
            n = self._faults.get(key)
            if n is None:
                return
            streak = self._streak.get(key, 0) + 1
            n = max(0, n - 1)
            if streak >= self.reset_successes or n == 0:
                self._faults.pop(key, None)
                self._until.pop(key, None)
                self._streak.pop(key, None)
                return
            self._streak[key] = streak
            self._faults[key] = n
            if n < self.threshold:
                self._until.pop(key, None)

    def faults(self, key: str) -> int:
        with self._lock:
            return self._faults.get(key, 0)

    def rank(self, keys) -> list:
        """``keys`` healthiest-first: unboxed before boxed, fewer
        faults before more, stable otherwise (the caller's preference
        order breaks ties). Read-only — no parole side effects."""
        with self._lock:
            now = time.monotonic()

            def score(k):
                t = self._until.get(k)
                return (1 if (t is not None and t > now) else 0,
                        self._faults.get(k, 0))

            return sorted(keys, key=score)

    def penalized(self, key: str) -> bool:
        with self._lock:
            t = self._until.get(key)
            if t is None:
                return False
            if time.monotonic() >= t:
                # parole: out of the box, but one more fault re-boxes
                del self._until[key]
                self._faults[key] = self.threshold - 1
                return False
            return True

    @property
    def boxed(self) -> list[str]:
        with self._lock:
            now = time.monotonic()
            return [k for k, t in self._until.items() if t > now]

    def snapshot(self) -> dict:
        """Introspection view (the MSG_STATS scrape surface and the
        final stats record's recovery block): fault counts, success
        streaks and who is boxed right now."""
        with self._lock:
            now = time.monotonic()
            return {"faults": dict(self._faults),
                    "streaks": dict(self._streak),
                    "boxed": [k for k, t in self._until.items()
                              if t > now]}

    def restore(self, snap: dict) -> None:
        """Re-seed fault/streak records from a checkpoint manifest
        (resume path). Active box TIMERS are deliberately NOT restored —
        ``_until`` holds monotonic deadlines that do not survive process
        death; a supplier at/over the threshold re-boxes on its next
        fault anyway (the parole posture in :meth:`penalized`)."""
        with self._lock:
            for k, v in (snap.get("faults") or {}).items():
                self._faults[str(k)] = int(v)
            for k, v in (snap.get("streaks") or {}).items():
                self._streak[str(k)] = int(v)


class MergeManager:
    """Orchestrates fetch -> pack -> device merge -> framed emission for
    one reduce task."""

    def __init__(self, client: InputClient, key_type: KeyType | str,
                 config: Optional[Config] = None,
                 progress: Optional[Callable[[int, int], None]] = None,
                 seed: int = 0):
        self.cfg = config or Config()
        self.client = client
        self.key_type = (get_key_type(key_type) if isinstance(key_type, str)
                         else key_type)
        self.key_width = self.cfg.get("uda.tpu.key.width")
        self.chunk_size = self.cfg.get("mapred.rdma.buf.size") * 1024
        self.window = max(1, self.cfg.get("mapred.rdma.wqe.per.conn"))
        self.progress = progress
        self.seed = seed
        self.emitter = FramedEmitter(self.chunk_size)
        self.retry_policy = RetryPolicy.from_config(self.cfg)
        self.penalty_box = PenaltyBox(
            threshold=self.cfg.get("uda.tpu.fetch.penalty.threshold"),
            penalty_s=self.cfg.get("uda.tpu.fetch.penalty.ms") / 1e3)
        # the survivable-shuffle layer (ISSUE 8): speculation, resume
        # and k-of-n reconstruction all share ONE recovery ledger
        self.ledger = RecoveryLedger(self.penalty_box)
        self.speculation = SpeculationPolicy.from_config(self.cfg)
        self.resume_fetch = bool(self.cfg.get("uda.tpu.fetch.resume"))
        self.coding_scheme = parse_scheme(
            self.cfg.get("uda.tpu.coding.scheme"))
        spec = self.cfg.get("uda.tpu.failpoints")
        if spec:
            failpoints.arm_spec(spec)
        if self.cfg.get("uda.tpu.stats.enable"):
            metrics.enable_stats()
        # the black box rides every task (utils/flightrec.py): config
        # knobs AND the env kill switch must both say on
        from uda_tpu.utils.flightrec import flightrec_enabled_from_env
        flightrec.configure(
            enabled=(bool(self.cfg.get("uda.tpu.flightrec.enable"))
                     and flightrec_enabled_from_env()),
            capacity=int(self.cfg.get("uda.tpu.flightrec.events")),
            dump_dir=str(self.cfg.get("uda.tpu.flightrec.dir")))
        # the time-accounting plane (utils/profiler + utils/critpath):
        # arm the sampling profiler when asked (config wins, env
        # otherwise; arming is sticky — a later manager with the 0
        # default never disarms a profiler the operator turned on) and
        # expose the where-time-goes block over MSG_STATS
        from uda_tpu.utils.critpath import install_stats_provider
        from uda_tpu.utils.profiler import profile_hz_from_env, profiler
        install_stats_provider()
        prof_hz = (float(self.cfg.get("uda.tpu.profile.hz"))
                   or profile_hz_from_env())
        if prof_hz > 0:
            profiler.start(prof_hz)
        self._stop = threading.Event()
        # admission control + liveness (uda_tpu.utils.budget/.watchdog):
        # the budget is built lazily (platform detection must not run
        # for explicitly-configured approaches), the watchdog per run()
        self._budget_obj: Optional[MemoryBudget] = None
        self.last_admission = None     # routing decision (tests/diag)
        self._live_segments: list[Optional[Segment]] = []
        self._active_overlap = None
        # crash-consistent checkpointing (merger/checkpoint.py): live
        # only while a run() with uda.tpu.ckpt.dir set is in flight
        self._ckpt = None
        self._watchdog: Optional[StallWatchdog] = None
        self._stall_error: Optional[StallError] = None
        self._emit_progress = 0
        # push plane (ISSUE 19): reduce-side staging, armed by
        # arm_push() — ideally by the embedder the moment the reduce
        # task is SCHEDULED (pushes then overlap the entire map phase);
        # fetch_all arms it lazily otherwise
        self._push_staging = None

    def arm_push(self, job_id: str, reduce_id: int, hosts=None):
        """Arm reduce-side push staging for this task and subscribe the
        supplier fleet (``uda.tpu.push.enable``). Idempotent; returns
        the staging or None when the plane stays pull-only: flag off,
        a transport without a push plane (LocalFetchClient, custom
        connects), or a byte-domain-transforming wrapper
        (DecompressingClient — pushed bytes are the on-disk compressed
        stream, the Segment ledger's domain is the decompressed one).

        Call it BEFORE the map phase finishes to win overlap: pushes
        land while maps are still running, and the fetch wave then
        starts from the staged offsets instead of zero."""
        if self._push_staging is not None:
            return self._push_staging
        if not bool(self.cfg.get("uda.tpu.push.enable")):
            return None
        if getattr(self.client, "inner", None) is not None:
            return None
        reg = getattr(self.client, "push_register", None)
        if not callable(reg):
            return None
        from uda_tpu.net.push import PushStaging

        staging = PushStaging(job_id, int(reduce_id), cfg=self.cfg,
                              budget=self.budget())
        reg(job_id, int(reduce_id), staging, hosts=hosts)
        self._push_staging = staging
        return staging

    def _release_push(self) -> None:
        """Unsubscribe and discard unclaimed staged bytes (idempotent;
        run()'s finally). Late pushes after this draw
        PUSH_NACK(UNKNOWN) and the supplier goes pull-only — no frame
        is ever left unanswered."""
        staging, self._push_staging = self._push_staging, None
        if staging is None:
            return
        unreg = getattr(self.client, "push_unregister", None)
        if callable(unreg):
            unreg(staging.job_id, staging.reduce_id)
        staging.close()

    def _push_adopt(self, seg: Segment) -> None:
        """Right before a segment starts: claim its map in staging and
        arm the staged prefix as a resumed fetch (Segment.ckpt_preload
        — the PUSHED bytes land in the offset ledger exactly like a
        checkpoint's, so retry/speculation/reconstruction compose
        unchanged). The claim stands even when nothing usable is
        staged: from here the fetch is in flight, and later pushes for
        this map are refused CLAIMED (dedup)."""
        staging = self._push_staging
        if staging is None:
            return
        kw = staging.take(seg.map_id)
        if kw is None:
            return
        if seg._next_offset or seg.batches:
            return  # a checkpoint ledger is further along; keep it
        try:
            seg.ckpt_preload(**kw)
        except UdaError as e:
            metrics.add("push.invalidated")
            log.warn(f"pushed prefix of map {seg.map_id} rejected, "
                     f"fetching from zero: {e}")
            return
        metrics.add("push.adopted")
        metrics.add("push.adopted.bytes", int(kw["next_offset"]))

    def budget(self) -> MemoryBudget:
        if self._budget_obj is None:
            self._budget_obj = MemoryBudget.from_config(self.cfg)
        return self._budget_obj

    # -- elastic membership (ISSUE 18) --------------------------------------

    def notify_join(self, host: str) -> int:
        """A supplier joined mid-job: widen every in-flight segment's
        candidate list so the joiner becomes eligible at the next
        ledger-ranked decision point (retry re-pick, speculation
        alternate, reconstruction anchor), and fold the host into the
        routing client's membership ring (so its transport re-dials and
        observes the joiner's CAP_ELASTIC banner). Returns the number
        of segments widened. Already-completed segments and segments
        that already know the host are untouched — join is advisory,
        never a re-route of live attempts."""
        notify = getattr(self.client, "notify_join", None)
        if callable(notify):
            notify(host)
        else:
            metrics.add("elastic.joins", supplier=host)
        widened = 0
        for seg in list(self._live_segments):
            if seg is not None and seg.add_host(host):
                widened += 1
        self.ledger.record("join", supplier=host)
        flightrec.record("elastic.join", supplier=host,
                         widened=widened)
        log.info(f"elastic: supplier {host!r} joined mid-job; "
                 f"{widened} in-flight segment(s) widened")
        return widened

    def notify_drain(self, host: str) -> None:
        """The symmetric departure: demote the host in routing (no new
        placements; in-flight fetches against it complete normally —
        its MOFs migrate to the blob tier via StoreManager.drain, so
        fetch-after-departure resolves there, migrated not
        reconstructed)."""
        notify = getattr(self.client, "notify_drain", None)
        if callable(notify):
            notify(host)
        self.ledger.record("drain", supplier=host)
        flightrec.record("elastic.drain", supplier=host)

    # -- fetch phase --------------------------------------------------------

    def fetch_all(self, job_id: str, map_ids: Sequence,
                  reduce_id: int,
                  on_segment: Optional[Callable[[int, Segment], None]] = None,
                  skip=None, preload: Optional[dict] = None
                  ) -> list:
        """Fetch every map's partition, randomized order, sliding window.

        Resume hooks (merger/checkpoint.py): ``skip`` holds indexes
        whose run files a previous attempt already spooled — no segment
        is built (the returned list holds None there) and no byte is
        refetched; ``preload`` maps index -> a checkpointed offset
        ledger, applied via Segment.ckpt_preload before start() so the
        fetch resumes mid-stream (an invalid ledger degrades to a fresh
        fetch from zero, never an error).

        The window refills as individual segments complete (true
        credit-flow semantics: in-flight count stays at ``window`` until
        the tail, rather than draining at batch boundaries). Returns
        segments in the *original* map order (merge stability and
        reproducibility do not depend on fetch completion order).

        ``on_segment(index, segment)`` fires on each successful segment
        completion, from the transport's completion thread — the hook
        the overlapped merge uses to stage runs while later fetches are
        still in flight.

        Fault feedback: every transport fault reports the segment's
        supplier to the penalty box; maps of a boxed supplier rotate to
        the back of the pending schedule (see :class:`PenaltyBox`).
        """
        # entries are "map_id", ("host", "map_id"), or
        # (["host", ...], "map_id") — hosts route through a per-host
        # transport (HostRoutingClient); a host LIST means replicas
        # (every listed supplier holds the map output) and must lead
        # with the map WRITER's host (the stripe placement anchor):
        # fetching opens against the best PenaltyBox-ranked replica and
        # speculation duplicates to the alternates
        def _norm(m):
            if isinstance(m, tuple):
                host, mid = m
                hosts = (list(host) if isinstance(host, (list, tuple))
                         else [host])
            else:
                hosts, mid = [""], m
            return hosts or [""], mid

        entries = [_norm(m) for m in map_ids]
        # push plane: arm lazily if the embedder did not (no overlap
        # won at this point — the map phase may already be over — but
        # pushes still beat pulls for any map that commits during this
        # fetch wave)
        self.arm_push(job_id, reduce_id,
                      hosts={h for hosts, _ in entries for h in hosts
                             if h})
        stripe_ctx = None
        if self.coding_scheme is not None:
            from uda_tpu.coding.recovery import StripeContext

            # the placement domain: the job's canonically-ordered
            # supplier universe (sorted unique hosts — writers derive
            # the identical order; see uda_tpu.coding). Host-less local
            # entries ("") are NOT suppliers: mixed in with real hosts
            # they would shift the ring against the writer's
            # supplier_roots; the all-local degenerate keeps [""]
            universe = sorted({h for hosts, _ in entries
                               for h in hosts if h}) or [""]
            from uda_tpu.coding import parse_domains

            stripe_ctx = StripeContext(
                self.coding_scheme, universe, ledger=self.ledger,
                domains=parse_domains(
                    str(self.cfg.get("uda.tpu.coding.domains"))))
        skip = frozenset(skip or ())
        segs = [None if i in skip else
                Segment(self.client, job_id, mid, reduce_id,
                        self.chunk_size, host=hosts[0],
                        policy=self.retry_policy, hosts=hosts,
                        ledger=self.ledger,
                        speculation=self.speculation,
                        resume=self.resume_fetch, stripe=stripe_ctx)
                for i, (hosts, mid) in enumerate(entries)]
        for i, kw in (preload or {}).items():
            if segs[i] is None:
                continue
            try:
                segs[i].ckpt_preload(**kw)
            except UdaError as e:
                # a ledger that fails revalidation degrades to a fresh
                # fetch from zero — resume is an optimization, never a
                # correctness dependency
                metrics.add("ckpt.invalidated", cause="ledger")
                log.warn(f"checkpointed ledger of map "
                         f"{segs[i].map_id} rejected, refetching: {e}")
        index_of = {id(s): i for i, s in enumerate(segs) if s is not None}
        order = [i for i in range(len(segs)) if i not in skip]
        random.Random(self.seed).shuffle(order)  # MergeManager.cc:58-63
        nskip = len(segs) - len(order)
        live_total = len(order)
        credits = threading.Semaphore(self.window)
        done_lock = TrackedLock("merge.fetch_done")
        done = 0
        all_notified = threading.Event()  # ALL on_done callbacks returned
        cb_errors: list[Exception] = []
        box = self.penalty_box

        def supplier_of(seg) -> str:
            # single-host transports (host == "") degrade to per-map
            return seg.supplier

        def on_fault(seg, exc) -> None:
            # the STRUCTURED cause wins over the segment's current
            # source: a speculation loser's fault must punish the host
            # whose attempt failed, not whichever source the segment
            # switched to (UDA005: attribute, never reason-string)
            sup = getattr(exc, "supplier", None) or supplier_of(seg)
            self.ledger.record("fault", supplier=sup, map_id=seg.map_id,
                               error=exc)
            if box.punish(sup):
                log.warn(f"supplier {sup!r} penalized "
                         f"after repeated fetch faults ({exc})")

        def on_done(seg) -> None:
            nonlocal done
            if seg.ready:
                box.forgive(supplier_of(seg))
            credits.release()
            try:
                if on_segment is not None and seg.ready:
                    on_segment(index_of[id(seg)], seg)
            except Exception as e:  # surfaced after the waits below
                cb_errors.append(e)
            finally:
                with done_lock:
                    done += 1
                    d = done
                if d == live_total:
                    all_notified.set()
            if self.progress and (d + nskip) % PROGRESS_INTERVAL == 0:
                self.progress(d + nskip, len(segs))

        started: list[Segment] = []

        def drained() -> bool:
            with done_lock:
                return done >= len(started)

        def stop_drain() -> None:
            """The stop path must not abandon in-flight segments: abort
            the overlapped merger first (a completion thread blocked in
            its bounded feed() would otherwise never deliver on_done),
            administratively fail every started segment (idempotent —
            already-finished ones keep their outcome), then wait for the
            on_done callbacks so credits/progress are fully accounted
            before the caller sees the error."""
            om = (self._active_overlap if on_segment is not None
                  else None)
            if om is not None:
                om.abort()
            error = self._stall_error or MergeError(
                "merge manager stopped during fetch")
            for s in started:
                s.fail(error)
            deadline = time.monotonic() + 10.0
            while not drained() and time.monotonic() < deadline:
                time.sleep(0.01)
            if not drained():
                log.warn("stop drain: some fetch completions did not "
                         "deliver within 10 s; proceeding")

        self._live_segments = segs
        with metrics.timer("fetch"):
            pending = deque(order)
            while pending:
                # stop-responsive credit wait: stop() (watchdog rescue,
                # reduce_exit) must break a fetch loop that is blocked
                # on credits held by wedged segments
                while not credits.acquire(timeout=0.25):
                    if self._stop.is_set():
                        break
                if self._stop.is_set():
                    stop_drain()
                    raise (self._stall_error
                           or MergeError("merge manager stopped during "
                                         "fetch"))
                i = self._next_fetch_index(pending, segs, supplier_of)
                segs[i].on_done = on_done
                segs[i].on_fault = on_fault
                started.append(segs[i])
                # adopt the staged push prefix AT START TIME, not at
                # construction: maps that committed while earlier
                # segments held the window get their pushed bytes in
                self._push_adopt(segs[i])
                segs[i].start()
            for s in segs:
                if s is not None:
                    s.wait()
            # a segment's _done fires BEFORE its on_done callback runs:
            # wait for the callbacks too, or a caller could finalize its
            # on_segment consumer (e.g. the overlapped merger) while the
            # last completion is still being delivered. Stop-aware: a
            # completion thread can be wedged INSIDE an on_segment
            # consumer (e.g. blocked in the overlapped merger's bounded
            # feed) — a watchdog/stop() must be able to break this wait
            # too, not only the credit wait above
            if live_total:
                while not all_notified.wait(timeout=0.25):
                    if self._stop.is_set():
                        stop_drain()
                        raise (self._stall_error
                               or MergeError("merge manager stopped "
                                             "during fetch"))
        if cb_errors:
            raise cb_errors[0]
        if self.progress:
            self.progress(len(segs), len(segs))
        return segs

    def _next_fetch_index(self, pending: deque, segs, supplier_of) -> int:
        """Penalty-box-aware pick: the first pending segment whose
        supplier is not boxed; boxed ones rotate to the back. When every
        pending supplier is boxed, take the head anyway — the box
        deprioritizes, it never starves."""
        for _ in range(len(pending) - 1):
            if not self.penalty_box.penalized(supplier_of(segs[pending[0]])):
                break
            pending.rotate(-1)
            metrics.add("fetch.deprioritized")
        return pending.popleft()

    # -- merge phase --------------------------------------------------------

    def merge_segments(self, segments: Sequence[Segment]) -> RecordBatch:
        """Device-merge all fetched segments into one sorted batch.
        Routed by ``uda.tpu.merge.two_phase``: the two-phase device sort
        (per-run partial sort + HBM-resident merge tree) or the
        whole-shuffle re-sort — byte-identical either way."""
        batches = [s.record_batch() for s in segments]
        metrics.add("merge.records", sum(b.num_records for b in batches))
        mode = merge_ops.resolve_merge_mode(
            str(self.cfg.get("uda.tpu.merge.two_phase")), len(batches))
        with metrics.timer("merge"):
            if mode == "two_phase":
                return merge_ops.merge_batches_two_phase(
                    batches, self.key_type, self.key_width)
            return merge_ops.merge_batches(batches, self.key_type,
                                           self.key_width)

    def emit_framed(self, merged: RecordBatch,
                    consumer: Callable[[memoryview], None]) -> int:
        """Stream the sorted batch to ``consumer`` in IFile-framed blocks
        of at most the staging-buffer size (the dataFromUda contract:
        each call hands one filled KV block whose memory is only valid
        during the call, reference UdaPlugin.java:368-402). Framing runs
        through the native bulk framer when built (emit_batch). Returns
        total bytes emitted."""
        return self.emitter.emit_batch(merged, consumer)

    def run(self, job_id: str, map_ids: Sequence, reduce_id: int,
            consumer: Callable[[memoryview], None]) -> int:
        """The full online merge: fetch overlapped with device merge ->
        emit (reference merge_online, MergeManager.cc:184-193; the
        overlap restores the network-levitated property — see
        uda_tpu.merger.overlap).

        Failure contract: a terminal engine error (retries exhausted,
        merge invariant violation, spill failure — any ``UdaError``)
        is re-raised as :class:`FallbackSignal` carrying the root cause,
        so the consumer falls back to its vanilla path instead of
        crashing on an internal type (the reference's ``failureInUda``
        flip, UdaBridge.cc:506-530). Non-UdaError exceptions (embedder
        bugs, injected foreign errors) propagate unwrapped.

        Liveness contract (``uda.tpu.watchdog.stall.s`` > 0): a stall
        watchdog samples the task's progress counters; when nothing
        advances for the deadline it dumps every thread stack + the span
        tree and (``uda.tpu.watchdog.fallback``, default on) fails the
        in-flight segments so this call terminates with a
        ``FallbackSignal(StallError)`` instead of hanging forever."""
        # task-local emit progress (the watchdog token must not read
        # process-global counters — another task's emission would mask
        # this one's wedge); counted AFTER delivery so a consumer that
        # never returns reads as a stall
        self._emit_progress = 0

        def tracked_consumer(block: memoryview) -> None:
            consumer(block)
            self._emit_progress += len(block)

        wd = self._start_watchdog(reduce_id)
        # the MSG_STATS / final-stats-record scrape surface for THIS
        # task: penalty box, recovery ledger and the last admission
        # decision, live for the run's duration
        from uda_tpu.utils.stats import (register_stats_provider,
                                         unregister_stats_provider)

        def _recovery_provider() -> dict:
            adm = self.last_admission
            return {"penalty_box": self.penalty_box.snapshot(),
                    "ledger": self.ledger.snapshot(),
                    "admission": ({"decision": adm.decision,
                                   "cause": adm.cause,
                                   "reason": adm.reason}
                                  if adm is not None else None)}

        provider_name = f"recovery.r{reduce_id}"
        register_stats_provider(provider_name, _recovery_provider)
        try:
            # the trace root: every phase timer and per-segment fetch
            # span below hangs off this reduce-task span
            with metrics.span("reduce_task", job=job_id, reduce=reduce_id,
                              maps=len(map_ids)):
                return self._run(job_id, map_ids, reduce_id,
                                 tracked_consumer)
        except FallbackSignal as e:
            # a lower layer already chose fallback: the black box still
            # owes the post-mortem (run() is the one dump point, so a
            # task failure produces exactly ONE dump)
            flightrec.dump("fallback", extra={
                "job": job_id, "reduce": reduce_id,
                "error": type(e.cause).__name__})
            raise
        except UdaError as e:
            # a watchdog rescue surfaces through whichever waiter woke
            # first (a failed segment's wait, the stopped fetch loop);
            # report the STALL as the root cause, not the wake artifact
            stall = self._stall_error
            if stall is not None and not isinstance(e, StallError):
                e = stall
            metrics.add("fallback.signals")
            log.error(f"merge failed terminally, requesting fallback: {e}")
            # the flight-recorder post-mortem: the event stream behind
            # this fallback (injected faults, segment transitions,
            # recovery events) plus the terminal cause, dumped before
            # the signal leaves the engine
            flightrec.dump("fallback", extra={
                "job": job_id, "reduce": reduce_id,
                "error": type(e).__name__,
                "supplier": getattr(e, "supplier", None)})
            raise FallbackSignal(e) from e
        finally:
            unregister_stats_provider(provider_name, _recovery_provider)
            self._release_push()
            if wd is not None:
                wd.stop()
                self._watchdog = None

    def _revalidate_spilled(self, job_id: str) -> None:
        """Resume-side locator revalidation: reachable only when the
        transport is in-process (a LocalFetchClient — possibly behind a
        DecompressingClient — over an engine with an attached
        StoreManager); remote suppliers run the same check on their own
        resume path. Raises the store's typed error on damage."""
        client = self.client
        inner = getattr(client, "inner", None)
        if inner is not None:
            client = inner
        engine = getattr(client, "engine", None)
        store_mgr = getattr(engine, "store", None)
        if store_mgr is None:
            return
        n = store_mgr.validate_spilled(job_id)
        if n:
            log.info(f"ckpt: revalidated {n} spilled blob object(s) of "
                     f"job {job_id} before resume")

    # -- liveness -----------------------------------------------------------

    def _progress_token(self) -> tuple:
        """THIS task's progress signature, sampled by the watchdog.
        Deliberately task-local — built from this manager's own
        segments, overlapped merger and emit counter, never the
        process-global metrics hub: a co-located task's counters
        advancing must not mask this one's wedge. Any component
        changing (bytes fetched, retries consumed, segments finishing,
        runs staged/merged/pending, bytes delivered) counts as alive."""
        segs = self._live_segments
        ndone = nrec = noff = nret = 0
        for s in segs:
            if s is None:  # checkpoint-adopted slot: nothing to sample
                continue
            nrec += s.num_records
            noff += s._next_offset
            nret += s._retries_left
            if s._done.is_set():
                ndone += 1
        om = self._active_overlap
        om_sig = ((om.stats["staged_runs"], om.stats["device_merges"],
                   om.stats["pending"]) if om is not None else ())
        # the ledger version makes RECOVERY progress visible: a
        # reconstruction fetching stripe shards advances nothing on the
        # segment itself, but it is progress, not a stall. Same for the
        # checkpoint version: a long fsync/snapshot quiesces the
        # counters above, yet each completed save IS progress — without
        # it the watchdog would administratively fail a task for being
        # durable (the ISSUE 16 watchdog fix)
        ckpt = self._ckpt
        return (len(segs), ndone, nrec, noff, nret, om_sig,
                self.ledger.version, getattr(self, "_emit_progress", 0),
                ckpt.version if ckpt is not None else 0)

    def _start_watchdog(self, reduce_id: int) -> Optional[StallWatchdog]:
        stall_s = float(self.cfg.get("uda.tpu.watchdog.stall.s"))
        if stall_s <= 0:
            return None
        on_stall = (self._on_stall
                    if self.cfg.get("uda.tpu.watchdog.fallback") else None)
        wd = StallWatchdog(stall_s, self._progress_token,
                           on_stall=on_stall,
                           name=f"uda-watchdog-r{reduce_id}")
        self._watchdog = wd
        return wd.start()

    def _on_stall(self, err: StallError) -> None:
        """Watchdog rescue (runs on the watchdog thread): record the
        stall, stop the manager (breaks the fetch loop's credit and
        all-notified waits), abort the overlapped merger (unblocks
        completion threads wedged in its bounded feed / stager loops),
        and administratively fail every live segment so blocked waiters
        wake — the failure then flows through the normal FallbackSignal
        contract. A wedge inside the embedder's consumer callback itself
        cannot be interrupted from here; it still gets the diagnostic
        dump."""
        self._stall_error = err
        self._stop.set()
        try:
            self.client.stop()
        except Exception as e:  # noqa: BLE001 - rescue must not die here
            log.warn(f"watchdog: client stop failed: {e}")
        om = self._active_overlap
        if om is not None:
            try:
                om.abort()
            except Exception as e:  # noqa: BLE001
                log.warn(f"watchdog: overlap abort failed: {e}")
        for seg in list(self._live_segments):
            if seg is None:
                continue
            try:
                seg.fail(err)
            except Exception as e:  # noqa: BLE001
                log.warn(f"watchdog: failing segment "
                         f"{seg.map_id} raised: {e}")

    # -- crash-consistent checkpointing (merger/checkpoint.py) ---------------

    def _ckpt_state(self, job_id: str, reduce_id: int, mids: list,
                    store) -> tuple:
        """The snapshot collector handed to TaskCheckpoint: one
        crash-consistent view of everything the task would lose to a
        kill — spooled run files (already durable; recorded with
        length+CRC so a torn one is detected), in-flight fetch offset
        ledgers (Segment.ckpt_export), the recovery journal, penalty-box
        state and the merge-forest watermark. Returns
        ``(payload, parts)`` per the TaskCheckpoint.save contract."""
        from uda_tpu.merger import checkpoint

        runs: dict = {}
        for i, (n, nbytes, crc) in store.manifest().items():
            runs[str(i)] = {"map": mids[i], "records": int(n),
                            "bytes": int(nbytes),
                            "length": int(nbytes) + checkpoint.RUN_EOF_LEN,
                            "crc": int(crc)}
        ledgers: dict = {}
        parts: dict = {}
        for i, seg in enumerate(self._live_segments):
            if seg is None or str(i) in runs:
                continue
            ex = seg.ckpt_export()
            if ex is None:
                continue
            parts[i] = ex.pop("data")
            host = seg.supplier
            ex.update(map=seg.map_id, host=host,
                      generation=self.client.generation(host))
            ledgers[str(i)] = ex
        om = self._active_overlap
        payload = {"job": job_id, "reduce": int(reduce_id),
                   "maps": list(mids), "runs": runs, "ledgers": ledgers,
                   "journal": self.ledger.snapshot()["events"],
                   "penalty": self.penalty_box.snapshot(),
                   "forest": dict(om.stats) if om is not None else {}}
        return payload, parts

    def _resume_from_manifest(self, man: dict, mids: list, store, om,
                              ckpt) -> tuple:
        """Revalidate a loaded manifest and adopt what survives the
        ladder (generation -> epoch [at load] -> length+CRC ->
        drop-and-refetch). Returns ``(adopted, preload,
        adopted_records)``: indexes whose run files re-join the merge
        forest without refetching, and per-index ckpt_preload kwargs
        for mid-fetch offset-ledger resume. Anything that fails a check
        degrades to a fresh fetch of that segment — never an error."""
        from uda_tpu.merger import checkpoint

        if list(man.get("maps") or []) != list(mids):
            # a different map list is a different shuffle: nothing in
            # this manifest is addressable by index
            metrics.add("ckpt.invalidated", cause="maps")
            log.warn(f"checkpoint manifest for {ckpt.task} lists a "
                     f"different map set; starting fresh")
            return set(), {}, 0
        adopted: set = set()
        preload: dict = {}
        adopted_records = 0
        for key, rec in (man.get("runs") or {}).items():
            try:
                i = int(key)
                if not (0 <= i < len(mids)) or rec.get("map") != mids[i]:
                    raise StorageError(f"run index {key} does not map")
                run_path, off_path = store._paths(i)
                batch = checkpoint.read_run(run_path, off_path, rec)
            except (OSError, UdaError, ValueError, KeyError) as e:
                metrics.add("ckpt.invalidated", cause="crc")
                log.warn(f"checkpointed run {key} failed revalidation, "
                         f"refetching: {e}")
                try:
                    store.discard(int(key))
                except (ValueError, OSError):
                    pass  # udalint: disable=UDA006 - cleanup best effort
                continue
            store.adopt(i, int(rec["records"]), int(rec["bytes"]),
                        int(rec["crc"]))
            om.adopt_run(i, batch)
            adopted.add(i)
            adopted_records += batch.num_records
        for key, rec in (man.get("ledgers") or {}).items():
            try:
                i = int(key)
            except ValueError:
                continue
            if i in adopted or not (0 <= i < len(mids)) \
                    or rec.get("map") != mids[i]:
                continue
            host = str(rec.get("host") or "")
            gen_then = rec.get("generation")
            gen_now = self.client.generation(host)
            if (gen_then is not None and gen_now is not None
                    and int(gen_then) != int(gen_now)) \
                    or not self.client.resume_ok(host):
                # cold supplier restart: its map output was rebuilt, so
                # mid-stream offsets no longer address the same bytes
                metrics.add("ckpt.invalidated", cause="generation")
                log.warn(f"supplier {host!r} restarted since the "
                         f"checkpoint; refetching map {rec.get('map')} "
                         f"from zero")
                continue
            try:
                data = ckpt.part_bytes(rec)
            except StorageError as e:
                metrics.add("ckpt.invalidated", cause="ledger")
                log.warn(f"checkpointed ledger part of map "
                         f"{rec.get('map')} rejected, refetching: {e}")
                continue
            preload[i] = {"data": data,
                          "carry_len": int(rec.get("carry_len", 0)),
                          "next_offset": int(rec.get("next_offset", 0)),
                          "raw_length": rec.get("raw_length"),
                          "num_records": int(rec.get("num_records", 0))}
        self.ledger.restore(man.get("journal") or [])
        self.penalty_box.restore(man.get("penalty") or {})
        metrics.add("ckpt.resumed")
        metrics.add("ckpt.runs.adopted", len(adopted))
        log.info(f"resuming {ckpt.task} from checkpoint seq "
                 f"{man.get('seq')}: {len(adopted)} run(s) adopted, "
                 f"{len(preload)} in-flight ledger(s), "
                 f"{len(mids) - len(adopted)} map(s) to fetch")
        flightrec.record("ckpt.resume", task=ckpt.task,
                         seq=man.get("seq"), adopted=len(adopted),
                         ledgers=len(preload))
        return adopted, preload, adopted_records

    def _run(self, job_id: str, map_ids: Sequence, reduce_id: int,
             consumer: Callable[[memoryview], None]) -> int:
        approach = self.cfg.get("mapred.netmerger.merge.approach")
        streaming = bool(self.cfg.get("uda.tpu.online.streaming"))
        self.last_admission = None  # per-run routing record
        if approach == 0:
            # Auto policy (beyond the reference, which made the user
            # pick via mapred.netmerger.merge.approach), now budget-
            # aware (uda_tpu.utils.budget): the transport's size
            # estimate routes through MemoryBudget.route —
            #   in budget + small -> hybrid LPQ/RPQ (fastest at
            #     small/mid scale: 1.05 GB: 102 s vs streaming 192 s);
            #   in budget + large -> streaming online (wins at scale
            #     with O(window) host memory: 10.24 GB: 579 s vs 866 s
            #     at a third of the RSS) — REGRESSION_cpu_
            #     x{,x}large_r05.json;
            #   over the HBM/host budget -> streaming with bounded
            #     device runs (the degradation, never an OOM);
            #   over the hard ceiling (uda.tpu.budget.hard.mb) ->
            #     FallbackSignal BEFORE any fetch or allocation;
            #   unknown size -> streaming: bounded memory is the only
            #     safe default for an unbounded input.
            est = self.client.estimate_partition_bytes(
                job_id, map_ids, reduce_id)
            threshold = (self.cfg.get("uda.tpu.auto.approach.threshold.mb")
                         * (1 << 20))
            # checkpointing needs the run-spool (streaming) path: the
            # sorted run files ARE the durable half of the snapshot, and
            # hybrid's LPQ/RPQ state has no resume story — so an armed
            # ckpt dir steers the auto policy away from hybrid
            adm = self.budget().route(
                est, threshold,
                prefer_streaming=bool(str(self.cfg.get("uda.tpu.ckpt.dir"))))
            self.last_admission = adm
            # admission decisions carry their STRUCTURED cause into the
            # black box — a post-mortem reads why the task took the
            # path it did, not just that it failed on it
            flightrec.record("admission", decision=adm.decision,
                             cause=adm.cause, rejected=adm.rejected,
                             estimate=est)
            if adm.rejected:
                raise UdaError(
                    f"partition refused by admission control: "
                    f"{adm.reason} — falling back to the vanilla path "
                    f"(raise uda.tpu.budget.hard.mb to admit)")
            if adm.decision == "hybrid":
                approach = 2
            else:
                approach, streaming = 1, True
            log.info(f"auto merge approach: estimate="
                     f"{'unknown' if est is None else est} bytes -> "
                     f"{'hybrid' if approach == 2 else 'streaming online'}"
                     f" ({adm.reason})")
        if approach == 2:
            from uda_tpu.merger.hybrid import run_hybrid
            return run_hybrid(self, job_id, map_ids, reduce_id, consumer)
        if not streaming and not self.cfg.get("uda.tpu.merge.overlap"):
            segments = self.fetch_all(job_id, map_ids, reduce_id)
            merged = self.merge_segments(segments)
            return self.emit_framed(merged, consumer)

        from uda_tpu.merger.overlap import OverlappedMerger

        store = None
        ckpt = None
        manifest = None
        collect = None
        if streaming:
            # bounded-host-memory online mode (uda.tpu.online.streaming):
            # segments spool to sorted runs + release their bytes; the
            # bounded feed queue keeps pending segments at O(window);
            # emission interleaves the runs with sequential cursors —
            # no shuffle-sized host allocation anywhere (the reference's
            # staging-loop memory model, StreamRW.cc:151-225)
            from uda_tpu.merger.streaming import RunStore, spill_dirs

            ckpt_dir = str(self.cfg.get("uda.tpu.ckpt.dir"))
            if ckpt_dir:
                # crash-consistent checkpointing (merger/checkpoint.py):
                # run files spool into the checkpoint's FIXED dir (they
                # are the durable half of every snapshot; a tmpdir would
                # die with the process) and each spool boundary offers a
                # manifest save
                from uda_tpu.merger.checkpoint import TaskCheckpoint

                ckpt = TaskCheckpoint(
                    ckpt_dir, job_id, reduce_id,
                    interval_s=float(
                        self.cfg.get("uda.tpu.ckpt.interval.s")),
                    keep=int(self.cfg.get("uda.tpu.ckpt.keep")),
                    epoch=int(self.cfg.get("uda.tpu.tenant.epoch")))
                self._ckpt = ckpt
                manifest = ckpt.load()
                store = RunStore(tag=f"{job_id}.r{reduce_id}",
                                 fixed_dir=ckpt.runs_dir)
            else:
                store = RunStore(spill_dirs(self.cfg),
                                 tag=f"{job_id}.r{reduce_id}")
        # admission may have rerouted here BECAUSE the device row forest
        # would blow the HBM budget: then the streaming merger must not
        # stage runs to the device at all — run files + bounded k-way
        # merge instead ("streaming with bounded device runs")
        adm = self.last_admission
        bounded_device = (streaming and adm is not None
                          and adm.cause == "hbm")
        # staged pipeline (uda.tpu.stage.pipeline, default on): stage
        # pool + merge consumer with an in-flight byte budget; off =
        # the serial stage loop (the A/B twin). Pool width:
        # uda.tpu.stage.pool, else the legacy stagers knob, else auto.
        pipelined = bool(self.cfg.get("uda.tpu.stage.pipeline"))
        pool = int(self.cfg.get("uda.tpu.stage.pool"))
        stagers = int(self.cfg.get("uda.tpu.online.stagers"))
        if ckpt is not None:
            mids = [m[1] if isinstance(m, tuple) else m for m in map_ids]
            collect = functools.partial(self._ckpt_state, job_id,
                                        reduce_id, mids, store)
        om = OverlappedMerger(
            self.key_type, self.key_width, run_store=store,
            max_pending=self.window if streaming else 0,
            stagers=pool if (pipelined and pool > 0) else stagers,
            device_runs=not bounded_device,
            pipeline=pipelined,
            inflight_bytes=stage_inflight_cap(
                self.cfg, self.window, self.chunk_size,
                budget=self._budget_obj),
            on_spool=((lambda i: ckpt.maybe_save(collect))
                      if ckpt is not None else None))
        self._active_overlap = om  # observability (tests/diagnostics)
        adopted: set = set()
        preload: dict = {}
        adopted_records = 0
        self._live_segments = []
        if manifest is not None:
            # elastic-store interaction (ISSUE 18): partitions may have
            # SPILLED to the blob tier while this task was down — before
            # trusting the manifest's run files and offset ledgers,
            # re-verify every spilled object's CRC so damage surfaces
            # here as a typed StoreError, not later as a Segment CRC
            # mismatch blamed on the wire
            self._revalidate_spilled(job_id)
            adopted, preload, adopted_records = self._resume_from_manifest(
                manifest, mids, store, om, ckpt)
            # snapshot #0: the loaded manifest was consumed-on-load
            # (zombie fencing), so re-persist the adopted state before
            # fetching — a crash during THIS attempt's fetch phase must
            # still find a manifest (older retained generations back it
            # up, but re-persisting keeps the walk short)
            ckpt.maybe_save(collect, force=True)
        try:
            # feed the Segment itself: record_batch() (a full concat of
            # the segment's chunks) then runs on the merge thread, not
            # on the transport's completion thread
            segments = self.fetch_all(job_id, map_ids, reduce_id,
                                      on_segment=om.feed,
                                      skip=adopted, preload=preload)
        except Exception:
            # the abort (which also cleans up the run store) must never
            # MASK the fetch error that got us here: a failing cleanup
            # replacing the root cause is how errors get dropped on the
            # floor mid-unwind. In checkpoint mode nothing here discards
            # the manifest or the fixed-dir run files — they ARE the
            # next attempt's resume state (RunStore.cleanup is a no-op
            # for a fixed dir)
            try:
                om.abort()
            except Exception as cleanup_err:  # noqa: BLE001
                metrics.add("errors.swallowed")
                log.warn(f"overlap abort during failure unwind itself "
                         f"failed: {cleanup_err}")
            raise
        # the "merge" timer covers drain + forest carry inside the
        # finish paths; emission stays under the emitter's "emit" timer
        if streaming:
            out = om.finish_streaming(
                self.emitter, consumer,
                expected_records=(sum(s.num_records for s in segments
                                      if s is not None)
                                  + adopted_records))
            if ckpt is not None:
                # the emitted output is the durable artifact now; a
                # retained checkpoint would resume a FINISHED task
                ckpt.discard()
                self._ckpt = None
            return out
        return om.emit_stream([s.record_batch() for s in segments],
                              self.emitter, consumer)

    def stop(self) -> None:
        self._stop.set()
        self._release_push()
        self.client.stop()
