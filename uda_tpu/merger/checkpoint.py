"""Crash-consistent checkpoint/resume of a half-merged reduce task.

A reducer death used to lose every fetched, spooled and half-merged
byte (ROADMAP item 4's missing rung; Exoshuffle, arXiv:2203.05072,
argues shuffle durability should be a property of the shuffle library,
and Exoshuffle-CloudSort, arXiv:2301.03734, shows decoupling shuffle
state from worker lifetime is what makes restartable large sorts
economical). This module makes the reduce task's durable state an
atomic, versioned *manifest* under ``uda.tpu.ckpt.dir``:

- the **sorted run files** are already durable — the RunStore writes
  them to disk as segments spool; the manifest records each run's
  record count, byte length and CRC so a torn spool is detected and
  re-fetched rather than merged;
- the **fetch offset ledgers** of in-flight segments (framed batches +
  carry + next offset per source, from ``Segment.ckpt_export``) are
  persisted as side ``part`` files, so a restart continues each fetch
  mid-partition instead of from zero;
- the **RecoveryLedger journal** and **penalty-box** state ride along,
  so a resumed task keeps its supplier-health knowledge;
- the **merge-forest watermark** (the OverlappedMerger stats block) is
  recorded for diagnostics — the forest itself is device state and is
  rebuilt from the adopted runs on resume.

Manifest format (``manifest-<seq>.uckp``)::

    UCKP1 <crc32-of-payload> <payload-byte-length>\\n
    <payload: one JSON object>

Atomicity is write-to-temp + fsync + rename; the previous manifest is
retained until the new one lands (and ``uda.tpu.ckpt.keep`` older ones
after that), so a kill mid-snapshot — or an injected ``ckpt.save``
truncate fault — always leaves a previous valid manifest to fall back
to. A manifest is **consumed-on-load** (atomic rename claims it, like
the warm-restart handoff record of ISSUE 8), so a zombie reducer of a
superseded attempt can never resume state a successor already claimed;
tenant epoch fencing (PR 14) additionally refuses any manifest written
by a HIGHER epoch.

The revalidation ladder on resume (never trust, always verify):
supplier HELLO **generation** against the recorded one (cold supplier
restart drops that source's ledger, keeps its self-contained run
files) -> tenant **epoch** fence -> per-file **length+CRC** ->
drop-and-refetch on any mismatch. Checkpoint *saving* is strictly
best-effort: a failed snapshot degrades the resume point, it never
fails the task.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zlib
from typing import Callable, Optional

import numpy as np

from uda_tpu.utils.errors import StorageError
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.flightrec import flightrec
from uda_tpu.utils.ifile import EOF_MARKER, crack_partial
from uda_tpu.utils.locks import TrackedLock
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["TaskCheckpoint", "read_run"]

log = get_logger()

_MAGIC = b"UCKP1"
_MANIFEST_FMT = "manifest-%08d.uckp"


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync (the rename itself is what must be
    durable; some filesystems need the parent flushed too)."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # udalint: disable=UDA006 - durability best effort by design


def _write_atomic(path: str, data: bytes) -> None:
    """temp + fsync + rename: the file either exists complete or not at
    all (a torn write lives only under the .tmp name, never the real
    one)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)


def read_run(run_path: str, off_path: str, rec: dict):
    """Validate one checkpointed run file against its manifest record
    (length + CRC over the whole file including the EOF marker, offset
    sidecar shape) and re-crack it. Returns the RecordBatch; raises
    :class:`StorageError` on any mismatch — the caller then drops the
    file and re-fetches the segment from its source."""
    with open(run_path, "rb") as f:
        data = f.read()
    if len(data) != int(rec["length"]):
        raise StorageError(
            f"checkpointed run {run_path} is {len(data)} bytes, "
            f"manifest records {rec['length']} (torn spool)")
    if zlib.crc32(data) & 0xFFFFFFFF != int(rec["crc"]):
        raise StorageError(
            f"checkpointed run {run_path} failed its CRC check")
    records = int(rec["records"])
    nbytes = int(rec["bytes"])
    ends = np.fromfile(off_path, dtype="<i8")
    if ends.shape[0] != records or (records and int(ends[-1]) != nbytes):
        raise StorageError(
            f"checkpointed run {run_path}: offset sidecar shape "
            f"{ends.shape[0]}/{int(ends[-1]) if len(ends) else 0} does "
            f"not match the manifest ({records}/{nbytes})")
    batch, _, _ = crack_partial(data, expect_eof=True)
    if batch.num_records != records:
        raise StorageError(
            f"checkpointed run {run_path} re-cracked to "
            f"{batch.num_records} records, manifest says {records}")
    return batch


class TaskCheckpoint:
    """The durable snapshot store of ONE reduce task attempt.

    Layout under ``<root>/<job>.r<reduce>/``::

        manifest-<seq>.uckp   versioned manifests (newest wins on load)
        runs/                 the RunStore's fixed directory (run files
                              + offset sidecars survive the process)
        parts/                per-save in-flight fetch-ledger bytes
                              (p<seq>-s<seg>.part, named by save seq so
                              retained older manifests stay loadable)

    ``version`` is a monotone save-phase counter fed into the stall
    watchdog's progress token: a long fsync IS progress, never a stall.
    ``maybe_save`` is the run-spool-boundary trigger — rate-limited by
    ``interval_s`` (0 = every boundary), non-blocking across concurrent
    stage workers, and total: any save failure is counted
    (``ckpt.save.errors``) and logged, never raised into the task.
    """

    def __init__(self, root_dir: str, job_id: str, reduce_id: int, *,
                 interval_s: float = 30.0, keep: int = 2, epoch: int = 1):
        self.job_id = job_id
        self.reduce_id = int(reduce_id)
        self.interval_s = max(0.0, float(interval_s))
        self.keep = max(1, int(keep))
        self.epoch = int(epoch)
        self.task = f"{job_id}.r{reduce_id}"
        self.task_dir = os.path.join(root_dir, self.task)
        self.runs_dir = os.path.join(self.task_dir, "runs")
        self.parts_dir = os.path.join(self.task_dir, "parts")
        for d in (self.task_dir, self.runs_dir, self.parts_dir):
            os.makedirs(d, exist_ok=True)
        self.version = 0          # monotone save-phase counter (watchdog)
        self._seq = 0             # last written manifest sequence number
        self._last_save = 0.0     # monotonic time of the last save
        self._save_lock = TrackedLock("ckpt.save")

    # -- save side ----------------------------------------------------------

    def maybe_save(self, collect: Callable[[], tuple], *,
                   force: bool = False) -> bool:
        """The spool-boundary trigger: save when ``interval_s`` has
        elapsed since the last snapshot (``force`` bypasses the
        interval). Concurrent callers skip instead of queueing (one
        consistent snapshot per boundary is enough), and EVERY failure
        is absorbed here — checkpointing must never fail the task it
        protects."""
        if not force and self.interval_s > 0 and \
                time.monotonic() - self._last_save < self.interval_s:
            return False
        if not self._save_lock.acquire(blocking=False):
            return False  # a concurrent stage worker is already saving
        try:
            self._save_locked(collect)
            return True
        except Exception as e:  # noqa: BLE001 - best-effort by contract:
            # a failed snapshot only degrades the resume point
            metrics.add("ckpt.save.errors")
            log.warn(f"checkpoint save of {self.task} failed "
                     f"(task continues, resume point unchanged): {e}")
            return False
        finally:
            self._save_lock.release()

    def save(self, collect: Callable[[], tuple]) -> None:
        """One forced snapshot; raises on failure (tests / the explicit
        post-adoption snapshot go through :meth:`maybe_save` with
        ``force=True`` in production paths)."""
        with self._save_lock:
            self._save_locked(collect)

    def _save_locked(self, collect: Callable[[], tuple]) -> None:
        t0 = time.perf_counter()
        seq = self._seq + 1
        payload, parts = collect()
        total_bytes = 0
        # part files first: the manifest must only ever reference parts
        # that are already durable (named by seq, so retained OLDER
        # manifests keep referencing their own seq's parts)
        for i, data in parts.items():
            entry = payload["ledgers"].get(str(i))
            if entry is None:
                continue
            name = f"p{seq:08d}-s{int(i):05d}.part"
            _write_atomic(os.path.join(self.parts_dir, name), data)
            entry["part"] = name
            entry["part_len"] = len(data)
            entry["part_crc"] = zlib.crc32(data) & 0xFFFFFFFF
            total_bytes += len(data)
            self.version += 1  # each durable phase is watchdog progress
        payload["seq"] = seq
        payload["epoch"] = self.epoch
        body = json.dumps(payload, sort_keys=True).encode("utf-8")
        file_bytes = (b"%s %d %d\n" % (_MAGIC, zlib.crc32(body) & 0xFFFFFFFF,
                                       len(body))) + body
        # the injectable boundary: truncate = a torn manifest on disk
        # (load must fall back to the previous one), error = a failed
        # snapshot (absorbed by maybe_save), delay = a slow fsync (the
        # watchdog-token test rides it)
        file_bytes = failpoint("ckpt.save", data=file_bytes, key=self.task)
        path = os.path.join(self.task_dir, _MANIFEST_FMT % seq)
        _write_atomic(path, bytes(file_bytes))
        _fsync_dir(self.task_dir)
        self._seq = seq
        self._last_save = time.monotonic()
        self.version += 1
        total_bytes += len(file_bytes)
        self._prune()
        save_ms = (time.perf_counter() - t0) * 1e3
        metrics.add("ckpt.snapshots")
        metrics.add("ckpt.bytes", total_bytes)
        metrics.observe("ckpt.save_ms", save_ms)
        flightrec.record("ckpt.save", seq=seq,
                         runs=len(payload.get("runs") or {}),
                         ledgers=len(payload.get("ledgers") or {}),
                         bytes=total_bytes)

    def _manifests(self) -> list[tuple[int, str]]:
        """(seq, path) of every live manifest, newest first."""
        out = []
        try:
            names = os.listdir(self.task_dir)
        except OSError:
            return []
        for name in names:
            if not name.startswith("manifest-") or \
                    not name.endswith(".uckp"):
                continue
            try:
                seq = int(name[len("manifest-"):-len(".uckp")])
            except ValueError:
                continue
            out.append((seq, os.path.join(self.task_dir, name)))
        out.sort(reverse=True)
        return out

    def _prune(self) -> None:
        """Drop manifests beyond ``keep`` and part files older than the
        oldest retained manifest's save (parts are referenced only by
        the manifest of their own seq, by construction)."""
        manifests = self._manifests()
        keep_seqs = {s for s, _ in manifests[:self.keep]}
        for seq, path in manifests[self.keep:]:
            try:
                os.unlink(path)
            except OSError:
                pass  # udalint: disable=UDA006 - prune best effort
        floor = min(keep_seqs) if keep_seqs else 0
        try:
            part_names = os.listdir(self.parts_dir)
        except OSError:
            return
        for name in part_names:
            if not (name.startswith("p") and name.endswith(".part")):
                continue
            try:
                seq = int(name[1:9])
            except ValueError:
                continue
            if seq < floor:
                try:
                    os.unlink(os.path.join(self.parts_dir, name))
                except OSError:
                    pass  # udalint: disable=UDA006 - prune best effort

    # -- load side ----------------------------------------------------------

    def load(self) -> Optional[dict]:
        """Find, validate and CONSUME the newest manifest of this task.

        Walks manifests newest-first: a torn one (bad magic, length or
        CRC — e.g. a kill mid-snapshot or an injected ``ckpt.save``
        truncate) is unlinked and the walk falls back to the previous
        manifest — never a broken one, never a crash. A manifest
        written by a HIGHER tenant epoch means THIS process is the
        zombie: it must not consume its successor's state. The winner
        is claimed by atomic rename (consumed-on-load), so two racing
        attempts can never both resume it. Returns the payload dict or
        None (fresh start)."""
        try:
            failpoint("ckpt.load", key=self.task)
        except StorageError as e:
            # an unreadable checkpoint store degrades to a fresh start,
            # never a crash (the whole point of best-effort durability)
            metrics.add("ckpt.invalidated", cause="load")
            log.warn(f"checkpoint load of {self.task} failed; starting "
                     f"fresh: {e}")
            return None
        for seq, path in self._manifests():
            payload = self._read_manifest(path)
            if payload is None:
                metrics.add("ckpt.invalidated", cause="torn")
                log.warn(f"checkpoint manifest {path} is torn; falling "
                         f"back to the previous one")
                try:
                    os.unlink(path)
                except OSError:
                    pass  # udalint: disable=UDA006 - cleanup best effort
                continue
            if int(payload.get("epoch", 0)) > self.epoch:
                # epoch fence (PR 14): the manifest belongs to a NEWER
                # attempt — this process is the zombie; leave the state
                # for its rightful owner
                metrics.add("ckpt.invalidated", cause="epoch")
                log.warn(f"checkpoint manifest {path} was written by "
                         f"epoch {payload.get('epoch')} > ours "
                         f"{self.epoch}; refusing to resume it")
                return None
            try:
                os.rename(path, path + ".consumed")
            except OSError:
                return None  # a racing attempt claimed it first
            try:
                os.unlink(path + ".consumed")
            except OSError:
                pass  # udalint: disable=UDA006 - claim already durable
            self._seq = max(self._seq, seq)
            flightrec.record("ckpt.load", seq=seq,
                             runs=len(payload.get("runs") or {}),
                             ledgers=len(payload.get("ledgers") or {}))
            return payload
        return None

    @staticmethod
    def _read_manifest(path: str) -> Optional[dict]:
        """Parse + integrity-check one manifest; None when torn."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
            head, _, body = raw.partition(b"\n")
            fields = head.split(b" ")
            if len(fields) != 3 or fields[0] != _MAGIC:
                return None
            crc, length = int(fields[1]), int(fields[2])
            if len(body) != length or \
                    zlib.crc32(body) & 0xFFFFFFFF != crc:
                return None
            payload = json.loads(body.decode("utf-8"))
            return payload if isinstance(payload, dict) else None
        except (OSError, ValueError, UnicodeDecodeError):
            return None

    def part_bytes(self, entry: dict) -> bytes:
        """Read + integrity-check one ledger part file; raises
        :class:`StorageError` on any mismatch (the caller drops the
        ledger and re-fetches that segment from zero)."""
        name = str(entry.get("part") or "")
        if not name or os.sep in name or name.startswith("."):
            raise StorageError(f"checkpoint ledger names no valid part "
                               f"file ({name!r})")
        path = os.path.join(self.parts_dir, name)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            raise StorageError(f"checkpoint part {name} unreadable: "
                               f"{e}") from e
        if len(data) != int(entry.get("part_len", -1)) or \
                zlib.crc32(data) & 0xFFFFFFFF != int(entry.get("part_crc",
                                                               -1)):
            raise StorageError(
                f"checkpoint part {name} failed its length/CRC check")
        return data

    # -- lifecycle ----------------------------------------------------------

    def discard(self) -> None:
        """Remove the whole task checkpoint (the task completed: its
        emitted output is the durable artifact now)."""
        shutil.rmtree(self.task_dir, ignore_errors=True)
        flightrec.record("ckpt.discard", task=self.task)


# the manifest's run "length" convention: framed record bytes + the
# IFile EOF marker, i.e. the complete on-disk run file size
RUN_EOF_LEN = len(EOF_MARKER)
