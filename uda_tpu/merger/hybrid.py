"""Hybrid LPQ/RPQ merge: bounded-memory hierarchical merge.

Equivalent of the reference's merge_hybrid (reference
src/Merger/MergeManager.cc:202-288): when the shuffle exceeds memory, the
fetch stream is split into LPQs (local priority queues) of
``num_maps/num_lpqs`` segments — ``num_lpqs`` defaulting to
sqrt(num_maps) (reference src/Merger/reducer.cc:270-279) — each LPQ is
merged and spilled to a file ``<dir>/uda.<task>.lpq-NNN`` in round-robin
local dirs, and a final RPQ (residual priority queue) streams the merge
of the spill files (``SuperSegment``s, reference
src/Merger/StreamRW.cc:813-861) to the consumer with compression forced
off. LPQ parallelism is quota-bounded (``mapred.rdma.num.parallel.lpqs``,
min 3 — the concurrent_external_quota_queue semantics, reference
src/include/concurrent_queue.h:197-271).

TPU mapping: each LPQ merge is a device sort (runs sized to HBM); the
RPQ phase is a bounded-memory host heap-stream over the sorted spill
files, since its output leaves for the consumer anyway (host-bound by
contract, like the reference's final merge feeding Java).
"""

from __future__ import annotations

import math
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

from uda_tpu import native
from uda_tpu.ops import merge as merge_ops
from uda_tpu.utils.ifile import iter_file_records, native_enabled
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["run_hybrid", "num_lpqs_for"]

log = get_logger()


def num_lpqs_for(num_maps: int, lpq_size: int) -> int:
    """LPQ count: num_maps/lpq_size when configured, else sqrt(num_maps)
    (reference reducer.cc:270-279)."""
    if lpq_size > 0:
        return max(1, math.ceil(num_maps / lpq_size))
    return max(1, round(math.sqrt(num_maps)))


class SuperSegment:
    """File-backed sorted run; deletes its spill file when consumed
    (reference ~SuperSegment, StreamRW.cc:824-830)."""

    def __init__(self, path: str, buffer_size: int = 1 << 20):
        self.path = path
        self.buffer_size = buffer_size

    def stream(self):
        """Bounded-memory record cursor over the spill file."""
        return iter_file_records(self.path, self.buffer_size)

    def delete(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


def run_hybrid(mm, job_id: str, map_ids: Sequence, reduce_id: int,
               consumer: Callable[[memoryview], None]) -> int:
    """Fetch in LPQ-sized groups, spill device-merged runs, stream the
    final RPQ merge. ``mm`` is the owning MergeManager."""
    cfg = mm.cfg
    num_maps = len(map_ids)
    lpqs = num_lpqs_for(num_maps, cfg.get("mapred.netmerger.hybrid.lpq.size"))
    group = math.ceil(num_maps / lpqs)
    parallel = cfg.get("mapred.rdma.num.parallel.lpqs") or 3
    from uda_tpu.merger.streaming import spill_dirs as _spill_dirs

    spill_dirs = _spill_dirs(cfg)

    groups = [list(map_ids[i:i + group]) for i in range(0, num_maps, group)]
    log.info(f"hybrid merge: {num_maps} maps -> {len(groups)} LPQs of <= "
             f"{group}, {parallel} parallel")

    # every spill path is registered BEFORE its file is opened so a
    # failing LPQ (disk full, fetch error) can't orphan the completed
    # groups' multi-GB spill files — the reference leaned on ~SuperSegment
    # dtors for this (StreamRW.cc:824-830)
    spill_paths: list[str] = []
    paths_lock = threading.Lock()

    def spill_one(idx_group) -> SuperSegment:
        idx, g = idx_group
        segments = mm.fetch_all(job_id, g, reduce_id)
        merged = mm.merge_segments(segments)
        d = spill_dirs[idx % len(spill_dirs)]
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"uda.{job_id}.r{reduce_id}.lpq-{idx:03d}")
        with paths_lock:
            spill_paths.append(path)
        with metrics.timer("lpq_spill"):
            with open(path, "wb") as f:
                # native bulk framing in bounded chunks replaces the
                # per-record Python append loop (the hybrid write hot
                # spot) while keeping the spill STREAMED — peak RAM is
                # one chunk, not the multi-GB spill
                for piece in native.iter_framed_chunks(merged):
                    f.write(piece)
        return SuperSegment(path)

    try:
        with metrics.timer("lpq_phase"):
            with ThreadPoolExecutor(max_workers=parallel,
                                    thread_name_prefix="uda-lpq") as pool:
                supers = list(pool.map(spill_one, enumerate(groups)))
    except BaseException:
        for p in spill_paths:
            try:
                os.unlink(p)
            except OSError:
                pass
        raise

    # RPQ: bounded-memory streaming merge of the sorted spill files —
    # each SuperSegment contributes a buffered file cursor, so peak RAM
    # is one read-buffer per spill file, never the whole shuffle
    # (compression off by contract, MergeManager.cc:240-288). The hot
    # path is the native loser tree (merge.cc — the reference ran this
    # final merge in C++, MergeQueue.h:276-427 + StreamRW.cc:151-225);
    # the Python heap remains the semantic reference for comparators
    # the native table doesn't cover and when native is off/unbuilt
    # (byte-identical either way, tests/test_native.py).
    try:
        with metrics.timer("rpq_phase"):
            if (native_enabled() and native.kway_supported(mm.key_type)
                    and native.build()):
                log.info(f"RPQ: native loser-tree merge of "
                         f"{len(supers)} spills")
                pieces = native.kway_merge_paths(
                    [s.path for s in supers], mm.key_type)
                return mm.emitter.emit_framed(pieces, consumer)
            streams = [s.stream() for s in supers]
            merged = merge_ops.merge_record_streams(streams, mm.key_type)
            return mm.emitter.emit(merged, consumer)
    finally:
        for s in supers:
            s.delete()
