"""Staging buffer arena: the mem_desc slot state machine.

Equivalent of the reference's registered-memory pools (client buffer
pairs, reference src/DataNet/RDMAClient.cc:437-496 ``split_mem_pool_to_
pairs``; per-buffer state machine ``mem_desc_t`` {INIT, FETCH_READY,
MERGE_READY, BUSY} with cyclic start/end for the compression path,
reference src/Merger/MergeQueue.h:37-115).

On TPU there is no RDMA registration: the arena manages *host* staging
buffers with the same bounded-slot backpressure the reference got from
its fixed pool (wait-for-mem condition, accumulated in the
``total_wait_mem_time`` counter, reference reducer.h:80-90). It backs
(a) the 2-slot framed-emission double buffer (uda_tpu.merger.emitter —
the reference's 2 x 1 MB KV staging pool) and (b) H2D staging in the
exchange path. Fetch-side memory is bounded elsewhere, by the fetch
window (see uda_tpu.mofserver.data_engine docstring).
"""

from __future__ import annotations

import enum
import time
from typing import Callable, Optional

import numpy as np

from uda_tpu.utils.errors import MergeError
from uda_tpu.utils.locks import TrackedCondition, TrackedLock
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

log = get_logger()

__all__ = ["SlotState", "BufferSlot", "BufferArena"]


class SlotState(enum.Enum):
    # reference MergeQueue.h:44-49
    INIT = 0
    FETCH_READY = 1   # being filled by a fetch
    MERGE_READY = 2   # filled, ready for the merger
    BUSY = 3          # being consumed by the merger


class BufferSlot:
    """One staging buffer with its state + fill bookkeeping."""

    __slots__ = ("buf", "state", "length", "owner")

    def __init__(self, size: int):
        self.buf = np.empty(size, np.uint8)
        self.state = SlotState.INIT
        self.length = 0       # valid bytes
        self.owner = None     # segment currently holding the slot

    @property
    def size(self) -> int:
        return int(self.buf.shape[0])

    def write(self, data: bytes, offset: int = 0) -> None:
        end = offset + len(data)
        if end > self.size:
            raise MergeError(f"slot overflow: {end} > {self.size}")
        self.buf[offset:end] = np.frombuffer(data, np.uint8)
        self.length = end

    def view(self) -> np.ndarray:
        return self.buf[: self.length]


class BufferArena:
    """Fixed population of slots with blocking acquire (backpressure).

    ``acquire`` blocks until a slot is free, accumulating the wait in the
    ``wait_mem_time`` metric (reference total_wait_mem_time,
    reducer.h:84). Slots are sized once at construction like the
    reference page-aligns and validates its buffer size at INIT
    (reducer.cc:100-133).
    """

    def __init__(self, num_slots: int, slot_size: int,
                 on_pressure: Optional[Callable[[float], None]] = None,
                 pressure_after_s: float = 1.0):
        if num_slots <= 0 or slot_size <= 0:
            raise MergeError("arena needs positive slot count and size")
        self.slot_size = slot_size
        self._free: list[BufferSlot] = [BufferSlot(slot_size)
                                        for _ in range(num_slots)]
        # lockdep-tracked (utils/locks.py, UDA_TPU_LOCKDEP=1): the
        # arena cv is where the reference's wait-for-mem blocked, the
        # canonical seat of a lost-wakeup/inversion deadlock
        self._lock = TrackedLock("arena")
        self._cv = TrackedCondition(self._lock)
        self.num_slots = num_slots
        # soft-pressure hook: an acquire that waits past the threshold
        # reports the exhaustion ONCE per acquire (uda.tpu.arena.
        # pressure.s) — the signal a budget/stats layer uses to observe
        # "free slots stay exhausted" without ever blocking the arena
        self.on_pressure = on_pressure
        self.pressure_after_s = max(0.0, pressure_after_s)

    def acquire(self, owner=None, timeout: Optional[float] = None) -> BufferSlot:
        """Block until a slot frees. ``timeout`` is a TOTAL monotonic
        deadline across every wakeup — a notify/spurious wakeup that
        finds the free list empty resumes the SAME deadline instead of
        restarting the clock (the pre-fix bug: each loop iteration
        re-waited the full timeout, so a caller racing busy releasers
        could wait far longer than requested)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        t0 = time.monotonic()
        pressured = False
        with metrics.timer("wait_mem"):
            with self._cv:
                while not self._free:
                    now = time.monotonic()
                    remaining = (None if deadline is None
                                 else deadline - now)
                    if remaining is not None and remaining <= 0:
                        raise MergeError(
                            f"timed out waiting for a staging slot "
                            f"({timeout:g} s total deadline)")
                    wait_s = remaining
                    if (not pressured and self.on_pressure is not None):
                        to_pressure = self.pressure_after_s - (now - t0)
                        if to_pressure <= 0:
                            pressured = True
                            # drop the lock around the hook: a callback
                            # that reads arena state (free_slots) would
                            # otherwise self-deadlock
                            self._cv.release()
                            try:
                                self._pressure(now - t0)
                            finally:
                                self._cv.acquire()
                            continue
                        wait_s = (to_pressure if wait_s is None
                                  else min(wait_s, to_pressure))
                    self._cv.wait(timeout=wait_s)
                slot = self._free.pop()
        metrics.gauge_add("arena.slots_in_use", 1)
        slot.state = SlotState.FETCH_READY
        slot.length = 0
        slot.owner = owner
        return slot

    def _pressure(self, waited_s: float) -> None:
        """Fire the soft-pressure callback (caller holds the lock; the
        hook must be cheap and non-blocking — it is observability, not
        control flow, and its errors never fail the acquire)."""
        metrics.add("arena.pressure_events")
        try:
            self.on_pressure(waited_s)
        except Exception as e:  # noqa: BLE001
            log.warn(f"arena pressure callback failed: {e}")

    def try_acquire(self, owner=None) -> Optional[BufferSlot]:
        with self._cv:
            if not self._free:
                return None
            slot = self._free.pop()
        # the +1 rides the returned slot: whoever holds a BufferSlot
        # owns the -1 via release() (acquire() is the same contract;
        # it is exempted as the pair's own implementation name)
        metrics.gauge_add("arena.slots_in_use", 1)  # udalint: disable=UDA101
        slot.state = SlotState.FETCH_READY
        slot.length = 0
        slot.owner = owner
        return slot

    def release(self, slot: BufferSlot) -> None:
        metrics.gauge_add("arena.slots_in_use", -1)
        slot.state = SlotState.INIT
        slot.owner = None
        slot.length = 0
        with self._cv:
            self._free.append(slot)
            self._cv.notify()

    @property
    def free_slots(self) -> int:
        with self._lock:
            return len(self._free)
