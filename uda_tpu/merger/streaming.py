"""Streaming bounded-memory emission for the online merge.

The reference's online merge never materialized the shuffle on the host:
records flowed RDMA chunk buffers -> k-way heap -> 2 x 1 MB staging
buffers -> consumer (reference src/Merger/MergeManager.cc:155-182,
src/Merger/StreamRW.cc:151-225), so host memory stayed at
O(fetch window), independent of shuffle size. The TPU-native online path
computes the global sort permutation on device instead of running a
comparison heap — which is faster, but naively needs every segment's
bytes resident for the final gather. This module restores the
reference's memory model around the device permutation:

- **Sorted run spooling** (:class:`RunStore`): as each segment's fetch
  completes, its records are written to local disk *in per-segment
  sorted order* as an IFile-framed run plus an ``.off`` sidecar of
  cumulative framed-record end offsets; the raw fetched bytes are then
  released. Host memory during fetch = the in-flight window.
- **Permutation-driven interleave** (:func:`interleave_runs`): the
  merged device rows already encode, for every output position, which
  segment supplies the next record. Because each run is sorted, every
  run is consumed strictly *sequentially* — the emit phase is k
  buffered file cursors and one output slab, no comparisons, no random
  access ever (the property that let the reference emit from 1 MB
  staging buffers, MergeQueue.h:276-427).
- **Slab gather** (:func:`slab_batch`): the in-memory twin used when
  streaming is off — gathers each output slab's bytes directly from the
  per-segment batches, so even the memory-resident path never
  concatenates the whole shuffle a second time.

Everything is vectorized numpy; the only per-record work is done by the
native framer when runs are written.
"""

from __future__ import annotations

import os
import tempfile
import threading
import zlib
from typing import Iterator, Optional, Sequence

import numpy as np

from uda_tpu import native
from uda_tpu.utils.errors import MergeError, StorageError
from uda_tpu.utils.ifile import EOF_MARKER, RecordBatch, native_enabled
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["RunStore", "framed_lengths", "interleave_runs", "slab_batch",
           "iter_row_slabs", "SLAB_RECORDS"]

log = get_logger()

# records per emission slab: bounds transient host memory at emit to one
# slab's bytes (the streaming analogue of the reference's staging loop)
SLAB_RECORDS = 1 << 16


def _vlong_sizes(values: np.ndarray) -> np.ndarray:
    """Vectorized ``vint.vlong_size`` for non-negative lengths."""
    v = np.asarray(values, dtype=np.int64)
    if np.any(v < 0):
        raise MergeError("negative record length")
    # 1 byte for <=127; else 1 tag byte + minimal big-endian body
    nbits = np.zeros_like(v)
    nz = v > 0
    # number of bits via log2 on float64 is exact for lengths < 2^53
    nbits[nz] = np.floor(np.log2(v[nz])).astype(np.int64) + 1
    body = (nbits + 7) // 8
    return np.where(v <= 127, 1, body + 1)


def framed_lengths(key_len: np.ndarray, val_len: np.ndarray) -> np.ndarray:
    """Per-record IFile framed byte length: VInt(klen) VInt(vlen) key
    value (the ``write_kv_to_stream`` framing, StreamRW.cc:151-225)."""
    return (_vlong_sizes(key_len) + _vlong_sizes(val_len)
            + np.asarray(key_len, np.int64) + np.asarray(val_len, np.int64))


def _expand_spans(off: np.ndarray, length: np.ndarray) -> np.ndarray:
    """Flat int64 indices covering [off_i, off_i + length_i) for every i,
    concatenated in order — the vectorized byte-gather index (the
    pure-numpy fallback of :func:`_gather_spans`)."""
    length = np.asarray(length, np.int64)
    total = int(length.sum())
    if total == 0:
        return np.empty(0, np.int64)
    ends = np.cumsum(length)
    starts = ends - length
    return np.repeat(np.asarray(off, np.int64) - starts, length) + np.arange(
        total, dtype=np.int64)


_gather_impl = None  # resolved build/availability, cached per process


def _gather_spans(src: np.ndarray, src_off: np.ndarray, lens: np.ndarray,
                  dst: np.ndarray, dst_off: np.ndarray) -> None:
    """dst[dst_off_i : +len_i] = src[src_off_i : +len_i] per record —
    native memcpy loop when built (8x less memory traffic than the
    expand-index fallback, the streaming emit hot path). Library
    availability is resolved once per process; the ``uda.tpu.use.native``
    kill switch stays LIVE (re-read per call, like frame_batch)."""
    global _gather_impl
    if _gather_impl is None and native_enabled():
        _gather_impl = (native.gather_spans_native
                        if native.build() and native.available() else False)
    if (_gather_impl and native_enabled()
            and _gather_impl(src, src_off, lens, dst, dst_off)):
        return
    dst[_expand_spans(dst_off, lens)] = src[_expand_spans(src_off, lens)]


def _group_ranks(seg: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """For a slab's segment-index column, return (unique_segs,
    per-record rank within its segment group, per-seg counts) — the
    sequential-cursor positions each record consumes."""
    unique, inverse, counts = np.unique(seg, return_inverse=True,
                                        return_counts=True)
    # rank of each occurrence within its group, preserving slab order
    order = np.argsort(inverse, kind="stable")
    ranks_sorted = np.arange(seg.shape[0], dtype=np.int64) - np.repeat(
        np.cumsum(counts) - counts, counts)
    ranks = np.empty(seg.shape[0], np.int64)
    ranks[order] = ranks_sorted
    return unique, ranks, counts


def spill_dirs(cfg) -> list[str]:
    """Parse ``uda.tpu.spill.dirs`` into a rotation list (shared by the
    hybrid LPQ spiller and the streaming run store); empty = system
    tmp."""
    dirs = [d for d in str(cfg.get("uda.tpu.spill.dirs")).split(",") if d]
    return dirs or [tempfile.gettempdir()]


class RunStore:
    """Per-segment sorted run files + offset sidecars in scratch dirs.

    One run per staged segment: ``run-SSSSS.ifile`` holds the segment's
    records in sorted order with the EOF marker (a complete, valid IFile
    stream — so the comparator-level k-way merge can consume runs
    directly on the overflow fallback), and ``run-SSSSS.off`` holds
    int64 cumulative end offsets of each framed record (EOF excluded),
    letting the interleave slice records without parsing framing.
    Multiple base dirs rotate per segment (the reference's local-dir
    rotation the hybrid spiller also follows). Thread-safe: a staging
    pool may spool different segments concurrently.
    """

    def __init__(self, base_dirs=None, tag: str = "online",
                 fixed_dir: Optional[str] = None):
        # fixed_dir (checkpointing, merger/checkpoint.py): run files
        # live at a STABLE path that survives the process, so a
        # restarted attempt finds them where the manifest says; the
        # checkpoint owns the directory's lifetime (cleanup() keeps the
        # files — they ARE the durable state; TaskCheckpoint.discard
        # removes them on task success)
        self.fixed = fixed_dir is not None
        if self.fixed:
            os.makedirs(fixed_dir, exist_ok=True)
            self.dirs = [fixed_dir]
        else:
            if isinstance(base_dirs, str):
                base_dirs = [base_dirs]
            roots = (list(base_dirs) if base_dirs
                     else [tempfile.gettempdir()])
            self.dirs = []
            for root in roots:
                os.makedirs(root, exist_ok=True)
                self.dirs.append(
                    tempfile.mkdtemp(prefix=f"uda.{tag}.runs.", dir=root))
        self.counts: dict[int, int] = {}   # seg index -> record count
        self.bytes: dict[int, int] = {}    # seg index -> framed bytes (no EOF)
        self.crcs: dict[int, int] = {}     # seg index -> crc32 of the
        # whole run file including the EOF marker (the checkpoint
        # manifest's torn-spool detector)
        self._lock = threading.Lock()
        self._closed = False

    @property
    def dir(self) -> str:
        """Primary scratch dir (single-dir stores; tests)."""
        return self.dirs[0]

    def _paths(self, seg_index: int) -> tuple[str, str]:
        stem = os.path.join(self.dirs[seg_index % len(self.dirs)],
                            f"run-{seg_index:05d}")
        return stem + ".ifile", stem + ".off"

    def run_path(self, seg_index: int) -> str:
        return self._paths(seg_index)[0]

    @property
    def total_records(self) -> int:
        return sum(self.counts.values())

    @staticmethod
    def _contiguous_framed_span(batch: RecordBatch,
                                lens: np.ndarray) -> Optional[tuple]:
        """When the batch's records sit back-to-back in its data buffer
        in their original framing (the shape every cracked segment has),
        return the (start, end) byte span — the run file can then be
        written straight from the fetched bytes, skipping re-framing."""
        n = batch.num_records
        if n == 0:
            return None
        head = framed_lengths(batch.key_len, batch.val_len) \
            - batch.key_len - batch.val_len  # both VInt header bytes
        starts = batch.key_off - head
        ends = batch.val_off + batch.val_len
        if (int(starts[0]) >= 0 and np.all(starts[1:] == ends[:-1])
                and np.array_equal(lens, ends - starts)):
            return int(starts[0]), int(ends[-1])
        return None

    def write_run(self, seg_index: int, batch: RecordBatch,
                  order: np.ndarray) -> None:
        """Spool ``batch`` in ``order`` as this segment's sorted run.
        Streams framed chunks (native framer) — peak memory is one
        chunk, never the whole segment twice. Identity order over a
        contiguously framed batch (the already-sorted Hadoop MOF case)
        writes the fetched bytes verbatim."""
        with self._lock:
            if seg_index in self.counts:
                raise MergeError(f"segment {seg_index} staged twice")
            self.counts[seg_index] = -1  # reserve (pool-safe)
        sub = batch.take(order)
        run_path, off_path = self._paths(seg_index)
        lens = framed_lengths(sub.key_len, sub.val_len)
        ends = np.cumsum(lens)
        total = int(ends[-1]) if len(ends) else 0
        identity = (order.shape[0] > 0
                    and np.array_equal(order,
                                       np.arange(order.shape[0])))
        span = self._contiguous_framed_span(batch, lens) \
            if identity else None
        # CRC accumulated while writing (whole file incl. EOF): the
        # checkpoint manifest's torn-spool detector costs one pass over
        # bytes already in cache, no re-read
        crc = 0
        with metrics.timer("run_spool"):
            with open(run_path, "wb") as f:
                if span is not None:
                    piece = memoryview(batch.data[span[0]:span[1]])
                    f.write(piece)
                    crc = zlib.crc32(piece)
                    f.write(EOF_MARKER)
                    crc = zlib.crc32(EOF_MARKER, crc)
                else:
                    for piece in native.iter_framed_chunks(
                            sub, write_eof=True):
                        f.write(piece)
                        crc = zlib.crc32(piece, crc)
                if self.fixed:
                    f.flush()
                    os.fsync(f.fileno())
            wrote = os.path.getsize(run_path)
            if wrote != total + len(EOF_MARKER):
                raise StorageError(
                    f"run {seg_index}: framed {wrote} bytes, offsets "
                    f"predict {total + len(EOF_MARKER)}")
            with open(off_path, "wb") as f:
                ends.astype("<i8").tofile(f)
                f.flush()
                if self.fixed:
                    # checkpoint mode: the sidecar must be durable
                    # before a manifest can reference this run
                    os.fsync(f.fileno())
        with self._lock:
            self.counts[seg_index] = sub.num_records
            self.bytes[seg_index] = total
            self.crcs[seg_index] = crc & 0xFFFFFFFF
        metrics.add("spool.bytes", total)

    def adopt(self, seg_index: int, records: int, nbytes: int,
              crc: int) -> None:
        """Register an already-on-disk run (checkpoint resume: the file
        was written — and validated against the manifest — by a prior
        attempt). Accounting only; no bytes move."""
        with self._lock:
            if seg_index in self.counts:
                raise MergeError(f"segment {seg_index} staged twice")
            self.counts[seg_index] = int(records)
            self.bytes[seg_index] = int(nbytes)
            self.crcs[seg_index] = int(crc) & 0xFFFFFFFF

    def discard(self, seg_index: int) -> None:
        """Unlink an UNREGISTERED run's files (a checkpoint adoption
        that failed revalidation — the segment re-fetches and write_run
        later rewrites the path)."""
        for p in self._paths(seg_index):
            try:
                os.unlink(p)
            except OSError:
                pass  # udalint: disable=UDA006 - cleanup best effort

    def manifest(self) -> dict[int, tuple[int, int, int]]:
        """Snapshot of COMPLETED runs for the checkpoint writer:
        {seg_index: (records, framed_bytes, crc)} — reserved-but-
        unfinished spools (count -1) are excluded; they will appear in
        a later snapshot once durable."""
        with self._lock:
            return {s: (n, self.bytes[s], self.crcs[s])
                    for s, n in self.counts.items() if n >= 0}

    def cleanup(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            segs = list(self.counts)
        if self.fixed:
            # checkpoint-owned directory: the run files ARE the durable
            # resume state — a failed attempt must leave them for the
            # next one; TaskCheckpoint.discard removes the whole task
            # dir once the merge output is delivered
            return
        for seg in segs:
            for p in self._paths(seg):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        for d in self.dirs:
            try:
                os.rmdir(d)
            except OSError:
                pass


# open-cursor cap for the interleave: 2 fds per open cursor, kept well
# under common ulimits however many segments the shuffle has (evicted
# cursors reopen + seek — reads stay strictly sequential either way)
MAX_OPEN_CURSORS = 256


class _RunCursor:
    """Sequential reader over one run: hands out the byte span covering
    the next ``count`` records. Suspendable: ``suspend()`` closes both
    file handles and a later read transparently reopens at the consumed
    position, so an interleave over thousands of runs stays within the
    process fd limit."""

    __slots__ = ("run_path", "off_path", "run_f", "off_f",
                 "consumed_bytes", "consumed_records")

    def __init__(self, run_path: str, off_path: str):
        self.run_path = run_path
        self.off_path = off_path
        self.run_f = None
        self.off_f = None
        self.consumed_bytes = 0
        self.consumed_records = 0

    @property
    def is_open(self) -> bool:
        return self.run_f is not None

    def _ensure_open(self) -> None:
        if self.run_f is None:
            self.run_f = open(self.run_path, "rb")
            self.off_f = open(self.off_path, "rb")
            self.run_f.seek(self.consumed_bytes)
            self.off_f.seek(self.consumed_records * 8)

    def next_span(self, count: int) -> tuple[np.ndarray, np.ndarray]:
        """Returns (span_bytes, record_lengths) for the next ``count``
        records."""
        self._ensure_open()
        ends = np.fromfile(self.off_f, dtype="<i8", count=count)
        if ends.shape[0] != count:
            raise StorageError("run offset sidecar truncated")
        lens = np.diff(ends, prepend=np.int64(self.consumed_bytes))
        span = np.fromfile(self.run_f, dtype=np.uint8,
                           count=int(ends[-1]) - self.consumed_bytes)
        if span.shape[0] != int(ends[-1]) - self.consumed_bytes:
            raise StorageError("run file truncated")
        self.consumed_bytes = int(ends[-1])
        self.consumed_records += count
        return span, lens

    def suspend(self) -> None:
        if self.run_f is not None:
            self.run_f.close()
            self.off_f.close()
            self.run_f = self.off_f = None

    def close(self) -> None:
        self.suspend()


def iter_row_slabs(rows, valid: int,
                   slab: int = SLAB_RECORDS) -> Iterator[np.ndarray]:
    """Yield the merged composite-key rows in bounded host slabs (the
    rows may be device-resident; each slice transfers one slab)."""
    for start in range(0, valid, slab):
        stop = min(start + slab, valid)
        yield np.asarray(rows[start:stop])


def interleave_runs(slabs: Iterator[np.ndarray], store: RunStore,
                    num_key_words: int) -> Iterator[bytes]:
    """Permutation-driven k-way interleave of the sorted runs.

    ``slabs`` yields merged rows whose column ``num_key_words + 1`` is
    the segment index (the OverlappedMerger row layout). Each slab
    becomes one framed output piece; runs are read strictly
    sequentially (2 file handles per segment, like the hybrid RPQ's one
    cursor per spill). The concatenation of the yielded pieces plus the
    EOF marker is the complete merged IFile stream.
    """
    cursors: dict[int, _RunCursor] = {}
    open_lru: dict[int, None] = {}  # insertion-ordered set of open segs

    def _touch(s: int, cur: _RunCursor) -> None:
        open_lru.pop(s, None)
        open_lru[s] = None
        while len(open_lru) > MAX_OPEN_CURSORS:
            victim, _ = next(iter(open_lru.items()))
            del open_lru[victim]
            cursors[victim].suspend()

    try:
        for rows in slabs:
            if rows.shape[0] == 0:
                continue
            seg = rows[:, num_key_words + 1].astype(np.int64)
            unique, ranks, counts = _group_ranks(seg)
            spans: dict[int, np.ndarray] = {}
            starts: dict[int, np.ndarray] = {}
            lens: dict[int, np.ndarray] = {}
            for s, c in zip(unique.tolist(), counts.tolist()):
                cur = cursors.get(s)
                if cur is None:
                    if s not in store.counts:
                        raise MergeError(
                            f"merged rows reference unstaged segment {s}")
                    cur = cursors[s] = _RunCursor(*store._paths(s))
                span, ln = cur.next_span(c)
                _touch(s, cur)
                spans[s] = span
                lens[s] = ln
                starts[s] = np.cumsum(ln) - ln
            # per-record framed length and source offset in its span
            rec_len = np.empty(seg.shape[0], np.int64)
            src_off = np.empty(seg.shape[0], np.int64)
            for s in unique.tolist():
                m = seg == s
                rec_len[m] = lens[s][ranks[m]]
                src_off[m] = starts[s][ranks[m]]
            out = np.empty(int(rec_len.sum()), np.uint8)
            dst_end = np.cumsum(rec_len)
            dst_start = dst_end - rec_len
            for s in unique.tolist():
                m = seg == s
                _gather_spans(spans[s], src_off[m], rec_len[m],
                              out, dst_start[m])
            yield out.tobytes()
    finally:
        for cur in cursors.values():
            cur.close()
    # verify every run was fully consumed (lost-records guard)
    for s, n in store.counts.items():
        cur_records = cursors[s].consumed_records if s in cursors else 0
        if cur_records != n:
            raise MergeError(
                f"run {s}: merged rows consumed {cur_records} of {n} records")
    yield EOF_MARKER


def slab_batch(batches: Sequence[RecordBatch], seg: np.ndarray,
               row: np.ndarray) -> RecordBatch:
    """Gather one output slab's records from per-segment batches into a
    compact RecordBatch (its own small data buffer) — the in-memory
    emission path's bounded gather, replacing whole-shuffle concat."""
    m = seg.shape[0]
    k_len = np.empty(m, np.int64)
    v_len = np.empty(m, np.int64)
    for s in np.unique(seg).tolist():
        msk = seg == s
        b = batches[s]
        r = row[msk]
        k_len[msk] = b.key_len[r]
        v_len[msk] = b.val_len[r]
    k_total = int(k_len.sum())
    buf = np.empty(k_total + int(v_len.sum()), np.uint8)
    k_off = np.cumsum(k_len) - k_len
    v_off = k_total + np.cumsum(v_len) - v_len
    for s in np.unique(seg).tolist():
        msk = seg == s
        b = batches[s]
        r = row[msk]
        _gather_spans(b.data, b.key_off[r], k_len[msk], buf, k_off[msk])
        _gather_spans(b.data, b.val_off[r], v_len[msk], buf, v_off[msk])
    return RecordBatch(buf, k_off, k_len, v_off, v_len)
