"""Framed emission: sorted records -> consumer blocks via the staging
arena.

The single place that implements the dataFromUda hand-off contract
(reference src/Merger/MergeManager.cc:155-182 + UdaPlugin.java:368-402):
records are IFile-framed into staging buffers of at most the configured
block size and handed to the consumer one filled block at a time, the
final block carrying the EOF marker. Both the online and the hybrid RPQ
paths emit through here (one framing implementation, no drift).

The staging buffers come from a 2-slot BufferArena — the reference's
2 x 1 MB KV staging pool (NETLEV_KV_POOL_EXPO, reference
src/include/NetlevComm.h:33, spawn_reduce_task reducer.cc:303-324). The
consumer receives a read-only memoryview of the slot, valid only for the
duration of the call (exactly the DirectByteBuffer contract: the Java
side copies out during dataFromUda); the double-buffering lets a
pipelined consumer still hold the previous block while the next fills.
"""

from __future__ import annotations

import io
from typing import Callable, Iterable, Optional, Tuple

from uda_tpu import native
from uda_tpu.merger.arena import BufferArena
from uda_tpu.utils.ifile import IFileWriter, RecordBatch
from uda_tpu.utils.metrics import metrics

__all__ = ["FramedEmitter", "emit_framed_records", "NUM_STAGE_BUFFERS"]

NUM_STAGE_BUFFERS = 2  # reference NUM_STAGE_MEM / 2x1MB kv pool

# records framed per native pass in emit_batch: bounds the transient
# framed-bytes copy to a few MB regardless of merge size
FRAME_CHUNK_RECORDS = 1 << 16


class FramedEmitter:
    """Reusable emitter bound to one arena + block size."""

    def __init__(self, block_size: int,
                 arena: Optional[BufferArena] = None):
        self.block_size = block_size
        self.arena = arena or BufferArena(NUM_STAGE_BUFFERS, block_size)

    def _deliver(self, piece: bytes, held: list,
                 consumer: Callable[[memoryview], None]) -> int:
        """Hand one <= block_size piece to the consumer through an arena
        slot, releasing the previous slot one call late (double-buffer:
        a pipelined consumer may still hold the prior block)."""
        slot = self.arena.acquire()
        held.append(slot)
        slot.write(piece)
        if len(held) > 1:
            self.arena.release(held.pop(0))
        with metrics.timer("emit"):
            consumer(slot.view().data.toreadonly())
        return len(piece)

    def emit(self, records: Iterable[Tuple[bytes, bytes]],
             consumer: Callable[[memoryview], None]) -> int:
        """Frame ``records`` and stream to ``consumer``; returns bytes
        emitted. The memoryview passed to the consumer is only valid
        during the call."""
        out = io.BytesIO()
        writer = IFileWriter(out)
        total = 0
        held: list = []  # acquired slots not yet released (<= 2)

        def flush() -> None:
            nonlocal total
            block = out.getvalue()
            out.seek(0)
            out.truncate()
            # a single oversized record may exceed the block size; split
            # across as many consumer calls as needed (each <= block_size)
            for start in range(0, len(block), self.block_size):
                total += self._deliver(block[start:start + self.block_size],
                                       held, consumer)

        try:
            for key, value in records:
                writer.append(key, value)
                if out.tell() >= self.block_size:
                    flush()
            writer.close()  # EOF marker
            if out.tell():
                flush()
        finally:
            # a consumer exception must not strand slots: the arena is
            # task-lifetime (a leaked slot deadlocks the next emit)
            for slot in held:
                self.arena.release(slot)
        metrics.add("emit.bytes", total)
        return total

    def emit_framed(self, pieces: Iterable[bytes],
                    consumer: Callable[[memoryview], None]) -> int:
        """Stream an already-framed record stream (``pieces`` concatenate
        to the complete IFile stream INCLUDING the EOF marker) to the
        consumer in exactly-block_size slices. The stream concatenation
        contract is identical to emit(); blocks are not record-aligned,
        which emit() already allows for oversized records. Feeds both
        emit_batch (native chunk framing) and the native RPQ merge
        (uda_tpu.native.kway_merge_paths)."""
        total = 0
        held: list = []
        buf = bytearray()
        try:
            for piece in pieces:
                buf += piece
                while len(buf) >= self.block_size:
                    total += self._deliver(bytes(buf[:self.block_size]),
                                           held, consumer)
                    del buf[:self.block_size]
            while buf:
                total += self._deliver(bytes(buf[:self.block_size]),
                                       held, consumer)
                del buf[:self.block_size]
        finally:
            for slot in held:
                self.arena.release(slot)
        metrics.add("emit.bytes", total)
        return total

    def emit_batch(self, batch: RecordBatch,
                   consumer: Callable[[memoryview], None]) -> int:
        """Bulk emission of a RecordBatch: records are framed in native
        chunk passes (uda_tpu.native.frame_batch — the C++ twin of the
        reference's write_kv_to_stream hot loop, StreamRW.cc:151-225)
        instead of a per-record Python loop, then streamed through
        emit_framed."""
        return self.emit_framed(
            native.iter_framed_chunks(batch, FRAME_CHUNK_RECORDS,
                                      write_eof=True), consumer)


def emit_framed_records(records: Iterable[Tuple[bytes, bytes]],
                        block_size: int,
                        consumer: Callable[[memoryview], None]) -> int:
    """One-shot convenience wrapper."""
    return FramedEmitter(block_size).emit(records, consumer)
