"""Overlapped fetch/merge: the network-levitated property itself.

The reference's entire reason to exist is that the merge runs WHILE
fetches stream in (reference src/Merger/MergeManager.cc:47-182: arriving
MOFs join the k-way heap; src/Merger/StreamRW.cc:462-590: the merge loop
re-issues each segment's next chunk), so by the time the last map output
lands, almost all comparison work is already done. The TPU-native shape
of that property is NOT a record-at-a-time heap (which cannot use the
VPU) but a **log-structured run forest**:

- as each segment's fetch completes it is packed (host, vectorized) and
  staged to the device as a sorted run, while later fetches are still
  in flight;
- runs merge pairwise on device with the O(n) Pallas merge-path kernel
  (uda_tpu.ops.pallas_merge.merge_sorted_pair) under a binary-counter
  policy: each run is padded to a power-of-two capacity and two runs of
  equal capacity merge immediately into one of twice the capacity —
  every record therefore moves through at most log2(k) merges, total
  work O(n log k), and only O(log) distinct kernel shapes ever compile
  (pallas_call executables are shape-specialized; unconstrained segment
  sizes would compile a fresh kernel per (na, nb) pair);
- ``finish()`` merges the O(log k) leftover runs, largest-capacity
  last, and gathers the final byte permutation on host.

Stability contract (identical to ops.merge.merge_batches): the device
rows carry (key words, content length, segment index, row index) as the
composite sort key, so equal comparator keys order by original (segment,
row) arrival — independent of fetch COMPLETION order, which under a
randomized fetch schedule is nondeterministic.

Overflow fallback: keys whose content exceeds the carried width compare
by overflow *rank*, which is only meaningful computed across ALL records
(ops.packing.overflow_ranks). Rather than serialize rank computation,
the forest detects oversize keys at staging and ``finish()`` falls back
to the global device re-sort (merge_batches) — correctness never
depends on the fast path applying. TeraSort-shaped keys (10 B <= width)
always stay on the fast path.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from uda_tpu.ops import merge as merge_ops
from uda_tpu.ops import packing
from uda_tpu.ops.pallas_merge import merge_sorted_pair
from uda_tpu.utils.comparators import KeyType
from uda_tpu.utils.errors import MergeError
from uda_tpu.utils.ifile import RecordBatch
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["OverlappedMerger", "MIN_RUN_CAPACITY"]

log = get_logger()

MIN_RUN_CAPACITY = 512  # smallest padded run (= default merge tile)

_PAD_WORD = np.uint32(0xFFFFFFFF)


def _next_pow2(n: int) -> int:
    p = MIN_RUN_CAPACITY
    while p < n:
        p *= 2
    return p


class _Run:
    """One sorted run of the forest.

    Rows are uint32[cap, C] with C = key words + 3: the composite key
    (words..., content length, segment index, row index). Device
    (pallas-engine) runs are padded to a power-of-two capacity with
    all-0xFFFFFFFF rows, which sort strictly after every real row (a
    real row's length column is a content length < 2^31), so valid rows
    stay a prefix through any merge; host runs are exact-sized.

    ``bucket`` is the binary-counter size class: staging assigns
    next_pow2(valid), each merge doubles it — so every record passes
    through at most log2(k) merges regardless of engine.
    """

    __slots__ = ("rows", "valid", "bucket")

    def __init__(self, rows, valid: int, bucket: int):
        self.rows = rows
        self.valid = valid
        self.bucket = bucket

    @property
    def capacity(self) -> int:
        return int(self.rows.shape[0])


class OverlappedMerger:
    """Consumes completed segments during the fetch phase; produces the
    final permutation over the concatenated batches.

    ``engine`` selects the pairwise merge backend: "pallas" (the device
    merge-path kernel; the real TPU path), "host" (vectorized numpy
    lexsort merge — the correctness twin, and the fast choice where the
    only accelerator is the XLA CPU backend, whose interpret-mode Pallas
    emulation compiles an unrolled grid per shape), or "auto" (host on
    CPU, pallas elsewhere).
    """

    def __init__(self, key_type: KeyType, width: int, engine: str = "auto"):
        self.key_type = key_type
        self.width = width
        if engine == "auto":
            engine = "host" if jax.default_backend() == "cpu" else "pallas"
        if engine not in ("host", "pallas"):
            raise MergeError(f"unknown overlap merge engine {engine!r}")
        self.engine = engine
        # off-TPU, a forced pallas engine runs in interpret mode
        self.interpret = jax.default_backend() == "cpu"
        self._q: "queue.Queue" = queue.Queue()
        self._forest: dict[int, _Run] = {}   # capacity -> run
        self._overflow = False
        self._error: Optional[Exception] = None
        self._merges = 0
        self._staged = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="uda-overlap-merge")
        self._thread.start()

    # -- producer side (fetch completion callbacks, any thread) -------------

    def feed(self, seg_index: int, source) -> None:
        """Stage one completed segment's records (non-blocking; safe to
        call from a transport completion thread). ``source`` is either a
        RecordBatch or an object with a ``record_batch()`` method (a
        Segment) — materialization happens on the merge thread."""
        self._q.put((seg_index, source))

    # -- merge thread --------------------------------------------------------

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            if self._error is not None:
                continue  # drain; finish() will surface the error
            try:
                self._stage(*item)
            except Exception as e:  # surfaced at finish()
                self._error = e

    def _stage(self, seg_index: int, source) -> None:
        if self._overflow:
            return  # fast path already disabled; finish() re-sorts all
        batch = (source if isinstance(source, RecordBatch)
                 else source.record_batch())
        if batch.num_records == 0:
            return
        with metrics.timer("overlap_pack"):
            packed = packing.pack_keys(batch, self.key_type, self.width)
        if int(np.max(packed.key_lens, initial=0)) > self.width:
            # rank-bearing keys: cross-run rank consistency needs the
            # global view; disable the fast path (see module docstring)
            self._overflow = True
            return
        n = batch.num_records
        kw = packed.key_words.shape[1]
        # device runs pad to a power-of-two capacity (bounded set of
        # kernel shapes); host runs stay exact-sized
        cap = _next_pow2(n) if self.engine == "pallas" else n
        rows = np.full((cap, kw + 3), _PAD_WORD, np.uint32)
        rows[:n, :kw] = packed.key_words
        rows[:n, kw] = packed.key_lens.astype(np.uint32)
        rows[:n, kw + 1] = np.uint32(seg_index)
        rows[:n, kw + 2] = np.arange(n, dtype=np.uint32)
        # per-segment sort on host key order (vectorized lexsort over the
        # composite; row index column is already arrival order)
        order = np.lexsort(tuple(rows[:n, c] for c in range(kw, -1, -1)))
        rows[:n] = rows[:n][order]
        self._staged += 1
        with metrics.timer("overlap_stage"):
            if self.engine == "pallas":
                rows = jax.device_put(rows)
            self._insert(_Run(rows, n, _next_pow2(n)))

    def _insert(self, run: _Run) -> None:
        # binary-counter carry: equal size classes merge immediately
        while run.bucket in self._forest:
            other = self._forest.pop(run.bucket)
            run = self._merge(other, run)
        self._forest[run.bucket] = run

    def _merge(self, a: _Run, b: _Run) -> _Run:
        bucket = 2 * max(a.bucket, b.bucket)
        with metrics.timer("overlap_device_merge"):
            if self.engine == "host":
                rows = np.concatenate([a.rows[:a.valid], b.rows[:b.valid]])
                order = np.lexsort(tuple(
                    rows[:, c] for c in range(rows.shape[1] - 1, -1, -1)))
                merged = rows[order]
            else:
                # every column is part of the composite key (words, len,
                # seg, row) — rows are totally ordered, so the kernel's
                # internal tie-break never decides anything
                merged = merge_sorted_pair(a.rows, b.rows,
                                           num_keys=int(a.rows.shape[1]),
                                           interpret=self.interpret)
        self._merges += 1
        return _Run(merged, a.valid + b.valid, bucket)

    # -- consumer side -------------------------------------------------------

    @property
    def stats(self) -> dict:
        """Counters for observability/tests: merges that have completed
        and segments staged so far (both monotone)."""
        return {"device_merges": self._merges, "staged_runs": self._staged,
                "pending": self._q.qsize(), "overflow": self._overflow}

    def finish(self, batches: Sequence[RecordBatch]) -> RecordBatch:
        """Drain, merge the leftover forest, and materialize the sorted
        batch. ``batches`` must be ALL segments' batches in original
        segment-index order (the indices fed to :meth:`feed`)."""
        self._q.put(None)
        self._thread.join()
        if self._error is not None:
            raise self._error
        if self._overflow:
            log.warn("overlap fast path disabled (oversize keys); "
                     "falling back to global device re-sort")
            return merge_ops.merge_batches(batches, self.key_type,
                                           self.width)
        cat = RecordBatch.concat(list(batches))
        if not self._forest:
            if cat.num_records:
                # records exist but nothing was ever staged: the caller
                # skipped feed() — returning cat here would silently
                # emit UNSORTED data as the merge result
                raise MergeError(
                    f"overlap merge fed 0 of {cat.num_records} records")
            return cat  # all segments legitimately empty
        # merge leftovers smallest-first; on the pallas engine, pad the
        # smaller run up to the larger capacity first (padding rows sort
        # last, so the validity prefix is preserved) — capacities stay
        # powers of two, so kernel shapes stay in the O(log) compiled set
        runs = [self._forest[c] for c in sorted(self._forest)]
        self._forest = {}  # release device-resident runs when done
        acc = runs[0]
        for nxt in runs[1:]:
            if self.engine == "pallas" and acc.capacity < nxt.capacity:
                pad = np.full((nxt.capacity - acc.capacity,
                               int(acc.rows.shape[1])), _PAD_WORD, np.uint32)
                acc = _Run(jnp.concatenate(
                    [acc.rows, jax.device_put(pad)], axis=0), acc.valid,
                    acc.bucket)
            acc = self._merge(acc, nxt)
        rows = np.asarray(acc.rows)[:acc.valid]
        kw = rows.shape[1] - 3
        seg_col = rows[:, kw + 1].astype(np.int64)
        row_col = rows[:, kw + 2].astype(np.int64)
        sizes = np.asarray([b.num_records for b in batches], np.int64)
        offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
        perm = offsets[seg_col] + row_col
        if perm.shape[0] != cat.num_records:
            raise MergeError(
                f"overlap merge lost records: {perm.shape[0]} of "
                f"{cat.num_records} (segments fed != segments finished?)")
        return cat.take(perm)

    def abort(self) -> None:
        """Stop the merge thread without producing output."""
        self._q.put(None)
        self._thread.join(timeout=5.0)
