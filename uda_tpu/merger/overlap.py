"""Overlapped fetch/merge: the network-levitated property itself.

The reference's entire reason to exist is that the merge runs WHILE
fetches stream in (reference src/Merger/MergeManager.cc:47-182: arriving
MOFs join the k-way heap; src/Merger/StreamRW.cc:462-590: the merge loop
re-issues each segment's next chunk), so by the time the last map output
lands, almost all comparison work is already done. The TPU-native shape
of that property is NOT a record-at-a-time heap (which cannot use the
VPU) but a **log-structured run forest**:

- as each segment's fetch completes it is packed (host, vectorized) and
  staged to the device as a sorted run, while later fetches are still
  in flight;
- runs merge pairwise on device with the O(n) Pallas merge-path kernel
  (uda_tpu.ops.pallas_merge.merge_sorted_pair) under a binary-counter
  policy: each run is padded to a power-of-two capacity and two runs of
  equal capacity merge immediately into one of twice the capacity —
  every record therefore moves through at most log2(k) merges, total
  work O(n log k), and only O(log) distinct kernel shapes ever compile
  (pallas_call executables are shape-specialized; unconstrained segment
  sizes would compile a fresh kernel per (na, nb) pair);
- ``finish()`` merges the O(log k) leftover runs, largest-capacity
  last, and gathers the final byte permutation on host.

**Staging pipeline** (``pipeline=True``, the deployment default via
``uda.tpu.stage.pipeline``): staging is a true fetch→decompress→pack→
stage pipeline instead of one stage-a-whole-segment-at-a-time loop. A
bounded pool of stage workers runs the host-side work — segment
materialization (which includes the decompress tail and any pure-Python
LZO blocks), vint-decode/pack, row-matrix build on reusable
pre-allocated host buffers, run spooling — concurrently across
DIFFERENT segments, while ONE merge consumer drains the staged-run
queue: it dispatches ``jax.device_put`` of the next run while the
device merges of the previous run are still executing (JAX dispatch is
async; the consumer blocks only at accounting points — the host-buffer
recycle after a transfer completes, and the finish drain). In-flight
bytes are budgeted (``uda.tpu.stage.inflight.mb``): ``feed()`` blocks
while fed-but-unmerged bytes would exceed the cap, which is the same
credit-flow backpressure posture the bounded queue gives streaming mode
(the reference's RDMA credit flow, MergeManager.cc:47-63). The serial
path (``pipeline=False``) is kept verbatim as the correctness twin the
A/B bench and the byte-identity tests diff against
(scripts/bench_pipeline.py).

``merge.wait_ms`` measures how long the merge waited for each run to
become mergeable: feed()-to-staged latency (queue wait + decompress +
pack + spool). Its complement is the ``feed()`` backpressure block
(``stage.backpressure_events``) — together they say whether the device
is starved by the host (high wait) or the host is throttled by the
device (backpressure).

Stability contract (identical to ops.merge.merge_batches): the device
rows carry (key words, content length, segment index, row index) as the
composite sort key, so equal comparator keys order by original (segment,
row) arrival — independent of fetch COMPLETION order, which under a
randomized fetch schedule is nondeterministic. Pipelined and serial
staging are byte-identical by construction for the same reason: forest
insertion order never decides anything.

Overflow fallback: keys whose content exceeds the carried width compare
by overflow *rank*, which is only meaningful computed across ALL records
(ops.packing.overflow_ranks). Rather than serialize rank computation,
the forest detects oversize keys at staging and ``finish()`` falls back
to the global device re-sort (merge_batches) — correctness never
depends on the fast path applying. TeraSort-shaped keys (10 B <= width)
always stay on the fast path.
"""

from __future__ import annotations

import functools
import os
import queue
import threading
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from uda_tpu.ops import merge as merge_ops
from uda_tpu.ops import packing
from uda_tpu.utils.comparators import KeyType, uses_default_bytewise
from uda_tpu.utils.errors import MergeError
from uda_tpu.utils.ifile import EOF_MARKER, RecordBatch
from uda_tpu.utils.locks import TrackedCondition, TrackedLock
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics
from uda_tpu.utils.resledger import resledger

__all__ = ["OverlappedMerger", "MIN_RUN_CAPACITY"]

log = get_logger()

MIN_RUN_CAPACITY = merge_ops.MIN_RUN_CAPACITY

_PAD_WORD = merge_ops.PAD_WORD

_next_pow2 = merge_ops.next_run_capacity

# widest per-key content the vectorized overflow lexsort materializes
# as an n-by-width matrix; rarer/wider keys keep the comparator loop
_LEXSORT_MAX_KEY = 4096


class _Run:
    """One sorted run of the forest.

    Rows are uint32[cap, C] with C = key words + 3: the composite key
    (words..., content length, segment index, row index). Device
    (pallas-engine) runs are padded to a power-of-two capacity with
    all-0xFFFFFFFF rows, which sort strictly after every real row (a
    real row's length column is a content length < 2^31), so valid rows
    stay a prefix through any merge; host runs are exact-sized.

    ``bucket`` is the binary-counter size class: staging assigns
    next_pow2(valid), each merge doubles it — so every record passes
    through at most log2(k) merges regardless of engine. ``lease`` is
    the pool-owned host buffer backing ``rows`` (host-engine pipeline
    mode), recycled when this run merges into a larger one.
    """

    __slots__ = ("rows", "valid", "bucket", "lease")

    def __init__(self, rows, valid: int, bucket: int, lease=None):
        self.rows = rows
        self.valid = valid
        self.bucket = bucket
        self.lease = lease

    @property
    def capacity(self) -> int:
        return int(self.rows.shape[0])


class _StagedRun:
    """A stage worker's output awaiting the merge consumer: sorted host
    rows (possibly a leased pool buffer), fed timestamp (the
    merge.wait_ms anchor) and the in-flight byte charge it releases
    once merged."""

    __slots__ = ("seg_index", "rows", "valid", "lease", "fed_t", "charge")

    def __init__(self, seg_index: int, rows, valid: int, lease,
                 fed_t: float, charge: int):
        self.seg_index = seg_index
        self.rows = rows
        self.valid = valid
        self.lease = lease
        self.fed_t = fed_t
        self.charge = charge


# Reusable pre-allocated host row buffers (ops.merge.RowBufferPool).
# Pallas engine: stage workers lease, the merge consumer recycles once
# the jax.device_put transfer completes. Host engine (pipeline mode):
# staged runs AND merge outputs lease, each buffer recycled when its
# run merges into a larger one — killing the per-merge large-alloc
# page-fault churn that would otherwise dominate k*log2(k) merge
# traffic on this class of host.
_RowBufferPool = merge_ops.RowBufferPool

# host-engine merges at/above this many output rows split across
# threads at merge-path partition points (ops.merge.merge_rows_split_into)
# — below it the split/join overhead beats the win
_MERGE_SPLIT_MIN_ROWS = 1 << 18


class OverlappedMerger:
    """Consumes completed segments during the fetch phase; produces the
    final permutation over the concatenated batches.

    ``engine`` selects the pairwise merge backend: "pallas" (the device
    merge-path kernel; the real TPU path), "host" (vectorized numpy
    lexsort merge — the correctness twin, and the fast choice where the
    only accelerator is the XLA CPU backend, whose interpret-mode Pallas
    emulation compiles an unrolled grid per shape), or "auto" (host on
    CPU, pallas elsewhere).

    ``pipeline`` selects the staging architecture: False = the serial
    stage-then-merge loop (one thread per ``stagers``, the r8 behavior
    and the A/B baseline); True = the bounded stage pool + single merge
    consumer (see module docstring). ``inflight_bytes`` > 0 bounds the
    fed-but-unmerged bytes in either mode (feed() blocks — the
    credit-flow backpressure).
    """

    def __init__(self, key_type: KeyType, width: int, engine: str = "auto",
                 run_store=None, max_pending: int = 0, stagers: int = 0,
                 device_runs: bool = True, pipeline: bool = False,
                 inflight_bytes: int = 0, on_spool=None):
        self.key_type = key_type
        self.width = width
        # run-spool boundary hook (merger/checkpoint.py): called with the
        # segment index right after its sorted run file is durable — the
        # natural crash-consistent snapshot trigger. Contract: the hook
        # never raises (TaskCheckpoint.maybe_save catches internally).
        self._on_spool = on_spool
        # device_runs=False (streaming mode only): admission control
        # decided the full row forest would not fit the HBM budget —
        # segments still spool to sorted run files, but no run is ever
        # staged to the device; finish_streaming() merges the run FILES
        # with the bounded k-way path instead of the device forest.
        # Run files are written in (words, len) row order, which equals
        # comparator order for within-width keys, so the k-way merge is
        # correct on both the fast path and the overflow path.
        self.device_runs = bool(device_runs)
        if not self.device_runs and run_store is None:
            raise MergeError("device_runs=False requires streaming mode "
                             "(a run store)")
        self.engine = merge_ops.resolve_run_engine(engine)
        # off-TPU, a forced pallas engine runs in interpret mode
        self.interpret = jax.default_backend() == "cpu"
        # streaming mode (uda.tpu.online.streaming): segments spool to
        # sorted run files and release their bytes after staging; the
        # bounded queue is the credit backpressure that keeps
        # completed-but-unstaged segments at O(window)
        self.run_store = run_store
        # staging threads adopt the constructing thread's span (the
        # reduce-task root) so their pack/stage/merge timers land in the
        # right trace subtree
        self._parent_span = metrics.current_span()
        # udarace: lockfree=_q,_staged_q - queue.Queue is internally
        # locked; cross-thread put/get rides the Queue's own mutex
        self._q: "queue.Queue" = queue.Queue(maxsize=max_pending)
        # udarace: lockfree=_aborted,_overflow - one-way bool latches
        # (GIL-atomic store; readers may lag one item, by design)
        self._aborted = False
        self._forest: dict[int, _Run] = {}   # capacity -> run
        self._forest_lock = threading.Lock()
        self._state_lock = threading.Lock()  # counters/overflow flag
        self._overflow = False
        # udarace: lockfree=_error - first-error latch: a lagging racer
        # overwrites with its own exception, either surfaces at finish()
        self._error: Optional[Exception] = None
        self._merges = 0
        self._staged = 0
        # in-flight bytes budget: feed() charges, the merge consumer
        # (or the spool/drop path) releases; 0 = unbounded
        self._inflight_cap = max(0, int(inflight_bytes))
        self._inflight = 0
        self._inflight_cv = TrackedCondition(TrackedLock("stage.inflight"))
        self._native_rows_merge = None
        if self.engine == "host":
            # the host merge path dispatches to the native row merge;
            # resolve it ONCE here so a cold .so compiles before any
            # carry runs under _forest_lock (a make inside the lock
            # would stall the whole staging pool) and the per-merge hot
            # path pays no imports
            self._native_rows_merge = merge_ops.resolve_native_rows_merge()
        self.pipeline = bool(pipeline)
        self._consumer_thread: Optional[threading.Thread] = None
        if self.pipeline:
            # bounded stage pool + single merge consumer. Pool width:
            # explicit ``stagers`` wins; auto = a few workers (staging
            # is numpy-heavy and releases the GIL, so width ~ cores).
            width_auto = max(2, min(4, os.cpu_count() or 2))
            nworkers = stagers if stagers > 0 else width_auto
            # staged-run queue is bounded: a slow device consumer
            # backpressures the workers (and, through the in-flight
            # budget, the transports feeding feed())
            self._staged_q: "queue.Queue" = queue.Queue(maxsize=nworkers + 2)
            # host-buffer reuse where ownership hands off cleanly:
            # pallas = rows are COPIED to the device (recycle after the
            # transfer; interpret-mode device_put may alias numpy memory,
            # so it owns its arrays), host+native = staged runs AND
            # merge outputs lease (recycle when a run merges away), and
            # large host merges split across threads at merge-path
            # partition points — the merge half of the pipeline uses
            # the cores the stage half leaves idle
            self._buf_pool = None
            self._merge_parts = 1
            if self.engine == "pallas" and not self.interpret:
                self._buf_pool = _RowBufferPool()
            elif (self.engine == "host"
                  and self._native_rows_merge is not None):
                self._buf_pool = _RowBufferPool()
                self._merge_parts = max(2, min(4, os.cpu_count() or 2))
            self._workers = [
                threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"uda-stage-w{i}")
                for i in range(nworkers)]
            self._consumer_thread = threading.Thread(
                target=self._consumer_loop, daemon=True,
                name="uda-overlap-merge")
            self._threads = self._workers + [self._consumer_thread]
        else:
            # serial staging (uda.tpu.online.stagers): pack+sort+spool
            # of DIFFERENT segments parallelize; forest carries
            # serialize under _forest_lock (the merge chain itself is
            # one run at a time anyway). One thread when unset — the r4
            # behavior.
            self._staged_q = None
            self._buf_pool = None
            self._merge_parts = 1
            self._workers = [
                threading.Thread(target=self._loop, daemon=True,
                                 name=f"uda-overlap-merge-{i}")
                for i in range(max(1, stagers))]
            self._threads = list(self._workers)
        for t in self._threads:
            t.start()

    # -- producer side (fetch completion callbacks, any thread) -------------

    def feed(self, seg_index: int, source) -> None:
        """Stage one completed segment's records (safe to call from a
        transport completion thread). ``source`` is either a RecordBatch
        or an object with a ``record_batch()`` method (a Segment) —
        materialization happens on a stage thread. This call BLOCKS when
        staging lags — on the bounded queue (streaming mode) and on the
        in-flight bytes budget (``uda.tpu.stage.inflight.mb``) — which
        is the intended backpressure: the transport thread holds off
        until host memory frees (the reference's RDMA credit-flow
        posture, MergeManager.cc:47-63)."""
        charge = self._charge(source)
        if charge < 0:
            return  # aborted while waiting on the budget
        item = (seg_index, source, time.perf_counter(), charge)
        if self._q.maxsize <= 0:
            self._q.put(item)
        else:
            while True:
                if self._aborted:
                    self._release_charge(charge)
                    return
                try:
                    self._q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue
        if self._aborted:
            # the put may have raced abort(): _charge() saw the flag
            # unset, abort() then drained _q (threads already joined)
            # before our item landed — nothing would ever release its
            # charge. Re-drain: either a still-live worker consumed the
            # item (drain is a no-op) or we reap it here; a queue item
            # is consumed exactly once, so the charge releases exactly
            # once either way.
            self._reap_input_queue()

    @staticmethod
    def _source_bytes(source) -> int:
        """Best-effort byte size of a fed segment for the in-flight
        budget: a Segment's raw_length (uncompressed record bytes), a
        RecordBatch's buffer size."""
        raw = getattr(source, "raw_length", None)
        if raw:
            return int(raw)
        data = getattr(source, "data", None)
        if data is not None:
            return int(len(data))
        return 0

    def _charge(self, source) -> int:
        """Charge the segment against the in-flight budget, blocking
        (abort-responsive) while over it. Returns the charged bytes, or
        -1 when the merger aborted during the wait. A single oversized
        segment is admitted when nothing else is in flight (the same
        escape the supplier read budget has) — the budget bounds
        concurrency, it never wedges progress."""
        if self._inflight_cap <= 0:
            return 0
        charge = self._source_bytes(source)
        if charge <= 0:
            return 0
        blocked = False
        with self._inflight_cv:
            while (not self._aborted and self._inflight > 0
                   and self._inflight + charge > self._inflight_cap):
                if not blocked:
                    blocked = True
                    metrics.add("stage.backpressure_events")
                self._inflight_cv.wait(timeout=0.1)
            if self._aborted:
                return -1
            self._inflight += charge
        # the +charge rides the returned int: feed() pairs every
        # non-negative _charge() with exactly one _release_charge()
        # (consumer dispatch, abort drain, or its own unwind)
        metrics.gauge_add("stage.inflight.bytes", charge)  # udalint: disable=UDA101
        return charge

    def _release_charge(self, charge: int) -> None:
        if charge <= 0:
            return
        with self._inflight_cv:
            self._inflight -= charge
            self._inflight_cv.notify_all()
        metrics.gauge_add("stage.inflight.bytes", -charge)

    # -- serial merge thread (pipeline=False; the A/B baseline) -------------

    def _loop(self) -> None:
        with metrics.use_span(self._parent_span):
            while True:
                try:
                    item = self._q.get(timeout=0.25)
                except queue.Empty:
                    if self._aborted:
                        return  # abort() without a reachable poison pill
                    continue
                if item is None:
                    return
                seg_index, source, fed_t, charge = item
                if self._error is not None or self._aborted:
                    self._release_charge(charge)
                    continue  # drain; finish() will surface the error
                try:
                    self._stage(seg_index, source, fed_t)
                except Exception as e:  # surfaced at finish()
                    self._error = e
                finally:
                    self._release_charge(charge)

    def _stage(self, seg_index: int, source, fed_t: float) -> None:
        staged = self._prepare(seg_index, source, fed_t)
        if staged is None:
            return
        self._observe_wait(fed_t)
        self._consume_run(staged)

    # -- pipelined staging (pipeline=True) -----------------------------------

    def _worker_loop(self) -> None:
        """Stage worker: decompress/materialize + pack + row build +
        spool for ONE segment at a time, concurrently across workers;
        finished runs queue for the merge consumer."""
        with metrics.use_span(self._parent_span):
            while True:
                try:
                    item = self._q.get(timeout=0.25)
                except queue.Empty:
                    if self._aborted:
                        return
                    continue
                if item is None:
                    return
                seg_index, source, fed_t, charge = item
                if self._error is not None or self._aborted:
                    self._release_charge(charge)
                    continue
                try:
                    staged = self._prepare(seg_index, source, fed_t)
                except Exception as e:  # surfaced at finish()
                    self._error = e
                    self._release_charge(charge)
                    continue
                if staged is None:
                    self._release_charge(charge)
                    continue
                staged.charge = charge
                self._put_staged(staged)

    def _put_staged(self, staged: _StagedRun) -> None:
        while not self._aborted:
            try:
                self._staged_q.put(staged, timeout=0.1)
                return
            except queue.Full:
                continue
        self._discard(staged)

    def _consumer_loop(self) -> None:
        """The merge loop as a consumer of staged runs: device_put of
        the next run is dispatched while the previous run's merges are
        still executing (async dispatch); the forest carry serializes
        here, which also makes _forest_lock uncontended in pipeline
        mode."""
        with metrics.use_span(self._parent_span):
            # merge.wait spans: the consumer's blocked-on-staging time
            # as a first-class trace lane (the span twin of the
            # merge.wait_ms histogram, critpath's "wait" bucket). One
            # span covers each contiguous wait; a no-op while spans
            # are disabled
            wait = metrics.start_span("merge.wait")
            while True:
                try:
                    staged = self._staged_q.get(timeout=0.25)
                except queue.Empty:
                    if self._aborted:
                        wait.end(aborted=True)
                        return
                    continue
                wait.end()
                if staged is None:
                    return
                if self._error is not None or self._aborted:
                    self._discard(staged)
                    wait = metrics.start_span("merge.wait")
                    continue
                try:
                    self._observe_wait(staged.fed_t)
                    self._consume_run(staged)
                    metrics.add("merge.pipeline.runs")
                except Exception as e:  # surfaced at finish()
                    self._error = e
                    self._recycle(staged)
                finally:
                    self._release_charge(staged.charge)
                    staged.charge = 0
                wait = metrics.start_span("merge.wait")

    def _discard(self, staged: _StagedRun) -> None:
        """Drop a staged run without merging (abort/error drain):
        release its budget charge and recycle its buffer lease."""
        self._release_charge(staged.charge)
        staged.charge = 0
        self._recycle(staged)

    def _recycle(self, staged: _StagedRun) -> None:
        if staged.lease is not None and self._buf_pool is not None:
            self._buf_pool.release(staged.lease)
        staged.lease = None

    @staticmethod
    def _observe_wait(fed_t: float) -> None:
        # merge-wait: how long the merge waited for this run to become
        # mergeable after its segment was fed (queue wait + decompress
        # tail + pack + spool). Its complement is the feed()
        # backpressure block (stage.backpressure_events): high wait =
        # the device is starved by the host, backpressure = the host is
        # throttled by the device.
        metrics.observe("merge.wait_ms",
                        (time.perf_counter() - fed_t) * 1e3)

    # -- staging ------------------------------------------------------------

    @staticmethod
    def _release(source) -> None:
        """Free a staged segment's raw bytes (streaming mode only: the
        sorted run on disk is now the record source of truth)."""
        release = getattr(source, "release", None)
        if release is not None:
            release()

    def _notify_spool(self, seg_index: int) -> None:
        """Fire the run-spool boundary hook (checkpoint trigger) outside
        every merger lock — the hook fsyncs."""
        hook = self._on_spool
        if hook is not None:
            hook(seg_index)

    def adopt_run(self, seg_index: int, batch: RecordBatch) -> None:
        """Resume path (merger/checkpoint.py): account a run file that a
        PREVIOUS attempt already spooled — the re-cracked, already-sorted
        batch joins the forest without re-spooling. Single-threaded by
        contract: called before any feed(), so no staging worker races
        the forest. Byte-identity with the uninterrupted run holds
        because the run file is in sorted order, so the identity order
        (row index = file position) reproduces exactly the rows the
        original ``_prepare`` built."""
        n = batch.num_records
        if n == 0:
            return
        with metrics.timer("overlap_pack"):
            packed = packing.pack_keys(batch, self.key_type, self.width)
        kw = packed.key_words.shape[1]
        if int(np.max(packed.key_lens, initial=0)) > self.width:
            # oversize keys: same posture as _prepare — disable the fast
            # path; finish_streaming's comparator k-way file merge (which
            # reads this adopted run file) is the correctness fallback
            self._overflow = True
        with self._state_lock:
            self._staged += 1
        metrics.add("merge.records", n)
        if self._overflow or not self.device_runs:
            return
        cap = _next_pow2(n) if self.engine == "pallas" else n
        rows = np.empty((cap, kw + merge_ops.ROW_EXTRA_COLS), np.uint32)
        merge_ops.fill_run_rows(rows, packed, None, seg_index)
        self._consume_run(_StagedRun(seg_index, rows, n, None,
                                     time.perf_counter(), 0))

    def _prepare(self, seg_index: int, source,
                 fed_t: float) -> Optional[_StagedRun]:
        """The host half of staging: materialize (the decompress tail
        runs here for Segment sources), pack, per-run sort, spool.
        Returns the device-bound staged run, or None when nothing needs
        the forest (empty segment, spool-only modes, overflow)."""
        streaming = self.run_store is not None
        if self._overflow and not streaming:
            return None  # fast path already disabled; finish() re-sorts
        batch = (source if isinstance(source, RecordBatch)
                 else source.record_batch())
        n = batch.num_records
        if n == 0:
            if streaming:
                self._release(source)
            return None
        with metrics.timer("overlap_pack"):
            packed = packing.pack_keys(batch, self.key_type, self.width)
        kw = packed.key_words.shape[1]
        metrics.add("stage.bytes",
                    int(batch.key_len.sum() + batch.val_len.sum()))
        if int(np.max(packed.key_lens, initial=0)) > self.width:
            # rank-bearing keys: cross-run rank consistency needs the
            # global view; disable the fast path (see module docstring)
            self._overflow = True
            if not streaming:
                return None
            # streaming keeps spooling: this run is ordered by the FULL
            # comparator, so finish falls back to the comparator-level
            # k-way merge over the run files — still O(window) host
            # memory
            order = self._overflow_order(batch, n)
            self.run_store.write_run(seg_index, batch, order)
            with self._state_lock:
                self._staged += 1
            metrics.add("merge.records", n)
            self._notify_spool(seg_index)
            self._observe_wait(fed_t)
            self._release(source)
            return None
        # per-segment sort on host key order: Hadoop map outputs arrive
        # ALREADY comparator-sorted (the map-side sort contract), and
        # for within-width keys comparator order == (words, len) order,
        # so the O(n·k) monotonicity check usually replaces the
        # O(n log n) lexsort (run_row_order) — the staging hot path
        # collapses to pack+spool at memory bandwidth. Unsorted input
        # (exchange-path buckets, foreign writers) still sorts.
        order = merge_ops.run_row_order(packed)
        if streaming:
            spool_order = (np.arange(n, dtype=np.int64) if order is None
                           else order)
            self.run_store.write_run(seg_index, batch, spool_order)
            self._release(source)
            self._notify_spool(seg_index)
        with self._state_lock:
            self._staged += 1
        metrics.add("merge.records", n)
        if self._overflow or not self.device_runs:
            self._observe_wait(fed_t)
            return None  # forest output won't be consumed; runs suffice
        # device runs pad to a power-of-two capacity (bounded set of
        # kernel shapes); host runs stay exact-sized
        cap = _next_pow2(n) if self.engine == "pallas" else n
        if self._buf_pool is not None:
            lease = self._buf_pool.lease(cap, kw + merge_ops.ROW_EXTRA_COLS)
            try:
                merge_ops.fill_run_rows(lease, packed, order, seg_index)
                return _StagedRun(seg_index, lease, n, lease, fed_t, 0)
            except BaseException:
                # a packing failure (bad order vector, width drift)
                # must not strand the host buffer: the abort drain
                # asserts the pool is whole, and a leaked lease pins
                # staging budget forever
                self._buf_pool.release(lease)
                raise
        rows = np.empty((cap, kw + merge_ops.ROW_EXTRA_COLS), np.uint32)
        merge_ops.fill_run_rows(rows, packed, order, seg_index)
        return _StagedRun(seg_index, rows, n, None, fed_t, 0)

    def _overflow_order(self, batch: RecordBatch, n: int) -> np.ndarray:
        """Full-comparator sort order for an oversize-key run. Default
        bytewise comparators vectorize: memcmp-with-shorter-is-smaller
        order == lexsort over (zero-padded content bytes, content
        length) — no O(n log n) interpreter-level compares on the hot
        path. A custom ``compare`` override (or pathologically wide
        keys) keeps the comparator-faithful cmp_to_key path."""
        kt = self.key_type
        if uses_default_bytewise(kt):
            contents = [kt.content(batch.key(i)) for i in range(n)]
            lens = np.fromiter((len(c) for c in contents),
                               np.int64, count=n)
            width = int(lens.max(initial=0))
            if 0 < width <= _LEXSORT_MAX_KEY:
                mat = np.zeros((n, width), np.uint8)
                for i, c in enumerate(contents):
                    mat[i, :len(c)] = np.frombuffer(c, np.uint8)
                cols = [mat[:, j] for j in range(width)] + [lens]
                # np.lexsort is stable -> ties keep arrival order, the
                # same (i - j) tiebreak the comparator path applies
                return np.lexsort(tuple(reversed(cols))).astype(np.int64)
        cmp = kt.compare
        keys = [batch.key(i) for i in range(n)]
        return np.asarray(sorted(range(n), key=functools.cmp_to_key(
            lambda i, j: cmp(keys[i], keys[j]) or (i - j))), np.int64)

    def _consume_run(self, staged: _StagedRun) -> None:
        """The device half of staging: transfer + forest insert. The
        merges this triggers dispatch asynchronously; the only block is
        the transfer completion that frees a leased host buffer."""
        rows = staged.rows
        with metrics.timer("overlap_stage"):
            if self.engine == "pallas":
                with metrics.span("merge.device_put", rows=staged.valid):
                    dev = jax.device_put(rows)
                    if staged.lease is not None:
                        # accounting point: the host buffer may only be
                        # reused once the transfer is done. Merges of
                        # the PREVIOUS run keep executing under this
                        # wait.
                        t0 = time.perf_counter()
                        jax.block_until_ready(dev)
                        metrics.observe("merge.pipeline.put_ms",
                                        (time.perf_counter() - t0) * 1e3)
                        self._recycle(staged)
                rows = dev
            # host engine: the run KEEPS its pool lease (recycled when
            # it merges away); ownership moves to the _Run so an
            # error-path _recycle can never double-release it
            lease, staged.lease = staged.lease, None
            self._insert(_Run(rows, staged.valid, _next_pow2(staged.valid),
                              lease=lease))

    def _insert(self, run: _Run) -> None:
        # binary-counter carry: equal size classes merge immediately.
        # The lock serializes carries across the staging pool (pack/
        # sort/spool of other segments proceed concurrently).
        with self._forest_lock:
            while run.bucket in self._forest:
                other = self._forest.pop(run.bucket)
                # the transitive join() is the split merge waiting on
                # its OWN compute workers — bounded work on data already
                # in hand, not a wait on external progress; serializing
                # carries under the lock is the forest design
                run = self._merge(other, run)  # udalint: disable=UDA102
            self._forest[run.bucket] = run

    def _merge(self, a: _Run, b: _Run) -> _Run:
        bucket = 2 * max(a.bucket, b.bucket)
        with metrics.timer("overlap_device_merge"):
            merged, lease = self._merge_rows(a, b)
        with self._state_lock:
            self._merges += 1
        return _Run(merged, a.valid + b.valid, bucket, lease)

    def _merge_rows(self, a: _Run, b: _Run):
        """One pairwise run merge. Host engine in pipeline mode merges
        into a pool-leased output buffer (no per-merge large-alloc
        page faults) and splits large merges across threads at
        merge-path partition points (the native call releases the GIL);
        the inputs' leases recycle immediately. Every other
        engine/mode keeps the plain merge_row_pair path."""
        if self.engine == "host" and self._buf_pool is not None:
            total = a.valid + b.valid
            out = self._buf_pool.lease(total, int(a.rows.shape[1]))
            parts = (self._merge_parts
                     if total >= _MERGE_SPLIT_MIN_ROWS else 1)
            try:
                ok = merge_ops.merge_rows_split_into(
                    a.rows[:a.valid], b.rows[:b.valid], out, parts)
            except BaseException:
                # a failed native merge fails the segment upstream; the
                # output lease must go back to the pool on that path
                # too, or every retry shrinks the staging budget
                self._buf_pool.release(out)
                raise
            if ok:
                self._buf_pool.release(a.lease)
                self._buf_pool.release(b.lease)
                a.lease = b.lease = None
                return out, out
            self._buf_pool.release(out)  # native .so went missing
        merged = merge_ops.merge_row_pair(
            a.rows, b.rows, a.valid, b.valid, self.engine,
            interpret=self.interpret,
            native_merge=self._native_rows_merge)
        return merged, None

    # -- consumer side -------------------------------------------------------

    @property
    def stats(self) -> dict:
        """Counters for observability/tests: merges that have completed
        and segments staged so far (both monotone)."""
        pending = self._q.qsize()
        if self._staged_q is not None:
            pending += self._staged_q.qsize()
        return {"device_merges": self._merges, "staged_runs": self._staged,
                "pending": pending, "overflow": self._overflow,
                "pipeline": self.pipeline,
                "inflight_bytes": self._inflight}

    def _reap_input_queue(self) -> None:
        """Release the budget charge of every item still in the input
        queue. Safe concurrently with live workers (each item is
        consumed exactly once — by a worker or by this drain, never
        both)."""
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                self._release_charge(item[3])

    def _reap_pending(self) -> None:
        """With every stage thread stopped, anything still queued holds
        budget charges (and possibly buffer leases): release them so an
        abort/error drain never leaks in-flight bytes (the gauge must
        return to zero)."""
        self._reap_input_queue()
        if self._staged_q is None:
            return
        while True:
            try:
                staged = self._staged_q.get_nowait()
            except queue.Empty:
                break
            if staged is not None:
                self._discard(staged)

    def _drain(self) -> None:
        """Signal end of input and wait for staging to finish."""
        for _ in self._workers:
            self._q.put(None)
        for t in self._workers:
            t.join()
        if self._consumer_thread is not None:
            self._staged_q.put(None)
            self._consumer_thread.join()
        # error paths drop their items without consuming them
        self._reap_pending()
        if self._error is not None:
            raise self._error

    def _release_run(self, run) -> None:
        """Recycle a run's pool lease (idempotent: lease goes to None)."""
        if run is not None and run.lease is not None \
                and self._buf_pool is not None:
            self._buf_pool.release(run.lease)
            run.lease = None

    def _release_forest(self) -> None:
        """Recycle every forest run's pool lease (the abort / overflow-
        fallback paths abandon the forest without merging it — the
        leases must still go home or the drain point reports them)."""
        with self._forest_lock:
            runs, self._forest = list(self._forest.values()), {}
        for run in runs:
            self._release_run(run)

    def _finish_cleanup(self, acc) -> None:
        """THE finish-path cleanup contract, shared by every finish
        flavor's ``finally``: the final accumulated run's lease and any
        abandoned forest runs' leases go home, then the drain point
        asserts this merger's pool books are empty."""
        self._release_run(acc)
        self._release_forest()
        self._ledger_drain("merger.finish")

    def _ledger_drain(self, point: str) -> None:
        """ResourceLedger drain point (UDA_TPU_RESLEDGER=1): with this
        merger finished or aborted, its pool leases must all be
        settled — anything open is the lost-worker-buffer leak shape,
        reported with its acquire stack. Drained under this merger's
        pool OWNER scope, so a concurrent merger's legitimately-open
        leases are untouched. The staging GAUGES are deliberately not
        drained here: their ledger records are process-global
        (owner-less), so a per-merger drain would confiscate a
        concurrent merger's live charges — and abort() additionally
        races in-flight feed() calls whose charges the PR 9 re-drain
        settles only after abort returns. Gauge obligations are
        asserted at the genuinely quiescent points instead: the
        per-test conftest teardown and the bridge-EXIT full drain."""
        if not resledger.enabled:
            return
        if self._buf_pool is not None:
            resledger.drain(point, pairs=("pool.lease",),
                            owner=id(self._buf_pool))

    def _merge_leftovers(self) -> Optional[_Run]:
        """Merge the O(log k) leftover forest runs, smallest-first; on
        the pallas engine, pad the smaller run up to the larger capacity
        first (padding rows sort last, so the validity prefix is
        preserved) — capacities stay powers of two, so kernel shapes
        stay in the O(log) compiled set. Returns None when nothing was
        staged."""
        # UDA202 (udarace): _insert writes the forest under
        # _forest_lock; take it here too — the leftover merge runs
        # after the stage pool quiesces, but "after join" is an
        # ordering argument the lock makes unnecessary (uncontended)
        with self._forest_lock:
            if not self._forest:
                return None
            runs = [self._forest[c] for c in sorted(self._forest)]
            self._forest = {}  # release device-resident runs when done
        acc = runs[0]
        for nxt in runs[1:]:
            if self.engine == "pallas" and acc.capacity < nxt.capacity:
                acc = _Run(merge_ops.pad_rows_to(acc.rows, nxt.capacity),
                           acc.valid, acc.bucket)
            acc = self._merge(acc, nxt)
        return acc

    def _warn_overflow(self, fallback: str) -> None:
        log.warn(f"overlap fast path disabled (oversize keys); "
                 f"falling back to {fallback}")

    def _check_accounting(self, acc: Optional[_Run], total: int) -> bool:
        """Lost-records guard shared by every finish variant. Returns
        False when nothing was staged AND nothing should have been (the
        all-empty case); raises when records went missing — silently
        emitting an incomplete or unsorted merge result is the one
        unforgivable failure mode."""
        if acc is None:
            if total:
                raise MergeError(
                    f"overlap merge fed 0 of {total} records")
            return False
        if acc.valid != total:
            raise MergeError(
                f"overlap merge lost records: {acc.valid} of {total} "
                f"(segments fed != segments finished?)")
        return True

    def finish(self, batches: Sequence[RecordBatch]) -> RecordBatch:
        """Drain, merge the leftover forest, and materialize the sorted
        batch. ``batches`` must be ALL segments' batches in original
        segment-index order (the indices fed to :meth:`feed`)."""
        acc = None
        try:
            self._drain()
            if self._overflow:
                self._warn_overflow("global device re-sort")
                return merge_ops.merge_batches(batches, self.key_type,
                                               self.width)
            cat = RecordBatch.concat(list(batches))
            acc = self._merge_leftovers()
            if not self._check_accounting(acc, cat.num_records):
                return cat  # all segments legitimately empty
            rows = np.asarray(acc.rows)[:acc.valid]
            kw = rows.shape[1] - 3
            seg_col = rows[:, kw + 1].astype(np.int64)
            row_col = rows[:, kw + 2].astype(np.int64)
            sizes = np.asarray([b.num_records for b in batches], np.int64)
            offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
            perm = offsets[seg_col] + row_col
            return cat.take(perm)
        finally:
            self._finish_cleanup(acc)

    def emit_stream(self, batches: Sequence[RecordBatch], emitter,
                    consumer) -> int:
        """In-memory streaming emission: the same result bytes as
        ``emitter.emit_batch(self.finish(batches))`` but without ever
        concatenating the shuffle — each output slab's bytes are
        gathered straight from the per-segment batches and framed
        natively, so transient host memory is one slab (the reference's
        staging-loop memory model over memory-resident segments)."""
        from uda_tpu.merger import streaming as stream_mod

        acc = None
        try:
            with metrics.timer("merge"):
                self._drain()
                merged = None
                if self._overflow:
                    self._warn_overflow("global device re-sort")
                    merged = merge_ops.merge_batches(batches, self.key_type,
                                                     self.width)
                else:
                    total = sum(b.num_records for b in batches)
                    acc = self._merge_leftovers()
            if merged is not None:
                return emitter.emit_batch(merged, consumer)
            if not self._check_accounting(acc, total):
                return emitter.emit_framed(iter([EOF_MARKER]), consumer)
            kw = int(acc.rows.shape[1]) - 3

            def pieces():
                from uda_tpu import native

                for rows in stream_mod.iter_row_slabs(acc.rows, acc.valid):
                    seg = rows[:, kw + 1].astype(np.int64)
                    row = rows[:, kw + 2].astype(np.int64)
                    sub = stream_mod.slab_batch(batches, seg, row)
                    yield native.frame_batch(sub, write_eof=False)
                yield EOF_MARKER

            return emitter.emit_framed(pieces(), consumer)
        finally:
            # emit_framed fully consumes pieces() before returning, so
            # the lease recycle here never races the emission
            self._finish_cleanup(acc)

    def finish_streaming(self, emitter, consumer,
                         expected_records: Optional[int] = None) -> int:
        """Streaming-mode finish: drain staging, then emit the merged
        stream straight from the sorted run files — the permutation-
        driven k-way interleave (uda_tpu.merger.streaming). Host memory
        is one slab + one read buffer per run; no shuffle-sized
        allocation exists on this path. Cleans up the run store."""
        from uda_tpu import native
        from uda_tpu.merger import streaming as stream_mod
        from uda_tpu.utils.ifile import iter_file_records, native_enabled

        store = self.run_store
        if store is None:
            raise MergeError("finish_streaming without a run store")
        acc = None
        try:
            no_forest = self._overflow or not self.device_runs
            with metrics.timer("merge"):
                self._drain()
                acc = None if no_forest else self._merge_leftovers()
            total = store.total_records
            if expected_records is not None and total != expected_records:
                raise MergeError(
                    f"staged {total} of {expected_records} records")
            if total == 0:
                return emitter.emit_framed(iter([EOF_MARKER]), consumer)
            if no_forest:
                # every run is comparator-sorted (oversize segments were
                # ordered by the full comparator at staging; in-width
                # runs by (words, len) == comparator order), so the
                # fallback is a comparator-level k-way merge over the
                # run FILES — bounded memory, like the hybrid RPQ
                if self._overflow:
                    self._warn_overflow("k-way merge over run files")
                else:
                    log.info("bounded-device streaming: k-way merge "
                             "over run files (no device forest)")
                paths = [store.run_path(s) for s in sorted(store.counts)]
                if (native_enabled() and native.kway_supported(self.key_type)
                        and native.build()):
                    return emitter.emit_framed(
                        native.kway_merge_paths(paths, self.key_type),
                        consumer)
                streams = [iter_file_records(p) for p in paths]
                return emitter.emit(
                    merge_ops.merge_record_streams(streams, self.key_type),
                    consumer)
            self._check_accounting(acc, total)  # total>0: raises on loss
            kw = int(acc.rows.shape[1]) - 3
            slabs = stream_mod.iter_row_slabs(acc.rows, acc.valid)
            return emitter.emit_framed(
                stream_mod.interleave_runs(slabs, store, kw), consumer)
        finally:
            store.cleanup()
            self._finish_cleanup(acc)

    def abort(self) -> None:
        """Stop the staging threads without producing output. Safe with
        a bounded queue: ``_aborted`` unblocks any transport thread
        waiting in feed() (queue OR in-flight budget) and makes the
        stage loops drain-and-exit even if no poison pill can land (they
        poll the flag on an empty queue). Queued items' budget charges
        and buffer leases are reaped once every thread has stopped — an
        abort never leaks in-flight bytes. The run store is only cleaned
        once every stager has stopped — never under a concurrent
        write_run."""
        self._aborted = True
        # black-box state transition: an abort is the merge half of
        # almost every failure post-mortem (utils/flightrec.py)
        from uda_tpu.utils.flightrec import flightrec
        flightrec.record("overlap.abort",
                         staged_runs=self.stats.get("staged_runs", 0),
                         pending=self.stats.get("pending", 0))
        try:
            self._q.put_nowait(None)  # best effort: wake one instantly
        except queue.Full:
            pass
        if self._staged_q is not None:
            try:
                self._staged_q.put_nowait(None)
            except queue.Full:
                pass
        with self._inflight_cv:
            self._inflight_cv.notify_all()  # wake budget-blocked feeds
        deadline = 10.0
        for t in self._threads:
            t0 = time.monotonic()
            t.join(timeout=max(0.1, deadline))
            deadline -= time.monotonic() - t0
        stragglers = any(t.is_alive() for t in self._threads)
        if not stragglers:
            self._reap_pending()
        if self.run_store is not None:
            if stragglers:
                log.warn("overlap abort: stager still running; leaving "
                         "scratch runs for it to fail safely")
            else:
                self.run_store.cleanup()
        if not stragglers:
            # the abandoned forest's leases go home, then the drain
            # point asserts nothing else is still open (a straggler
            # thread may still legitimately hold leases — no drain)
            self._release_forest()
            self._ledger_drain("merger.abort")
