"""Overlapped fetch/merge: the network-levitated property itself.

The reference's entire reason to exist is that the merge runs WHILE
fetches stream in (reference src/Merger/MergeManager.cc:47-182: arriving
MOFs join the k-way heap; src/Merger/StreamRW.cc:462-590: the merge loop
re-issues each segment's next chunk), so by the time the last map output
lands, almost all comparison work is already done. The TPU-native shape
of that property is NOT a record-at-a-time heap (which cannot use the
VPU) but a **log-structured run forest**:

- as each segment's fetch completes it is packed (host, vectorized) and
  staged to the device as a sorted run, while later fetches are still
  in flight;
- runs merge pairwise on device with the O(n) Pallas merge-path kernel
  (uda_tpu.ops.pallas_merge.merge_sorted_pair) under a binary-counter
  policy: each run is padded to a power-of-two capacity and two runs of
  equal capacity merge immediately into one of twice the capacity —
  every record therefore moves through at most log2(k) merges, total
  work O(n log k), and only O(log) distinct kernel shapes ever compile
  (pallas_call executables are shape-specialized; unconstrained segment
  sizes would compile a fresh kernel per (na, nb) pair);
- ``finish()`` merges the O(log k) leftover runs, largest-capacity
  last, and gathers the final byte permutation on host.

Stability contract (identical to ops.merge.merge_batches): the device
rows carry (key words, content length, segment index, row index) as the
composite sort key, so equal comparator keys order by original (segment,
row) arrival — independent of fetch COMPLETION order, which under a
randomized fetch schedule is nondeterministic.

Overflow fallback: keys whose content exceeds the carried width compare
by overflow *rank*, which is only meaningful computed across ALL records
(ops.packing.overflow_ranks). Rather than serialize rank computation,
the forest detects oversize keys at staging and ``finish()`` falls back
to the global device re-sort (merge_batches) — correctness never
depends on the fast path applying. TeraSort-shaped keys (10 B <= width)
always stay on the fast path.
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from uda_tpu.ops import merge as merge_ops
from uda_tpu.ops import packing
from uda_tpu.ops.pallas_merge import merge_sorted_pair
from uda_tpu.utils.comparators import KeyType
from uda_tpu.utils.errors import MergeError
from uda_tpu.utils.ifile import EOF_MARKER, RecordBatch
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["OverlappedMerger", "MIN_RUN_CAPACITY"]

log = get_logger()

MIN_RUN_CAPACITY = 512  # smallest padded run (= default merge tile)

_PAD_WORD = np.uint32(0xFFFFFFFF)


def _next_pow2(n: int) -> int:
    p = MIN_RUN_CAPACITY
    while p < n:
        p *= 2
    return p


def _rows_sorted(rows: np.ndarray) -> bool:
    """Vectorized lexicographic monotonicity of uint32 rows: True when
    every adjacent pair is non-decreasing under column-major priority
    (O(n·k), the already-sorted fast path of _stage)."""
    n = rows.shape[0]
    if n < 2:
        return True
    a, b = rows[:-1], rows[1:]
    # decided: a prior column already ordered the pair strictly
    lt = a[:, 0] < b[:, 0]
    eq = a[:, 0] == b[:, 0]
    for c in range(1, rows.shape[1]):
        lt = lt | (eq & (a[:, c] < b[:, c]))
        eq = eq & (a[:, c] == b[:, c])
    return bool(np.all(lt | eq))


class _Run:
    """One sorted run of the forest.

    Rows are uint32[cap, C] with C = key words + 3: the composite key
    (words..., content length, segment index, row index). Device
    (pallas-engine) runs are padded to a power-of-two capacity with
    all-0xFFFFFFFF rows, which sort strictly after every real row (a
    real row's length column is a content length < 2^31), so valid rows
    stay a prefix through any merge; host runs are exact-sized.

    ``bucket`` is the binary-counter size class: staging assigns
    next_pow2(valid), each merge doubles it — so every record passes
    through at most log2(k) merges regardless of engine.
    """

    __slots__ = ("rows", "valid", "bucket")

    def __init__(self, rows, valid: int, bucket: int):
        self.rows = rows
        self.valid = valid
        self.bucket = bucket

    @property
    def capacity(self) -> int:
        return int(self.rows.shape[0])


class OverlappedMerger:
    """Consumes completed segments during the fetch phase; produces the
    final permutation over the concatenated batches.

    ``engine`` selects the pairwise merge backend: "pallas" (the device
    merge-path kernel; the real TPU path), "host" (vectorized numpy
    lexsort merge — the correctness twin, and the fast choice where the
    only accelerator is the XLA CPU backend, whose interpret-mode Pallas
    emulation compiles an unrolled grid per shape), or "auto" (host on
    CPU, pallas elsewhere).
    """

    def __init__(self, key_type: KeyType, width: int, engine: str = "auto",
                 run_store=None, max_pending: int = 0, stagers: int = 0,
                 device_runs: bool = True):
        self.key_type = key_type
        self.width = width
        # device_runs=False (streaming mode only): admission control
        # decided the full row forest would not fit the HBM budget —
        # segments still spool to sorted run files, but no run is ever
        # staged to the device; finish_streaming() merges the run FILES
        # with the bounded k-way path instead of the device forest.
        # Run files are written in (words, len) row order, which equals
        # comparator order for within-width keys, so the k-way merge is
        # correct on both the fast path and the overflow path.
        self.device_runs = bool(device_runs)
        if not self.device_runs and run_store is None:
            raise MergeError("device_runs=False requires streaming mode "
                             "(a run store)")
        if engine == "auto":
            engine = "host" if jax.default_backend() == "cpu" else "pallas"
        if engine not in ("host", "pallas"):
            raise MergeError(f"unknown overlap merge engine {engine!r}")
        self.engine = engine
        # off-TPU, a forced pallas engine runs in interpret mode
        self.interpret = jax.default_backend() == "cpu"
        # streaming mode (uda.tpu.online.streaming): segments spool to
        # sorted run files and release their bytes after staging; the
        # bounded queue is the credit backpressure that keeps
        # completed-but-unstaged segments at O(window)
        self.run_store = run_store
        # staging threads adopt the constructing thread's span (the
        # reduce-task root) so their pack/stage/merge timers land in the
        # right trace subtree
        self._parent_span = metrics.current_span()
        self._q: "queue.Queue" = queue.Queue(maxsize=max_pending)
        self._aborted = False
        self._forest: dict[int, _Run] = {}   # capacity -> run
        self._forest_lock = threading.Lock()
        self._state_lock = threading.Lock()  # counters/overflow flag
        self._overflow = False
        self._error: Optional[Exception] = None
        self._merges = 0
        self._staged = 0
        self._native_rows_merge = None
        if self.engine == "host":
            # the host merge path dispatches to the native row merge;
            # resolve it ONCE here so a cold .so compiles before any
            # carry runs under _forest_lock (a make inside the lock
            # would stall the whole staging pool) and the per-merge hot
            # path pays no imports
            from uda_tpu import native
            from uda_tpu.utils.ifile import native_enabled

            if native_enabled() and native.build():
                self._native_rows_merge = native.merge_rows_native
        # staging pool (uda.tpu.online.stagers): pack+sort+spool of
        # DIFFERENT segments parallelize; forest carries serialize under
        # _forest_lock (the merge chain itself is one run at a time
        # anyway). One thread when unset — the r4 behavior.
        self._threads = [
            threading.Thread(target=self._loop, daemon=True,
                             name=f"uda-overlap-merge-{i}")
            for i in range(max(1, stagers))]
        for t in self._threads:
            t.start()

    # -- producer side (fetch completion callbacks, any thread) -------------

    def feed(self, seg_index: int, source) -> None:
        """Stage one completed segment's records (safe to call from a
        transport completion thread). ``source`` is either a RecordBatch
        or an object with a ``record_batch()`` method (a Segment) —
        materialization happens on the merge thread. With a bounded
        queue (streaming mode) this call BLOCKS when staging lags, which
        is the intended backpressure: the transport thread holds off
        until host memory frees (the reference's RDMA credit-flow
        posture, MergeManager.cc:47-63)."""
        if self._q.maxsize <= 0:
            self._q.put((seg_index, source))
            return
        while not self._aborted:
            try:
                self._q.put((seg_index, source), timeout=0.1)
                return
            except queue.Full:
                continue

    # -- merge thread --------------------------------------------------------

    def _loop(self) -> None:
        with metrics.use_span(self._parent_span):
            wait_t0 = time.perf_counter()
            while True:
                try:
                    item = self._q.get(timeout=0.25)
                except queue.Empty:
                    if self._aborted:
                        return  # abort() without a reachable poison pill
                    continue
                if item is None:
                    return
                # merge-wait: how long this stager idled for a completed
                # segment (the fetch-bound signal; its complement is the
                # feed() backpressure block, the staging-bound signal)
                metrics.observe(
                    "merge.wait_ms",
                    (time.perf_counter() - wait_t0) * 1e3)
                if self._error is not None or self._aborted:
                    wait_t0 = time.perf_counter()
                    continue  # drain; finish() will surface the error
                try:
                    self._stage(*item)
                except Exception as e:  # surfaced at finish()
                    self._error = e
                wait_t0 = time.perf_counter()

    @staticmethod
    def _release(source) -> None:
        """Free a staged segment's raw bytes (streaming mode only: the
        sorted run on disk is now the record source of truth)."""
        release = getattr(source, "release", None)
        if release is not None:
            release()

    def _stage(self, seg_index: int, source) -> None:
        streaming = self.run_store is not None
        if self._overflow and not streaming:
            return  # fast path already disabled; finish() re-sorts all
        batch = (source if isinstance(source, RecordBatch)
                 else source.record_batch())
        if batch.num_records == 0:
            if streaming:
                self._release(source)
            return
        with metrics.timer("overlap_pack"):
            packed = packing.pack_keys(batch, self.key_type, self.width)
        n = batch.num_records
        kw = packed.key_words.shape[1]
        if int(np.max(packed.key_lens, initial=0)) > self.width:
            # rank-bearing keys: cross-run rank consistency needs the
            # global view; disable the fast path (see module docstring)
            self._overflow = True
            if not streaming:
                return
            # streaming keeps spooling: this run is ordered by the FULL
            # comparator (rare, per-record Python), so finish falls back
            # to the comparator-level k-way merge over the run files —
            # still O(window) host memory
            cmp = self.key_type.compare
            keys = [batch.key(i) for i in range(n)]
            order = np.asarray(sorted(range(n), key=functools.cmp_to_key(
                lambda i, j: cmp(keys[i], keys[j]) or (i - j))), np.int64)
            self.run_store.write_run(seg_index, batch, order)
            with self._state_lock:
                self._staged += 1
            metrics.add("merge.records", n)
            self._release(source)
            return
        # device runs pad to a power-of-two capacity (bounded set of
        # kernel shapes); host runs stay exact-sized
        cap = _next_pow2(n) if self.engine == "pallas" else n
        rows = np.full((cap, kw + 3), _PAD_WORD, np.uint32)
        rows[:n, :kw] = packed.key_words
        rows[:n, kw] = packed.key_lens.astype(np.uint32)
        rows[:n, kw + 1] = np.uint32(seg_index)
        rows[:n, kw + 2] = np.arange(n, dtype=np.uint32)
        # per-segment sort on host key order. Hadoop map outputs arrive
        # ALREADY comparator-sorted (the map-side sort contract the
        # reference's merge leaned on — it never re-sorted segments,
        # MergeManager.cc:47-63), and for within-width keys comparator
        # order == (words, len) order, so an O(n·k) monotonicity check
        # usually replaces the O(n log n) lexsort — the staging hot
        # path collapses to pack+spool at memory bandwidth. Unsorted
        # input (exchange-path buckets, foreign writers) still sorts.
        if _rows_sorted(rows[:n, :kw + 1]):
            order = np.arange(n, dtype=np.int64)
        else:
            order = np.lexsort(tuple(rows[:n, c]
                                     for c in range(kw, -1, -1)))
            rows[:n] = rows[:n][order]
        if streaming:
            self.run_store.write_run(seg_index, batch,
                                     order.astype(np.int64))
            self._release(source)
        with self._state_lock:
            self._staged += 1
        metrics.add("merge.records", n)
        if self._overflow or not self.device_runs:
            return  # forest output won't be consumed; runs are enough
        with metrics.timer("overlap_stage"):
            if self.engine == "pallas":
                rows = jax.device_put(rows)
            self._insert(_Run(rows, n, _next_pow2(n)))

    def _insert(self, run: _Run) -> None:
        # binary-counter carry: equal size classes merge immediately.
        # The lock serializes carries across the staging pool (pack/
        # sort/spool of other segments proceed concurrently).
        with self._forest_lock:
            while run.bucket in self._forest:
                other = self._forest.pop(run.bucket)
                run = self._merge(other, run)
            self._forest[run.bucket] = run

    def _merge(self, a: _Run, b: _Run) -> _Run:
        bucket = 2 * max(a.bucket, b.bucket)
        with metrics.timer("overlap_device_merge"):
            if self.engine == "host":
                # linear two-pointer native merge when built (ties to
                # `a` = the earlier run, preserving the composite-key
                # stability); lexsort of the concatenation otherwise
                merged = None
                if self._native_rows_merge is not None:
                    merged = self._native_rows_merge(
                        np.asarray(a.rows[:a.valid]),
                        np.asarray(b.rows[:b.valid]))
                if merged is None:
                    rows = np.concatenate(
                        [a.rows[:a.valid], b.rows[:b.valid]])
                    order = np.lexsort(tuple(
                        rows[:, c]
                        for c in range(rows.shape[1] - 1, -1, -1)))
                    merged = rows[order]
            else:
                # every column is part of the composite key (words, len,
                # seg, row) — rows are totally ordered, so the kernel's
                # internal tie-break never decides anything
                merged = merge_sorted_pair(a.rows, b.rows,
                                           num_keys=int(a.rows.shape[1]),
                                           interpret=self.interpret)
        with self._state_lock:
            self._merges += 1
        return _Run(merged, a.valid + b.valid, bucket)

    # -- consumer side -------------------------------------------------------

    @property
    def stats(self) -> dict:
        """Counters for observability/tests: merges that have completed
        and segments staged so far (both monotone)."""
        return {"device_merges": self._merges, "staged_runs": self._staged,
                "pending": self._q.qsize(), "overflow": self._overflow}

    def _drain(self) -> None:
        """Signal end of input and wait for staging to finish."""
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join()
        if self._error is not None:
            raise self._error

    def _merge_leftovers(self) -> Optional[_Run]:
        """Merge the O(log k) leftover forest runs, smallest-first; on
        the pallas engine, pad the smaller run up to the larger capacity
        first (padding rows sort last, so the validity prefix is
        preserved) — capacities stay powers of two, so kernel shapes
        stay in the O(log) compiled set. Returns None when nothing was
        staged."""
        if not self._forest:
            return None
        runs = [self._forest[c] for c in sorted(self._forest)]
        self._forest = {}  # release device-resident runs when done
        acc = runs[0]
        for nxt in runs[1:]:
            if self.engine == "pallas" and acc.capacity < nxt.capacity:
                pad = np.full((nxt.capacity - acc.capacity,
                               int(acc.rows.shape[1])), _PAD_WORD, np.uint32)
                acc = _Run(jnp.concatenate(
                    [acc.rows, jax.device_put(pad)], axis=0), acc.valid,
                    acc.bucket)
            acc = self._merge(acc, nxt)
        return acc

    def _warn_overflow(self, fallback: str) -> None:
        log.warn(f"overlap fast path disabled (oversize keys); "
                 f"falling back to {fallback}")

    def _check_accounting(self, acc: Optional[_Run], total: int) -> bool:
        """Lost-records guard shared by every finish variant. Returns
        False when nothing was staged AND nothing should have been (the
        all-empty case); raises when records went missing — silently
        emitting an incomplete or unsorted merge result is the one
        unforgivable failure mode."""
        if acc is None:
            if total:
                raise MergeError(
                    f"overlap merge fed 0 of {total} records")
            return False
        if acc.valid != total:
            raise MergeError(
                f"overlap merge lost records: {acc.valid} of {total} "
                f"(segments fed != segments finished?)")
        return True

    def finish(self, batches: Sequence[RecordBatch]) -> RecordBatch:
        """Drain, merge the leftover forest, and materialize the sorted
        batch. ``batches`` must be ALL segments' batches in original
        segment-index order (the indices fed to :meth:`feed`)."""
        self._drain()
        if self._overflow:
            self._warn_overflow("global device re-sort")
            return merge_ops.merge_batches(batches, self.key_type,
                                           self.width)
        cat = RecordBatch.concat(list(batches))
        acc = self._merge_leftovers()
        if not self._check_accounting(acc, cat.num_records):
            return cat  # all segments legitimately empty
        rows = np.asarray(acc.rows)[:acc.valid]
        kw = rows.shape[1] - 3
        seg_col = rows[:, kw + 1].astype(np.int64)
        row_col = rows[:, kw + 2].astype(np.int64)
        sizes = np.asarray([b.num_records for b in batches], np.int64)
        offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
        perm = offsets[seg_col] + row_col
        return cat.take(perm)

    def emit_stream(self, batches: Sequence[RecordBatch], emitter,
                    consumer) -> int:
        """In-memory streaming emission: the same result bytes as
        ``emitter.emit_batch(self.finish(batches))`` but without ever
        concatenating the shuffle — each output slab's bytes are
        gathered straight from the per-segment batches and framed
        natively, so transient host memory is one slab (the reference's
        staging-loop memory model over memory-resident segments)."""
        from uda_tpu.merger import streaming as stream_mod

        with metrics.timer("merge"):
            self._drain()
            merged = None
            if self._overflow:
                self._warn_overflow("global device re-sort")
                merged = merge_ops.merge_batches(batches, self.key_type,
                                                 self.width)
            else:
                total = sum(b.num_records for b in batches)
                acc = self._merge_leftovers()
        if merged is not None:
            return emitter.emit_batch(merged, consumer)
        if not self._check_accounting(acc, total):
            return emitter.emit_framed(iter([EOF_MARKER]), consumer)
        kw = int(acc.rows.shape[1]) - 3

        def pieces():
            from uda_tpu import native

            for rows in stream_mod.iter_row_slabs(acc.rows, acc.valid):
                seg = rows[:, kw + 1].astype(np.int64)
                row = rows[:, kw + 2].astype(np.int64)
                sub = stream_mod.slab_batch(batches, seg, row)
                yield native.frame_batch(sub, write_eof=False)
            yield EOF_MARKER

        return emitter.emit_framed(pieces(), consumer)

    def finish_streaming(self, emitter, consumer,
                         expected_records: Optional[int] = None) -> int:
        """Streaming-mode finish: drain staging, then emit the merged
        stream straight from the sorted run files — the permutation-
        driven k-way interleave (uda_tpu.merger.streaming). Host memory
        is one slab + one read buffer per run; no shuffle-sized
        allocation exists on this path. Cleans up the run store."""
        from uda_tpu import native
        from uda_tpu.merger import streaming as stream_mod
        from uda_tpu.utils.ifile import iter_file_records, native_enabled

        store = self.run_store
        if store is None:
            raise MergeError("finish_streaming without a run store")
        try:
            no_forest = self._overflow or not self.device_runs
            with metrics.timer("merge"):
                self._drain()
                acc = None if no_forest else self._merge_leftovers()
            total = store.total_records
            if expected_records is not None and total != expected_records:
                raise MergeError(
                    f"staged {total} of {expected_records} records")
            if total == 0:
                return emitter.emit_framed(iter([EOF_MARKER]), consumer)
            if no_forest:
                # every run is comparator-sorted (oversize segments were
                # ordered by the full comparator at staging; in-width
                # runs by (words, len) == comparator order), so the
                # fallback is a comparator-level k-way merge over the
                # run FILES — bounded memory, like the hybrid RPQ
                if self._overflow:
                    self._warn_overflow("k-way merge over run files")
                else:
                    log.info("bounded-device streaming: k-way merge "
                             "over run files (no device forest)")
                paths = [store.run_path(s) for s in sorted(store.counts)]
                if (native_enabled() and native.kway_supported(self.key_type)
                        and native.build()):
                    return emitter.emit_framed(
                        native.kway_merge_paths(paths, self.key_type),
                        consumer)
                streams = [iter_file_records(p) for p in paths]
                return emitter.emit(
                    merge_ops.merge_record_streams(streams, self.key_type),
                    consumer)
            self._check_accounting(acc, total)  # total>0: raises on loss
            kw = int(acc.rows.shape[1]) - 3
            slabs = stream_mod.iter_row_slabs(acc.rows, acc.valid)
            return emitter.emit_framed(
                stream_mod.interleave_runs(slabs, store, kw), consumer)
        finally:
            store.cleanup()

    def abort(self) -> None:
        """Stop the staging threads without producing output. Safe with
        a bounded queue: ``_aborted`` unblocks any transport thread
        waiting in feed() and makes the stager loops drain-and-exit even
        if no poison pill can land (they poll the flag on an empty
        queue). The run store is only cleaned once every stager has
        stopped — never under a concurrent write_run."""
        self._aborted = True
        try:
            self._q.put_nowait(None)  # best effort: wake one instantly
        except queue.Full:
            pass
        deadline = 10.0
        for t in self._threads:
            t0 = time.monotonic()
            t.join(timeout=max(0.1, deadline))
            deadline -= time.monotonic() - t0
        if self.run_store is not None:
            if any(t.is_alive() for t in self._threads):
                log.warn("overlap abort: stager still running; leaving "
                         "scratch runs for it to fail safely")
            else:
                self.run_store.cleanup()
