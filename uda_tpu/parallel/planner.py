"""Host-side round planner + per-axis (ICI/DCN) exchange accounting.

The windowed exchange is *globally scheduled*: every device already
ships its per-(src, dst) bucket counts to the host (the one readback in
``prepare_layout``), so the host can decide — exactly, before any
collective runs — which windows move records at all and how many bytes
each fabric tier carries. This module is that decision plus its
evidence:

- :func:`plan_rounds` turns the ``[P, P]`` counts matrix into an
  ordered list of non-empty :class:`WindowPlan` s (globally-empty
  windows are skipped and counted — ``exchange.rounds.skipped``);
- each window carries the per-axis accounting the hierarchical
  exchange's win is proven with: ICI record bytes, DCN record bytes
  and the DCN **message** count — cross-pod (src, dst) *device* pairs
  for the flat single-stage exchange, coalesced *pod* pairs for the
  two-stage path (the reference's per-QP aggregation win,
  RDMAServer.cc chunked server pool);
- :func:`record_window_metrics` lands the numbers in
  ``exchange.ici.bytes`` / ``exchange.dcn.bytes`` /
  ``exchange.dcn.messages`` (DCN series labeled by source pod).

The counts are *predictions* only in the sense that the host computes
them before the device program runs; they are exact — the round bodies
move precisely the in-window rows the counts matrix describes. They
count RECORD rows/bytes, i.e. the populated payload: the dense
``lax.all_to_all`` buffers the staged body lowers to additionally
carry their unpopulated slots on the wire (see the scope note in
parallel/exchange.py) — the ledger here is the topology-invariant
payload measure the A/B gates compare, not the padded collective
footprint.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from uda_tpu.parallel.mesh import MeshTopology
from uda_tpu.utils.metrics import metrics

__all__ = ["WindowPlan", "RoundPlan", "plan_rounds",
           "plan_layout_rounds", "record_window_metrics",
           "record_executed_window", "record_plan_skips"]


@dataclasses.dataclass(frozen=True)
class WindowPlan:
    """One planned exchange window (round ``index`` moves each bucket's
    rows with in-bucket position in ``[index*capacity,
    (index+1)*capacity)``). Row counts are records, not bytes —
    multiply by the layout's record stride for bytes."""

    index: int
    moved_rows: int       # in-window rows over all (src, dst) pairs
    ici_rows: int         # rows moved over intra-pod links (off-device;
    #                       hierarchical: staging hops included)
    dcn_rows: int         # rows crossing a pod boundary
    dcn_messages: int     # flat: cross-pod device pairs with traffic;
    #                       hierarchical: pod pairs with traffic
    per_pod: Tuple[Tuple[int, int, int], ...]  # (src pod, dcn rows,
    #                                             dcn messages)

    @property
    def empty(self) -> bool:
        return self.moved_rows == 0


@dataclasses.dataclass(frozen=True)
class RoundPlan:
    windows: Tuple[WindowPlan, ...]   # the NON-empty windows, in order
    planned: int                      # windows considered (incl. empty)
    skipped: int                      # globally-empty windows dropped
    record_bytes: int
    hierarchical: bool


def _pod_vectors(n: int, topology: Optional[MeshTopology]):
    """(pod index, chip index) per device, or (None, None) when the
    mesh has no pod structure to account against."""
    if topology is None or topology.dcn_axis is None \
            or topology.num_pods <= 1:
        return None, None
    c = topology.pod_size
    dev = np.arange(n)
    return dev // c, dev % c


def plan_rounds(counts, capacity: int,
                topology: Optional[MeshTopology] = None,
                record_bytes: int = 0,
                hierarchical: bool = False) -> RoundPlan:
    """Plan the windowed rounds for one exchange from its gathered
    counts matrix (``counts[src, dst]``, any integer dtype).

    Always plans at least one window (the flat exchange's historical
    ``max(1, ceil(max_bucket / capacity))`` round count) so an
    all-empty shuffle shows up as one *skipped* window rather than a
    silently-free exchange. A non-positive ``capacity`` raises — it
    would otherwise plan zero deliverable windows and silently drop
    the whole shuffle (the pre-planner code crashed on the division).

    On the skip's reach: in-bucket positions are contiguous from 0, so
    window ``r < ceil(max_bucket/capacity)`` always carries rows of at
    least the biggest bucket — with today's layouts the only reachable
    skip is the all-empty exchange (which previously EXECUTED one
    pointless all_to_all). The per-window check is kept general anyway:
    it is one subtraction on a tiny host matrix, and it guards any
    future planner input whose buckets are not contiguous (e.g. a
    pre-filtered or resumed counts matrix). What a *skewed* workload
    gains per round is the accounting — ``dcn_messages`` counts only
    pairs with real in-window traffic, so the near-empty tail rounds of
    a hot bucket report 1 pod-pair message, not a full fabric sweep."""
    if capacity <= 0:
        raise ValueError(f"exchange capacity must be positive, got "
                         f"{capacity}")
    counts = np.asarray(counts, dtype=np.int64)
    n = counts.shape[0] if counts.ndim == 2 else 0
    if hierarchical and n * capacity >= 1 << 31:
        # the staged body's delivery tag (src_device*capacity + slot)
        # is computed in int32 on device — past this it wraps and rows
        # silently misdeliver (the buffer is unbuildable long before,
        # but fail loudly, not by physics)
        raise ValueError(f"hierarchical exchange tag overflow: "
                         f"{n} devices x capacity {capacity} >= 2^31")
    biggest = int(counts.max()) if counts.size else 0
    total = max(1, -(-biggest // capacity))
    pod, chip = _pod_vectors(n, topology)
    if pod is not None:
        cross = pod[:, None] != pod[None, :]
        intra_off = (~cross) & ~np.eye(n, dtype=bool)
        if hierarchical:
            c = topology.pod_size
            # staging hops of the two-stage path: src chip -> egress
            # chip (stage A) and ingress chip -> dst chip (stage C);
            # the egress/ingress chip of pair (g, g') is
            # MeshTopology.egress_chip = (g + g') % pod_size
            egress = (pod[:, None] + pod[None, :]) % c
            hops = ((chip[:, None] != egress).astype(np.int64)
                    + (egress != chip[None, :]).astype(np.int64))
    windows = []
    skipped = 0
    for r in range(total):
        inwin = np.clip(counts - r * capacity, 0, capacity) \
            if counts.size else np.zeros((0, 0), np.int64)
        moved = int(inwin.sum())
        if moved == 0:
            skipped += 1
            continue
        if pod is None:
            ici = int(inwin.sum() - np.trace(inwin))
            windows.append(WindowPlan(r, moved, ici, 0, 0, ()))
            continue
        if hierarchical:
            p = topology.num_pods
            pod_mat = inwin.reshape(p, topology.pod_size, p,
                                    topology.pod_size).sum(axis=(1, 3))
            off = pod_mat - np.diag(np.diag(pod_mat))
            dcn_rows = int(off.sum())
            msgs_mat = (off > 0).astype(np.int64)
            ici = (int(inwin[intra_off].sum())
                   + int((inwin * hops)[cross].sum()))
            per_pod = tuple(
                (g, int(off[g].sum()), int(msgs_mat[g].sum()))
                for g in range(p) if off[g].sum() or msgs_mat[g].sum())
            windows.append(WindowPlan(r, moved, ici, dcn_rows,
                                      int(msgs_mat.sum()), per_pod))
        else:
            dcn_rows = int(inwin[cross].sum())
            msgs = (inwin > 0) & cross
            per_pod = []
            for g in range(topology.num_pods):
                sel = pod == g
                rows_g = int(inwin[sel][cross[sel]].sum())
                msgs_g = int(msgs[sel].sum())
                if rows_g or msgs_g:
                    per_pod.append((g, rows_g, msgs_g))
            windows.append(WindowPlan(
                r, moved, int(inwin[intra_off].sum()), dcn_rows,
                int(msgs.sum()), tuple(per_pod)))
    return RoundPlan(tuple(windows), total, skipped, int(record_bytes),
                     bool(hierarchical))


def plan_layout_rounds(layout, capacity: int) -> RoundPlan:
    """Plan one prepared ``ShuffleLayout``'s windows — the single
    layout->planner wiring (counts matrix, topology, resolved dispatch,
    record stride) shared by ``exchange.shuffle_exchange`` and
    ``distributed.distributed_sort_multiround``."""
    return plan_rounds(layout.counts, capacity, layout.topology,
                       layout.record_bytes(), layout.hierarchical)


def record_executed_window(win: WindowPlan, plan: RoundPlan) -> None:
    """Account one executed window: the round counter plus its per-axis
    fabric metrics (one call site contract for every round loop)."""
    metrics.add("exchange.rounds")
    record_window_metrics(win, plan.record_bytes)


def record_plan_skips(plan: RoundPlan) -> None:
    if plan.skipped:
        metrics.add("exchange.rounds.skipped", plan.skipped)


def record_window_metrics(win: WindowPlan, record_bytes: int) -> None:
    """Land one executed window's per-axis accounting in the metrics
    hub. The DCN series carry a source-pod label (the labeled-counter
    machinery advances the unlabeled totals too)."""
    if win.ici_rows:
        metrics.add("exchange.ici.bytes", win.ici_rows * record_bytes)
    for g, rows, msgs in win.per_pod:
        if rows:
            metrics.add("exchange.dcn.bytes", rows * record_bytes,
                        pod=g)
        if msgs:
            metrics.add("exchange.dcn.messages", msgs, pod=g)
