"""Host-side round planner + per-axis (ICI/DCN) exchange accounting.

The windowed exchange is *globally scheduled*: every device already
ships its per-(src, dst) bucket counts to the host (the one readback in
``prepare_layout``), so the host can decide — exactly, before any
collective runs — which windows move records at all and how many bytes
each fabric tier carries. This module is that decision plus its
evidence:

- :func:`plan_rounds` turns the ``[P, P]`` counts matrix into an
  ordered list of non-empty :class:`WindowPlan` s (globally-empty
  windows are skipped and counted — ``exchange.rounds.skipped``);
- each window carries the per-axis accounting the hierarchical
  exchange's win is proven with: ICI record bytes, DCN record bytes
  and the DCN **message** count — cross-pod (src, dst) *device* pairs
  for the flat single-stage exchange, coalesced *pod* pairs for the
  two-stage path (the reference's per-QP aggregation win,
  RDMAServer.cc chunked server pool);
- with ``coded=True`` the plan additionally decides, per window,
  whether the CODED stage-B path runs (the Coded TeraSort multicast
  discipline, arXiv:1702.04850): a pod pair is *codable* when its
  in-window cross rows spread over >= 2 destination chips and the
  padded multicast chunk (``L`` = the largest per-destination block,
  rounded up to :data:`CODED_CHUNK_ROWS` — the code's chunk
  granularity) at least halves the pair's payload
  (:data:`CODED_WIN_FACTOR`, the break-even guard). A window
  is coded only when EVERY pair with cross traffic is codable — mixed
  or skewed windows fall back to the plain coalesced tile with zero
  coded overhead, by plan;
- :func:`record_window_metrics` lands the numbers in
  ``exchange.ici.bytes`` / ``exchange.dcn.bytes`` /
  ``exchange.dcn.messages`` (DCN series labeled by source pod), plus
  — for coded windows — ``exchange.dcn.coded.bytes`` (the multicast
  charge, which IS the window's ``exchange.dcn.bytes``) and
  ``exchange.dcn.saved.bytes``, with the bookkeeping invariant
  ``coded + saved == uncoded payload`` per pair and in total.

Scope of the coded charge (the PR 7 scope-note discipline): the coded
ledger books what a redundant-map Coded-TeraSort deployment moves over
the DCN — ONE multicast packet of ``L`` rows per pod pair serving all
``pod_size`` member reducers at once, their decode side information
being locally (re)computed from replicated map work. This virtual mesh
has no map redundancy to replicate, so the device tile ships the
full-rank coded chunk set (every member can decode every block) and
the side-information share of the tile rides the wire uncharged — the
gap between the model charge and the dense collective's wire footprint
is documented in parallel/exchange.py, README and PARITY, exactly like
the dense-padding note the hierarchical ledger already carries.

The counts are *predictions* only in the sense that the host computes
them before the device program runs; they are exact — the round bodies
move precisely the in-window rows the counts matrix describes. They
count RECORD rows/bytes, i.e. the populated payload: the dense
``lax.all_to_all`` buffers the staged body lowers to additionally
carry their unpopulated slots on the wire (see the scope note in
parallel/exchange.py) — the ledger here is the topology-invariant
payload measure the A/B gates compare, not the padded collective
footprint.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from uda_tpu.parallel.mesh import MeshTopology
from uda_tpu.utils.metrics import metrics

__all__ = ["WindowPlan", "RoundPlan", "plan_rounds",
           "plan_layout_rounds", "record_window_metrics",
           "record_executed_window", "record_plan_skips",
           "CODED_CHUNK_ROWS"]

# the code's chunk granularity: a pair's multicast chunk length L is
# the largest per-destination block padded UP to this many rows (the
# rs.chunk_len discipline applied to rows instead of bytes), so the
# device tile shape quantizes and the charge stays honest about the
# pad. A pair only codes when the padded L still beats its payload.
CODED_CHUNK_ROWS = 4

# break-even guard: a pair codes only when the multicast chunk at
# least HALVES its payload (L_pad * FACTOR <= S). The k-fold cut
# presumes roughly balanced destination blocks; a skew-dominant block
# makes L ~ S and coding pure overhead — those pairs (and any window
# containing one) ride the plain coalesced tile.
CODED_WIN_FACTOR = 2


@dataclasses.dataclass(frozen=True)
class WindowPlan:
    """One planned exchange window (round ``index`` moves each bucket's
    rows with in-bucket position in ``[index*capacity,
    (index+1)*capacity)``). Row counts are records, not bytes —
    multiply by the layout's record stride for bytes.

    ``dcn_rows``/``per_pod`` always hold the UNCODED payload figures
    (what the plain coalesced tile moves — and what a coded window
    books if its decode falls back mid-round); the ``coded*`` fields
    hold the multicast-model charges of the coded stage-B path and are
    meaningful only when ``coded`` is True."""

    index: int
    moved_rows: int       # in-window rows over all (src, dst) pairs
    ici_rows: int         # rows moved over intra-pod links (off-device;
    #                       hierarchical: staging hops included)
    dcn_rows: int         # rows crossing a pod boundary
    dcn_messages: int     # flat: cross-pod device pairs with traffic;
    #                       hierarchical: pod pairs with traffic
    per_pod: Tuple[Tuple[int, int, int], ...]  # (src pod, dcn rows,
    #                                             dcn messages)
    coded: bool = False   # this window runs the coded stage-B path
    l_rows: int = 0       # max padded chunk length over the window's
    #                       pairs (the device tile's static row count)
    coded_rows: int = 0   # multicast-model DCN charge (sum of L_pair)
    saved_rows: int = 0   # dcn_rows - coded_rows (>= 1 per coded pair)
    ici_rows_coded: int = 0  # ICI rows when the coded body runs (the
    #                       stage-C broadcast replaces the delivery
    #                       scatter: each coded chunk reaches every
    #                       member, the side-information trade)
    per_pod_coded: Tuple[Tuple[int, int, int], ...] = ()  # (src pod,
    #                       coded rows, saved rows)

    @property
    def empty(self) -> bool:
        return self.moved_rows == 0


@dataclasses.dataclass(frozen=True)
class RoundPlan:
    windows: Tuple[WindowPlan, ...]   # the NON-empty windows, in order
    planned: int                      # windows considered (incl. empty)
    skipped: int                      # globally-empty windows dropped
    record_bytes: int
    hierarchical: bool
    coded: bool = False               # coded dispatch requested AND
    #                                   possible on this topology
    coded_l_rows: int = 0             # ONE static chunk length for the
    #                                   whole plan (max over coded
    #                                   windows: one compiled coded
    #                                   program per shuffle)


def _pod_vectors(n: int, topology: Optional[MeshTopology]):
    """(pod index, chip index) per device, or (None, None) when the
    mesh has no pod structure to account against."""
    if topology is None or topology.dcn_axis is None \
            or topology.num_pods <= 1:
        return None, None
    c = topology.pod_size
    dev = np.arange(n)
    return dev // c, dev % c


def _pad_chunk(rows: int) -> int:
    """Pad a block length up to the code's chunk granularity."""
    if rows <= 0:
        return 0
    return -(-rows // CODED_CHUNK_ROWS) * CODED_CHUNK_ROWS


def _plan_window_coding(inwin, topology):
    """The per-window coding decision over the in-window counts.

    Returns ``(coded, l_rows, per_pod_coded, extra_ici)`` — coded is
    True only when EVERY pod pair with cross traffic is codable
    (>= 2 destination chips AND the padded multicast chunk at least
    halves the pair's payload) and at least one such pair exists.
    ``extra_ici``
    is the stage-C broadcast cost of the coded body: every coded chunk
    reaches all ``pod_size`` members ((c-1) off-device copies of the
    c-chunk tile per pair) instead of the plain delivery scatter."""
    p, c = topology.num_pods, topology.pod_size
    if not topology.coded_capable:
        return False, 0, (), 0
    # per (src pod, dst pod, dst chip): in-window rows
    chip_mat = inwin.reshape(p, c, p, c).sum(axis=1)
    pair_rows = chip_mat.sum(axis=2)            # [src pod, dst pod]
    np.fill_diagonal(pair_rows, 0)
    if not pair_rows.any():
        return False, 0, (), 0
    l_rows = 0
    extra_ici = 0
    per_pod: dict[int, list[int]] = {}
    for g in range(p):
        for g2 in range(p):
            if g == g2 or pair_rows[g, g2] == 0:
                continue
            s = int(pair_rows[g, g2])
            k_eff = int((chip_mat[g, g2] > 0).sum())
            l_pad = _pad_chunk(int(chip_mat[g, g2].max()))
            if k_eff < 2 or l_pad * CODED_WIN_FACTOR > s:
                return False, 0, (), 0      # one uncodable pair ->
                # the whole window rides the plain coalesced tile
            l_rows = max(l_rows, l_pad)
            extra_ici += (c - 1) * c * l_pad
            cr, sv = per_pod.setdefault(g, [0, 0])
            per_pod[g] = [cr + l_pad, sv + (s - l_pad)]
    ppc = tuple((g, cr, sv) for g, (cr, sv) in sorted(per_pod.items()))
    return True, l_rows, ppc, extra_ici


def plan_rounds(counts, capacity: int,
                topology: Optional[MeshTopology] = None,
                record_bytes: int = 0,
                hierarchical: bool = False,
                coded: bool = False) -> RoundPlan:
    """Plan the windowed rounds for one exchange from its gathered
    counts matrix (``counts[src, dst]``, any integer dtype).

    Always plans at least one window (the flat exchange's historical
    ``max(1, ceil(max_bucket / capacity))`` round count) so an
    all-empty shuffle shows up as one *skipped* window rather than a
    silently-free exchange. A non-positive ``capacity`` raises — it
    would otherwise plan zero deliverable windows and silently drop
    the whole shuffle (the pre-planner code crashed on the division).

    On the skip's reach: in-bucket positions are contiguous from 0, so
    window ``r < ceil(max_bucket/capacity)`` always carries rows of at
    least the biggest bucket — with today's layouts the only reachable
    skip is the all-empty exchange (which previously EXECUTED one
    pointless all_to_all). The per-window check is kept general anyway:
    it is one subtraction on a tiny host matrix, and it guards any
    future planner input whose buckets are not contiguous (e.g. a
    pre-filtered or resumed counts matrix). What a *skewed* workload
    gains per round is the accounting — ``dcn_messages`` counts only
    pairs with real in-window traffic, so the near-empty tail rounds of
    a hot bucket report 1 pod-pair message, not a full fabric sweep."""
    if capacity <= 0:
        raise ValueError(f"exchange capacity must be positive, got "
                         f"{capacity}")
    counts = np.asarray(counts, dtype=np.int64)
    n = counts.shape[0] if counts.ndim == 2 else 0
    coded = bool(coded) and bool(hierarchical) and topology is not None
    if hierarchical and n * capacity >= 1 << 31:
        # the staged body's delivery tag (src_device*capacity + slot)
        # is computed in int32 on device — past this it wraps and rows
        # silently misdeliver (the buffer is unbuildable long before,
        # but fail loudly, not by physics)
        raise ValueError(f"hierarchical exchange tag overflow: "
                         f"{n} devices x capacity {capacity} >= 2^31")
    biggest = int(counts.max()) if counts.size else 0
    total = max(1, -(-biggest // capacity))
    pod, chip = _pod_vectors(n, topology)
    if pod is not None:
        cross = pod[:, None] != pod[None, :]
        intra_off = (~cross) & ~np.eye(n, dtype=bool)
        if hierarchical:
            c = topology.pod_size
            # staging hops of the two-stage path: src chip -> egress
            # chip (stage A) and ingress chip -> dst chip (stage C);
            # the egress/ingress chip of pair (g, g') is
            # MeshTopology.egress_chip = (g + g') % pod_size
            egress = (pod[:, None] + pod[None, :]) % c
            hop_a = (chip[:, None] != egress).astype(np.int64)
            hops = hop_a + (egress != chip[None, :]).astype(np.int64)
    windows = []
    skipped = 0
    for r in range(total):
        inwin = np.clip(counts - r * capacity, 0, capacity) \
            if counts.size else np.zeros((0, 0), np.int64)
        moved = int(inwin.sum())
        if moved == 0:
            skipped += 1
            continue
        if pod is None:
            ici = int(inwin.sum() - np.trace(inwin))
            windows.append(WindowPlan(r, moved, ici, 0, 0, ()))
            continue
        if hierarchical:
            p = topology.num_pods
            pod_mat = inwin.reshape(p, topology.pod_size, p,
                                    topology.pod_size).sum(axis=(1, 3))
            off = pod_mat - np.diag(np.diag(pod_mat))
            dcn_rows = int(off.sum())
            msgs_mat = (off > 0).astype(np.int64)
            ici = (int(inwin[intra_off].sum())
                   + int((inwin * hops)[cross].sum()))
            per_pod = tuple(
                (g, int(off[g].sum()), int(msgs_mat[g].sum()))
                for g in range(p) if off[g].sum() or msgs_mat[g].sum())
            win_coded, l_win, ppc, extra_ici = (
                _plan_window_coding(inwin, topology) if coded
                else (False, 0, (), 0))
            ici_coded = 0
            if win_coded:
                # the coded body keeps stage A's egress staging hop
                # but replaces the stage-C delivery scatter with the
                # chunk broadcast (extra_ici): intra + hop A + bcast
                ici_coded = (int(inwin[intra_off].sum())
                             + int((inwin * hop_a)[cross].sum())
                             + extra_ici)
            windows.append(WindowPlan(
                r, moved, ici, dcn_rows, int(msgs_mat.sum()), per_pod,
                coded=win_coded, l_rows=l_win,
                coded_rows=sum(cr for _, cr, _ in ppc),
                saved_rows=sum(sv for _, _, sv in ppc),
                ici_rows_coded=ici_coded, per_pod_coded=ppc))
        else:
            dcn_rows = int(inwin[cross].sum())
            msgs = (inwin > 0) & cross
            per_pod = []
            for g in range(topology.num_pods):
                sel = pod == g
                rows_g = int(inwin[sel][cross[sel]].sum())
                msgs_g = int(msgs[sel].sum())
                if rows_g or msgs_g:
                    per_pod.append((g, rows_g, msgs_g))
            windows.append(WindowPlan(
                r, moved, int(inwin[intra_off].sum()), dcn_rows,
                int(msgs.sum()), tuple(per_pod)))
    l_plan = max((w.l_rows for w in windows if w.coded), default=0)
    return RoundPlan(tuple(windows), total, skipped, int(record_bytes),
                     bool(hierarchical), coded=coded,
                     coded_l_rows=l_plan)


def plan_layout_rounds(layout, capacity: int) -> RoundPlan:
    """Plan one prepared ``ShuffleLayout``'s windows — the single
    layout->planner wiring (counts matrix, topology, resolved dispatch,
    record stride) shared by ``exchange.shuffle_exchange`` and
    ``distributed.distributed_sort_multiround``."""
    return plan_rounds(layout.counts, capacity, layout.topology,
                       layout.record_bytes(), layout.hierarchical,
                       coded=getattr(layout, "coded", False))


def record_executed_window(win: WindowPlan, plan: RoundPlan,
                           coded: bool = False) -> None:
    """Account one executed window: the round counter plus its per-axis
    fabric metrics (one call site contract for every round loop).
    ``coded`` says which body ACTUALLY ran — a coded window whose
    decode fell back mid-round books the plain-tile figures."""
    metrics.add("exchange.rounds")
    record_window_metrics(win, plan.record_bytes, coded=coded)


def record_plan_skips(plan: RoundPlan) -> None:
    if plan.skipped:
        metrics.add("exchange.rounds.skipped", plan.skipped)


def record_window_metrics(win: WindowPlan, record_bytes: int,
                          coded: bool = False) -> None:
    """Land one executed window's per-axis accounting in the metrics
    hub. The DCN series carry a source-pod label (the labeled-counter
    machinery advances the unlabeled totals too). A CODED window books
    the multicast charge as its ``exchange.dcn.bytes`` plus the coded/
    saved breakdown — ``coded + saved == the plain window's payload``
    by construction (the ledger-sum invariant the tests pin)."""
    if coded and win.coded:
        if win.ici_rows_coded:
            metrics.add("exchange.ici.bytes",
                        win.ici_rows_coded * record_bytes)
        for g, crows, srows in win.per_pod_coded:
            if crows:
                metrics.add("exchange.dcn.bytes", crows * record_bytes,
                            pod=g)
                metrics.add("exchange.dcn.coded.bytes",
                            crows * record_bytes, pod=g)
            if srows:
                metrics.add("exchange.dcn.saved.bytes",
                            srows * record_bytes, pod=g)
        for g, _rows, msgs in win.per_pod:
            if msgs:
                metrics.add("exchange.dcn.messages", msgs, pod=g)
        return
    if win.ici_rows:
        metrics.add("exchange.ici.bytes", win.ici_rows * record_bytes)
    for g, rows, msgs in win.per_pod:
        if rows:
            metrics.add("exchange.dcn.bytes", rows * record_bytes,
                        pod=g)
        if msgs:
            metrics.add("exchange.dcn.messages", msgs, pod=g)
