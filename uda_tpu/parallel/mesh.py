"""Device mesh construction + the hierarchical topology descriptor.

The TPU equivalent of the reference's connection topology: where the
reference built one RDMA QP per (reducer, supplier-host) pair lazily
(reference src/DataNet/RDMAClient.cc:498-527), the TPU framework lays
all devices out in a ``jax.sharding.Mesh`` once and lets XLA route
collectives over ICI/DCN. The shuffle data plane uses one named axis
(default ``"shuffle"``); multi-axis meshes (e.g. ``dp x shuffle`` for
several concurrent jobs, or an ICI x DCN split for multi-pod) compose by
naming which axis carries the exchange.

Axis tagging: an axis whose name is ``dcn`` (or starts with ``dcn``) is
the cross-pod data-center-network axis; every other exchange axis is
ICI. A ``uda.tpu.mesh.shape`` of ``dcn:4,ici:8`` therefore describes 4
pods of 8 chips. :func:`mesh_topology` classifies a (mesh, axis-spec)
pair into a :class:`MeshTopology`, which the exchange uses to pick the
two-stage hierarchical round body (pod-local all-to-all + one coalesced
DCN tile per pod pair) over the flat single-stage path — and, when
``coded_capable``, to arm the coded multicast stage B (GF(2^8)-coded
pod-pair tiles, parallel/exchange.py ``coded_round_body``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import ConfigError

__all__ = ["make_mesh", "mesh_from_config", "shard_spec", "SHUFFLE_AXIS",
           "MeshTopology", "mesh_topology", "is_dcn_axis"]

SHUFFLE_AXIS = "shuffle"

AxisSpec = Union[str, Tuple[str, ...]]


def is_dcn_axis(name) -> bool:
    """An axis is DCN-tagged by NAME: ``dcn`` or any ``dcn``-prefixed
    name (``dcn``, ``dcn0`` ...). Everything else rides ICI."""
    return str(name).startswith("dcn")


@dataclasses.dataclass(frozen=True)
class MeshTopology:
    """How the exchange axes map onto the physical fabric.

    Device linear index contract: rows sharded with
    ``PartitionSpec((dcn_axis, ici_axis))`` land on devices in row-major
    (pod-major) order, so global device ``t`` is pod ``t // pod_size``,
    chip ``t % pod_size`` — every pod helper below assumes it.
    """

    dcn_axis: Optional[str]     # None = no DCN-tagged axis (flat mesh)
    ici_axis: Optional[str]     # the intra-pod axis name (None if flat
    #                             over an untagged multi-axis tuple)
    num_pods: int
    pod_size: int

    @property
    def num_devices(self) -> int:
        return self.num_pods * self.pod_size

    @property
    def hierarchical(self) -> bool:
        """True when the mesh has a real pod structure the two-stage
        exchange can exploit (>1 pod of >1 chip)."""
        return (self.dcn_axis is not None and self.num_pods > 1
                and self.pod_size > 1)

    @property
    def coded_capable(self) -> bool:
        """True when the CODED stage-B dispatch can run at all on this
        topology: a real pod structure whose pod size keeps the
        Cauchy-code points inside GF(2^8) (pod_size <= 128 — one coded
        chunk per member chip, uda_tpu.coding.gfjax). Whether a given
        WINDOW actually codes is the host plan's per-pair decision
        (parallel/planner.py)."""
        return self.hierarchical and self.pod_size <= 128

    def pod_of(self, device_index: int) -> int:
        return int(device_index) // self.pod_size

    def chip_of(self, device_index: int) -> int:
        return int(device_index) % self.pod_size

    def pod_members(self, pod: int) -> range:
        return range(pod * self.pod_size, (pod + 1) * self.pod_size)

    def egress_chip(self, src_pod: int, dst_pod: int) -> int:
        """The ONE designated chip of ``src_pod`` that stages the
        coalesced DCN tile for pod pair (src_pod -> dst_pod) — and, by
        the chip-index-preserving semantics of the DCN all_to_all, the
        ingress chip of ``dst_pod`` for the same pair. The rotation
        spreads pairs across chips so no chip is every pair's relay.
        Single definition of the contract: the device round body
        (exchange.hierarchical_round_body) and the host planner
        (parallel/planner.py) both compute exactly this."""
        return (src_pod + dst_pod) % self.pod_size


def mesh_topology(mesh: Mesh, axis: AxisSpec) -> MeshTopology:
    """Classify the exchange axes of ``mesh``.

    ``axis`` is the exchange axis spec as passed to the exchange APIs: a
    single name, or a tuple for multi-axis meshes. A 2-tuple whose OUTER
    axis is DCN-tagged and whose inner is not describes a (pods x chips)
    hierarchy; anything else is treated as one flat exchange group (the
    single-stage path — including untagged multi-axis tuples, where the
    linearized device order carries no pod semantics)."""
    if isinstance(axis, str):
        return MeshTopology(None, axis, 1, int(mesh.shape[axis]))
    names = tuple(axis)
    if len(names) == 1:
        return MeshTopology(None, names[0], 1, int(mesh.shape[names[0]]))
    if (len(names) == 2 and is_dcn_axis(names[0])
            and not is_dcn_axis(names[1])):
        return MeshTopology(names[0], names[1],
                            int(mesh.shape[names[0]]),
                            int(mesh.shape[names[1]]))
    size = 1
    for n in names:
        size *= int(mesh.shape[n])
    return MeshTopology(None, None, 1, size)


def make_mesh(num_devices: Optional[int] = None,
              axis: str = SHUFFLE_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """1D mesh over ``num_devices`` (default: all local devices)."""
    devs = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        if num_devices > len(devs):
            raise ConfigError(
                f"requested {num_devices} devices, have {len(devs)}")
        devs = devs[:num_devices]
    return Mesh(np.asarray(devs), (axis,))


def mesh_from_config(cfg: Config) -> Mesh:
    """Mesh from the ``uda.tpu.mesh.shape`` flag: ``'axis:N,axis2:M'``;
    empty = 1D over all devices. Axis names tag the fabric tier —
    ``'dcn:4,ici:8'`` is 4 pods x 8 chips (see :func:`mesh_topology`);
    the outer DCN axis must come first so pods are device-contiguous."""
    spec = str(cfg.get("uda.tpu.mesh.shape")).strip()
    if not spec:
        return make_mesh()
    names, sizes = [], []
    for part in spec.split(","):
        name, _, size = part.partition(":")
        if not size.isdigit():
            raise ConfigError(f"bad mesh spec segment {part!r}")
        names.append(name.strip())
        sizes.append(int(size))
    devs = jax.devices()
    need = int(np.prod(sizes))
    if need > len(devs):
        raise ConfigError(f"mesh {spec} needs {need} devices, have {len(devs)}")
    arr = np.asarray(devs[:need]).reshape(sizes)
    return Mesh(arr, tuple(names))


def shard_spec(mesh: Mesh, axis: str = SHUFFLE_AXIS) -> NamedSharding:
    """Row-sharded NamedSharding along the shuffle axis."""
    return NamedSharding(mesh, PartitionSpec(axis))
