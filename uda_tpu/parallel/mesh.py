"""Device mesh construction.

The TPU equivalent of the reference's connection topology: where the
reference built one RDMA QP per (reducer, supplier-host) pair lazily
(reference src/DataNet/RDMAClient.cc:498-527), the TPU framework lays
all devices out in a ``jax.sharding.Mesh`` once and lets XLA route
collectives over ICI/DCN. The shuffle data plane uses one named axis
(default ``"shuffle"``); multi-axis meshes (e.g. ``dp x shuffle`` for
several concurrent jobs, or an ICI x DCN split for multi-pod) compose by
naming which axis carries the exchange.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import ConfigError

__all__ = ["make_mesh", "mesh_from_config", "shard_spec", "SHUFFLE_AXIS"]

SHUFFLE_AXIS = "shuffle"


def make_mesh(num_devices: Optional[int] = None,
              axis: str = SHUFFLE_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """1D mesh over ``num_devices`` (default: all local devices)."""
    devs = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        if num_devices > len(devs):
            raise ConfigError(
                f"requested {num_devices} devices, have {len(devs)}")
        devs = devs[:num_devices]
    return Mesh(np.asarray(devs), (axis,))


def mesh_from_config(cfg: Config) -> Mesh:
    """Mesh from the ``uda.tpu.mesh.shape`` flag: ``'axis:N,axis2:M'``;
    empty = 1D over all devices."""
    spec = str(cfg.get("uda.tpu.mesh.shape")).strip()
    if not spec:
        return make_mesh()
    names, sizes = [], []
    for part in spec.split(","):
        name, _, size = part.partition(":")
        if not size.isdigit():
            raise ConfigError(f"bad mesh spec segment {part!r}")
        names.append(name.strip())
        sizes.append(int(size))
    devs = jax.devices()
    need = int(np.prod(sizes))
    if need > len(devs):
        raise ConfigError(f"mesh {spec} needs {need} devices, have {len(devs)}")
    arr = np.asarray(devs[:need]).reshape(sizes)
    return Mesh(arr, tuple(names))


def shard_spec(mesh: Mesh, axis: str = SHUFFLE_AXIS) -> NamedSharding:
    """Row-sharded NamedSharding along the shuffle axis."""
    return NamedSharding(mesh, PartitionSpec(axis))
