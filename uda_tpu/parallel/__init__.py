"""Multi-chip data plane (the DataNet/ layer of SURVEY §1, rebuilt as
mesh collectives): mesh helpers, windowed all-to-all exchange, fused
distributed sort step."""


def _resolve_shard_map():
    """Version-tolerant shard_map import: newer JAX exports it as
    ``jax.shard_map`` (sometimes as a module wrapping the function),
    older releases only under ``jax.experimental.shard_map`` — and the
    replication checker kwarg was renamed ``check_rep`` -> ``check_vma``
    along the way, so on old signatures the shim translates it. Call
    sites write the NEW spelling. Defined BEFORE the submodule imports
    below so ``from uda_tpu.parallel import shard_map`` works from
    inside them during package init."""
    try:
        from jax import shard_map as sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
    if not callable(sm):  # a jax.shard_map MODULE: take its function
        sm = sm.shard_map
    import inspect

    if "check_vma" in inspect.signature(sm).parameters:
        return sm, True
    import functools

    inner = sm

    @functools.wraps(inner)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return inner(*args, **kwargs)

    return shard_map, False


# SHARD_MAP_NATIVE_VMA: True when the ambient JAX has the varying-
# manual-axes checker (check_vma). On older releases the translated
# check_rep checker has no pallas_call rule, so callers that wrap
# Pallas kernels gate on this flag (parallel.distributed._vma_check_on).
shard_map, SHARD_MAP_NATIVE_VMA = _resolve_shard_map()

from uda_tpu.parallel.bytes_exchange import (ExchangeFetchClient,  # noqa: E402
                                             exchange_blobs)
from uda_tpu.parallel.distributed import (DistributedSortResult,
                                          distributed_sort_step,
                                          sample_splitters,
                                          uniform_splitters)
from uda_tpu.parallel.exchange import (ShuffleLayout, exchange_record_batches,
                                       exchange_round, prepare_layout,
                                       resolve_exchange_mode,
                                       shuffle_exchange)
from uda_tpu.parallel.mesh import (SHUFFLE_AXIS, MeshTopology, make_mesh,
                                   mesh_from_config, mesh_topology,
                                   shard_spec)
from uda_tpu.parallel.planner import RoundPlan, WindowPlan, plan_rounds

__all__ = ["DistributedSortResult", "distributed_sort_step",
           "sample_splitters", "uniform_splitters", "ShuffleLayout",
           "exchange_record_batches", "exchange_round", "prepare_layout",
           "resolve_exchange_mode", "shuffle_exchange", "exchange_blobs",
           "ExchangeFetchClient", "SHUFFLE_AXIS", "MeshTopology",
           "make_mesh", "mesh_from_config", "mesh_topology", "shard_spec",
           "RoundPlan", "WindowPlan", "plan_rounds", "shard_map"]
