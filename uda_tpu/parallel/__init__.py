"""Multi-chip data plane (the DataNet/ layer of SURVEY §1, rebuilt as
mesh collectives): mesh helpers, windowed all-to-all exchange, fused
distributed sort step."""

from uda_tpu.parallel.bytes_exchange import (ExchangeFetchClient,
                                             exchange_blobs)
from uda_tpu.parallel.distributed import (DistributedSortResult,
                                          distributed_sort_step,
                                          sample_splitters,
                                          uniform_splitters)
from uda_tpu.parallel.exchange import (ShuffleLayout, exchange_record_batches,
                                       exchange_round, prepare_layout,
                                       shuffle_exchange)
from uda_tpu.parallel.mesh import (SHUFFLE_AXIS, make_mesh, mesh_from_config,
                                   shard_spec)

__all__ = ["DistributedSortResult", "distributed_sort_step",
           "sample_splitters", "uniform_splitters", "ShuffleLayout",
           "exchange_record_batches", "exchange_round", "prepare_layout",
           "shuffle_exchange", "exchange_blobs", "ExchangeFetchClient",
           "SHUFFLE_AXIS", "make_mesh", "mesh_from_config", "shard_spec"]
