"""The shuffle data plane: windowed all-to-all exchange over the mesh.

TPU-native replacement of the reference's RDMA transport (reference
src/DataNet/): instead of per-request one-sided RDMA-WRITEs into remote
registered buffers (RDMAServer.cc:537-631) with credit-based flow
control (RDMAComm.cc:707-752), the exchange is *globally scheduled*:

- every device buckets its records by destination partition;
- each round moves at most ``capacity`` records per (src, dst) pair
  through one ``lax.all_to_all`` over the named mesh axis — the round
  capacity is the credit window, bounding peak HBM exactly like the
  reference's 1000-chunk server pool bounded registered memory
  (NetlevComm.h:35);
- skewed destinations simply take more rounds (the chunked-rounds
  answer to the reference's backlog list, RDMAComm.h:132-152).

Records travel as fixed-stride uint32 row matrices (packed by
uda_tpu.ops.packing); within one jitted round everything is static
shapes, so XLA lowers the exchange to ICI collectives with no host in
the loop. A host-side variable-length RecordBatch exchange is provided
for the Hadoop byte-exact path and as the CPU reference.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from uda_tpu.parallel import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uda_tpu.parallel.multihost import allgather, put_rows
from uda_tpu.utils.errors import TransportError
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.ifile import RecordBatch
from uda_tpu.utils.metrics import metrics

__all__ = ["ShuffleLayout", "prepare_layout", "window_round_body",
           "exchange_round", "shuffle_exchange", "exchange_record_batches"]


@dataclasses.dataclass
class ShuffleLayout:
    """Per-device bucketed layout, computed once per shuffle.

    All arrays are mesh-sharded along axis 0 (one row block per device):

    - ``words``: uint32[N, W] records, locally ordered by destination;
    - ``dest``: int32[N] destination partition of each local record;
    - ``pos``: int32[N] position of the record within its (src, dst)
      bucket — ``pos // capacity`` is the round it travels in;
    - ``counts``: int32[P, P] full count matrix (row = src device,
      col = dst) gathered to every device for round planning.
    """

    words: jax.Array
    dest: jax.Array
    pos: jax.Array
    counts: np.ndarray
    mesh: Mesh
    axis: str


def _bucket_local(words, dest, axis):
    """Stable local bucket-by-destination; returns sorted rows, dest,
    in-bucket positions and per-dest counts."""
    p = lax.psum(1, axis)
    order = jnp.argsort(dest, stable=True)
    sdest = jnp.take(dest, order)
    swords = jnp.take(words, order, axis=0)
    counts = jnp.bincount(sdest, length=p).astype(jnp.int32)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    pos = jnp.arange(sdest.shape[0], dtype=jnp.int32) - jnp.take(starts, sdest)
    return swords, sdest, pos, counts


def prepare_layout(words: jax.Array, dest: jax.Array, mesh: Mesh,
                   axis: str) -> ShuffleLayout:
    """Bucket every device's records and gather the count matrix."""
    spec_rows = NamedSharding(mesh, P(axis))

    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P(axis)),
             out_specs=(P(axis), P(axis), P(axis), P(axis)))
    def _prep(w, d):
        sw, sd, pos, counts = _bucket_local(w, d, axis)
        return sw, sd, pos, counts[None, :]

    words = put_rows(words, mesh, axis)
    dest = put_rows(dest, mesh, axis)
    sw, sd, pos, counts = _prep(words, dest)
    # count-matrix readback: allgather works on multi-process meshes
    # where the sharded array is not host-addressable
    return ShuffleLayout(sw, sd, pos, allgather(counts), mesh, axis)


def window_round_body(w, d, q, lo, axis: str, capacity: int):
    """One windowed exchange round, for use INSIDE a shard_map body (the
    single definition of the round wire protocol — exchange_round and
    the multiround scatter in uda_tpu.parallel.distributed both build on
    it). ``lo`` (the window base, round * capacity) may be traced.

    Returns ``(flat, recv_counts)``: the local [P*capacity, W] delivery
    (row block i = peer i's contribution) and per-peer valid counts [P].
    """
    p = lax.psum(1, axis)
    wcols = w.shape[1]
    in_round = (q >= lo) & (q < lo + capacity)
    slot = jnp.where(in_round, q - lo, capacity)  # overflow -> dropped row
    send = jnp.zeros((p, capacity + 1, wcols), w.dtype)
    send = send.at[d, slot].set(w, mode="drop")
    send_counts = jnp.bincount(
        jnp.where(in_round, d, p), length=p + 1)[:p].astype(jnp.int32)
    recv = lax.all_to_all(send[:, :capacity], axis, split_axis=0,
                          concat_axis=0, tiled=False)
    recv_counts = lax.all_to_all(send_counts[:, None], axis,
                                 split_axis=0, concat_axis=0,
                                 tiled=False).reshape(p)
    return recv.reshape(p * capacity, wcols), recv_counts


@partial(jax.jit, static_argnames=("capacity", "axis", "mesh"))
def _round_impl(words, dest, pos, round_index, mesh, axis, capacity):
    # round_index is TRACED: one compiled program serves every round
    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis), P(axis), P(axis), P()),
             out_specs=(P(axis), P(axis)))
    def _go(w, d, q, r):
        flat, recv_counts = window_round_body(w, d, q, r[0] * capacity,
                                              axis, capacity)
        return flat, recv_counts.reshape(1, -1)

    return _go(words, dest, pos, round_index)


def exchange_round(layout: ShuffleLayout, capacity: int, round_index: int):
    """One windowed all-to-all round.

    Returns ``(recv_words, recv_counts)``: per device, ``capacity`` rows
    from each peer (``recv_words`` row-block i = peer i's contribution,
    of which ``recv_counts[i]`` rows are valid).
    """
    return _round_impl(layout.words, layout.dest, layout.pos,
                       jnp.asarray([round_index], jnp.int32),
                       layout.mesh, layout.axis, capacity)


def shuffle_exchange(words, dest, mesh: Mesh, axis: str,
                     capacity: int,
                     max_rounds: Optional[int] = None):
    """Full exchange: as many rounds as the largest (src, dst) bucket
    needs. Returns ``(per_round_results, layout)`` where each round entry
    is the (recv_words, recv_counts) pair of exchange_round.

    The round count is data-dependent but *host*-decided (one count
    matrix readback per shuffle, analogous to the reference's per-MOF
    fetch bookkeeping) so every device executes the same static program.
    """
    layout = prepare_layout(words, dest, mesh, axis)
    biggest = int(layout.counts.max()) if layout.counts.size else 0
    rounds = max(1, -(-biggest // capacity))
    if max_rounds is not None and rounds > max_rounds:
        raise TransportError(
            f"skew needs {rounds} rounds (bucket {biggest} > capacity "
            f"{capacity} x {max_rounds}); raise capacity or max_rounds")
    results = []
    for r in range(rounds):
        # injection site for exchange-plane faults (a failed collective
        # surfaces as TransportError, like a reference WC error)
        failpoint("exchange.round", key=f"round{r}")
        results.append(exchange_round(layout, capacity, r))
        metrics.add("exchange.rounds")
    return results, layout


def exchange_record_batches(batches_by_dest: Sequence[Sequence[RecordBatch]]
                            ) -> list[RecordBatch]:
    """Host-side variable-length exchange: ``batches_by_dest[src][dst]``
    -> per-dst concatenated batch. The byte-exact path for Hadoop
    records (and the oracle the device exchange is tested against)."""
    ndst = max((len(row) for row in batches_by_dest), default=0)
    out = []
    for dst in range(ndst):
        out.append(RecordBatch.concat(
            [row[dst] for row in batches_by_dest if dst < len(row)]))
    return out
