"""The shuffle data plane: windowed all-to-all exchange over the mesh.

TPU-native replacement of the reference's RDMA transport (reference
src/DataNet/): instead of per-request one-sided RDMA-WRITEs into remote
registered buffers (RDMAServer.cc:537-631) with credit-based flow
control (RDMAComm.cc:707-752), the exchange is *globally scheduled*:

- every device buckets its records by destination partition;
- each round moves at most ``capacity`` records per (src, dst) pair
  through one ``lax.all_to_all`` over the named mesh axis — the round
  capacity is the credit window, bounding peak HBM exactly like the
  reference's 1000-chunk server pool bounded registered memory
  (NetlevComm.h:35);
- skewed destinations simply take more rounds (the chunked-rounds
  answer to the reference's backlog list, RDMAComm.h:132-152).

Records travel as fixed-stride uint32 row matrices (packed by
uda_tpu.ops.packing); within one jitted round everything is static
shapes, so XLA lowers the exchange to ICI collectives with no host in
the loop. A host-side variable-length RecordBatch exchange is provided
for the Hadoop byte-exact path and as the CPU reference.

Hierarchical (multi-pod) meshes: on a ``(dcn, ici)`` 2-axis mesh the
flat round would give every cross-pod *device* pair its own DCN lane —
O((p*c)^2) per-round DCN messages. The two-stage round body
(:func:`hierarchical_round_body`) instead runs the all_to_all only
over the ICI axis, staging every record's cross-pod hop onto the ONE
designated egress chip of its (pod, peer-pod) pair, moves one
coalesced tile per pod pair over the DCN axis — O(p^2) messages, the
reference's per-QP aggregation win (RDMAServer.cc chunked server
pool) — and delivers with a second pod-local scatter. Same window
semantics, same delivery contract, byte-identical output; the host
planner (parallel/planner.py) proves the per-round message reduction
and accounts the RECORD bytes each tier carries (identical to flat on
the DCN by construction — the same rows cross pods either way).

Coded multicast stage B (``mode="coded"``, Coded TeraSort
arXiv:1702.04850): when the host plan says a window's pod pairs are
*codable* (cross rows spread over >= 2 destination chips and the
padded multicast chunk beats the payload — parallel/planner.py), the
egress chip compacts each destination chip's rows into an ``L``-row
block and GF(2^8)-encodes the ``pod_size`` blocks through a full-rank
Cauchy matrix (uda_tpu.coding.gfjax — the in-tree RS machinery's
square case), so the pair's ONE DCN tile carries coded chunks instead
of disjoint per-destination blocks; stage C broadcasts the arrived
chunks pod-locally (``lax.all_gather`` over ICI — the cheap fabric
pays for the expensive one, the Coded TeraSort trade) and every
member decodes its OWN block locally with the inverse row of its chip
index. Delivery tags ride through encode/decode untouched, so the
post-decode scatter reproduces the exact flat (peer row-block, slot)
layout — byte-identity vs the flat oracle stays gated by
construction. Windows the plan declines (skew, single-destination
pairs, 1-pod meshes) ride the plain coalesced tile with zero coded
overhead, and a decode failure (failpoint site ``exchange.decode``)
falls back to the plain tile within the round.

Scope of the byte accounting: ``lax.all_to_all`` lowers to DENSE
static buffers, so the stage-B collective's wire footprint includes
the unpopulated tile slots of non-egress chips (a ~pod_size padding
factor over the populated rows; stage C likewise on ICI). A
sparse/ragged collective (``lax.ragged_all_to_all``, newer JAX) is
the lever that makes the wire footprint match the record accounting —
until then the hierarchical win this module claims, measures and
gates is the MESSAGE/coalescing structure (per-transfer setup cost,
the per-QP analogy) plus the per-tier record-byte ledger, not the
padded collective payload. The CODED ledger extends the same
discipline one step: ``exchange.dcn.coded.bytes`` charges what a
redundant-map Coded-TeraSort deployment would move — one L-row
multicast packet per pod pair serving every member at once, decode
side information being map-redundancy the deployment computes
locally. This virtual mesh has no map redundancy, so the coded tile
ships the full-rank chunk set (any member can decode every block) and
the side-information share of the tile rides the wire outside the
model charge — see the planner docstring, README and PARITY for the
full statement. ``shuffle_exchange``/``prepare_layout`` dispatch on
the mesh topology (flat 1-axis meshes keep the single-stage path).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from uda_tpu.parallel import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uda_tpu.parallel.mesh import MeshTopology, mesh_topology
from uda_tpu.parallel.multihost import allgather, put_rows
from uda_tpu.utils.errors import (ConfigError, StorageError,
                                  TransportError)
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.ifile import RecordBatch
from uda_tpu.utils.metrics import metrics

__all__ = ["ShuffleLayout", "prepare_layout", "window_round_body",
           "hierarchical_round_body", "coded_round_body",
           "run_round_body", "resolve_exchange_mode",
           "exchange_dispatch", "exchange_round",
           "execute_planned_window", "shuffle_exchange",
           "exchange_record_batches"]

EXCHANGE_MODES = ("auto", "flat", "hierarchical", "coded")


def resolve_exchange_mode(mesh: Mesh, axis, mode: str = "auto"):
    """Resolve the exchange dispatch for a (mesh, axis) pair.

    Returns ``(topology, hierarchical, coded)``. ``auto`` takes the
    two-stage path exactly when the mesh has a real pod structure (a
    DCN-tagged outer axis with >1 pod of >1 chip); ``flat`` forces the
    single-stage path on any mesh (the A/B baseline); ``hierarchical``
    demands a hierarchical mesh and refuses otherwise. ``coded`` ARMS
    the coded stage-B dispatch on hierarchical meshes — whether any
    window actually codes is the host plan's per-window decision — and
    deliberately degrades to the plain path elsewhere (a 1-pod mesh
    has no pod pairs to encode across: zero coded overhead, not an
    error)."""
    if mode not in EXCHANGE_MODES:
        raise ConfigError(f"unknown exchange mode {mode!r} "
                          f"(one of {EXCHANGE_MODES})")
    topo = mesh_topology(mesh, axis)
    if mode == "hierarchical" and not topo.hierarchical:
        raise ConfigError(
            f"exchange mode 'hierarchical' needs a (dcn, ici) mesh with "
            f">1 pod of >1 chip; got axes {axis!r} on mesh "
            f"{dict(mesh.shape)}")
    hier = topo.hierarchical if mode in ("auto", "coded") \
        else mode == "hierarchical"
    return topo, hier, (mode == "coded" and topo.hierarchical)


def exchange_dispatch(topology: Optional[MeshTopology],
                      hierarchical: bool) -> dict:
    """The static dispatch triple every jitted exchange entry point
    shares (``_round_impl``, ``distributed._sort_step``,
    ``distributed._round_scatter``) — ONE definition so the fused,
    multiround and plain-exchange paths can never disagree on which
    round body a mesh runs."""
    hier = bool(hierarchical) and topology is not None
    return {"exchange_mode": "hierarchical" if hier else "flat",
            "dcn_axis": topology.dcn_axis if hier else None,
            "ici_axis": topology.ici_axis if hier else None}


@dataclasses.dataclass
class ShuffleLayout:
    """Per-device bucketed layout, computed once per shuffle.

    All arrays are mesh-sharded along axis 0 (one row block per device):

    - ``words``: uint32[N, W] records, locally ordered by destination;
    - ``dest``: int32[N] destination partition of each local record;
    - ``pos``: int32[N] position of the record within its (src, dst)
      bucket — ``pos // capacity`` is the round it travels in;
    - ``counts``: int32[P, P] full count matrix (row = src device,
      col = dst) gathered to every device for round planning;
    - ``topology``/``hierarchical``/``coded``: the resolved fabric
      dispatch — which round body :func:`exchange_round` runs
      (``coded`` arms the per-window coded stage-B decision in the
      host plan; the staged machinery is shared, so coded implies
      hierarchical).
    """

    words: jax.Array
    dest: jax.Array
    pos: jax.Array
    counts: np.ndarray
    mesh: Mesh
    axis: str
    topology: Optional[MeshTopology] = None
    hierarchical: bool = False
    coded: bool = False

    def dispatch(self) -> dict:
        """Static round-body dispatch kwargs (see
        :func:`exchange_dispatch`)."""
        return exchange_dispatch(self.topology, self.hierarchical)

    def record_bytes(self) -> int:
        """Wire stride of one record row — the byte unit of the
        planner's ICI/DCN accounting."""
        return (int(self.words.shape[1])
                * int(np.dtype(self.words.dtype).itemsize))


def _bucket_local(words, dest, axis):
    """Stable local bucket-by-destination; returns sorted rows, dest,
    in-bucket positions and per-dest counts."""
    p = lax.psum(1, axis)
    order = jnp.argsort(dest, stable=True)
    sdest = jnp.take(dest, order)
    swords = jnp.take(words, order, axis=0)
    counts = jnp.bincount(sdest, length=p).astype(jnp.int32)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    pos = jnp.arange(sdest.shape[0], dtype=jnp.int32) - jnp.take(starts, sdest)
    return swords, sdest, pos, counts


def prepare_layout(words: jax.Array, dest: jax.Array, mesh: Mesh,
                   axis: str, mode: str = "auto") -> ShuffleLayout:
    """Bucket every device's records and gather the count matrix.
    ``mode`` resolves the fabric dispatch (see
    :func:`resolve_exchange_mode`)."""
    topo, hier, coded = resolve_exchange_mode(mesh, axis, mode)

    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P(axis)),
             out_specs=(P(axis), P(axis), P(axis), P(axis)))
    def _prep(w, d):
        sw, sd, pos, counts = _bucket_local(w, d, axis)
        return sw, sd, pos, counts[None, :]

    words = put_rows(words, mesh, axis)
    dest = put_rows(dest, mesh, axis)
    sw, sd, pos, counts = _prep(words, dest)
    # count-matrix readback: allgather works on multi-process meshes
    # where the sharded array is not host-addressable
    return ShuffleLayout(sw, sd, pos, allgather(counts), mesh, axis,
                         topo, hier, coded)


def window_round_body(w, d, q, lo, axis: str, capacity: int):
    """One windowed exchange round, for use INSIDE a shard_map body (the
    single definition of the round wire protocol — exchange_round and
    the multiround scatter in uda_tpu.parallel.distributed both build on
    it). ``lo`` (the window base, round * capacity) may be traced.

    Returns ``(flat, recv_counts)``: the local [P*capacity, W] delivery
    (row block i = peer i's contribution) and per-peer valid counts [P].
    """
    p = lax.psum(1, axis)
    wcols = w.shape[1]
    in_round = (q >= lo) & (q < lo + capacity)
    slot = jnp.where(in_round, q - lo, capacity)  # overflow -> dropped row
    send = jnp.zeros((p, capacity + 1, wcols), w.dtype)
    send = send.at[d, slot].set(w, mode="drop")
    send_counts = jnp.bincount(
        jnp.where(in_round, d, p), length=p + 1)[:p].astype(jnp.int32)
    recv = lax.all_to_all(send[:, :capacity], axis, split_axis=0,
                          concat_axis=0, tiled=False)
    recv_counts = lax.all_to_all(send_counts[:, None], axis,
                                 split_axis=0, concat_axis=0,
                                 tiled=False).reshape(p)
    return recv.reshape(p * capacity, wcols), recv_counts


def _staged_stage_a(w, d, q, lo, dcn_axis: str, ici_axis: str,
                    capacity: int):
    """The staged bodies' shared prologue + stage A (pod-local
    all_to_all: intra-pod records straight to their final chip,
    cross-pod records onto the pair's egress chip, every row tagged
    ``src_device * capacity + slot + 1``). ONE definition for the
    hierarchical and coded bodies — the staging row formula, the
    trash-row trick and the tag discipline can never diverge between
    them. Returns ``(p, c, g, i, m, wcols, wex, intra_rows, cross)``
    with ``cross`` shaped [src chip, peer-pod rank, dst chip, slot,
    word]."""
    p = lax.psum(1, dcn_axis)           # pods
    c = lax.psum(1, ici_axis)           # chips per pod
    g = lax.axis_index(dcn_axis)        # my pod
    i = lax.axis_index(ici_axis)        # my chip
    m = -(-p // c)                      # peer-pod slots per egress chip
    wcols = w.shape[1]
    in_round = (q >= lo) & (q < lo + capacity)
    slot = q - lo
    tag = ((g * c + i) * capacity + slot + 1).astype(w.dtype)
    ext = jnp.concatenate([w, tag[:, None]], axis=1)
    wex = wcols + 1
    dpod = d // c
    dchip = d % c
    intra = dpod == g
    rows_a = capacity + m * c * capacity
    blk = jnp.where(intra, dchip, (g + dpod) % c)
    row = jnp.where(intra, slot,
                    capacity + (dpod // c) * (c * capacity)
                    + dchip * capacity + slot)
    row = jnp.where(in_round, row, rows_a)      # trash row, sliced off
    send_a = jnp.zeros((c, rows_a + 1, wex), w.dtype)
    send_a = send_a.at[blk, row].set(ext, mode="drop")
    recv_a = lax.all_to_all(send_a[:, :rows_a], ici_axis, split_axis=0,
                            concat_axis=0, tiled=False)
    intra_rows = recv_a[:, :capacity].reshape(c * capacity, wex)
    cross = recv_a[:, capacity:].reshape(c, m, c, capacity, wex)
    return p, c, g, i, m, wcols, wex, intra_rows, cross


def _tag_assemble(arrived, wcols, nd, capacity: int):
    """The staged bodies' shared delivery: tag - 1 IS the output row
    of the flat ``[P*capacity, W]`` layout (0 marks an empty slot),
    recv_counts from the tags' source devices. Shared so the
    byte-identity contract has exactly one assembly definition."""
    atag = arrived[:, wcols].astype(jnp.int32)
    valid = atag > 0
    idx = jnp.where(valid, atag - 1, nd * capacity)
    out = jnp.zeros((nd * capacity + 1, wcols), arrived.dtype)
    out = out.at[idx].set(arrived[:, :wcols],
                          mode="drop")[:nd * capacity]
    peer_dev = jnp.where(valid, (atag - 1) // capacity, nd)
    recv_counts = jnp.bincount(peer_dev, length=nd + 1)[:nd].astype(
        jnp.int32)
    return out, recv_counts


def hierarchical_round_body(w, d, q, lo, dcn_axis: str, ici_axis: str,
                            capacity: int):
    """The two-stage (pod-local + coalesced DCN) round body, for use
    INSIDE a shard_map over BOTH mesh axes. Same window semantics and
    same delivery contract as :func:`window_round_body` — callers
    cannot tell which body ran except through the fabric accounting:

    - **stage A (ICI all_to_all):** records are re-bucketed by
      destination POD; an intra-pod record goes straight to its final
      chip, a cross-pod record to the ONE designated egress chip of its
      (pod, peer-pod) pair (``MeshTopology.egress_chip`` =
      ``(g + g') % c``, rotating pairs across chips);
    - **stage B (DCN all_to_all):** each populated egress chip moves
      ONE coalesced tile per peer pod — O(p^2) DCN messages per round
      instead of the flat body's O((p*c)^2) device pairs;
    - **stage C (ICI all_to_all):** the ingress chip scatters arrived
      rows to their final chips.

    Delivery slots are carried, not recomputed: every staged row rides
    with a ``tag`` column (``src_device * capacity + in_window_slot +
    1``; 0 marks an empty staging slot), and the final scatter places
    row ``tag - 1`` of the ``[P*capacity, W]`` output — exactly the
    (peer row-block, slot) layout of the flat body, so the output is
    byte-identical by construction, not by sort order luck. The tag is
    computed and decoded in int32, capping ``P * capacity`` at
    2^31 - 1 — a bound the [P*capacity, W] delivery buffer hits in HBM
    long before the tag does, and which the host planner
    (parallel/planner.py plan_rounds) rejects loudly.
    """
    # -- stage A (shared with the coded body): pod-local all_to_all
    # (direct delivery / egress stage)
    p, c, g, i, m, wcols, wex, intra_rows, cross = _staged_stage_a(
        w, d, q, lo, dcn_axis, ici_axis, capacity)
    nd = p * c

    # -- stage B: ONE coalesced tile per pod pair over the DCN axis.
    # I am the egress chip of peer pods g' with (g + g') % c == i, i.e.
    # g' = ((i - g) mod c) + k*c for rank k — and by the same formula
    # the INGRESS chip for tiles arriving from those pods.
    peers = ((i - g) % c) + jnp.arange(m) * c
    tiles = jnp.swapaxes(cross, 0, 1).reshape(m, c * c * capacity, wex)
    send_b = jnp.zeros((p + 1, c * c * capacity, wex), w.dtype)
    send_b = send_b.at[jnp.where(peers < p, peers, p)].set(
        tiles, mode="drop")
    recv_b = lax.all_to_all(send_b[:p], dcn_axis, split_axis=0,
                            concat_axis=0, tiled=False)

    # -- stage C: pod-local scatter of the arrived tiles (only the
    # blocks whose source pod I ingress for are populated; compact to
    # the m populated ranks before the all_to_all)
    compact = jnp.take(recv_b, jnp.minimum(peers, p - 1), axis=0)
    compact = jnp.where((peers < p)[:, None, None], compact, 0)
    compact = compact.reshape(m, c, c, capacity, wex)
    send_c = jnp.transpose(compact, (2, 0, 1, 3, 4)).reshape(
        c, m * c * capacity, wex)
    recv_c = lax.all_to_all(send_c, ici_axis, split_axis=0,
                            concat_axis=0, tiled=False)

    # -- final assembly: tag - 1 IS the output row (shared)
    arrived = jnp.concatenate([
        intra_rows, recv_c.reshape(c * m * c * capacity, wex)])
    return _tag_assemble(arrived, wcols, nd, capacity)


def coded_round_body(w, d, q, lo, dcn_axis: str, ici_axis: str,
                     capacity: int, l_rows: int):
    """The CODED two-stage round body: same staging as
    :func:`hierarchical_round_body`, but the pod-pair DCN tile carries
    GF(2^8)-coded chunks instead of disjoint per-destination blocks
    (the Coded TeraSort multicast phase, arXiv:1702.04850):

    - **stage A** is byte-for-byte the hierarchical staging (cross-pod
      rows onto the pair's egress chip, tags riding along);
    - **encode:** the egress chip COMPACTS each destination chip's
      rows to the front of an ``l_rows``-row block (``l_rows`` is the
      host plan's padded chunk length — the plan guarantees every
      block fits) and multiplies the ``c`` blocks through the full-
      rank Cauchy matrix (uda_tpu.coding.gfjax), one coded chunk per
      member chip;
    - **stage B** moves ONE ``[c, l_rows]`` coded tile per pod pair
      over the DCN axis — the same O(p^2) coalescing, with the tile
      now ``c*l_rows`` rows instead of ``c^2*capacity`` slots (the
      compaction also shrinks the dense collective buffer);
    - **stage C** is an ICI ``all_gather``: every member receives
      every arrived tile (the broadcast that stands in for the CDC
      side information — charged to the ICI ledger by the planner)
      and decodes its OWN destination block with the inverse-matrix
      row of its chip index (``gfjax.gf_decode_row``, traced row).

    Tags ride INSIDE the coded words (the GF action is exact), so the
    final tag-indexed scatter reproduces the flat layout precisely —
    byte-identity by construction, the same contract as the plain
    staged body. ``l_rows`` must be positive and cover the biggest
    per-(pair, destination-chip) in-window block; the host plan
    (parallel/planner.py) guarantees both before dispatching here.
    """
    from uda_tpu.coding.gfjax import (coded_matrices, gf_decode_row,
                                      gf_matmul_words)

    # -- stage A: the SHARED hierarchical staging (_staged_stage_a)
    p, c, g, i, m, wcols, wex, intra_rows, cross = _staged_stage_a(
        w, d, q, lo, dcn_axis, ici_axis, capacity)
    nd = p * c
    # [src chip, peer-pod rank, dst chip, slot, word] -> destination-
    # block view [peer slot, dst chip, (src chip, slot), word]
    blocks_full = jnp.transpose(cross, (1, 2, 0, 3, 4)).reshape(
        m, c, c * capacity, wex)

    # -- compaction: populated rows (tag > 0) to the chunk front; the
    # plan guarantees rank < l_rows for every populated row, so the
    # trash row at l_rows only ever receives empties
    populated = blocks_full[:, :, :, wcols] > 0
    rank = jnp.cumsum(populated.astype(jnp.int32), axis=2) - 1
    idx = jnp.where(populated, rank, l_rows)
    mi = jnp.arange(m)[:, None, None]
    ci = jnp.arange(c)[None, :, None]
    blocks = jnp.zeros((m, c, l_rows + 1, wex), w.dtype)
    blocks = blocks.at[mi, ci, idx].set(blocks_full,
                                        mode="drop")[:, :, :l_rows]

    # -- encode: coded chunk t = XOR_j A[t, j] * block[j] (per peer
    # slot; A static, built at trace time from the static pod size)
    enc, dec = coded_matrices(c)
    coded = gf_matmul_words(enc, jnp.swapaxes(blocks, 0, 1))
    tiles = jnp.swapaxes(coded, 0, 1).reshape(m, c * l_rows, wex)

    # -- stage B: one coded tile per pod pair over the DCN axis
    peers = ((i - g) % c) + jnp.arange(m) * c
    send_b = jnp.zeros((p + 1, c * l_rows, wex), w.dtype)
    send_b = send_b.at[jnp.where(peers < p, peers, p)].set(
        tiles, mode="drop")
    recv_b = lax.all_to_all(send_b[:p], dcn_axis, split_axis=0,
                            concat_axis=0, tiled=False)
    compact = jnp.take(recv_b, jnp.minimum(peers, p - 1), axis=0)
    compact = jnp.where((peers < p)[:, None, None], compact, 0)

    # -- stage C: pod-local broadcast of the arrived coded tiles —
    # every member needs the full chunk set to decode its block
    gathered = lax.all_gather(compact, ici_axis, axis=0, tiled=False)
    chunks = jnp.transpose(
        gathered.reshape(c, m, c, l_rows, wex),
        (2, 0, 1, 3, 4))                # [chunk t, ingress, slot, ...]

    # -- local decode: my destination block only (inverse row = my
    # chip index, traced — gf_decode_row combines with traced coeffs)
    mine = gf_decode_row(dec, i, chunks)

    # -- final assembly: tag - 1 IS the output row (shared)
    arrived = jnp.concatenate([
        intra_rows, mine.reshape(c * m * l_rows, wex)])
    return _tag_assemble(arrived, wcols, nd, capacity)


def run_round_body(w, d, q, lo, capacity: int, axis,
                   exchange_mode="flat", dcn_axis=None, ici_axis=None,
                   coded_l_rows=None):
    """The flat-vs-hierarchical-vs-coded body dispatch, for use INSIDE
    a shard_map body — the single branch shared by ``_round_impl``,
    ``distributed._sort_step`` and ``distributed._round_scatter``
    (fed the static kwargs of :func:`exchange_dispatch`), completing
    the one-definition contract: a new mode or body signature changes
    exactly here. ``exchange_mode="coded"`` needs the host plan's
    static chunk length (``coded_l_rows``); a coded dispatch WITHOUT
    one runs the plain staged body — the plan is what turns coding on
    per window (the fused single-round step has no plan and lands
    there by design)."""
    if exchange_mode == "coded" and coded_l_rows:
        return coded_round_body(w, d, q, lo, dcn_axis, ici_axis,
                                capacity, int(coded_l_rows))
    if exchange_mode in ("hierarchical", "coded"):
        return hierarchical_round_body(w, d, q, lo, dcn_axis, ici_axis,
                                       capacity)
    return window_round_body(w, d, q, lo, axis, capacity)


@partial(jax.jit, static_argnames=("capacity", "axis", "mesh",
                                   "exchange_mode", "dcn_axis",
                                   "ici_axis", "coded_l_rows"))
def _round_impl(words, dest, pos, round_index, mesh, axis, capacity,
                exchange_mode="flat", dcn_axis=None, ici_axis=None,
                coded_l_rows=None):
    # round_index is TRACED: one compiled program serves every round
    # (and, coded, every coded window — the plan's single coded_l_rows)
    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis), P(axis), P(axis), P()),
             out_specs=(P(axis), P(axis)))
    def _go(w, d, q, r):
        flat, recv_counts = run_round_body(
            w, d, q, r[0] * capacity, capacity, axis,
            exchange_mode, dcn_axis, ici_axis, coded_l_rows)
        return flat, recv_counts.reshape(1, -1)

    return _go(words, dest, pos, round_index)


def exchange_round(layout: ShuffleLayout, capacity: int,
                   round_index: int, coded_l_rows: Optional[int] = None):
    """One windowed exchange round (single-stage, the two-stage
    hierarchical body when the layout resolved a pod topology, or the
    coded stage-B body when ``coded_l_rows`` carries the host plan's
    chunk length for a coded window).

    Returns ``(recv_words, recv_counts)``: per device, ``capacity`` rows
    from each peer (``recv_words`` row-block i = peer i's contribution,
    of which ``recv_counts[i]`` rows are valid).
    """
    dispatch = layout.dispatch()
    if coded_l_rows:
        dispatch = dict(dispatch, exchange_mode="coded",
                        coded_l_rows=int(coded_l_rows))
    return _round_impl(layout.words, layout.dest, layout.pos,
                       jnp.asarray([round_index], jnp.int32),
                       layout.mesh, layout.axis, capacity, **dispatch)


def execute_planned_window(win, plan, coded_exec, plain_exec):
    """The ONE coded-window dispatch, shared by ``shuffle_exchange``
    and ``distributed.distributed_sort_multiround`` (the same
    one-definition contract as :func:`run_round_body`): fire the
    decode-failure rung (failpoint site ``exchange.decode``, keyed
    ``round<i>`` — it fires BEFORE the coded body runs, so the
    fallback re-dispatches an untouched window), run ``coded_exec``
    for plan-approved windows with in-round fallback to
    ``plain_exec`` on a decode failure (counted
    ``exchange.decode.fallbacks``), and book the ledger for the body
    that ACTUALLY ran."""
    from uda_tpu.parallel.planner import record_executed_window

    if plan.coded and win.coded:
        decode_ok = True
        try:
            failpoint("exchange.decode", key=f"round{win.index}")
        except StorageError:
            metrics.add("exchange.decode.fallbacks")
            decode_ok = False
        if decode_ok:
            # OUTSIDE the try by design: the multiround caller's
            # coded executor consumes a DONATED accumulator — an
            # error escaping the coded body itself must propagate,
            # never re-dispatch the already-deleted buffer on the
            # plain path (the fallback contract covers decode
            # failures, which fire before the body runs)
            out = coded_exec()
            record_executed_window(win, plan, coded=True)
            return out
    out = plain_exec()
    record_executed_window(win, plan, coded=False)
    return out


def shuffle_exchange(words, dest, mesh: Mesh, axis: str,
                     capacity: int,
                     max_rounds: Optional[int] = None,
                     mode: str = "auto"):
    """Full exchange: as many rounds as the largest (src, dst) bucket
    needs. Returns ``(per_round_results, layout)`` where each round entry
    is the (recv_words, recv_counts) pair of exchange_round.

    The round schedule is data-dependent but *host*-decided (one count
    matrix readback per shuffle, analogous to the reference's per-MOF
    fetch bookkeeping) so every device executes the same static
    program: the planner (parallel/planner.py) derives every window
    from the counts matrix, skips globally-empty ones
    (``exchange.rounds.skipped``) and records the per-axis fabric
    accounting (``exchange.ici.bytes`` / ``exchange.dcn.bytes`` /
    ``exchange.dcn.messages``) for each executed round. ``mode``
    picks flat vs two-stage hierarchical vs coded dispatch (see
    :func:`resolve_exchange_mode`); with ``mode="coded"`` the plan
    decides per window whether the coded stage-B body runs (skew and
    single-destination pairs stay on the plain tile at zero coded
    overhead), a decode failure (failpoint ``exchange.decode``) falls
    back to the plain tile within the round, and coded windows
    additionally book ``exchange.dcn.coded.bytes`` /
    ``exchange.dcn.saved.bytes``.
    """
    from uda_tpu.parallel.planner import (plan_layout_rounds,
                                          record_plan_skips)

    layout = prepare_layout(words, dest, mesh, axis, mode)
    plan = plan_layout_rounds(layout, capacity)
    if max_rounds is not None and plan.planned > max_rounds:
        biggest = int(layout.counts.max()) if layout.counts.size else 0
        raise TransportError(
            f"skew needs {plan.planned} rounds (bucket {biggest} > "
            f"capacity {capacity} x {max_rounds}); raise capacity or "
            f"max_rounds")
    results = []
    for win in plan.windows:
        # injection site for exchange-plane faults (a failed collective
        # surfaces as TransportError, like a reference WC error)
        failpoint("exchange.round", key=f"round{win.index}")
        if layout.hierarchical:
            # stage-resolved rung: a fault in the cross-pod DCN stage
            # (arm with match:stageB) must surface exactly like a
            # whole-round collective failure
            failpoint("exchange.round", key=f"round{win.index}.stageB")
        results.append(execute_planned_window(
            win, plan,
            lambda: exchange_round(layout, capacity, win.index,
                                   plan.coded_l_rows),
            lambda: exchange_round(layout, capacity, win.index)))
    record_plan_skips(plan)
    return results, layout


def exchange_record_batches(batches_by_dest: Sequence[Sequence[RecordBatch]]
                            ) -> list[RecordBatch]:
    """Host-side variable-length exchange: ``batches_by_dest[src][dst]``
    -> per-dst concatenated batch. The byte-exact path for Hadoop
    records (and the oracle the device exchange is tested against)."""
    ndst = max((len(row) for row in batches_by_dest), default=0)
    out = []
    for dst in range(ndst):
        out.append(RecordBatch.concat(
            [row[dst] for row in batches_by_dest if dst < len(row)]))
    return out
