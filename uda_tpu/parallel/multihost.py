"""Multi-host (multi-process) wiring for the distributed exchange.

The reference's data plane is cross-NODE by definition — one RDMA
connection per remote supplier host (reference
src/DataNet/RDMAClient.cc:498-527, 602-629 per-host DNS cache) over the
IB fabric. The TPU-native equivalent spans hosts with the SAME SPMD
program the single-host path runs: ``jax.distributed`` brings every
process's local devices into one global runtime, the mesh covers all
global devices, XLA lowers ``all_to_all`` to ICI within a slice and DCN
across slices, and the host control plane (this module) only moves
metadata.

What this module provides:

- ``initialize``: process bring-up (the rdma_cm connect dance of
  RDMAClient.cc:215-356, replaced by the JAX coordination service);
- ``global_mesh``: a shuffle mesh over every device of every process;
- ``shard_rows`` / ``replicate``: build global arrays from
  process-local data without requiring full addressability
  (device_put needs every shard local; these do not);
- ``allgather``: fetch a globally-sharded result back to every host
  (the test/validation path — production consumers keep results
  device-resident).

CPU testing: JAX supports multi-process CPU (each process serves
``--xla_force_host_platform_device_count`` virtual devices; collectives
run over the coordination service), so the cross-process path is
exercised by tests/test_multihost.py with 2 processes x 4 devices and
no TPU pod — the multi-node-without-a-cluster capability the reference
never had (SURVEY §4.5).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uda_tpu.parallel.mesh import SHUFFLE_AXIS

__all__ = ["initialize", "global_mesh", "global_mesh_2axis", "shard_rows",
           "replicate", "allgather", "put_global", "put_rows",
           "zeros_global"]


def initialize(coordinator_address: str, num_processes: int,
               process_id: int) -> None:
    """Join the global JAX runtime (jax.distributed): process 0 hosts the
    coordination service at ``coordinator_address`` (host:port), every
    process connects to it. Call before any other JAX API touches
    devices. (Per-process CPU device count for tests comes from
    --xla_force_host_platform_device_count, set BEFORE jax import.)"""
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh(axis: str = SHUFFLE_AXIS) -> Mesh:
    """1-D shuffle mesh over every device of every process, in global
    device order (process-major, so each process's row block is local)."""
    return Mesh(np.asarray(jax.devices()), (axis,))


def global_mesh_2axis(dcn_axis: str = "dcn",
                      ici_axis: str = SHUFFLE_AXIS) -> Mesh:
    """The deployment-shaped 2-axis mesh: the PROCESS boundary is the
    outer (DCN) axis, each process's local devices the inner (ICI)
    axis — exactly the v5p multi-host topology where collectives ride
    ICI within a host/pod and DCN across (the roofline shape in
    PARITY.md). Devices arrive process-major from jax.devices(), so the
    reshape puts every row of the inner axis on one process."""
    # jax.devices() does not guarantee process-contiguous ordering:
    # sort by (process_index, id) so each outer row IS one process,
    # and verify — a mixed row would silently break the DCN semantics
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    nproc = jax.process_count()
    if len(devs) % nproc:
        raise ValueError(f"{len(devs)} devices not divisible by "
                         f"{nproc} processes")
    grid = np.asarray(devs).reshape(nproc, len(devs) // nproc)
    for row in grid:
        owners = {d.process_index for d in row}
        if len(owners) != 1:
            raise ValueError(f"devices of processes {sorted(owners)} "
                             "share one dcn row; uneven per-process "
                             "device counts are not supported")
    return Mesh(grid, (dcn_axis, ici_axis))


def put_global(arr: np.ndarray, sharding: NamedSharding) -> jax.Array:
    """device_put that also works when the sharding spans processes:
    jax.device_put requires every shard to be addressable; on a
    multi-host mesh the global array is assembled from the local shards
    via make_array_from_callback (each process materializes only its
    devices' index slices)."""
    arr = np.asarray(arr)
    if sharding.is_fully_addressable:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def put_rows(words, mesh: Mesh, axis: str = SHUFFLE_AXIS) -> jax.Array:
    """Row-shard a GLOBAL array onto the mesh. A jax.Array already
    sharded over the mesh (e.g. built by shard_rows on a multi-process
    mesh, where device_put of host data is impossible) passes through;
    host data goes through put_global."""
    spec = NamedSharding(mesh, P(axis))
    if isinstance(words, jax.Array) and words.sharding == spec:
        return words
    return put_global(np.asarray(words), spec)


def zeros_global(shape, dtype, sharding: NamedSharding) -> jax.Array:
    """Globally-sharded zeros WITHOUT materializing the global array on
    any host (put_global of np.zeros(shape) would allocate the full
    global buffer per process — host RAM scaling with the global
    shuffle size instead of the local shard)."""
    if sharding.is_fully_addressable:
        return jax.device_put(np.zeros(shape, dtype), sharding)

    def shard_zeros(idx):
        dims = [len(range(*s.indices(dim))) for s, dim in zip(idx, shape)]
        return np.zeros(dims, dtype)

    return jax.make_array_from_callback(shape, sharding, shard_zeros)


def shard_rows(local_rows: np.ndarray, mesh: Mesh,
               axis=SHUFFLE_AXIS) -> jax.Array:
    """Global row-sharded array from each process's LOCAL row block
    (every process passes its own rows; global row count = sum).
    ``axis`` may be a tuple for 2-axis meshes (rows shard over the
    linearized (dcn, ici) device order)."""
    sharding = NamedSharding(mesh, P(axis))
    if sharding.is_fully_addressable:
        return jax.device_put(local_rows, sharding)
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(
        local_rows, mesh, P(axis))


def replicate(arr: np.ndarray, mesh: Mesh) -> jax.Array:
    """Globally replicated array from identical per-process data."""
    return put_global(np.asarray(arr), NamedSharding(mesh, P()))


def allgather(arr: jax.Array) -> np.ndarray:
    """Full global value on every process (host readback). On a
    single-process mesh this is just np.asarray."""
    if arr.is_fully_addressable:
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
