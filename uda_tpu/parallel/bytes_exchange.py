"""Opaque-bytes transport over the mesh + the exchange-fed fetch client.

The reference moves IFile segment bytes between hosts with one-sided
RDMA-WRITEs into registered buffers (reference src/DataNet/
RDMAServer.cc:537-631, consumed by the reduce-side InputClient,
src/Merger/InputClient.h:30-56). The mesh equivalent here:

- ``exchange_blobs``: pack arbitrary byte blobs into fixed-stride
  uint32 rows (2 header words — blob id, valid bytes — plus the
  payload slice) and move them with the SAME windowed all-to-all the
  record exchange uses (parallel.exchange.shuffle_exchange). Round
  windows walk the in-bucket position in order and each round's valid
  rows are delivered densely per source, so per-(src, dst) byte order
  is preserved end-to-end and reassembly is a linear scan.
- ``ExchangeFetchClient``: an InputClient serving the delivered
  segments to the reduce-side MergeManager chunk by chunk — the full
  reference flow (supplier MOF -> transport -> reduce-side merge) with
  the device mesh as the wire instead of an RDMA fabric.

Together with merger.MergeManager this closes the loop the reference
calls "network levitation": the transport tier and the merge tier are
separate components joined only by the InputClient contract, so either
side can be swapped (DataEngine locally, the mesh across chips).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh

from uda_tpu.merger.segment import InputClient
from uda_tpu.mofserver.data_engine import FetchResult, ShuffleRequest
from uda_tpu.utils.errors import MergeError

__all__ = ["exchange_blobs", "exchange_group_size", "ExchangeFetchClient"]


def exchange_group_size(mesh: Mesh, axis) -> int:
    """Number of exchange participants = product of the NAMED axes only
    (a multi-axis mesh with a single exchange axis runs one independent
    exchange per replica of the other axes; counting all axes would
    address dests the all_to_all never reaches and silently drop their
    rows). The one rule callers sizing ``blobs`` must share."""
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    return int(np.prod([mesh.shape[a] for a in axes]))

_SENTINEL = np.uint32(0xFFFFFFFF)   # blob id of padding rows
_HDR_WORDS = 2                      # [blob_id, valid_bytes]


def _pack_src(items: Sequence[Tuple[int, bytes]], row_payload: int
              ) -> tuple[np.ndarray, np.ndarray]:
    """One source's blobs -> (rows uint32[N, W], dest int32[N]).
    Every blob becomes ceil(len/row_payload) rows (an empty blob still
    emits one valid=0 row so it reassembles as b'')."""
    w = _HDR_WORDS + row_payload // 4
    rows, dest = [], []
    for blob_id, (dst, data) in enumerate(items):
        chunks = ([data[o:o + row_payload]
                   for o in range(0, len(data), row_payload)] or [b""])
        for chunk in chunks:
            row = np.zeros(w, np.uint32)
            row[0] = blob_id
            row[1] = len(chunk)
            padded = chunk + b"\0" * (row_payload - len(chunk))
            row[_HDR_WORDS:] = np.frombuffer(padded, np.uint32)
            rows.append(row)
            dest.append(dst)
    return (np.stack(rows) if rows else np.zeros((0, w), np.uint32),
            np.asarray(dest, np.int32))


def exchange_blobs(blobs: Sequence[Sequence[Tuple[int, bytes]]],
                   mesh: Mesh, axis: str,
                   capacity: Optional[int] = None,
                   row_payload_bytes: int = 256
                   ) -> list[list[list[bytes]]]:
    """Move byte blobs across the mesh: ``blobs[src]`` is that source
    device's send list of ``(dst_device, payload)`` pairs; returns
    ``out[dst][src]`` = the payloads from ``src`` to ``dst`` in send
    order. ``capacity`` is the per-(src, dst) row window per round
    (default: one round, sized to the largest bucket).
    """
    from uda_tpu.parallel.exchange import shuffle_exchange

    p = exchange_group_size(mesh, axis)
    if len(blobs) != p:
        raise ValueError(f"blobs has {len(blobs)} sources for a {p}-way "
                         f"exchange over {axis!r}")
    for s, items in enumerate(blobs):
        for dst, _ in items:
            if not 0 <= dst < p:
                raise ValueError(f"source {s}: dest {dst} outside the "
                                 f"{p}-way exchange group")
    if row_payload_bytes <= 0 or row_payload_bytes % 4:
        raise ValueError("row_payload_bytes must be a positive multiple "
                         "of 4")
    packed = [_pack_src(items, row_payload_bytes) for items in blobs]
    w = _HDR_WORDS + row_payload_bytes // 4
    nmax = max((r.shape[0] for r, _ in packed), default=0) or 1
    words = np.zeros((p * nmax, w), np.uint32)
    dest = np.zeros(p * nmax, np.int32)
    for s, (rows, d) in enumerate(packed):
        n = rows.shape[0]
        words[s * nmax:s * nmax + n] = rows
        dest[s * nmax:s * nmax + n] = d
        # padding rows: sentinel blob id, dest 0, valid 0 — they ride
        # the exchange and are skipped at reassembly
        words[s * nmax + n:(s + 1) * nmax, 0] = _SENTINEL
    if capacity is None:
        counts = np.zeros((p, p), np.int64)
        for s, (_, d) in enumerate(packed):
            np.add.at(counts[s], d, 1)
        counts[:, 0] += nmax - np.asarray([r.shape[0] for r, _ in packed])
        capacity = max(1, int(counts.max()))

    from uda_tpu.parallel.multihost import allgather

    results, _ = shuffle_exchange(words, dest, mesh, axis, capacity)
    cap = capacity
    streams: list[list[list[np.ndarray]]] = [
        [[] for _ in range(p)] for _ in range(p)]
    for recv_words, recv_counts in results:
        # allgather: host-readable on every process of a multi-host
        # mesh (np.asarray alone only covers fully-addressable arrays)
        rw = allgather(recv_words).reshape(p, p, cap, w)
        rc = allgather(recv_counts).reshape(p, p)
        for d in range(p):
            for s in range(p):
                if rc[d, s]:
                    streams[d][s].append(rw[d, s, :rc[d, s]])

    out: list[list[list[bytes]]] = [[[] for _ in range(p)] for _ in range(p)]
    for d in range(p):
        for s in range(p):
            if not streams[d][s]:
                continue
            rows = np.concatenate(streams[d][s])
            cur_id, parts = None, []
            for row in rows:
                if row[0] == _SENTINEL:
                    continue
                if cur_id is not None and row[0] != cur_id:
                    out[d][s].append(b"".join(parts))
                    parts = []
                cur_id = int(row[0])
                parts.append(row[_HDR_WORDS:].tobytes()[:int(row[1])])
            if cur_id is not None:
                out[d][s].append(b"".join(parts))
    return out


class ExchangeFetchClient(InputClient):
    """Reduce-side InputClient over mesh-delivered segments.

    ``segments`` maps map id -> that map output's partition bytes for
    THIS reduce task (as delivered by exchange_blobs). Fetches complete
    inline — the bytes already crossed the wire; chunking preserves the
    Segment carry-buffer contract (records split across chunks) so the
    whole reduce-side stack behaves exactly as over the RDMA-style
    transport.

    ``raw_lengths`` carries each partition's UNCOMPRESSED size when the
    exchanged bytes are codec-compressed (the spill index's raw_length
    vs part_length split). It exists for FetchResult CONTRACT fidelity —
    the reference ACK carries both lengths (RDMAServer.cc:597-607) —
    not because the decompression path needs it: DecompressingClient
    tracks uncompressed progress itself and never reads the inner
    raw_length. Defaults to the on-wire length — correct ONLY for
    uncompressed segments, so callers exchanging codec-compressed bytes
    MUST pass ``raw_lengths`` (run_reduces_mesh does) or
    FetchResult.raw_length misreports the part_length."""

    def __init__(self, segments: dict[str, bytes],
                 raw_lengths: Optional[dict[str, int]] = None):
        self._segments = dict(segments)
        self._raw = dict(raw_lengths or {})

    def start_fetch(self, req: ShuffleRequest, on_complete) -> None:
        data = self._segments.get(req.map_id)
        if data is None:
            on_complete(MergeError(f"no exchanged segment for map "
                                   f"{req.map_id!r}"))
            return
        chunk = data[req.offset:req.offset + req.chunk_size]
        last = req.offset + len(chunk) >= len(data)
        on_complete(FetchResult(chunk,
                                self._raw.get(req.map_id, len(data)),
                                len(data), req.offset,
                                f"mesh://{req.map_id}", last))
