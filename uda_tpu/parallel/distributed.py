"""Distributed shuffle+merge: the flagship multi-chip step.

The TPU-native equivalent of UDA's whole reason to exist: the all-to-all
segment exchange between M map outputs and R reducers (reference
partition addressing jobid/mapid/reduceid, src/DataNet/RDMAClient.cc:
575-586, src/MOFServer/MOFServlet.cc:28-96) fused with the reduce-side
merge (src/Merger/MergeManager.cc) into ONE jitted SPMD program:

    partition (splitter search) -> bucket -> all_to_all (ICI) ->
    local lexicographic sort -> globally sorted, device-sharded output

Global order: destinations are monotone in key-prefix, so after the
exchange device d holds exactly range-partition d and the concatenation
of per-device sorted shards is the total order — the same contract as
the reference's per-reducer partition files, but computed in one XLA
program with no host round-trips.

Range splitters come from the host (uniform for TeraSort-style keys, or
sampled quantiles), mirroring how Hadoop's TotalOrderPartitioner feeds
TeraSort.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uda_tpu.utils.errors import TransportError

__all__ = ["uniform_splitters", "sample_splitters", "distributed_sort_step",
           "DistributedSortResult"]

_INVALID = jnp.uint32(0xFFFFFFFF)


def uniform_splitters(num_partitions: int) -> np.ndarray:
    """Range splitters on the first key word for uniformly distributed
    keys (TeraSort's keyspace): partition i covers
    [i*2^32/P, (i+1)*2^32/P)."""
    edges = (np.arange(1, num_partitions, dtype=np.uint64)
             * (1 << 32)) // num_partitions
    return edges.astype(np.uint32)


def sample_splitters(first_words: np.ndarray, num_partitions: int,
                     oversample: int = 64) -> np.ndarray:
    """Sampled quantile splitters for skewed key distributions (the
    TotalOrderPartitioner analogue). ``first_words`` is any sample of
    first key words."""
    sample = np.sort(np.asarray(first_words, dtype=np.uint32))
    if sample.size == 0:
        return uniform_splitters(num_partitions)
    idx = (np.arange(1, num_partitions) * sample.size) // num_partitions
    return sample[np.minimum(idx, sample.size - 1)]


class DistributedSortResult:
    """Device-sharded sorted output of one distributed sort step."""

    def __init__(self, words: jax.Array, valid_counts: jax.Array,
                 send_overflow: jax.Array):
        self.words = words              # [P*cap_total rows, W] sharded
        self.valid_counts = valid_counts  # [P] valid rows per device
        self.send_overflow = send_overflow  # [P] records dropped (0 = ok)

    def check(self) -> None:
        over = np.asarray(self.send_overflow)
        if over.sum() != 0:
            raise TransportError(
                f"exchange capacity overflow on devices {np.nonzero(over)[0]}"
                f" ({over.sum()} records); raise capacity or use "
                "shuffle_exchange's multi-round path")


@partial(jax.jit, static_argnames=("mesh", "axis", "capacity", "num_keys",
                                   "payload_path"))
def _sort_step(words, splitters, mesh, axis, capacity, num_keys,
               payload_path="carry"):
    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P()),
             out_specs=(P(axis), P(axis), P(axis)))
    def _go(w, spl):
        p = lax.psum(1, axis)
        n, wcols = w.shape
        # 1. partition: monotone in the first key word
        dest = jnp.searchsorted(spl[0], w[:, 0], side="right").astype(jnp.int32)
        # 2. bucket locally (stable by arrival)
        order = jnp.argsort(dest, stable=True)
        sd = jnp.take(dest, order)
        sw = jnp.take(w, order, axis=0)
        counts = jnp.bincount(sd, length=p).astype(jnp.int32)
        starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                  jnp.cumsum(counts)[:-1].astype(jnp.int32)])
        pos = jnp.arange(n, dtype=jnp.int32) - jnp.take(starts, sd)
        # 3. single-round exchange (overflow reported, not silently lost)
        slot = jnp.where(pos < capacity, pos, capacity)
        send = jnp.zeros((p, capacity + 1, wcols), w.dtype)
        send = send.at[sd, slot].set(sw)
        send_counts = jnp.minimum(counts, capacity)
        overflow = jnp.sum(jnp.maximum(counts - capacity, 0))
        recv = lax.all_to_all(send[:, :capacity], axis, split_axis=0,
                              concat_axis=0, tiled=False)
        recv_counts = lax.all_to_all(send_counts[:, None], axis,
                                     split_axis=0, concat_axis=0,
                                     tiled=False).reshape(p)
        flat = recv.reshape(p * capacity, wcols)
        # 4. local sort: invalid rows forced past every real key.
        # payload_path="carry": all record columns ride the sort network
        # (fastest runtime, but XLA variadic-sort compile time grows
        # superlinearly in operand count — prohibitive on TPU
        # remote-compile backends). "gather": a narrow sort computes the
        # permutation, per-column gathers apply it (bounded compile).
        row = jnp.arange(p * capacity, dtype=jnp.int32)
        valid = (row % capacity) < jnp.take(recv_counts, row // capacity)
        keycols = tuple(jnp.where(valid, flat[:, i], _INVALID)
                        for i in range(num_keys))
        if payload_path == "carry":
            payload = tuple(flat[:, i] for i in range(wcols))
            sorted_ops = lax.sort(
                (*keycols, jnp.where(valid, 0, 1), *payload),
                num_keys=num_keys + 1, is_stable=True)
            out = jnp.stack(sorted_ops[num_keys + 1:], axis=1)
        else:
            # permutation from a narrow sort, applied per column ([n]
            # gathers keep the SoA/no-lane-padding rationale of
            # terasort.bench_step; a row gather on the [n, W] matrix
            # would touch the 5x lane-padded layout)
            *_, perm = lax.sort(
                (*keycols, jnp.where(valid, 0, 1), row),
                num_keys=num_keys + 1, is_stable=True)
            out = jnp.stack(tuple(jnp.take(flat[:, i], perm, axis=0)
                                  for i in range(wcols)), axis=1)
        nvalid = jnp.sum(recv_counts)
        return out, nvalid[None], overflow[None]

    out, nvalid, overflow = _go(words, splitters[None, :])
    return out, nvalid, overflow


def distributed_sort_step(words, splitters, mesh: Mesh, axis: str,
                          capacity: int, num_keys: int,
                          payload_path: str = "auto"
                          ) -> DistributedSortResult:
    """Run the fused partition/exchange/sort step.

    ``words``: uint32[N, W] records (rows sharded over ``axis``; the
    first ``num_keys`` columns are the big-endian key words).
    ``capacity``: per-(src, dst) records per round — the credit window.
    ``payload_path``: how the local sort moves value columns ("auto":
    operand-carry on CPU meshes, permutation+gather on accelerators
    where wide variadic sorts compile pathologically slowly).
    """
    from uda_tpu.ops.sort import resolve_sort_path

    payload_path = resolve_sort_path(payload_path)
    spec = NamedSharding(mesh, P(axis))
    words = jax.device_put(words, spec)
    splitters = jax.device_put(jnp.asarray(splitters, dtype=jnp.uint32),
                               NamedSharding(mesh, P()))
    out, nvalid, overflow = _sort_step(words, splitters, mesh, axis,
                                       capacity, num_keys, payload_path)
    return DistributedSortResult(out, nvalid, overflow)
