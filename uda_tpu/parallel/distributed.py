"""Distributed shuffle+merge: the flagship multi-chip step.

The TPU-native equivalent of UDA's whole reason to exist: the all-to-all
segment exchange between M map outputs and R reducers (reference
partition addressing jobid/mapid/reduceid, src/DataNet/RDMAClient.cc:
575-586, src/MOFServer/MOFServlet.cc:28-96) fused with the reduce-side
merge (src/Merger/MergeManager.cc) into ONE jitted SPMD program:

    partition (splitter search) -> bucket -> all_to_all (ICI) ->
    local lexicographic sort -> globally sorted, device-sharded output

Global order: destinations are monotone in key-prefix, so after the
exchange device d holds exactly range-partition d and the concatenation
of per-device sorted shards is the total order — the same contract as
the reference's per-reducer partition files, but computed in one XLA
program with no host round-trips.

Range splitters come from the host (uniform for TeraSort-style keys, or
sampled quantiles), mirroring how Hadoop's TotalOrderPartitioner feeds
TeraSort.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from uda_tpu.parallel import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uda_tpu.parallel.multihost import put_global, put_rows, zeros_global
from uda_tpu.utils.errors import TransportError

__all__ = ["uniform_splitters", "sample_splitters", "distributed_sort_step",
           "distributed_sort_multiround", "DistributedSortResult"]

# numpy scalar, NOT jnp: a module-level jnp constant would materialize
# a device array at import time, initializing the XLA backend and
# breaking any later jax.distributed.initialize (multi-host bring-up)
_INVALID = np.uint32(0xFFFFFFFF)


def _lanes_interpret(payload_path: str, mesh: Mesh) -> bool:
    """Pallas interpret-mode flag for the lanes paths, resolved EAGERLY
    off the MESH's device platform (CPU meshes — tests, dryruns — have
    no Mosaic lowering, even when the host's default backend is a TPU).
    False for every other path so it never splits their jit cache."""
    from uda_tpu.ops.sort import LANES_ENGINES

    return (payload_path in LANES_ENGINES
            and mesh.devices.flat[0].platform == "cpu")


def _resolve_payload_path(path: str, wcols: int, num_keys: int,
                          n_rows: int = 0) -> str:
    """route_engine with the lanes engines admitted. The built-in
    "auto" defaults never resolve to a lanes engine (TPU auto =
    carrychunk, the fly-off champion, which has no record-width limit
    — see resolve_sort_path), so no width gate is needed here; an
    EXPLICIT lanes-engine request (or a deployed UDA_TPU_SORT_PATH
    winner) is passed through and fails loudly in
    _sort_valid_rows_lanes if the record exceeds the 32-row layout.
    ``n_rows`` is the GLOBAL row count — per-device shards are smaller,
    so the small-batch steering (route_engine) is conservative: a
    globally-small batch is certainly small per device.
    ``wcols``/``num_keys`` stay in the signature for that error path's
    callers and for any future auto policy that reconsiders lanes."""
    del wcols, num_keys  # no auto path needs the width today
    from uda_tpu.ops.sort import route_engine

    return route_engine(n_rows, path, lanes_ok=True)


def uniform_splitters(num_partitions: int) -> np.ndarray:
    """Range splitters on the first key word for uniformly distributed
    keys (TeraSort's keyspace): partition i covers
    [i*2^32/P, (i+1)*2^32/P)."""
    edges = (np.arange(1, num_partitions, dtype=np.uint64)
             * (1 << 32)) // num_partitions
    return edges.astype(np.uint32)


def sample_splitters(first_words: np.ndarray, num_partitions: int,
                     oversample: int = 64) -> np.ndarray:
    """Sampled quantile splitters for skewed key distributions (the
    TotalOrderPartitioner analogue). ``first_words`` is any sample of
    first key words."""
    sample = np.sort(np.asarray(first_words, dtype=np.uint32))
    if sample.size == 0:
        return uniform_splitters(num_partitions)
    idx = (np.arange(1, num_partitions) * sample.size) // num_partitions
    return sample[np.minimum(idx, sample.size - 1)]


class DistributedSortResult:
    """Device-sharded sorted output of one distributed sort step."""

    def __init__(self, words: jax.Array, valid_counts: jax.Array,
                 send_overflow: jax.Array, overflow_total=None):
        self.words = words              # [P*cap_total rows, W] sharded
        self.valid_counts = valid_counts  # [P] valid rows per device
        self.send_overflow = send_overflow  # [P] records dropped (0 = ok)
        # replicated scalar: readable on EVERY process of a multi-host
        # mesh (the per-device vector is not addressable cross-process)
        self._overflow_total = overflow_total

    def overflow(self) -> int:
        if self._overflow_total is not None:
            return int(np.asarray(self._overflow_total))
        return int(np.asarray(self.send_overflow).sum())

    def check(self) -> None:
        total = self.overflow()
        if total != 0:
            detail = ""
            if self.send_overflow.is_fully_addressable:
                over = np.asarray(self.send_overflow)
                detail = f" on devices {np.nonzero(over)[0].tolist()}"
            raise TransportError(
                f"exchange capacity overflow{detail} ({total} records); "
                "raise capacity or use the multi-round path")


def _vma_check_on(payload_path: str, interpret: bool) -> bool:
    """shard_map varying-manual-axes checker gate: ON everywhere except
    lanes engines under INTERPRET mode (the Pallas interpreter's grid
    machinery mis-types; scripts/repro_check_vma.py is the committed
    repro — the compiled path traces clean since the _pass_splits carry
    pcast). UDA_TPU_FORCE_NO_CHECK_VMA=1 is the operational escape
    hatch for a first-hardware-run surprise; using it should be
    reported back into the repro script."""
    from uda_tpu.ops.sort import LANES_ENGINES
    from uda_tpu.parallel import SHARD_MAP_NATIVE_VMA

    if os.environ.get("UDA_TPU_FORCE_NO_CHECK_VMA") == "1":
        return False
    if not SHARD_MAP_NATIVE_VMA:
        # pre-vma JAX: the legacy check_rep checker has no pallas_call
        # replication rule, so any lanes engine would fail to trace;
        # the property is only checkable on native-vma releases
        return payload_path not in LANES_ENGINES
    return not (payload_path in LANES_ENGINES and interpret)


def _sort_valid_rows(flat, valid, num_keys, payload_path, interpret=False):
    """Stable local sort of ``flat``'s rows by the first ``num_keys``
    columns, with ``valid``-masked rows forced past every real key (the
    shared tail of the fused step and the multi-round accumulator sort).

    payload_path="lanes": the Pallas bitonic pipeline
    (ops.pallas_sort.sort_lanes) — bounded compile (two Mosaic kernels
    regardless of n and width) AND streaming payload movement; the TPU
    default. "keys8": same pipeline on an 8-row keys-only view plus one
    global XLA payload gather (see _sort_valid_rows_lanes). "lanes2":
    the in-kernel two-phase variant (needs Mosaic dynamic-gather
    lowering). The (masked keys, invalid flag) sort key rides as lanes
    rows, stability via the pipeline's arrival tie-break, so equal-key
    order is IDENTICAL to the lax.sort paths below. "carry": all record
    columns ride the sort network (fast runtime, but XLA variadic-sort
    compile time grows superlinearly in operand count — prohibitive on
    TPU remote-compile backends). "gather": a narrow sort computes the
    permutation and per-column gathers on [n] arrays apply it (bounded
    compile, avoids the lane-padded [n, W] layout). "gather2": the same
    narrow-sort permutation applied with ONE minor-dim gather on the
    transposed [W, n] view instead — deliberately trading layouts; the
    faster of the two is backend-dependent and bench.py's fly-off
    measures it. "carrychunk": the same permutation applied with NO
    gathers at all — inverted via a 2-operand sort and re-applied in
    narrow carry-sort chunks (ops.sort.apply_perm_chunked), every sort
    far below the operand count where compile blows up."""
    from uda_tpu.ops.sort import LANES_ENGINES

    n, wcols = flat.shape
    if payload_path in LANES_ENGINES:
        return _sort_valid_rows_lanes(
            flat, valid, num_keys, interpret,
            two_phase=payload_path == "lanes2",
            keys8=payload_path in ("keys8", "keys8f"),
            folded=payload_path == "keys8f")
    keycols = tuple(jnp.where(valid, flat[:, i], _INVALID)
                    for i in range(num_keys))
    invalid_last = jnp.where(valid, 0, 1)
    if payload_path == "carry":
        payload = tuple(flat[:, i] for i in range(wcols))
        sorted_ops = lax.sort(
            (*keycols, invalid_last, *payload),
            num_keys=num_keys + 1, is_stable=True)
        return jnp.stack(sorted_ops[num_keys + 1:], axis=1)
    row = jnp.arange(n, dtype=jnp.int32)
    *_, perm = lax.sort((*keycols, invalid_last, row),
                        num_keys=num_keys + 1, is_stable=True)
    if payload_path == "gather2":
        # one minor-dim gather of all columns at once (vs "gather"'s
        # per-column takes) — same permutation, same output
        return jnp.take(flat.T, perm, axis=1,
                        unique_indices=True, mode="clip").T
    if payload_path == "carrychunk":
        # gather-free permutation apply (ops.sort.apply_perm_chunked)
        from uda_tpu.ops.sort import apply_perm_chunked

        cols = apply_perm_chunked(perm,
                                  [flat[:, i] for i in range(wcols)])
        return jnp.stack(cols, axis=1)
    return jnp.stack(tuple(jnp.take(flat[:, i], perm, axis=0)
                           for i in range(wcols)), axis=1)


def _sort_valid_rows_lanes(flat, valid, num_keys, interpret,
                           two_phase=False, keys8=False, folded=False):
    """Lanes-path body of _sort_valid_rows: pack rows into the [32, n]
    lanes layout with sort key (masked key words, invalid flag), pad the
    lane count to a power of two with +inf-key lanes, run the Pallas
    pipeline, unpack the payload rows.

    Order parity with the lax.sort paths: identical sort key, and the
    pipeline's arrival-index tie-break == their stable row order. The
    padding lanes share the invalid rows' (+inf, 1) key but have LARGER
    arrival indices than every real lane, so they sort strictly after
    all real rows and truncating back to n lanes drops exactly them."""
    from uda_tpu.ops import pallas_sort

    n, wcols = flat.shape
    first_pay = num_keys + 1             # payload starts past the flag row
    tb = pallas_sort.TB_ROW_DEFAULT
    npad, tile = pallas_sort.pad_pow2(n, 1024)
    keyrows = jnp.stack([jnp.where(valid, flat[:, i], _INVALID)
                         for i in range(num_keys)]
                        + [jnp.where(valid, jnp.uint32(0), jnp.uint32(1))])
    # padding lanes (n..npad) keep _INVALID in the flag row too: (keys
    # +inf, flag +inf) sorts strictly after real invalid lanes' (keys
    # +inf, flag 1), so no arrival-index comparison against padding
    # ever decides a real lane's position
    if keys8:
        # keys8 engine: the whole cascade runs on an 8-row keys-only
        # array (4x less VPU/HBM work per stage than the 32-row
        # pipeline) and the payload never stages into a lanes matrix at
        # all — it moves ONCE, a global XLA lane gather straight off
        # ``flat`` (minor-dim layout, no lane padding). Same sort key
        # and tie-break as the full-width pipeline, so equal-key order
        # is identical; record width is unconstrained (no 32-row limit).
        k8 = num_keys + 1                # masked keys + invalid flag
        if k8 > 7:
            raise ValueError(
                f"num_keys={num_keys} does not fit the 8-row keys view; "
                "use payload_path='lanes'")
        if folded and k8 > 3:
            raise ValueError(
                f"keys8f needs num_keys <= 2 here (keys + invalid flag "
                f"must fit the folded 4-row slot); got {num_keys} — use "
                "payload_path='keys8'")
        base = jnp.full((k8, npad), _INVALID, jnp.uint32)
        keyr = lax.dynamic_update_slice(base, keyrows, (0, 0))
        # the n real lanes sort strictly before the padding, so the
        # first n arrival indices all reference real rows of flat
        _, perm = pallas_sort.keys8_sort_perm(keyr, tile=tile,
                                              interpret=interpret,
                                              folded=folded)
        return jnp.take(flat.T, perm[:n], axis=1,
                        unique_indices=True, mode="clip").T
    if first_pay + wcols > tb:
        raise ValueError(
            f"record width {wcols} + {num_keys} keys does not fit the "
            f"{pallas_sort.ROWS}-row lanes layout; use payload_path="
            "'gather'")
    mat = jnp.full((pallas_sort.ROWS, npad), _INVALID, jnp.uint32)
    mat = lax.dynamic_update_slice(mat, keyrows, (0, 0))
    mat = lax.dynamic_update_slice(mat, flat.T, (first_pay, 0))
    out = pallas_sort.sort_lanes(mat, num_keys=num_keys + 1, tb_row=tb,
                                 tile=tile, interpret=interpret,
                                 two_phase=two_phase)
    return out[first_pay:first_pay + wcols, :n].T


@partial(jax.jit, static_argnames=("mesh", "axis", "capacity", "num_keys",
                                   "payload_path", "interpret",
                                   "exchange_mode", "dcn_axis",
                                   "ici_axis"))
def _sort_step(words, splitters, mesh, axis, capacity, num_keys,
               payload_path="carry", interpret=False,
               exchange_mode="flat", dcn_axis=None, ici_axis=None):
    # check_vma now runs on the REAL lanes path too: the merge-pass
    # fori_loop carry is pcast to the data's vma at init
    # (ops/pallas_sort.py _pass_splits), which was the only mis-typing
    # in our own code — all four lanes engines trace clean with
    # check_vma=True and interpret=False (r5; previously bypassed
    # wholesale). The one REMAINING bypass is interpret mode: the
    # Pallas interpreter expands pallas_call into eval_jaxpr whose
    # grid-machinery dynamic_slice mixes replicated block indices with
    # varying operands — an emulator limitation, not a property of the
    # compiled kernel (minimal repro: scripts/repro_check_vma.py).
    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P()),
             out_specs=(P(axis), P(axis), P(axis)),
             check_vma=_vma_check_on(payload_path, interpret))
    def _go(w, spl):
        from uda_tpu.parallel.exchange import run_round_body

        p = lax.psum(1, axis)
        n, wcols = w.shape
        # 1. partition: monotone in the first key word
        dest = jnp.searchsorted(spl[0], w[:, 0], side="right").astype(jnp.int32)
        # 2. bucket locally (stable by arrival)
        order = jnp.argsort(dest, stable=True)
        sd = jnp.take(dest, order)
        sw = jnp.take(w, order, axis=0)
        counts = jnp.bincount(sd, length=p).astype(jnp.int32)
        starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                  jnp.cumsum(counts)[:-1].astype(jnp.int32)])
        pos = jnp.arange(n, dtype=jnp.int32) - jnp.take(starts, sd)
        # 3. single-round exchange at window base 0 (the shared round
        # bodies of parallel/exchange.py; overflow — rows past the
        # credit window — is reported, not silently lost)
        overflow = jnp.sum(jnp.maximum(counts - capacity, 0))
        flat, recv_counts = run_round_body(sw, sd, pos, 0, capacity,
                                           axis, exchange_mode,
                                           dcn_axis, ici_axis)
        # 4. local sort: invalid rows forced past every real key
        row = jnp.arange(p * capacity, dtype=jnp.int32)
        valid = (row % capacity) < jnp.take(recv_counts, row // capacity)
        out = _sort_valid_rows(flat, valid, num_keys, payload_path,
                               interpret)
        nvalid = jnp.sum(recv_counts)
        return out, nvalid[None], overflow[None]

    out, nvalid, overflow = _go(words, splitters[None, :])
    # replicated total: host-readable on every process of a multi-host
    # mesh, where the per-device overflow vector is not addressable
    return out, nvalid, overflow, jnp.sum(overflow)


def distributed_sort_step(words, splitters, mesh: Mesh, axis: str,
                          capacity: int, num_keys: int,
                          payload_path: str = "auto",
                          multiround: str = "auto",
                          exchange_mode: str = "auto"
                          ) -> DistributedSortResult:
    """Run the fused partition/exchange/sort step.

    ``words``: uint32[N, W] records (rows sharded over ``axis``; the
    first ``num_keys`` columns are the big-endian key words).
    ``axis``: one mesh axis name, or a TUPLE of axis names for
    multi-pod meshes — e.g. ``("dcn", "shuffle")`` on a (pods, chips)
    mesh shards rows over both; results are byte-identical to the flat
    single-axis mesh of the same device order.
    ``exchange_mode``: fabric dispatch for multi-pod meshes —
    ``"auto"`` (default) runs the two-stage hierarchical round body
    (pod-local all_to_all, ONE coalesced DCN tile per pod pair, pod-
    local delivery scatter — parallel/exchange.py) whenever the mesh
    has a DCN-tagged outer axis with >1 pod of >1 chip; ``"flat"``
    forces the single-stage body (the A/B baseline, where XLA routes
    one global all_to_all per axis); ``"hierarchical"`` demands a pod
    mesh. ``"coded"`` arms the coded multicast stage B on the WINDOWED
    path: the fused single-round attempt runs the plain staged body
    (coding is a per-window host-plan decision and the fused program
    has no plan), while the multiround path codes every window the
    plan approves — so ``multiround="always"`` is the fully-coded
    entry and the auto overflow re-run inherits it.
    ``capacity``: per-(src, dst) records per round — the credit window.
    ``payload_path``: how the local sort moves value columns ("auto":
    operand-carry on CPU meshes, chunked operand-carry ("carrychunk",
    the measured fly-off champion — bounded compile, no record-width
    limit) on TPU; the Pallas lanes engines and the gather paths stay
    available explicitly — see _sort_valid_rows for the trade-offs).
    ``multiround``: skew completion policy. "auto" (default) runs the
    fused single-round program and, if any (src, dst) bucket overflowed
    the credit window, re-runs the shuffle through the windowed
    multi-round exchange — the backlog-drain guarantee of the
    reference's credit flow (RDMAComm.cc:707-752: no-credit sends queue
    on the backlog and drain as credits return, so ANY skew eventually
    completes). "never" reports overflow in the result (caller handles
    it); "always" skips the fused attempt.
    """
    from uda_tpu.parallel.exchange import (exchange_dispatch,
                                           resolve_exchange_mode)

    payload_path = _resolve_payload_path(payload_path, int(words.shape[1]),
                                         num_keys, int(words.shape[0]))
    if multiround not in ("auto", "never", "always"):
        raise ValueError(f"unknown multiround policy {multiround!r}")
    topo, hier, _coded = resolve_exchange_mode(mesh, axis, exchange_mode)
    if multiround == "always":
        return distributed_sort_multiround(words, splitters, mesh, axis,
                                           capacity, num_keys, payload_path,
                                           exchange_mode)
    words = put_rows(words, mesh, axis)
    splitters_dev = put_global(np.asarray(splitters, dtype=np.uint32),
                               NamedSharding(mesh, P()))
    out, nvalid, overflow, total = _sort_step(
        words, splitters_dev, mesh, axis, capacity, num_keys, payload_path,
        interpret=_lanes_interpret(payload_path, mesh),
        **exchange_dispatch(topo, hier))
    res = DistributedSortResult(out, nvalid, overflow, total)
    if multiround == "auto" and res.overflow() != 0:
        return distributed_sort_multiround(words, splitters, mesh, axis,
                                           capacity, num_keys, payload_path,
                                           exchange_mode)
    return res


@partial(jax.jit, static_argnames=("mesh", "axis", "capacity",
                                   "exchange_mode", "dcn_axis",
                                   "ici_axis", "coded_l_rows"),
         donate_argnames=("acc",))
def _round_scatter(words, dest, pos, acc, colbase, r, mesh, axis, capacity,
                   exchange_mode="flat", dcn_axis=None, ici_axis=None,
                   coded_l_rows=None):
    """One windowed exchange round scattered into the accumulator.

    The accumulator (donated: updated in place across rounds) holds each
    device's final shard grouped by (src peer, in-bucket arrival):
    the row from peer s with in-bucket position q lands at
    ``colbase[s] + q``. Rows outside this round's window or past a
    peer's bucket count scatter to the drop sentinel. ``r`` is TRACED,
    so ONE compiled program serves every round. On hierarchical meshes
    the round runs the staged two-stage body — identical delivery
    contract, so the scatter below is dispatch-blind.
    """

    from uda_tpu.parallel.exchange import run_round_body

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P()),
             out_specs=P(axis))
    def _go(w, d, q, acc, cb, rr):
        p = lax.psum(1, axis)
        lo = rr[0] * capacity
        flat, recv_counts = run_round_body(w, d, q, lo, capacity, axis,
                                           exchange_mode, dcn_axis,
                                           ici_axis, coded_l_rows)
        row = jnp.arange(p * capacity, dtype=jnp.int32)
        peer = row // capacity
        slot = row % capacity
        valid = slot < jnp.take(recv_counts, peer)
        idx = jnp.where(valid, jnp.take(cb[0], peer) + lo + slot,
                        acc.shape[0])
        return acc.at[idx].set(flat, mode="drop")

    return _go(words, dest, pos, acc, colbase, r[None])


@partial(jax.jit, static_argnames=("mesh", "axis", "num_keys",
                                   "payload_path", "interpret"))
def _sort_shard(acc, nvalid, mesh, axis, num_keys, payload_path,
                interpret=False):
    """Local stable sort of the accumulated shard. The accumulator is
    already in (src peer, arrival) order, so a stable sort by (keys,
    valid flag) reproduces exactly the fused single-round program's
    equal-key order."""

    # same interpret-mode-only checker gate as _sort_step
    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P(axis)),
             out_specs=P(axis),
             check_vma=_vma_check_on(payload_path, interpret))
    def _go(a, nv):
        row = jnp.arange(a.shape[0], dtype=jnp.int32)
        return _sort_valid_rows(a, row < nv[0], num_keys, payload_path,
                                interpret)

    return _go(acc, nvalid)


def distributed_sort_multiround(words, splitters, mesh: Mesh, axis: str,
                                capacity: int, num_keys: int,
                                payload_path: str = "auto",
                                exchange_mode: str = "auto"
                                ) -> DistributedSortResult:
    """Skew-proof distributed sort: windowed multi-round exchange
    scattered into a shard-sized accumulator, then one local sort.

    The round schedule comes from the gathered count matrix (one host
    readback per shuffle, planned by parallel/planner.py — globally-
    empty windows are skipped and the per-axis ICI/DCN accounting is
    recorded per executed round), so every (src, dst) bucket — however
    skewed — drains completely: the TPU-native equivalent of the
    reference's credit backlog (reference src/DataNet/RDMAComm.cc:
    707-752, drained in RDMAClient.cc:64-92). Peak memory per device is
    O(largest destination shard + P x capacity): each round's delivery
    is compacted into the accumulator immediately (donated buffer), so
    nothing scales with the round count.
    """
    from uda_tpu.parallel.exchange import (execute_planned_window,
                                           prepare_layout)
    from uda_tpu.parallel.planner import (plan_layout_rounds,
                                          record_plan_skips)

    payload_path = _resolve_payload_path(payload_path, int(words.shape[1]),
                                         num_keys, int(words.shape[0]))
    p = int(np.prod(list(mesh.shape.values())))
    spec = NamedSharding(mesh, P(axis))
    words = put_rows(words, mesh, axis)
    splitters_dev = put_global(np.asarray(splitters, dtype=np.uint32),
                               NamedSharding(mesh, P()))

    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P()),
             out_specs=P(axis))
    def _dests(w, spl):
        return jnp.searchsorted(spl[0], w[:, 0],
                                side="right").astype(jnp.int32)

    dest = _dests(words, splitters_dev[None, :])
    layout = prepare_layout(words, dest, mesh, axis, exchange_mode)
    counts = layout.counts                      # [src, dst]
    plan = plan_layout_rounds(layout, capacity)
    # destination-side layout: shard sized to the largest destination,
    # rows grouped by (src, in-bucket arrival)
    colbase = np.zeros((p, p), np.int32)        # [dst, src] exclusive cumsum
    colbase[:, 1:] = np.cumsum(counts.T[:, :-1], axis=1)
    per_dst = counts.sum(axis=0).astype(np.int64)
    shard_rows = max(int(per_dst.max()), 1)
    acc = zeros_global((p * shard_rows, int(words.shape[1])), np.uint32,
                       spec)
    colbase_dev = put_global(colbase, spec)
    dispatch = layout.dispatch()
    for win in plan.windows:
        # the shared coded-window dispatch (decode-failure rung +
        # in-round fallback + coded-vs-plain ledger; the exchange.
        # decode failpoint fires BEFORE the scatter runs, so the
        # fallback re-dispatches the untouched donated accumulator)
        acc = execute_planned_window(
            win, plan,
            lambda: _round_scatter(
                layout.words, layout.dest, layout.pos, acc,
                colbase_dev, jnp.int32(win.index), mesh, axis,
                capacity, **dict(dispatch, exchange_mode="coded",
                                 coded_l_rows=plan.coded_l_rows)),
            lambda: _round_scatter(layout.words, layout.dest,
                                   layout.pos, acc, colbase_dev,
                                   jnp.int32(win.index), mesh, axis,
                                   capacity, **dispatch))
    record_plan_skips(plan)
    nvalid = put_global(per_dst.astype(np.int32), spec)
    out = _sort_shard(acc, nvalid, mesh, axis, num_keys, payload_path,
                      interpret=_lanes_interpret(payload_path, mesh))
    overflow = put_global(np.zeros(p, np.int32), spec)
    return DistributedSortResult(out, nvalid, overflow,
                                 overflow_total=np.int32(0))
