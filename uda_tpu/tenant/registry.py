"""TenantRegistry: the job/epoch registry of the multi-tenant daemon.

One long-lived supplier process serves MANY jobs (ROADMAP item 1, the
Exoshuffle service thesis): every job announces itself with an
authenticated ``MSG_JOB`` frame carrying ``(tenant, job, epoch)`` and
every subsequent REQ on the data plane is validated against this
registry. The lifecycle:

- **register** — first registration creates the record; re-registering
  the SAME epoch is a heartbeat; a HIGHER epoch supersedes (fences) the
  old one — a restarted job attempt registers epoch+1 and the
  predecessor's connections start drawing typed :class:`TenantError`
  on their next REQ, so a zombie reducer can never read bytes meant
  for its successor; a LOWER epoch is refused outright (stale).
- **heartbeat** — refreshes the idle clock (``uda.tpu.tenant.ttl.s``;
  0 = jobs never expire). Any validated REQ counts as one.
- **retire** — the job is done: later REQs draw typed errors, the
  retire callbacks fire (the DataEngine drains the tenant's
  ResourceLedger books there, attributing any leaked admission bytes
  to the job that leaked them), and the record is kept as a tombstone
  until the TTL sweep collects it.

Authentication: when ``uda.tpu.tenant.secret`` is set, MSG_JOB must
carry ``sign_job(secret, tenant, job, epoch)`` — an HMAC-SHA256 over
the identity triple, compared constant-time. An empty secret disables
the check (the trusted-fabric default, matching the reference's
unauthenticated rdma_cm plane).

Thread-safety: every method is safe from any thread (one registry
serves the event loop, the engine's pool workers and the MSG_STATS
dispatcher); the lock is a leaf.
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac
import time
from typing import Callable, Dict, List, Optional, Tuple

from uda_tpu.utils.errors import TenantError
from uda_tpu.utils.failpoints import failpoint
from uda_tpu.utils.locks import TrackedLock
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["DEFAULT_TENANT", "TenantRecord", "TenantRegistry", "sign_job"]

log = get_logger()

# The implicit tenant of connections that never sent MSG_JOB (old
# clients; the HELLO capability bit is advertisement, not demand) and
# of every request when tenancy is off. Weight 1, full budget — the
# single-job behavior of PRs 1-13, bit for bit.
DEFAULT_TENANT = "default"


def sign_job(secret: str, tenant_id: str, job_id: str, epoch: int) -> str:
    """The MSG_JOB authentication token: HMAC-SHA256 over the identity
    triple. Empty secret -> empty token (auth off)."""
    if not secret:
        return ""
    msg = f"{tenant_id}|{job_id}|{epoch}".encode("utf-8")
    return hmac.new(secret.encode("utf-8"), msg,
                    hashlib.sha256).hexdigest()


@dataclasses.dataclass
class TenantRecord:
    """One (tenant, job)'s registry entry."""

    tenant_id: str
    job_id: str
    epoch: int
    weight: int = 1
    state: str = "active"        # "active" | "retired"
    registered_mono: float = 0.0
    last_seen_mono: float = 0.0

    @property
    def active(self) -> bool:
        return self.state == "active"


class TenantRegistry:
    """The registry. ``secret``/``ttl_s``/``max_jobs`` may come from a
    Config (``from_config``) or be passed directly (tests, embedders)."""

    def __init__(self, secret: str = "", ttl_s: float = 0.0,
                 max_jobs: int = 4096):
        self.secret = str(secret or "")
        self.ttl_s = float(ttl_s)
        self.max_jobs = int(max_jobs)
        self._lock = TrackedLock("tenant.registry")
        self._jobs: Dict[Tuple[str, str], TenantRecord] = {}
        # tenant -> weight, maintained INCREMENTALLY (set on register,
        # recomputed-or-dropped for the affected tenant on retire and
        # TTL expiry): the scheduler's weight_of view AND the admission
        # gate's share table — share_bytes runs per served chunk, so it
        # must be O(active tenants), never a walk of the job table
        self._weights: Dict[str, int] = {}
        self._retire_cbs: List[Callable[[str, str], None]] = []

    @classmethod
    def from_config(cls, cfg) -> "TenantRegistry":
        return cls(secret=str(cfg.get("uda.tpu.tenant.secret")),
                   ttl_s=float(cfg.get("uda.tpu.tenant.ttl.s")))

    # -- lifecycle -----------------------------------------------------------

    def _check_token(self, tenant_id: str, job_id: str, epoch: int,
                     token: str) -> None:
        if not self.secret:
            return
        want = sign_job(self.secret, tenant_id, job_id, epoch)
        if not hmac.compare_digest(want, token or ""):
            metrics.add("tenant.rejected", cause="auth")
            raise TenantError(
                f"MSG_JOB authentication failed for tenant "
                f"{tenant_id!r} job {job_id!r}")

    def register(self, tenant_id: str, job_id: str, epoch: int,
                 weight: int = 1, token: str = "") -> TenantRecord:
        """Register (or heartbeat, or fence) one (tenant, job, epoch).
        Raises :class:`TenantError` on auth failure or a stale epoch."""
        tenant_id = str(tenant_id or DEFAULT_TENANT)
        epoch = int(epoch)
        if epoch < 1:
            raise TenantError(f"job epoch must be >= 1, got {epoch}")
        self._check_token(tenant_id, job_id, epoch, token)
        failpoint("tenant.register", key=tenant_id)
        now = time.monotonic()
        with self._lock:
            self._expire_locked(now)
            key = (tenant_id, job_id)
            rec = self._jobs.get(key)
            if rec is not None:
                if epoch < rec.epoch:
                    metrics.add("tenant.rejected", cause="stale_epoch")
                    raise TenantError(
                        f"stale epoch {epoch} for {tenant_id}/{job_id} "
                        f"(current {rec.epoch}); a predecessor attempt "
                        f"cannot re-register under its successor")
                if epoch == rec.epoch:
                    if not rec.active:
                        metrics.add("tenant.rejected", cause="retired")
                        raise TenantError(
                            f"{tenant_id}/{job_id} epoch {epoch} is "
                            f"retired; a finished job cannot resume — "
                            f"restart with a higher epoch")
                    rec.last_seen_mono = now
                    rec.weight = max(1, int(weight))
                    self._weights[tenant_id] = rec.weight
                    metrics.add("tenant.heartbeats")
                    return rec
                # epoch > rec.epoch: fence the predecessor
                metrics.add("tenant.epoch.fenced")
                log.warn(f"tenant {tenant_id}/{job_id}: epoch "
                         f"{rec.epoch} fenced by {epoch}")
            elif len(self._jobs) >= self.max_jobs:
                metrics.add("tenant.rejected", cause="capacity")
                raise TenantError(
                    f"tenant registry full ({self.max_jobs} jobs); "
                    f"retire finished jobs or raise the cap")
            rec = TenantRecord(tenant_id, job_id, epoch,
                               weight=max(1, int(weight)),
                               registered_mono=now, last_seen_mono=now)
            self._jobs[key] = rec
            self._weights[tenant_id] = rec.weight
            active = sum(1 for r in self._jobs.values() if r.active)
        metrics.add("tenant.registered", tenant=tenant_id)
        metrics.gauge("tenant.jobs.active", active)
        log.info(f"tenant {tenant_id}: job {job_id} registered at "
                 f"epoch {epoch} (weight {rec.weight})")
        return rec

    def heartbeat(self, tenant_id: str, job_id: str) -> None:
        with self._lock:
            rec = self._jobs.get((str(tenant_id or DEFAULT_TENANT),
                                  job_id))
            if rec is not None and rec.active:
                rec.last_seen_mono = time.monotonic()
        metrics.add("tenant.heartbeats")

    def _reweigh_locked(self, tenant_id: str) -> None:
        """Recompute one tenant's weight from its remaining ACTIVE
        jobs (max wins — deterministic across dict order); a tenant
        with none leaves the active-weight table entirely, so it stops
        diluting the neighbors' budget shares."""
        ws = [r.weight for (tid, _), r in self._jobs.items()
              if tid == tenant_id and r.active]
        if ws:
            self._weights[tenant_id] = max(ws)
        else:
            self._weights.pop(tenant_id, None)

    def retire(self, tenant_id: str, job_id: str, epoch: int,
               token: str = "") -> None:
        """Retire one job (idempotent; a stale-epoch retire is ignored —
        the successor attempt owns the record now). Fires the retire
        callbacks OUTSIDE the lock."""
        tenant_id = str(tenant_id or DEFAULT_TENANT)
        self._check_token(tenant_id, job_id, int(epoch), token)
        fired = False
        with self._lock:
            rec = self._jobs.get((tenant_id, job_id))
            if rec is not None and rec.active and int(epoch) >= rec.epoch:
                rec.state = "retired"
                rec.last_seen_mono = time.monotonic()
                self._reweigh_locked(tenant_id)
                fired = True
            active = sum(1 for r in self._jobs.values() if r.active)
        if fired:
            metrics.add("tenant.retired", tenant=tenant_id)
            metrics.gauge("tenant.jobs.active", active)
            log.info(f"tenant {tenant_id}: job {job_id} retired")
            for cb in list(self._retire_cbs):
                try:
                    cb(tenant_id, job_id)
                except Exception as e:  # noqa: BLE001 - one consumer's
                    # retire hook must not block another's (or the
                    # data plane); counted, never silent
                    metrics.add("errors.swallowed")
                    log.warn(f"tenant retire callback failed: {e}")

    def on_retire(self, cb: Callable[[str, str], None]) -> None:
        """Register a retire hook (the DataEngine drains the tenant's
        obligation books there)."""
        self._retire_cbs.append(cb)

    # -- the per-REQ gate ----------------------------------------------------

    def validate(self, tenant_id: str, job_id: str,
                 epoch: Optional[int] = None) -> TenantRecord:
        """THE data-plane gate: every REQ on a tenant-bound connection
        flows through here. Raises typed :class:`TenantError` for an
        unknown job, a retired job, or a stale epoch (the connection
        bound before a successor fenced it). A validated REQ is a
        heartbeat."""
        tenant_id = str(tenant_id or DEFAULT_TENANT)
        failpoint("tenant.validate", key=tenant_id)
        now = time.monotonic()
        with self._lock:
            self._expire_locked(now)
            rec = self._jobs.get((tenant_id, job_id))
            if rec is None:
                metrics.add("tenant.rejected", cause="unknown")
                raise TenantError(
                    f"unknown job {tenant_id}/{job_id}: not registered "
                    f"(or expired past uda.tpu.tenant.ttl.s)")
            if not rec.active:
                metrics.add("tenant.rejected", cause="retired")
                raise TenantError(
                    f"job {tenant_id}/{job_id} is retired")
            if epoch is not None and int(epoch) != rec.epoch:
                metrics.add("tenant.rejected", cause="stale_epoch")
                raise TenantError(
                    f"stale epoch {epoch} for {tenant_id}/{job_id} "
                    f"(current {rec.epoch}): a restarted job's "
                    f"predecessor cannot read its chunks")
            rec.last_seen_mono = now
            return rec

    def _expire_locked(self, now: float) -> None:
        """TTL sweep (lock held): idle jobs expire, retired tombstones
        are collected one TTL after retirement. 0 = never."""
        if self.ttl_s <= 0:
            return
        dead = [k for k, r in self._jobs.items()
                if now - r.last_seen_mono > self.ttl_s]
        for k in dead:
            rec = self._jobs.pop(k)
            if rec.active:
                log.warn(f"tenant {rec.tenant_id}: job {rec.job_id} "
                         f"expired after {self.ttl_s:g}s idle")
                metrics.add("tenant.expired")
        # recompute only the AFFECTED tenants (a multi-job tenant must
        # keep its surviving jobs' weight, not an arbitrary one's)
        for tenant_id in {k[0] for k in dead}:
            self._reweigh_locked(tenant_id)

    # -- consumers (scheduler, engine, introspection) ------------------------

    def weight_of(self, tenant_id: str) -> int:
        with self._lock:
            return self._weights.get(tenant_id, 1)

    def share_bytes(self, tenant_id: str, total_bytes: int) -> int:
        """This tenant's slice of a shared byte budget: weight over the
        sum of ACTIVE tenants' weights. A lone (or unknown) tenant gets
        the whole budget — partitions only bind under contention, so
        the single-job deployment keeps PR 3's exact admission. Runs
        per served chunk inside the engine's admission gate, so it
        reads the incrementally-maintained active-weight table —
        O(active tenants), never a walk of the (up to max_jobs) job
        table."""
        with self._lock:
            weights = self._weights
            if len(weights) <= 1 or tenant_id not in weights:
                return int(total_bytes)
            mine = weights[tenant_id]
            return max(1, int(total_bytes) * mine // sum(weights.values()))

    def active_tenants(self) -> List[str]:
        with self._lock:
            return sorted({tid for (tid, _), r in self._jobs.items()
                           if r.active})

    def snapshot(self) -> dict:
        """The MSG_STATS introspection block."""
        now = time.monotonic()
        with self._lock:
            jobs = [{"tenant": r.tenant_id, "job": r.job_id,
                     "epoch": r.epoch, "weight": r.weight,
                     "state": r.state,
                     "idle_s": round(now - r.last_seen_mono, 3)}
                    for r in self._jobs.values()]
        jobs.sort(key=lambda j: (j["tenant"], j["job"]))
        return {"jobs": jobs, "ttl_s": self.ttl_s,
                "auth": bool(self.secret)}
