"""Per-tenant SLI/SLO accounting — the service-level ledger of the
multi-tenant daemon (PR 14's TenantRegistry + CreditScheduler).

Shuffle-as-a-service (Exoshuffle, arXiv:2203.05072) is only operable
when "is tenant B getting what it was promised" has a live, numeric
answer. The :class:`SliBook` subscribes to the
:class:`~uda_tpu.utils.timeseries.TimeSeries` rollup feed and keeps,
per tenant:

- **bytes** fetched/served (tenant-labeled counter deltas — PR 17 put
  tenant labels on every fetch/serve site, so no joins are needed);
- **latency percentiles** — per-interval p99 of ``fetch.latency_ms``
  and ``supplier.read.latency_ms`` tenant series, and the parked
  **queue-wait** p99 (``tenant.queue.wait_ms``, observed by the
  CreditScheduler at every unpark);
- **credit-starvation time** — seconds a tenant sat with backlog while
  receiving zero scheduled bytes (cumulative + the current streak, the
  feed of the ``starvation`` anomaly detector);
- **scheduled-vs-entitled share** — the continuous fairness audit of
  the WDRR scheduler: granted-byte share over the window vs the
  weight-proportional entitlement among tenants that had demand.

SLO targets (``uda.tpu.slo.*``) turn SLIs into per-interval compliance
bits; attainment over the rolling window and the **burn rate**
``(1 - attainment) / (1 - objective)`` (>1 = burning error budget
faster than the objective allows) are exported in every snapshot, in
StatsReporter's final ``slo`` block, over MSG_STATS (CAP_OBS) and in
the udatop/udafleet consoles.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from uda_tpu.utils.metrics import metrics

__all__ = ["SliBook", "sli_book", "series_labels"]

# the SLI names with configurable targets (the slo_block schema)
_SLI_FETCH = "fetch_p99_ms"
_SLI_SERVE = "serve_p99_ms"
_SLI_SHARE = "share"


def series_labels(key: str) -> tuple:
    """Split a metrics series key ``name{k=v,...}`` into
    ``(name, labels)`` (plain names -> empty labels)."""
    if not key.endswith("}") or "{" not in key:
        return key, {}
    name, _, inner = key.partition("{")
    labels = {}
    for kv in inner[:-1].split(","):
        if "=" in kv:
            k, _, v = kv.partition("=")
            labels[k] = v
    return name, labels


def _tenant_counter_deltas(roll: Dict, counter: str) -> Dict[str, float]:
    """Sum one rollup's labeled deltas of ``counter`` by tenant."""
    out: Dict[str, float] = {}
    for key, delta in roll["counters"].items():
        name, labels = series_labels(key)
        t = labels.get("tenant")
        if name == counter and t:
            out[t] = out.get(t, 0.0) + delta
    return out


def _tenant_p99(roll: Dict, hist: str) -> Dict[str, float]:
    """Count-weighted per-tenant p99 of one histogram family in this
    interval (a tenant fetching from several suppliers has one series
    per supplier; the weighted fold is the tenant's tail)."""
    acc: Dict[str, list] = {}
    for key, s in roll["percentiles"].items():
        name, labels = series_labels(key)
        t = labels.get("tenant")
        if name == hist and t:
            pair = acc.setdefault(t, [0.0, 0])
            pair[0] += s["p99"] * s["count"]
            pair[1] += s["count"]
    return {t: v[0] / v[1] for t, v in acc.items() if v[1]}


class _TenantSli:
    """One tenant's accumulators + rolling compliance window."""

    __slots__ = ("bytes_fetched", "bytes_served", "sched_bytes",
                 "starved_s", "starve_streak_s", "window",
                 "last_p99", "last_share", "last_entitled")

    def __init__(self, window: int):
        self.bytes_fetched = 0.0
        self.bytes_served = 0.0
        self.sched_bytes = 0.0          # lifetime scheduled (granted)
        self.starved_s = 0.0
        self.starve_streak_s = 0.0
        # per-interval records: {"dt", "sched", "demand", "entitled",
        #  "ok": {sli: bool|None}} — share/attainment read from here
        self.window: deque = deque(maxlen=max(2, window))
        self.last_p99: Dict[str, Optional[float]] = {}
        self.last_share: Optional[float] = None
        self.last_entitled: Optional[float] = None


class SliBook:
    """The per-tenant SLI/SLO ledger (module singleton
    :data:`sli_book`; private instances for tests)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.armed = False
        self.timeseries = None
        self._sched = None
        self._registry = None
        self._tenants: Dict[str, _TenantSli] = {}
        self._last_granted: Dict[str, float] = {}
        self._window = 120
        # SLO targets: 0/None = SLI tracked, no target
        self.slo_fetch_p99_ms = 0.0
        self.slo_serve_p99_ms = 0.0
        self.slo_share_frac = 0.5
        self.objective = 0.99

    # -- lifecycle -----------------------------------------------------------

    def arm_from_config(self, config, ts) -> bool:
        """Read the ``uda.tpu.slo.*`` targets and subscribe to the
        rollup feed. Idempotent."""
        with self._lock:
            self.slo_fetch_p99_ms = float(
                config.get("uda.tpu.slo.fetch.p99.ms"))
            self.slo_serve_p99_ms = float(
                config.get("uda.tpu.slo.serve.p99.ms"))
            self.slo_share_frac = float(
                config.get("uda.tpu.slo.share.frac"))
            self.objective = min(0.999999, max(
                0.0, float(config.get("uda.tpu.slo.objective"))))
            self._window = ts.window_len
            if not self.armed:
                self.timeseries = ts
                ts.add_listener(self.on_rollup)
                self.armed = True
        return True

    def attach(self, scheduler=None, registry=None) -> None:
        """The daemon's scheduler/registry hookup (ShuffleServer.start);
        share/starvation SLIs need the CreditScheduler's view."""
        with self._lock:
            self._sched = scheduler
            self._registry = registry

    def detach(self, scheduler=None) -> None:
        """Drop the hookup — only if still ours (the replaced-provider
        discipline of unregister_stats_provider)."""
        with self._lock:
            if scheduler is None or self._sched is scheduler:
                self._sched = None
                self._registry = None

    def reset(self) -> None:
        with self._lock:
            ts, self.timeseries = self.timeseries, None
            self.armed = False
            self._sched = None
            self._registry = None
            self._tenants.clear()
            self._last_granted.clear()
        if ts is not None:
            ts.remove_listener(self.on_rollup)

    def _sli(self, tenant: str) -> _TenantSli:
        s = self._tenants.get(tenant)
        if s is None:
            s = self._tenants[tenant] = _TenantSli(self._window)
        return s

    # -- the per-rollup pass -------------------------------------------------

    def on_rollup(self, roll: Dict) -> None:
        dt = roll["dt"]
        fetched = _tenant_counter_deltas(roll, "fetch.bytes")
        served = _tenant_counter_deltas(roll, "supplier.bytes")
        fetch_p99 = _tenant_p99(roll, "fetch.latency_ms")
        serve_p99 = _tenant_p99(roll, "supplier.read.latency_ms")
        wait_p99 = _tenant_p99(roll, "tenant.queue.wait_ms")
        sched = self._sched
        sched_stats = None
        if sched is not None:
            try:
                sched_stats = sched.stats()
            except RuntimeError:
                sched_stats = None  # racing a structural mutation:
                # skip the scheduler SLIs this interval
        with self._lock:
            granted_delta: Dict[str, float] = {}
            demand: Dict[str, bool] = {}
            weights: Dict[str, float] = {}
            if sched_stats is not None:
                for t, st in sched_stats["tenants"].items():
                    g = st["granted_cost"]
                    granted_delta[t] = g - self._last_granted.get(t, 0.0)
                    self._last_granted[t] = g
                    # demand this interval = scheduled work or backlog
                    demand[t] = bool(granted_delta[t] > 0
                                     or st["parked"]
                                     or st["inflight"])
                    weights[t] = max(1, int(st["weight"]))
            total_granted = sum(granted_delta.values())
            demand_weight = sum(w for t, w in weights.items()
                                if demand.get(t))
            tenants = (set(fetched) | set(served) | set(fetch_p99)
                       | set(serve_p99) | set(granted_delta))
            for t in tenants:
                s = self._sli(t)
                s.bytes_fetched += fetched.get(t, 0.0)
                s.bytes_served += served.get(t, 0.0)
                s.sched_bytes += granted_delta.get(t, 0.0)
                share = entitled = None
                if t in granted_delta and demand.get(t):
                    if total_granted > 0:
                        share = granted_delta[t] / total_granted
                    if demand_weight > 0:
                        entitled = weights[t] / demand_weight
                    starving = (granted_delta[t] <= 0
                                and sched_stats["tenants"][t]["parked"])
                    if starving:
                        s.starved_s += dt
                        s.starve_streak_s += dt
                    else:
                        s.starve_streak_s = 0.0
                s.last_p99 = {"fetch": fetch_p99.get(t),
                              "serve": serve_p99.get(t),
                              "wait": wait_p99.get(t)}
                if share is not None:
                    s.last_share = share
                    s.last_entitled = entitled
                ok: Dict[str, Optional[bool]] = {}
                ok[_SLI_FETCH] = (
                    fetch_p99[t] <= self.slo_fetch_p99_ms
                    if self.slo_fetch_p99_ms and t in fetch_p99 else None)
                ok[_SLI_SERVE] = (
                    serve_p99[t] <= self.slo_serve_p99_ms
                    if self.slo_serve_p99_ms and t in serve_p99 else None)
                ok[_SLI_SHARE] = (
                    share >= self.slo_share_frac * entitled
                    if share is not None and entitled else None)
                for sli, good in ok.items():
                    if good is False:
                        metrics.add("sli.slo.breach", tenant=t, sli=sli)
                s.window.append({"dt": dt,
                                 "sched": granted_delta.get(t, 0.0),
                                 "demand": bool(demand.get(t)),
                                 "ok": ok})

    # -- the anomaly feed ----------------------------------------------------

    def starving_tenants(self, min_s: float) -> Dict[str, float]:
        """Tenants whose CURRENT starvation streak (backlog, zero
        scheduled bytes) is at least ``min_s`` seconds long."""
        with self._lock:
            return {t: s.starve_streak_s
                    for t, s in self._tenants.items()
                    if s.starve_streak_s >= min_s}

    # -- export --------------------------------------------------------------

    @staticmethod
    def _attainment(s: _TenantSli, sli: str) -> Optional[float]:
        judged = [rec["ok"][sli] for rec in s.window
                  if rec["ok"].get(sli) is not None]
        if not judged:
            return None
        return sum(1 for ok in judged if ok) / len(judged)

    def _burn(self, attainment: Optional[float]) -> Optional[float]:
        if attainment is None:
            return None
        return round((1.0 - attainment) / (1.0 - self.objective), 3)

    def _tenant_block(self, t: str, s: _TenantSli) -> Dict:
        wsched = sum(rec["sched"] for rec in s.window)
        wtotal = 0.0
        for other in self._tenants.values():
            wtotal += sum(rec["sched"] for rec in other.window)
        slo = {}
        for sli, target in ((_SLI_FETCH, self.slo_fetch_p99_ms),
                            (_SLI_SERVE, self.slo_serve_p99_ms),
                            (_SLI_SHARE, self.slo_share_frac)):
            att = self._attainment(s, sli)
            slo[sli] = {"target": target, "attainment":
                        round(att, 4) if att is not None else None,
                        "burn": self._burn(att)}
        return {
            "bytes_fetched": s.bytes_fetched,
            "bytes_served": s.bytes_served,
            "sched_bytes": s.sched_bytes,
            "window_share": round(wsched / wtotal, 4) if wtotal else None,
            "share": s.last_share, "entitled": s.last_entitled,
            "starved_s": round(s.starved_s, 3),
            "starve_streak_s": round(s.starve_streak_s, 3),
            "p99_ms": {k: (round(v, 3) if v is not None else None)
                       for k, v in s.last_p99.items()},
            "slo": slo,
        }

    def snapshot(self) -> Dict:
        """The provider / MSG_STATS ``sli`` block: every tenant's SLIs
        + the SLO configuration they are judged against."""
        with self._lock:
            return {
                "armed": self.armed,
                "objective": self.objective,
                "targets": {_SLI_FETCH: self.slo_fetch_p99_ms,
                            _SLI_SERVE: self.slo_serve_p99_ms,
                            _SLI_SHARE: self.slo_share_frac},
                "tenants": {t: self._tenant_block(t, s)
                            for t, s in sorted(self._tenants.items())},
            }

    def slo_block(self) -> Optional[Dict]:
        """The final-record attainment summary (None when the book
        never saw a tenant — the block is additive)."""
        with self._lock:
            if not self._tenants:
                return None
            worst: Optional[float] = None
            out: Dict = {"objective": self.objective, "tenants": {}}
            for t, s in sorted(self._tenants.items()):
                slos = {}
                for sli, target in (
                        (_SLI_FETCH, self.slo_fetch_p99_ms),
                        (_SLI_SERVE, self.slo_serve_p99_ms),
                        (_SLI_SHARE, self.slo_share_frac)):
                    att = self._attainment(s, sli)
                    if att is None:
                        continue
                    slos[sli] = {"target": target,
                                 "attainment": round(att, 4),
                                 "burn": self._burn(att)}
                    worst = att if worst is None else min(worst, att)
                out["tenants"][t] = slos
            out["worst_attainment"] = (round(worst, 4)
                                       if worst is not None else None)
            return out


sli_book = SliBook()
