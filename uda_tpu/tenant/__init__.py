"""Multi-tenant service plane: one long-lived shuffle daemon, many jobs.

The Exoshuffle thesis (arXiv:2203.05072) applied to this engine:
shuffle as a SHARED service rather than a per-job plugin. The pieces:

- :class:`~uda_tpu.tenant.registry.TenantRegistry` — the job/epoch
  registry with register/heartbeat/retire lifecycle, epoch fencing and
  HMAC-authenticated wire registration (``MSG_JOB``);
- :class:`~uda_tpu.tenant.sched.CreditScheduler` — weighted deficit
  round-robin over parked requests, replacing the single global
  ``mapred.rdma.wqe.per.conn`` cap with per-tenant weighted-fair
  credit flow (plus the tenant penalty box: an abusive tenant is
  deprioritized, never starved);
- per-tenant read-budget partitions in ``DataEngine`` admission and
  per-tenant ``MemoryBudget`` shares on the reduce side
  (``uda.tpu.tenant.budget.share``).

``current_tenant()`` is the process-local tenant identity the reduce
side stamps onto its hot-path metric labels (set once at bridge INIT
from ``uda.tpu.tenant.id``; a module-global read so the per-chunk cost
is one attribute load).
"""

from __future__ import annotations

from uda_tpu.tenant.registry import (DEFAULT_TENANT, TenantRecord,
                                     TenantRegistry, sign_job)
from uda_tpu.tenant.sched import CreditScheduler

__all__ = ["TenantRegistry", "TenantRecord", "CreditScheduler",
           "DEFAULT_TENANT", "sign_job", "current_tenant",
           "set_current_tenant"]

_CURRENT_TENANT = ""


def set_current_tenant(tenant: str) -> None:
    """Install this process's tenant identity (bridge INIT; empty =
    untenanted, labels stay off the hot paths)."""
    global _CURRENT_TENANT
    _CURRENT_TENANT = str(tenant or "")


def current_tenant() -> str:
    return _CURRENT_TENANT
