"""CreditScheduler: weighted-fair credit flow across tenants.

PR 4's single ``mapred.rdma.wqe.per.conn`` cap bounded the pipeline per
CONNECTION — with many jobs on one daemon that is no bound at all: one
tenant opening N connections (or bursting on one) takes N x credit of
the shared engine while a neighbor drains at a trickle. This scheduler
is the shared bound: a pool of ``uda.tpu.tenant.wqe.total`` credits
over ALL connections, granted by weighted deficit round-robin (DRR,
Shreedhar & Varghese) over the per-tenant parked queues:

- a request that cannot take a credit parks in ITS tenant's FIFO (the
  server pauses that connection's read interest — TCP backpressure is
  still the credit return, now per tenant);
- every settled response releases one credit and runs the grant sweep:
  each non-empty tenant queue is visited in ring order, its deficit
  grows by ``quantum x weight``, and it unparks one request per whole
  deficit unit — so over any busy interval tenant grants converge to
  the weight ratio regardless of arrival order or connection count;
- deficits are capped at one round's earning and reset when a queue
  empties (the classic DRR anti-burst rule), so the deficit of any
  tenant is bounded by ``quantum x weight`` — the fairness invariant
  ``tests/test_tenant.py`` pins.

The **tenant penalty box** (the PenaltyBox idea, tenant-scoped): an
abusive tenant — repeated admission rejections, injected faults on its
requests — is *deprioritized*: while boxed, its queue is only visited
when no unboxed tenant has backlog. Never starved: with no competing
backlog a boxed tenant is served normally, so the box degrades exactly
one tenant and only under contention (the isolation contract).

Threading: loop-thread-confined BY DESIGN (the event-loop server owns
every parked request); no locks. ``penalize`` may be called from
completion threads via ``EventLoop.call_soon``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["CreditScheduler"]

log = get_logger()


class _TenantQ:
    __slots__ = ("queue", "deficit", "faults", "boxed_until")

    def __init__(self) -> None:
        self.queue: deque = deque()   # (conn, entry) waiting for credit
        self.deficit = 0.0
        self.faults = 0
        self.boxed_until = 0.0


class CreditScheduler:
    """``total`` credits shared across tenants; ``weight_of(tenant)``
    supplies the live weights (the registry's view, consulted at each
    sweep so a re-registration's new weight applies immediately)."""

    def __init__(self, total: int,
                 weight_of: Optional[Callable[[str], int]] = None,
                 quantum: float = 1.0,
                 penalty_threshold: int = 4, penalty_ms: int = 1000):
        self.total = max(1, int(total))
        self._free = self.total
        self._weight_of = weight_of or (lambda t: 1)
        self.quantum = float(quantum)
        self.penalty_threshold = max(1, int(penalty_threshold))
        self.penalty_s = max(0, int(penalty_ms)) / 1e3
        self._tenants: Dict[str, _TenantQ] = {}
        self._ring: List[str] = []    # visit order (insertion)
        self._ring_pos = 0
        # a turn interrupted by credit exhaustion RESUMES at the same
        # tenant with its leftover deficit (and without re-earning):
        # without this, single-credit settles would degrade weighted
        # DRR to plain round-robin — every sweep would start a fresh
        # turn at the next ring position
        self._turn_earned = False
        self._inflight: Dict[str, int] = {}
        self.grants = 0               # lifetime grants (tests/invariants)

    # -- queries -------------------------------------------------------------

    @property
    def free(self) -> int:
        return self._free

    def backlog(self, tenant: Optional[str] = None) -> int:
        if tenant is not None:
            tq = self._tenants.get(tenant)
            return len(tq.queue) if tq else 0
        return sum(len(tq.queue) for tq in self._tenants.values())

    def inflight(self, tenant: str) -> int:
        return self._inflight.get(tenant, 0)

    def _tq(self, tenant: str) -> _TenantQ:
        tq = self._tenants.get(tenant)
        if tq is None:
            tq = self._tenants[tenant] = _TenantQ()
            self._ring.append(tenant)
        return tq

    def _boxed(self, tq: _TenantQ, now: float) -> bool:
        return tq.boxed_until > now

    # -- credit flow ---------------------------------------------------------

    def admit(self, tenant: str, item: Tuple) -> bool:
        """Take a credit NOW (True) or park ``item`` in the tenant's
        queue (False). A tenant with backlog — or in the penalty box
        while others compete — always parks behind its queue, so a
        burst cannot overtake its own earlier requests or jump a
        neighbor's earned deficit."""
        tq = self._tq(tenant)
        now = time.monotonic()
        if (self._free > 0 and not tq.queue
                and not (self._boxed(tq, now) and self._other_backlog(
                    tenant, now))):
            self._grant(tenant)
            return True
        tq.queue.append(item)
        metrics.add("tenant.sched.parked")
        return False

    def _other_backlog(self, tenant: str, now: float) -> bool:
        for t, tq in self._tenants.items():
            if t != tenant and tq.queue and not self._boxed(tq, now):
                return True
        return False

    def _grant(self, tenant: str) -> None:
        self._free -= 1
        self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
        self.grants += 1
        metrics.add("tenant.sched.grants", tenant=tenant)

    def release(self, tenant: str) -> None:
        """One response settled: its credit returns to the pool. The
        caller follows with :meth:`grant_parked`."""
        self._free = min(self.total, self._free + 1)
        left = self._inflight.get(tenant, 0) - 1
        if left > 0:
            self._inflight[tenant] = left
        else:
            self._inflight.pop(tenant, None)

    def grant_parked(self) -> List[Tuple]:
        """The DRR sweep: unpark up to ``free`` items across tenants by
        weighted deficit round-robin. Returns the granted (conn, entry)
        items — each HOLDS one credit; the caller starts them (and
        releases via :meth:`release` when they settle or drop)."""
        granted: List[Tuple] = []
        ring = self._ring
        n = len(ring)
        if n == 0 or self._free <= 0:
            return granted
        now = time.monotonic()
        # visit budget: every full ring pass with eligible backlog
        # serves at least one item (an unboxed non-empty queue earns
        # >= one quantum), so the loop is bounded by grants + ring
        # passes, never by backlog depth
        visits = n * (self.total + 2)
        while self._free > 0 and visits > 0:
            unboxed_backlog = any(
                tq.queue and not self._boxed(tq, now)
                for tq in self._tenants.values())
            if not unboxed_backlog and not any(
                    tq.queue for tq in self._tenants.values()):
                break
            tenant = ring[self._ring_pos % n]
            tq = self._tenants[tenant]
            if not tq.queue or (self._boxed(tq, now)
                                and unboxed_backlog):
                if not tq.queue:
                    tq.deficit = 0.0  # DRR: an empty queue forfeits
                    # banked credit (anti-burst)
                self._advance()
                visits -= 1
                continue
            if not self._turn_earned:
                weight = max(1, int(self._weight_of(tenant)))
                earn = self.quantum * weight
                tq.deficit = min(tq.deficit + earn, earn)
                self._turn_earned = True
            while tq.queue and tq.deficit >= self.quantum \
                    and self._free > 0:
                tq.deficit -= self.quantum
                item = tq.queue.popleft()
                self._grant(tenant)
                granted.append(item)
            if tq.queue and tq.deficit >= self.quantum:
                break  # credits ran out mid-turn: the NEXT sweep
                # resumes this tenant's turn with its leftover deficit
            if not tq.queue:
                tq.deficit = 0.0
            self._advance()
            visits -= 1
        metrics.gauge("tenant.sched.backlog", self.backlog())
        return granted

    def _advance(self) -> None:
        self._ring_pos = (self._ring_pos + 1) % max(1, len(self._ring))
        self._turn_earned = False

    def drop_conn(self, conn) -> int:
        """A connection died: its parked (unstarted, creditless) items
        leave the queues. Returns how many were dropped."""
        dropped = 0
        for tq in self._tenants.values():
            keep = deque(it for it in tq.queue if it[0] is not conn)
            dropped += len(tq.queue) - len(keep)
            tq.queue = keep
        return dropped

    # -- the tenant penalty box ----------------------------------------------

    def note_fault(self, tenant: str) -> None:
        """One abusive event (admission rejection, injected fault on
        this tenant's request): past the threshold the tenant enters
        the box for ``penalty_ms`` (extended while faults continue;
        a clean grant sweep is the implicit forgiveness — the box
        simply expires)."""
        tq = self._tq(tenant)
        tq.faults += 1
        if tq.faults >= self.penalty_threshold:
            now = time.monotonic()
            first = tq.boxed_until <= now
            tq.boxed_until = now + self.penalty_s
            tq.faults = 0
            if first:
                metrics.add("tenant.penalties", tenant=tenant)
                log.warn(f"tenant {tenant!r} penalty-boxed for "
                         f"{self.penalty_s:g}s (repeated faults); its "
                         f"parked requests yield to other tenants")

    def boxed(self, tenant: str) -> bool:
        tq = self._tenants.get(tenant)
        return bool(tq and self._boxed(tq, time.monotonic()))

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        now = time.monotonic()
        return {
            "total": self.total, "free": self._free,
            "grants": self.grants,
            "tenants": {
                t: {"parked": len(tq.queue),
                    "inflight": self._inflight.get(t, 0),
                    "deficit": round(tq.deficit, 3),
                    "weight": max(1, int(self._weight_of(t))),
                    "boxed": self._boxed(tq, now)}
                for t, tq in sorted(self._tenants.items())},
        }
