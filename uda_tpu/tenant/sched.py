"""CreditScheduler: weighted-fair credit flow across tenants.

PR 4's single ``mapred.rdma.wqe.per.conn`` cap bounded the pipeline per
CONNECTION — with many jobs on one daemon that is no bound at all: one
tenant opening N connections (or bursting on one) takes N x credit of
the shared engine while a neighbor drains at a trickle. This scheduler
is the shared bound: a pool of ``uda.tpu.tenant.wqe.total`` credits
over ALL connections, granted by weighted deficit round-robin (DRR,
Shreedhar & Varghese) over the per-tenant parked queues:

- a request that cannot take a credit parks in ITS tenant's FIFO (the
  server pauses that connection's read interest — TCP backpressure is
  still the credit return, now per tenant);
- every settled response releases one credit and runs the grant sweep:
  each non-empty tenant queue is visited in ring order, its deficit
  grows by ``quantum x weight``, and it unparks requests while the
  deficit covers their COST — so over any busy interval tenant grants
  converge to the weight ratio regardless of arrival order or
  connection count;
- a BACKLOGGED queue accumulates deficit uncapped (classic DRR: over
  any busy interval deficit tracks earned-minus-served, which is what
  keeps grants weight-proportional even when head costs dwarf one
  turn's earning); banked POSITIVE credit is forfeited when the queue
  empties (the anti-burst rule; negative deficit — byte DEBT from a
  force-served oversized head — survives the reset, or serial big
  requests would never repay) — the fairness invariants
  ``tests/test_tenant.py`` pins.

**Byte-cost quanta** (ROADMAP item 1 follow-up): cost is the unit the
deficit is earned and charged in. The server passes each request's
REQUESTED BYTES (``ShuffleRequest.chunk_size``) as its cost and sets
``quantum`` from ``uda.tpu.tenant.quantum.kb``, so mixed chunk sizes
stay byte-fair: a tenant fetching 1 MB chunks draws weight-
proportional BYTES, not weight-proportional request counts. Callers
that pass no cost get the request-count behavior unchanged (cost 1,
quantum 1). Classic DRR assumes quantum >= the largest packet; a head
request dearer than one turn's earning instead ACCUMULATES deficit
across sweeps (uncapped while backlogged — see above), and a sweep
that would otherwise return empty-handed with free credits and
eligible backlog force-serves the most-indebted head (largest
earned-minus-served, i.e. the weighted-fair pick; its deficit goes
negative — the byte debt is repaid before its next grant), so an
oversized request can delay but never deadlock the pool.

The **tenant penalty box** (the PenaltyBox idea, tenant-scoped): an
abusive tenant — repeated admission rejections, injected faults on its
requests — is *deprioritized*: while boxed, its queue is only visited
when no unboxed tenant has backlog. Never starved: with no competing
backlog a boxed tenant is served normally, so the box degrades exactly
one tenant and only under contention (the isolation contract).

Threading: loop-thread-confined BY DESIGN (the event-loop server owns
every parked request); no locks. ``penalize`` may be called from
completion threads via ``EventLoop.call_soon``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from uda_tpu.utils.locks import race_instrument
from uda_tpu.utils.logging import get_logger
from uda_tpu.utils.metrics import metrics

__all__ = ["CreditScheduler"]

log = get_logger()


class _TenantQ:
    __slots__ = ("queue", "deficit", "faults", "boxed_until",
                 "vfinish")

    def __init__(self) -> None:
        self.queue: deque = deque()   # ((conn, entry), cost) waiting
        self.deficit = 0.0
        self.faults = 0
        self.boxed_until = 0.0
        self.vfinish = 0.0            # SFQ virtual finish of the last
        # grant (cost/weight units) — the force-serve pick's clock


@race_instrument("_tenants")
class CreditScheduler:
    """``total`` credits shared across tenants; ``weight_of(tenant)``
    supplies the live weights (the registry's view, consulted at each
    sweep so a re-registration's new weight applies immediately)."""

    def __init__(self, total: int,
                 weight_of: Optional[Callable[[str], int]] = None,
                 quantum: float = 1.0,
                 penalty_threshold: int = 4, penalty_ms: int = 1000):
        self.total = max(1, int(total))
        self._free = self.total
        self._weight_of = weight_of or (lambda t: 1)
        self.quantum = float(quantum)
        self.penalty_threshold = max(1, int(penalty_threshold))
        self.penalty_s = max(0, int(penalty_ms)) / 1e3
        self._tenants: Dict[str, _TenantQ] = {}
        self._ring: List[str] = []    # visit order (insertion)
        self._ring_pos = 0
        # a turn interrupted by credit exhaustion RESUMES at the same
        # tenant with its leftover deficit (and without re-earning):
        # without this, single-credit settles would degrade weighted
        # DRR to plain round-robin — every sweep would start a fresh
        # turn at the next ring position
        self._turn_earned = False
        self._inflight: Dict[str, int] = {}
        self._vtime = 0.0             # SFQ system virtual time
        self.grants = 0               # lifetime grants (tests/invariants)
        self.granted_cost: Dict[str, int] = {}  # lifetime granted cost
        # per tenant (bytes under byte quanta) — the byte-fairness
        # record the WDRR invariant tests read

    # -- queries -------------------------------------------------------------

    @property
    def free(self) -> int:
        return self._free

    def backlog(self, tenant: Optional[str] = None) -> int:
        if tenant is not None:
            tq = self._tenants.get(tenant)
            return len(tq.queue) if tq else 0
        return sum(len(tq.queue) for tq in self._tenants.values())

    def inflight(self, tenant: str) -> int:
        return self._inflight.get(tenant, 0)

    def _tq(self, tenant: str) -> _TenantQ:
        tq = self._tenants.get(tenant)
        if tq is None:
            tq = self._tenants[tenant] = _TenantQ()
            self._ring.append(tenant)
        return tq

    def _boxed(self, tq: _TenantQ, now: float) -> bool:
        return tq.boxed_until > now

    # -- credit flow ---------------------------------------------------------

    def admit(self, tenant: str, item: Tuple, cost: int = 1) -> bool:
        """Take a credit NOW (True) or park ``item`` in the tenant's
        queue (False). ``cost`` is the deficit charge of serving this
        item (requested bytes under byte quanta; 1 = request-count
        mode). A tenant with backlog — or in the penalty box while
        others compete — always parks behind its queue, so a burst
        cannot overtake its own earlier requests or jump a neighbor's
        earned deficit."""
        tq = self._tq(tenant)
        now = time.monotonic()
        if (self._free > 0 and not tq.queue
                and not (self._boxed(tq, now) and self._other_backlog(
                    tenant, now))):
            if tq.deficit < 0:
                # a debtor's uncontended inline draw stays granted
                # (work conservation: an idle credit serves nobody by
                # waiting, and denying here could strand the park with
                # no settle to sweep it) but DEEPENS the recorded
                # debt — repayment binds at the next contention, when
                # DRR earning must cover it before in-loop serves and
                # the SFQ clock orders the force-serves
                tq.deficit -= max(1, int(cost))
            self._grant(tenant, cost)
            return True
        tq.queue.append((item, max(1, int(cost)), now))
        metrics.add("tenant.sched.parked")
        return False

    def _other_backlog(self, tenant: str, now: float) -> bool:
        for t, tq in self._tenants.items():
            if t != tenant and tq.queue and not self._boxed(tq, now):
                return True
        return False

    def _grant(self, tenant: str, cost: int = 1) -> None:
        self._free -= 1
        self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
        self.grants += 1
        self.granted_cost[tenant] = (self.granted_cost.get(tenant, 0)
                                     + max(1, int(cost)))
        # SFQ virtual clock (start-time fair queuing): every grant
        # stamps its tenant's virtual finish = max(own finish, system
        # time) + cost/weight, and advances system time to the grant's
        # virtual START. The force-serve pick orders by this clock —
        # the scheme that stays weight-PROPORTIONAL when the pool's
        # service rate (one settle, one grant), not deficit earnings,
        # is the binding constraint (max-debt picking there converges
        # to equal-drift round robin instead; measured on the 4 MB-
        # chunk bench regime). max(own, system) is the fresh-start
        # rule: an idle tenant rejoins at the current clock, it cannot
        # bank virtual time.
        weight = max(1, int(self._weight_of(tenant)))
        tq = self._tq(tenant)
        vstart = max(tq.vfinish, self._vtime)
        self._vtime = vstart
        tq.vfinish = vstart + max(1, int(cost)) / weight
        metrics.add("tenant.sched.grants", tenant=tenant)

    def release(self, tenant: str) -> None:
        """One response settled: its credit returns to the pool. The
        caller follows with :meth:`grant_parked`."""
        self._free = min(self.total, self._free + 1)
        left = self._inflight.get(tenant, 0) - 1
        if left > 0:
            self._inflight[tenant] = left
        else:
            self._inflight.pop(tenant, None)

    def grant_parked(self) -> List[Tuple]:
        """The DRR sweep: unpark up to ``free`` items across tenants by
        weighted deficit round-robin. Returns the granted (conn, entry)
        items — each HOLDS one credit; the caller starts them (and
        releases via :meth:`release` when they settle or drop)."""
        granted: List[Tuple] = []
        ring = self._ring
        n = len(ring)
        if n == 0 or self._free <= 0:
            return granted
        now = time.monotonic()
        # visit budget: a full ring pass with eligible backlog either
        # serves an item or grows some queue's deficit toward its head
        # cost (bounded passes per head under byte quanta); the
        # force-serve fallback below guarantees progress even when the
        # budget runs out with credits free
        visits = n * (self.total + 2)
        while self._free > 0 and visits > 0:
            unboxed_backlog = any(
                tq.queue and not self._boxed(tq, now)
                for tq in self._tenants.values())
            if not unboxed_backlog and not any(
                    tq.queue for tq in self._tenants.values()):
                break
            tenant = ring[self._ring_pos % n]
            tq = self._tenants[tenant]
            if not tq.queue or (self._boxed(tq, now)
                                and unboxed_backlog):
                if not tq.queue:
                    # DRR: an empty queue forfeits banked credit
                    # (anti-burst) — but KEEPS its debt: a force-served
                    # oversized head's negative deficit must survive
                    # the queue emptying, or a tenant issuing big
                    # requests one at a time never repays
                    tq.deficit = min(tq.deficit, 0.0)
                self._advance()
                visits -= 1
                continue
            if not self._turn_earned:
                weight = max(1, int(self._weight_of(tenant)))
                earn = self.quantum * weight
                # a BACKLOGGED queue accumulates uncapped (classic
                # DRR: the anti-burst forfeit applies when the queue
                # EMPTIES, not while it waits). Capping accumulation
                # at the head cost saturated EVERY backlogged tenant
                # at the same ceiling under oversized heads — the
                # weight signal vanished and grants degenerated to
                # round-robin (measured: 2x-weight goodput 1.96 ->
                # ~1.3). Uncapped, deficit tracks earned-minus-served,
                # so both the in-loop serve and the force-serve
                # max-debt pick converge to weight-proportional BYTES
                tq.deficit += earn
                self._turn_earned = True
            while tq.queue and tq.deficit >= tq.queue[0][1] \
                    and self._free > 0:
                item, cost, t_enq = tq.queue.popleft()
                tq.deficit -= cost
                self._grant(tenant, cost)
                metrics.observe("tenant.queue.wait_ms",
                                (now - t_enq) * 1000.0, tenant=tenant)
                granted.append(item)
            if tq.queue and tq.deficit >= tq.queue[0][1]:
                break  # credits ran out mid-turn: the NEXT sweep
                # resumes this tenant's turn with its leftover deficit
            if not tq.queue:
                tq.deficit = min(tq.deficit, 0.0)  # forfeit credit,
                # keep debt (see above)
            self._advance()
            visits -= 1
        if not granted and self._free > 0:
            # progress guarantee under byte quanta: free credits +
            # eligible backlog must never idle behind a head whose
            # cost outruns the visit budget — serve the most-indebted
            # eligible head; the negative deficit is the byte debt its
            # tenant repays before its next grant
            self._force_serve(granted, now)
        metrics.gauge("tenant.sched.backlog", self.backlog())
        return granted

    def _force_serve(self, granted: List[Tuple], now: float) -> None:
        unboxed = [(t, tq) for t, tq in self._tenants.items()
                   if tq.queue and not self._boxed(tq, now)]
        pool = unboxed or [(t, tq) for t, tq in self._tenants.items()
                           if tq.queue]
        if not pool:
            return
        # SFQ pick: the earliest virtual START (see _grant) — weight-
        # proportional service under oversized heads, where the
        # deficit clock cannot bite within one sweep's visit budget
        tenant, tq = min(
            pool, key=lambda x: max(x[1].vfinish, self._vtime))
        item, cost, t_enq = tq.queue.popleft()
        tq.deficit -= cost
        self._grant(tenant, cost)
        metrics.observe("tenant.queue.wait_ms",
                        (now - t_enq) * 1000.0, tenant=tenant)
        granted.append(item)

    def _advance(self) -> None:
        self._ring_pos = (self._ring_pos + 1) % max(1, len(self._ring))
        self._turn_earned = False

    def drop_conn(self, conn) -> int:
        """A connection died: its parked (unstarted, creditless) items
        leave the queues. Returns how many were dropped."""
        dropped = 0
        for tq in self._tenants.values():
            keep = deque(entry for entry in tq.queue
                         if entry[0][0] is not conn)
            dropped += len(tq.queue) - len(keep)
            tq.queue = keep
        return dropped

    # -- the tenant penalty box ----------------------------------------------

    def note_fault(self, tenant: str) -> None:
        """One abusive event (admission rejection, injected fault on
        this tenant's request): past the threshold the tenant enters
        the box for ``penalty_ms`` (extended while faults continue;
        a clean grant sweep is the implicit forgiveness — the box
        simply expires)."""
        tq = self._tq(tenant)
        tq.faults += 1
        if tq.faults >= self.penalty_threshold:
            now = time.monotonic()
            first = tq.boxed_until <= now
            tq.boxed_until = now + self.penalty_s
            tq.faults = 0
            if first:
                metrics.add("tenant.penalties", tenant=tenant)
                log.warn(f"tenant {tenant!r} penalty-boxed for "
                         f"{self.penalty_s:g}s (repeated faults); its "
                         f"parked requests yield to other tenants")

    def boxed(self, tenant: str) -> bool:
        tq = self._tenants.get(tenant)
        return bool(tq and self._boxed(tq, time.monotonic()))

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        now = time.monotonic()
        return {
            "total": self.total, "free": self._free,
            "grants": self.grants,
            "tenants": {
                t: {"parked": len(tq.queue),
                    "parked_cost": sum(e[1] for e in tq.queue),
                    "granted_cost": self.granted_cost.get(t, 0),
                    "inflight": self._inflight.get(t, 0),
                    "deficit": round(tq.deficit, 3),
                    "weight": max(1, int(self._weight_of(t))),
                    "boxed": self._boxed(tq, now)}
                for t, tq in sorted(self._tenants.items())},
        }
