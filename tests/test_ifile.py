"""IFile framing + columnar crack (reference src/Merger/StreamRW.cc)."""

import io

import numpy as np
import pytest

from uda_tpu.utils import ifile
from uda_tpu.utils.errors import StorageError


def _records(n=100, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        klen = int(rng.integers(0, 40))
        vlen = int(rng.integers(0, 200))
        out.append((rng.bytes(klen), rng.bytes(vlen)))
    return out


def test_round_trip_stream():
    recs = _records()
    buf = ifile.write_records(recs)
    assert buf.endswith(ifile.EOF_MARKER)
    got = list(ifile.IFileReader(io.BytesIO(buf)))
    assert got == recs


def test_crack_columnar():
    recs = _records(200, seed=1)
    buf = ifile.write_records(recs)
    batch = ifile.crack(buf)
    assert batch.num_records == len(recs)
    for i, (k, v) in enumerate(recs):
        assert batch.key(i) == k
        assert batch.value(i) == v
    assert list(batch.iter_records()) == recs


def test_crack_missing_eof():
    recs = _records(5)
    buf = ifile.write_records(recs)[: -len(ifile.EOF_MARKER)]
    with pytest.raises(StorageError):
        ifile.crack(buf)
    batch = ifile.crack(buf, expect_eof=False)
    assert batch.num_records == 5


def test_crack_corrupt():
    with pytest.raises(StorageError):
        # klen=-2 is invalid (only -1/-1 EOF allowed)
        ifile.crack(b"\xfe\xfe")


def test_batch_concat_and_take():
    a = ifile.crack(ifile.write_records(_records(10, seed=2)))
    b = ifile.crack(ifile.write_records(_records(7, seed=3)))
    cat = ifile.RecordBatch.concat([a, b])
    assert cat.num_records == 17
    recs = list(a.iter_records()) + list(b.iter_records())
    assert list(cat.iter_records()) == recs
    order = np.arange(17)[::-1]
    assert list(cat.take(order).iter_records()) == recs[::-1]


def test_crc_trailer():
    out = io.BytesIO()
    w = ifile.IFileWriter(out, with_crc=True)
    w.append(b"k", b"v")
    w.close()
    raw = out.getvalue()
    # CRC covers framing + EOF marker; last 4 bytes are the trailer.
    import zlib
    assert int.from_bytes(raw[-4:], "big") == zlib.crc32(raw[:-4])
    # read path verifies the trailer...
    batch = ifile.crack(raw, verify_crc=True)
    assert batch.num_records == 1
    # ...and detects a bit flip
    flipped = bytearray(raw)
    flipped[2] ^= 1
    with pytest.raises(StorageError, match="CRC mismatch"):
        ifile.crack(bytes(flipped), verify_crc=True)
    # missing trailer
    with pytest.raises(StorageError, match="missing CRC"):
        ifile.crack(ifile.write_records([(b"k", b"v")]), verify_crc=True)


def test_truncation_raises_storage_error():
    # truncation mid-VInt must surface as StorageError (the fallback
    # contract catches UdaError, not IndexError)
    with pytest.raises(StorageError):
        ifile.crack(b"\x8e\x01")  # VInt cut mid-body
    with pytest.raises(StorageError):
        list(ifile.IFileReader(io.BytesIO(b"\x01\x01a")))  # no EOF marker
