"""Online tuning cache lifecycle (ISSUE 13): probe persists winners, a
second/fresh process routes from the cache without re-probing,
corrupt/truncated/version-bumped files are ignored (counted, never
fatal), env-var winners beat the cache, and a cold cache is
byte-for-byte today's built-in routing."""

import json
import os
import subprocess
import sys
import time

import pytest

from uda_tpu.ops import sort as sort_ops
from uda_tpu.utils import tuncache
from uda_tpu.utils.config import Config
from uda_tpu.utils.metrics import metrics
from uda_tpu.utils.tuncache import TuneCache, rows_bucket

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sort_key(n_rows, lanes_ok=False):
    import jax

    return (f"{jax.default_backend()}|rows{rows_bucket(n_rows)}"
            f"|lanes{int(lanes_ok)}")


@pytest.fixture()
def cache_at(tmp_path, monkeypatch):
    """A fresh cache file wired in as the process-default instance
    (what route_engine consults)."""
    path = str(tmp_path / "tune.json")
    cache = TuneCache(path)
    monkeypatch.setattr(tuncache, "tune_cache", cache)
    return cache


# -- record/lookup round trip -------------------------------------------------


def test_record_lookup_round_trip(cache_at):
    cache_at.record("sort.engine", "cpu|rows16|lanes0",
                    {"engine": "gather"}, metric=1.25, probe="t")
    rec = cache_at.lookup("sort.engine", "cpu|rows16|lanes0")
    assert rec["winner"] == {"engine": "gather"}
    assert rec["metric"] == 1.25
    assert cache_at.age_s("sort.engine", "cpu|rows16|lanes0") < 60
    assert cache_at.lookup("sort.engine", "nope") is None
    assert metrics.get("tune.cache.hits", domain="sort.engine") == 1
    assert metrics.get("tune.cache.misses", domain="sort.engine") == 1


def test_second_instance_reads_persisted_winner(cache_at):
    """The 'second process' shape in-process: a brand-new TuneCache on
    the same path (fresh mtime state) serves the persisted winner."""
    cache_at.record("io.read", "linux", {"batch": "on", "gap_kb": 64})
    second = TuneCache(cache_at.path)
    rec = second.lookup("io.read", "linux")
    assert rec["winner"]["gap_kb"] == 64


def test_concurrent_domains_merge_not_clobber(cache_at):
    cache_at.record("sort.engine", "k1", {"engine": "carry"})
    other = TuneCache(cache_at.path)
    other.record("io.read", "k2", {"batch": "on"})
    assert cache_at.lookup("sort.engine", "k1") is not None
    assert cache_at.lookup("io.read", "k2") is not None


# -- invalid files: ignored, counted, never fatal -----------------------------


@pytest.mark.parametrize("content", [
    "{ not json at all",                                   # torn JSON
    json.dumps({"schema": 999, "entries": {}}),            # version bump
    json.dumps({"schema": 1, "entries": "not-a-dict"}),    # malformed
    "",                                                    # truncated
])
def test_invalid_cache_ignored_and_counted(cache_at, content):
    with open(cache_at.path, "w") as f:
        f.write(content)
    assert cache_at.lookup("sort.engine", "anything") is None
    assert metrics.get("tune.cache.invalid") >= 1
    # routing still works on the defaults
    assert sort_ops.route_engine(1 << 16, "auto") \
        == sort_ops.resolve_sort_path("auto")


def test_invalid_entries_filtered_not_fatal(cache_at):
    with open(cache_at.path, "w") as f:
        json.dump({"schema": 1, "entries": {
            "sort.engine|good": {"winner": {"engine": "gather"}},
            "sort.engine|bad": "not-a-record",
        }}, f)
    assert cache_at.lookup("sort.engine", "good") is not None
    assert cache_at.lookup("sort.engine", "bad") is None


# -- route_engine integration -------------------------------------------------


def test_cold_cache_routes_exactly_todays_defaults(cache_at,
                                                   monkeypatch):
    monkeypatch.setattr(sort_ops, "DEPLOYED_SORT_PATH", "")
    for n in (1, 1 << 10, 1 << 16, 1 << 20, 1 << 22):
        for lanes_ok in (False, True):
            assert sort_ops.route_engine(n, "auto", lanes_ok) == \
                sort_ops.resolve_sort_path("auto", lanes_ok)
    # explicit paths bypass the cache entirely
    assert sort_ops.route_engine(1 << 16, "gather") == "gather"


def test_route_engine_consults_cached_winner(cache_at, monkeypatch):
    monkeypatch.setattr(sort_ops, "DEPLOYED_SORT_PATH", "")
    n = 1 << 16
    cache_at.record("sort.engine", _sort_key(n),
                    {"engine": "gather2"}, metric=2.0)
    assert sort_ops.route_engine(n, "auto") == "gather2"
    assert metrics.get("tune.cache.hits", domain="sort.engine") >= 1
    # a different size class misses the cache -> built-in default
    assert sort_ops.route_engine(1 << 22, "auto") == \
        sort_ops.resolve_sort_path("auto")


def test_env_winner_beats_cache(cache_at, monkeypatch):
    n = 1 << 16
    cache_at.record("sort.engine", _sort_key(n),
                    {"engine": "gather2"})
    monkeypatch.setattr(sort_ops, "DEPLOYED_SORT_PATH", "carrychunk")
    assert sort_ops.route_engine(n, "auto") == "carrychunk"


def test_invalid_cached_engine_ignored(cache_at, monkeypatch):
    monkeypatch.setattr(sort_ops, "DEPLOYED_SORT_PATH", "")
    n = 1 << 16
    cache_at.record("sort.engine", _sort_key(n, lanes_ok=False),
                    {"engine": "totally-made-up"})
    assert sort_ops.route_engine(n, "auto") == \
        sort_ops.resolve_sort_path("auto")
    # a lanes winner cached for a lanes-capable key must not leak to a
    # lanes-incapable caller (validation per lookup, not per file)
    cache_at.record("sort.engine", _sort_key(n, lanes_ok=False),
                    {"engine": "lanes"})
    assert sort_ops.route_engine(n, "auto", lanes_ok=False) == \
        sort_ops.resolve_sort_path("auto", lanes_ok=False)


def test_fresh_process_routes_from_cache_without_probe(cache_at):
    """THE acceptance round trip: a persisted winner is consulted by
    route_engine in a FRESH interpreter — cache hit recorded, probe
    counter ZERO (nothing re-measures on the routing path)."""
    n = 1 << 16
    # the fresh process is CPU-backend (env below): key accordingly
    key = f"cpu|rows{rows_bucket(n)}|lanes0"
    cache_at.record("sort.engine", key, {"engine": "gather2"},
                    metric=9.9, probe="lifecycle-test")
    code = (
        "import os\n"
        "from uda_tpu.ops import sort as sort_ops\n"
        "from uda_tpu.utils.metrics import metrics\n"
        f"engine = sort_ops.route_engine({n}, 'auto')\n"
        "print('ENGINE', engine)\n"
        "print('PROBES', int(metrics.get('tune.probes')))\n"
        "print('HITS', int(metrics.get('tune.cache.hits')))\n"
    )
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("UDA_TPU_SORT_PATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["UDA_TPU_TUNE_CACHE"] = cache_at.path
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         env=env, capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 0, out.stderr
    assert "ENGINE gather2" in out.stdout
    assert "PROBES 0" in out.stdout
    assert "HITS 1" in out.stdout


# -- the io.read consumer -----------------------------------------------------


def _engine_with_cache(tmp_path, cache_path, overrides=None):
    from tests.test_iobatch import SyntheticResolver, _write

    path = _write(str(tmp_path), "f.mof", 4096)
    cfg = {"uda.tpu.tune.cache.path": cache_path}
    cfg.update(overrides or {})
    from uda_tpu.mofserver.data_engine import DataEngine

    return DataEngine(SyntheticResolver(path, 4096), Config(cfg))


def test_io_plane_consults_cache_winner(tmp_path, cache_at):
    cache_at.record("io.read", sys.platform,
                    {"batch": "off", "gap_kb": 256, "batch_max": 32,
                     "backend": "pread"})
    engine = _engine_with_cache(tmp_path, cache_at.path)
    try:
        assert engine.batch_enabled is False
        assert engine.coalesce_gap_bytes == 256 << 10
        assert engine.batch_max == 32
        assert engine.io_backend == "pread"
    finally:
        engine.stop()


def test_io_plane_explicit_config_beats_cache(tmp_path, cache_at):
    cache_at.record("io.read", sys.platform,
                    {"batch": "off", "gap_kb": 256})
    engine = _engine_with_cache(
        tmp_path, cache_at.path,
        {"uda.tpu.read.batch": "on",
         "uda.tpu.read.coalesce.gap.kb": 8})
    try:
        assert engine.batch_enabled is True
        assert engine.coalesce_gap_bytes == 8 << 10
    finally:
        engine.stop()


def test_config_path_installs_process_default(tmp_path, cache_at,
                                              monkeypatch):
    """An explicitly-configured uda.tpu.tune.cache.path must reach
    route_engine too (which has no Config in scope): constructing the
    engine installs the path as the process default — unless the env
    var is set, which always wins."""
    monkeypatch.setattr(sort_ops, "DEPLOYED_SORT_PATH", "")
    other = str(tmp_path / "other_tune.json")
    TuneCache(other).record("sort.engine", _sort_key(1 << 16),
                            {"engine": "gather2"})
    monkeypatch.delenv("UDA_TPU_TUNE_CACHE", raising=False)
    engine = _engine_with_cache(tmp_path, other)
    try:
        assert tuncache.tune_cache.path == other
        assert sort_ops.route_engine(1 << 16, "auto") == "gather2"
    finally:
        engine.stop()
    # with the env channel set, config must NOT displace it
    monkeypatch.setenv("UDA_TPU_TUNE_CACHE", cache_at.path)
    before = tuncache.tune_cache
    engine = _engine_with_cache(tmp_path, str(tmp_path / "third.json"))
    try:
        assert tuncache.tune_cache is before
    finally:
        engine.stop()


def test_io_plane_invalid_winner_values_ignored(tmp_path, cache_at):
    cache_at.record("io.read", sys.platform,
                    {"batch": "maybe", "gap_kb": "lots",
                     "batch_max": -3, "backend": "carrier-pigeon"})
    engine = _engine_with_cache(tmp_path, cache_at.path)
    try:
        assert engine.batch_enabled is True           # default on
        assert engine.coalesce_gap_bytes == 64 << 10  # flag default
        assert engine.batch_max == 256                # flag default
        assert engine.io_backend in ("io_uring", "preadv", "pread")
    finally:
        engine.stop()


# -- background re-probe rung -------------------------------------------------


def test_ensure_fresh_reprobes_stale_entry(cache_at, monkeypatch):
    calls = []
    monkeypatch.setitem(tuncache._PROBES, "sort.engine",
                        lambda key: calls.append(key))
    cache_at.record("sort.engine", "k", {"engine": "carry"})
    # fresh: no re-probe
    tuncache.ensure_fresh(cache_at, "sort.engine", "k", 3600.0)
    assert not calls
    # absent: no re-probe either (first measurement is the probe
    # script's job, never the routing hot path's)
    tuncache.ensure_fresh(cache_at, "sort.engine", "absent", 0.001)
    # stale: the background thread re-measures
    with open(cache_at.path) as f:
        doc = json.load(f)
    doc["entries"]["sort.engine|k"]["probed_unix"] = time.time() - 999
    with open(cache_at.path, "w") as f:
        json.dump(doc, f)
    tuncache.ensure_fresh(cache_at, "sort.engine", "k", 1.0)
    deadline = time.monotonic() + 5.0
    while not calls and time.monotonic() < deadline:
        time.sleep(0.01)
    assert calls == ["k"]
    assert metrics.get("tune.reprobes") == 1
    # disabled horizon (0): never
    calls.clear()
    tuncache.ensure_fresh(cache_at, "sort.engine", "k", 0.0)
    time.sleep(0.05)
    assert not calls
