"""Shared test helpers: synthetic map-output generation.

Builds the on-disk layout the supplier serves (``<root>/<job>/<map>/
file.out[.index]``) the way a Hadoop mapper would: per-map records
partitioned by reducer, each partition sorted and IFile-framed, index
triples pointing into the concatenated MOF.
"""

from __future__ import annotations

import io
import os
from typing import Callable

import numpy as np

from uda_tpu.mofserver.index import write_index_file
from uda_tpu.utils.ifile import IFileWriter


def default_partitioner(key: bytes, num_reducers: int) -> int:
    import zlib
    return zlib.crc32(key) % num_reducers


def make_mof_tree(root: str, job_id: str, num_maps: int, num_reducers: int,
                  records_per_map: int, seed: int = 0,
                  key_bytes: int = 10, val_bytes: int = 30,
                  partitioner: Callable[[bytes, int], int] = default_partitioner,
                  sort_key=None) -> dict[int, list[tuple[bytes, bytes]]]:
    """Write a full MOF tree; returns expected records per reducer
    (unsorted)."""
    rng = np.random.default_rng(seed)
    expected: dict[int, list[tuple[bytes, bytes]]] = {r: [] for r in range(num_reducers)}
    sort_key = sort_key or (lambda kv: kv[0])
    for m in range(num_maps):
        map_id = f"attempt_{job_id}_m_{m:06d}_0"
        parts: dict[int, list[tuple[bytes, bytes]]] = {r: [] for r in range(num_reducers)}
        for _ in range(records_per_map):
            k = rng.bytes(key_bytes)
            v = rng.bytes(val_bytes)
            r = partitioner(k, num_reducers)
            parts[r].append((k, v))
            expected[r].append((k, v))
        d = os.path.join(root, job_id, map_id)
        os.makedirs(d, exist_ok=True)
        mof = io.BytesIO()
        triples = []
        for r in range(num_reducers):
            start = mof.tell()
            w = IFileWriter(mof)
            for k, v in sorted(parts[r], key=sort_key):
                w.append(k, v)
            w.close()
            length = mof.tell() - start
            triples.append((start, length, length))
        with open(os.path.join(d, "file.out"), "wb") as f:
            f.write(mof.getvalue())
        write_index_file(os.path.join(d, "file.out.index"), triples)
    return expected


def map_ids(job_id: str, num_maps: int) -> list[str]:
    return [f"attempt_{job_id}_m_{m:06d}_0" for m in range(num_maps)]
