"""End-to-end single-host slice: MOFs on disk -> fetch -> device merge ->
framed IFile emission (SURVEY §7.3's minimum slice), online and hybrid."""

import functools
import io

import numpy as np
import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.merger import LocalFetchClient, MergeManager
from uda_tpu.merger.arena import BufferArena
from uda_tpu.merger.hybrid import num_lpqs_for
from uda_tpu.mofserver import DataEngine, DirIndexResolver
from uda_tpu.utils import comparators
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import MergeError
from uda_tpu.utils.ifile import IFileReader


def _run_merge(tmp_path, cfg=None, num_maps=6, num_reducers=2,
               records_per_map=80, job="jobA", seed=1):
    expected = make_mof_tree(str(tmp_path), job, num_maps, num_reducers,
                             records_per_map, seed=seed)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    results = {}
    try:
        for r in range(num_reducers):
            mm = MergeManager(LocalFetchClient(engine), kt, cfg)
            blocks = []
            # consumer gets a memoryview valid only during the call: copy
            total = mm.run(job, map_ids(job, num_maps), r,
                           lambda b: blocks.append(bytes(b)))
            stream = b"".join(blocks)
            assert total == len(stream)
            results[r] = list(IFileReader(io.BytesIO(stream)))
    finally:
        engine.stop()
    return expected, results


def _check_sorted_equal(expected, got, kt):
    want = sorted(expected, key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert [k for k, _ in got] == [k for k, _ in want]
    assert sorted(v for _, v in got) == sorted(v for _, v in want)


def test_online_merge_end_to_end(tmp_path):
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    expected, results = _run_merge(tmp_path)
    for r, got in results.items():
        assert len(got) == len(expected[r])
        _check_sorted_equal(expected[r], got, kt)


def test_online_merge_small_chunks_split_records(tmp_path):
    # chunk smaller than a record forces the carry/join path
    # (reference switch_mem/join, StreamRW.cc:542-590)
    cfg = Config({"mapred.rdma.buf.size": 1})  # 1 KB chunks... still big
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    expected, results = _run_merge(tmp_path, cfg, num_maps=2,
                                   records_per_map=30, job="jobB", seed=2)
    for r, got in results.items():
        assert len(got) == len(expected[r])
        _check_sorted_equal(expected[r], got, kt)


def test_hybrid_merge_end_to_end(tmp_path):
    cfg = Config({"mapred.netmerger.merge.approach": 2,
                  "mapred.netmerger.hybrid.lpq.size": 2,
                  "uda.tpu.spill.dirs": str(tmp_path / "spill")})
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    expected, results = _run_merge(tmp_path, cfg, num_maps=7, job="jobC",
                                   seed=3)
    for r, got in results.items():
        assert len(got) == len(expected[r])
        _check_sorted_equal(expected[r], got, kt)
    # spill files are deleted after the RPQ phase (~SuperSegment)
    spill = tmp_path / "spill"
    assert not spill.exists() or not any(spill.iterdir())


def test_hybrid_empty_spill_dirs_falls_back_to_tmp(tmp_path):
    # regression: explicit '' must mean "system tmp", not crash
    cfg = Config({"mapred.netmerger.merge.approach": 2,
                  "uda.tpu.spill.dirs": ""})
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    expected, results = _run_merge(tmp_path, cfg, num_maps=4, job="jobE",
                                   seed=5)
    for r, got in results.items():
        _check_sorted_equal(expected[r], got, kt)


def test_emitter_block_size_and_eof():
    from uda_tpu.merger.emitter import FramedEmitter
    recs = [(bytes([i]) * 4, b"v" * 50) for i in range(100)]
    em = FramedEmitter(block_size=256)
    blocks = []
    total = em.emit(iter(recs), lambda b: blocks.append(bytes(b)))
    assert all(len(b) <= 256 for b in blocks)
    assert total == sum(len(b) for b in blocks)
    stream = b"".join(blocks)
    got = list(IFileReader(io.BytesIO(stream)))
    assert got == recs
    # oversized single record still emits (split across blocks)
    big = [(b"k", b"x" * 2000)]
    blocks2 = []
    em.emit(iter(big), lambda b: blocks2.append(bytes(b)))
    got2 = list(IFileReader(io.BytesIO(b"".join(blocks2))))
    assert got2 == big


def test_emit_batch_matches_emit_stream():
    # the bulk (native) path must produce the identical byte stream as
    # the per-record writer path, under any block size
    from uda_tpu.merger.emitter import FramedEmitter
    from uda_tpu.utils.ifile import crack, write_records

    rng = np.random.default_rng(17)
    recs = [(rng.bytes(1 + int(rng.integers(12))),
             rng.bytes(int(rng.integers(200)))) for _ in range(500)]
    batch = crack(write_records(recs))
    for block in (64, 300, 1 << 20):
        a, b = [], []
        FramedEmitter(block).emit(iter(recs), lambda x: a.append(bytes(x)))
        FramedEmitter(block).emit_batch(batch, lambda x: b.append(bytes(x)))
        assert all(len(x) <= block for x in b)
        assert b"".join(a) == b"".join(b), f"block={block}"


def test_emit_batch_empty_and_consumer_exception():
    from uda_tpu.merger.emitter import FramedEmitter
    from uda_tpu.utils.ifile import EOF_MARKER, RecordBatch, crack, write_records

    em = FramedEmitter(block_size=64)
    blocks = []
    total = em.emit_batch(RecordBatch.concat([]),
                          lambda b: blocks.append(bytes(b)))
    assert b"".join(blocks) == EOF_MARKER and total == 2

    batch = crack(write_records([(bytes([i]), b"v" * 40)
                                 for i in range(20)]))

    def boom(_):
        raise RuntimeError("downstream broke")

    with pytest.raises(RuntimeError):
        em.emit_batch(batch, boom)
    # arena recovered: the next emit_batch on the same emitter works
    blocks2 = []
    em.emit_batch(batch, lambda b: blocks2.append(bytes(b)))
    got = list(IFileReader(io.BytesIO(b"".join(blocks2))))
    assert got == list(batch.iter_records())


def test_frame_batch_python_fallback_parity(monkeypatch):
    # force the pure-Python fallback and check byte equality vs native
    from uda_tpu import native
    from uda_tpu.utils.ifile import crack, write_records

    rng = np.random.default_rng(23)
    recs = [(rng.bytes(6), rng.bytes(30)) for _ in range(100)]
    batch = crack(write_records(recs))
    want = native.frame_batch(batch, write_eof=True)
    monkeypatch.setattr(native, "build", lambda quiet=True: False)
    got = native.frame_batch(batch, write_eof=True)
    assert got == want


def test_iter_file_records_streaming(tmp_path):
    from uda_tpu.utils.ifile import iter_file_records, write_records
    recs = [(np.random.default_rng(i).bytes(10),
             np.random.default_rng(i + 1000).bytes(200)) for i in range(300)]
    # include a value that ends with the EOF marker bytes (must not be
    # mistaken for end of stream)
    recs[7] = (b"trap", b"data\xff\xff")
    path = str(tmp_path / "run.ifile")
    with open(path, "wb") as f:
        f.write(write_records(recs))
    got = list(iter_file_records(path, buffer_size=97))
    assert got == recs


def test_emitter_consumer_exception_releases_slots():
    from uda_tpu.merger.emitter import FramedEmitter
    em = FramedEmitter(block_size=64)

    def boom(_):
        raise RuntimeError("downstream broke")

    recs = [(bytes([i]), b"v" * 40) for i in range(20)]
    with pytest.raises(RuntimeError):
        em.emit(iter(recs), boom)
    # arena fully recovered: the next emit on the same emitter works
    blocks = []
    em.emit(iter(recs), lambda b: blocks.append(bytes(b)))
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    assert got == recs


def test_empty_partition_zero_raw_length(tmp_path):
    # a foreign writer may index an empty partition as raw_length=0 (no
    # records, no EOF marker); the fetch must yield zero records, not fail
    import os

    from uda_tpu.mofserver.index import write_index_file

    d = tmp_path / "jobZ" / "attempt_jobZ_m_000000_0"
    os.makedirs(d)
    with open(d / "file.out", "wb") as f:
        f.write(b"")
    write_index_file(str(d / "file.out.index"), [(0, 0, 0)])
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    try:
        mm = MergeManager(LocalFetchClient(engine), "uda.tpu.RawBytes")
        blocks = []
        total = mm.run("jobZ", ["attempt_jobZ_m_000000_0"], 0,
                       lambda b: blocks.append(bytes(b)))
        got = list(IFileReader(io.BytesIO(b"".join(blocks))))
        assert got == []
        assert total == 2  # just the EOF marker
    finally:
        engine.stop()


def test_sliding_window_bounds_concurrency(tmp_path):
    # in-flight segments never exceed the window, and all complete
    import threading

    from uda_tpu.merger.segment import InputClient

    make_mof_tree(str(tmp_path), "jobW", num_maps=20, num_reducers=1,
                  records_per_map=5, seed=9)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    peak = [0]
    active = [0]
    lock = threading.Lock()

    class Counting(LocalFetchClient):
        def start_fetch(self, req, on_complete):
            if req.offset == 0:
                with lock:
                    active[0] += 1
                    peak[0] = max(peak[0], active[0])

            def wrapped(res):
                if not isinstance(res, Exception) and res.is_last:
                    with lock:
                        active[0] -= 1
                on_complete(res)

            super().start_fetch(req, wrapped)

    cfg = Config({"mapred.rdma.wqe.per.conn": 4})
    try:
        mm = MergeManager(Counting(engine), "uda.tpu.RawBytes", cfg)
        segs = mm.fetch_all("jobW", map_ids("jobW", 20), 0)
        assert all(s.ready for s in segs)
        assert peak[0] <= 4
    finally:
        engine.stop()


def test_hybrid_spill_cleanup_on_failure(tmp_path):
    # a failing LPQ must not orphan completed groups' spill files
    make_mof_tree(str(tmp_path), "jobF", num_maps=4, num_reducers=1,
                  records_per_map=10, seed=11)
    spill = tmp_path / "spill"
    cfg = Config({"mapred.netmerger.merge.approach": 2,
                  "mapred.netmerger.hybrid.lpq.size": 1,
                  "mapred.rdma.num.parallel.lpqs": 1,
                  "uda.tpu.spill.dirs": str(spill)})
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    try:
        mm = MergeManager(LocalFetchClient(engine), "uda.tpu.RawBytes", cfg)
        maps = map_ids("jobF", 4) + ["attempt_jobF_m_000099_0"]  # missing
        with pytest.raises(Exception):
            mm.run("jobF", maps, 0, lambda b: None)
    finally:
        engine.stop()
    assert not spill.exists() or not any(spill.iterdir())


def test_num_lpqs():
    assert num_lpqs_for(16, 0) == 4          # sqrt rule (reducer.cc:278)
    assert num_lpqs_for(100, 10) == 10       # explicit lpq size
    assert num_lpqs_for(1, 0) == 1


def test_progress_reports(tmp_path):
    make_mof_tree(str(tmp_path), "jobD", 45, 1, 5, seed=4)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    seen = []
    try:
        mm = MergeManager(LocalFetchClient(engine), "uda.tpu.RawBytes",
                          progress=lambda done, total: seen.append((done, total)))
        mm.run("jobD", map_ids("jobD", 45), 0, lambda b: None)
    finally:
        engine.stop()
    # every PROGRESS_INTERVAL segments + final (MergeManager.cc:44)
    assert (20, 45) in seen and (40, 45) in seen and (45, 45) in seen


def test_arena_backpressure():
    arena = BufferArena(2, 1024)
    a = arena.acquire()
    b = arena.acquire()
    assert arena.try_acquire() is None
    with pytest.raises(MergeError):
        arena.acquire(timeout=0.05)
    arena.release(a)
    c = arena.acquire()
    assert c is a
    arena.release(b)
    arena.release(c)
    assert arena.free_slots == 2


def test_arena_slot_write_overflow():
    arena = BufferArena(1, 16)
    slot = arena.acquire()
    slot.write(b"x" * 16)
    with pytest.raises(MergeError):
        slot.write(b"y" * 17)
    arena.release(slot)


def test_host_routing_client_lazy_connect(tmp_path):
    """Per-host transport table (reference RDMAClient.cc:498-527): maps
    live on two different 'hosts' (separate MOF roots + DataEngines);
    the router connects lazily on first use and the merge interleaves
    records from both suppliers; an unknown host fails the fetch."""
    import functools
    import io

    from tests.helpers import make_mof_tree, map_ids
    from uda_tpu.merger import (HostRoutingClient, LocalFetchClient,
                                MergeManager)
    from uda_tpu.mofserver import DataEngine, DirIndexResolver, ShuffleRequest
    from uda_tpu.utils import comparators
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.ifile import IFileReader

    job = "jobHosts"
    roots = {h: tmp_path / h for h in ("hostA", "hostB")}
    expected = []
    engines = {}
    for i, (h, root) in enumerate(sorted(roots.items())):
        root.mkdir()
        exp = make_mof_tree(str(root), job, 2, 1, 25, seed=100 + i)
        expected += exp[0]
        engines[h] = DataEngine(DirIndexResolver(str(root)), Config())
    connects = []

    def connect(host):
        connects.append(host)
        return LocalFetchClient(engines[host])

    router = HostRoutingClient(connect)
    try:
        mm = MergeManager(router, "uda.tpu.RawBytes", Config())
        maps = ([("hostA", m) for m in map_ids(job, 2)]
                + [("hostB", m) for m in map_ids(job, 2)])
        blocks = []
        mm.run(job, maps, 0, lambda b: blocks.append(bytes(b)))
    finally:
        for e in engines.values():
            e.stop()
    # one lazy connect per host, not per fetch
    assert sorted(connects) == ["hostA", "hostB"]
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    want = sorted(expected, key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want
    # unknown host -> fetch completes with the connect error
    errs = []
    router.start_fetch(ShuffleRequest(job, "m", 0, 0, 10, host="nope"),
                       errs.append)
    assert errs and isinstance(errs[0], KeyError)


class FlakyClient:
    """Fault-injecting transport: fails the first ``fail_count`` fetches
    per (map, offset-0 restart) — the fake the reference never had
    (SURVEY §4.5: no mocks of the RDMA layer existed)."""

    def __init__(self, inner, fail_count=2):
        self.inner = inner
        self.fail_count = fail_count
        self.calls = 0
        import threading as _t
        self._lock = _t.Lock()

    def start_fetch(self, req, on_complete):
        with self._lock:
            self.calls += 1
            fail = self.calls <= self.fail_count
        if fail:
            on_complete(ConnectionError(f"injected failure {self.calls}"))
            return
        self.inner.start_fetch(req, on_complete)

    def stop(self):
        self.inner.stop()


def test_fetch_retry_recovers_from_transient_failures(tmp_path):
    # transport errors within the retry budget are retried from offset 0
    # (the reference's connect-retry x5, RDMAClient.cc:41, 235-344) and
    # the merge output is byte-exact
    import functools
    import io

    from tests.helpers import make_mof_tree, map_ids
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.utils import comparators
    from uda_tpu.utils.config import Config
    from uda_tpu.utils.ifile import IFileReader

    job = "jobFlaky"
    expected = make_mof_tree(str(tmp_path), job, 3, 1, 30, seed=81)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    flaky = FlakyClient(LocalFetchClient(engine), fail_count=2)
    try:
        mm = MergeManager(flaky, "uda.tpu.RawBytes", Config())
        blocks = []
        mm.run(job, map_ids(job, 3), 0, lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    want = sorted(expected[0], key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want


def test_fetch_retry_budget_exhaustion_fails(tmp_path):
    from tests.helpers import make_mof_tree, map_ids
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.utils.config import Config

    job = "jobFlaky2"
    make_mof_tree(str(tmp_path), job, 1, 1, 10, seed=82)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    flaky = FlakyClient(LocalFetchClient(engine), fail_count=10**6)
    try:
        mm = MergeManager(flaky, "uda.tpu.RawBytes", Config())
        with pytest.raises(ConnectionError):
            mm.run(job, map_ids(job, 1), 0, lambda b: None)
        # 1 initial + 3 retries (uda.tpu.fetch.retries default)
        assert flaky.calls == 4
    finally:
        engine.stop()


def test_fetch_retry_inline_failures_do_not_recurse(tmp_path):
    # a transport failing INLINE (connect error delivered on the same
    # stack, like HostRoutingClient's connect failure) must be retried
    # iteratively: a huge retry budget may not overflow the stack
    from uda_tpu.merger.segment import Segment

    class InlineFail:
        calls = 0

        def start_fetch(self, req, on_complete):
            InlineFail.calls += 1
            on_complete(ConnectionError("inline"))

    seg = Segment(InlineFail(), "j", "m", 0, 1024, retries=5000)
    seg.start()
    with pytest.raises(ConnectionError):
        seg.wait(timeout=30)
    assert InlineFail.calls == 5001


def test_fetch_sync_raise_fails_segment_not_transport_thread(tmp_path):
    # a transport that RAISES from start_fetch (e.g. DataEngine already
    # stopped) must fail the segment instead of leaking the exception
    # into the completion thread and leaving wait() hanging
    from uda_tpu.merger.segment import Segment
    from uda_tpu.utils.errors import StorageError

    class RaiseClient:
        def start_fetch(self, req, on_complete):
            raise StorageError("engine stopped")

    seg = Segment(RaiseClient(), "j", "m", 0, 1024, retries=2)
    seg.start()
    with pytest.raises(StorageError):
        seg.wait(timeout=30)


def test_auto_approach_picks_by_size_estimate(tmp_path):
    # approach=0: the transport's size estimate routes small partitions
    # to hybrid and large ones to bounded streaming online — assert the
    # PATH taken (the two are byte-identical by design, so output
    # equality alone would not catch an inverted comparison), then the
    # output itself
    import io as _io

    from uda_tpu.utils.ifile import IFileReader as Reader

    expected = make_mof_tree(str(tmp_path), "jobAuto", 4, 1, 60, seed=2)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    try:
        for threshold_mb, want_streaming in ((1 << 20, False), (0, True)):
            cfg = Config({"mapred.netmerger.merge.approach": 0,
                          "uda.tpu.auto.approach.threshold.mb":
                          threshold_mb})
            mm = MergeManager(LocalFetchClient(engine), kt, cfg)
            blocks = []
            mm.run("jobAuto", map_ids("jobAuto", 4), 0,
                   lambda b: blocks.append(bytes(b)))
            took_streaming = getattr(mm, "_active_overlap", None) is not None
            assert took_streaming == want_streaming, threshold_mb
            got = list(Reader(_io.BytesIO(b"".join(blocks))))
            assert got == sorted(expected[0]), threshold_mb
    finally:
        engine.stop()


def test_auto_approach_unknown_size_defaults_to_streaming(tmp_path):
    # a transport without a size estimate must land on the
    # bounded-memory path, not the host-resident one
    import io as _io

    from uda_tpu.merger.merge_manager import MergeManager as MM
    from uda_tpu.merger.segment import InputClient
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.utils.ifile import IFileReader as Reader

    expected = make_mof_tree(str(tmp_path), "jobU", 4, 1, 50, seed=3)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))

    class Blind(LocalFetchClient):
        def estimate_partition_bytes(self, job_id, mids, reduce_id):
            return InputClient.estimate_partition_bytes(
                self, job_id, mids, reduce_id)  # None

    cfg = Config({"mapred.netmerger.merge.approach": 0})
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    mm = MM(Blind(engine), kt, cfg)
    blocks = []
    try:
        mm.run("jobU", map_ids("jobU", 4), 0,
               lambda b: blocks.append(bytes(b)))
        # the streaming path goes through the overlapped merger
        assert getattr(mm, "_active_overlap", None) is not None
    finally:
        engine.stop()
    got = list(Reader(_io.BytesIO(b"".join(blocks))))
    assert got == sorted(expected[0])


def test_truncated_chunk_rejoins_split_record(tmp_path):
    """A truncation failpoint cuts chunks mid-record (satellite of the
    reference's switch_mem/join contract, StreamRW.cc:542-590): the
    carry buffer must re-join each split record with the re-fetched
    remainder exactly — output byte-identical to the unfaulted run."""
    from uda_tpu.utils.failpoints import failpoints

    cfg = Config({"mapred.rdma.buf.size": 1})  # 1 KB chunks
    job = "jobTr"
    expected = make_mof_tree(str(tmp_path), job, 3, 1, 40, seed=51)
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    kt = comparators.get_key_type("uda.tpu.RawBytes")

    def run_once():
        mm = MergeManager(LocalFetchClient(engine), kt, cfg)
        blocks = []
        mm.run(job, map_ids(job, 3), 0, lambda b: blocks.append(bytes(b)))
        return b"".join(blocks)

    try:
        clean = run_once()
        # 37 is coprime to the 57-byte framed record: every truncation
        # lands mid-record, forcing the carry/join path on each re-fetch
        hits0 = failpoints.hits["data_engine.pread"]
        with failpoints.scoped("data_engine.pread=truncate:37:every:2"):
            faulted = run_once()
            assert failpoints.hits["data_engine.pread"] > hits0
    finally:
        engine.stop()
    assert faulted == clean
    got = list(IFileReader(io.BytesIO(faulted)))
    want = sorted(expected[0], key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want
