"""udalint + lockdep tier-1 coverage.

Three layers:

1. per-rule fixtures: every rule (UDA001-UDA008) is proven to FIRE on a
   minimal bad snippet and to stay quiet on the corresponding good
   shape, with injected registries so the fixtures never chase the live
   tables;
2. the suppression contract (``# udalint: disable=...``);
3. the whole-tree clean gate: ``uda_tpu/`` and ``scripts/`` must be
   finding-free — the same gate ``scripts/udalint.py`` (and ci.sh) runs;

plus the dynamic half: TrackedLock/TrackedCondition lockdep unit tests
including the seeded AB/BA inversion fixture (marked ``faults`` so the
chaos tier's lockdep rung re-proves detection under fault schedules).
Fixture inversions use PRIVATE LockDep instances: the process-global
validator must report zero cycles on real code, and a seeded fixture
cycle must never pollute that invariant (or its ``lockdep.cycles``
metric).
"""

from __future__ import annotations

import os
import textwrap
import threading

import pytest

from uda_tpu.analysis.core import Engine, Finding
from uda_tpu.analysis.rules import (ALL_RULES, BlockingInLockRule,
                                    ConfigKeyRule, EventLoopBlockingRule,
                                    FailpointSiteRule, MetricsNameRule,
                                    RawSocketCloseRule,
                                    ReasonStringBranchRule,
                                    SpanNameRule,
                                    SwallowedExceptionRule)
from uda_tpu.utils.locks import LockDep, TrackedCondition, TrackedLock

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NAME_RE = r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+"


def lint(src: str, rules, rel: str = "uda_tpu/x.py") -> list[Finding]:
    return Engine(rules).lint_source(textwrap.dedent(src), rel)


def rule_ids(findings) -> list[str]:
    return [f.rule for f in findings]


# -- UDA001: config keys -----------------------------------------------------


class TestConfigKeyRule:
    RULES = [ConfigKeyRule(flags={"uda.tpu.known", "mapred.known.key"})]

    def test_undeclared_key_fires(self):
        out = lint('cfg.get("uda.tpu.un.known")\n', self.RULES)
        assert rule_ids(out) == ["UDA001"]
        assert "uda.tpu.un.known" in out[0].message

    def test_declared_keys_pass(self):
        out = lint('cfg.get("uda.tpu.known")\n'
                   'cfg.set("mapred.known.key", 1)\n', self.RULES)
        assert out == []

    def test_docstrings_and_prose_skipped(self):
        out = lint('"""Talks about uda.tpu.un.known at length."""\n'
                   'x = "see the uda.tpu.un.known knob"\n', self.RULES)
        assert out == []  # docstring + non-key-shaped prose

    def test_mapred_prefix_checked_too(self):
        out = lint('cfg.get("mapred.not.a.key")\n', self.RULES)
        assert rule_ids(out) == ["UDA001"]

    def test_suppression_silences(self):
        out = lint('cfg.get("uda.tpu.un.known")  '
                   '# udalint: disable=UDA001\n', self.RULES)
        assert out == []


# -- UDA002: metrics names ---------------------------------------------------


class TestMetricsNameRule:
    def rules(self):
        return [MetricsNameRule(registry={"fetch.bytes"},
                                prefixes=("failpoint.",),
                                name_re=NAME_RE)]

    def test_registered_literal_passes(self):
        assert lint('metrics.add("fetch.bytes", 4)\n', self.rules()) == []

    def test_unregistered_literal_fires(self):
        out = lint('metrics.add("nope.metric")\n', self.rules())
        assert rule_ids(out) == ["UDA002"]
        assert out[0].data["reason"] == "not listed in METRICS_REGISTRY"

    def test_bad_namespace_fires(self):
        out = lint('metrics.gauge("NotDotted", 1)\n', self.rules())
        assert out[0].data["reason"] == "not dotted domain.metric namespace"

    def test_non_literal_name_fires(self):
        out = lint('metrics.observe(some_var, 1)\n', self.rules())
        assert "string literal" in out[0].data["reason"]

    def test_fstring_prefix_family(self):
        good = lint('metrics.add(f"failpoint.{site}")\n', self.rules())
        assert good == []
        bad = lint('metrics.add(f"mystery.{site}")\n', self.rules())
        assert rule_ids(bad) == ["UDA002"]

    def test_aliased_receiver_caught(self):
        # the old regex engine ONLY matched the spelling `metrics.` —
        # an import alias walked straight past it
        src = """
        from uda_tpu.utils.metrics import metrics as m
        m.add("nope.metric")
        """
        assert rule_ids(lint(src, self.rules())) == ["UDA002"]

    def test_assigned_alias_caught(self):
        src = """
        from uda_tpu.utils.metrics import metrics
        hub = metrics
        hub.gauge_add("nope.metric", 1)
        """
        assert rule_ids(lint(src, self.rules())) == ["UDA002"]

    def test_multiline_call_caught(self):
        # the other regex blind spot: the name on a continuation line
        src = """
        metrics.add(
            "nope.metric",
            42)
        """
        assert rule_ids(lint(src, self.rules())) == ["UDA002"]

    def test_set_add_not_confused(self):
        assert lint('seen.add("anything at all")\n', self.rules()) == []


# -- UDA003: failpoint sites -------------------------------------------------


class TestFailpointSiteRule:
    RULES = [FailpointSiteRule(sites={"good.site"})]

    def test_registered_site_passes(self):
        assert lint('failpoint("good.site", key="k")\n', self.RULES) == []

    def test_unknown_site_fires(self):
        out = lint('failpoint("typo.site")\n', self.RULES)
        assert rule_ids(out) == ["UDA003"]

    def test_dynamic_site_fires(self):
        out = lint('failpoint(site_var)\n', self.RULES)
        assert rule_ids(out) == ["UDA003"]

    def test_live_inventory_matches_tree(self):
        # the default-constructed rule loads KNOWN_SITES; every real
        # call site must resolve (this is the live half of the gate)
        from uda_tpu.utils.failpoints import KNOWN_SITES
        assert "segment.fetch" in KNOWN_SITES


# -- UDA004: raw socket close in net/ ----------------------------------------


class TestRawSocketCloseRule:
    RULES = [RawSocketCloseRule()]

    def test_raw_close_in_net_fires(self):
        out = lint("sock.close()\n", self.RULES,
                   rel="uda_tpu/net/server.py")
        assert rule_ids(out) == ["UDA004"]

    def test_close_hard_passes(self):
        out = lint("wire.close_hard(sock)\n", self.RULES,
                   rel="uda_tpu/net/server.py")
        assert out == []

    def test_wire_py_exempt(self):
        # close_hard's own implementation must be allowed to close
        out = lint("sock.close()\n", self.RULES, rel="uda_tpu/net/wire.py")
        assert out == []

    def test_outside_net_exempt(self):
        out = lint("sock.close()\n", self.RULES,
                   rel="uda_tpu/merger/segment.py")
        assert out == []

    def test_self_sock_attribute_fires(self):
        out = lint("self._sock.close()\n", self.RULES,
                   rel="uda_tpu/net/client.py")
        assert rule_ids(out) == ["UDA004"]


# -- UDA005: reason-string branching -----------------------------------------


class TestReasonStringBranchRule:
    RULES = [ReasonStringBranchRule()]

    def test_str_exception_membership_fires(self):
        src = """
        try:
            work()
        except Exception as e:
            if "timed out" in str(e):
                retry()
        """
        assert rule_ids(lint(src, self.RULES)) == ["UDA005"]

    def test_str_exception_equality_fires(self):
        src = """
        try:
            work()
        except Exception as e:
            if str(e) == "pool exhausted":
                backoff()
        """
        assert rule_ids(lint(src, self.RULES)) == ["UDA005"]

    def test_str_exception_startswith_fires(self):
        src = """
        try:
            work()
        except Exception as e:
            if str(e).startswith("supplier read pool"):
                backoff()
        """
        assert rule_ids(lint(src, self.RULES)) == ["UDA005"]

    def test_reason_attr_compare_fires(self):
        src = 'retry = adm.reason == "over the host budget"\n'
        assert rule_ids(lint(src, self.RULES)) == ["UDA005"]

    def test_cause_enum_compare_passes(self):
        src = 'bounded = adm.cause == "hbm"\n'
        assert lint(src, self.RULES) == []

    def test_str_of_non_exception_passes(self):
        src = 'ok = str(port) == "9012"\n'
        assert lint(src, self.RULES) == []


# -- UDA006: swallowed exceptions --------------------------------------------


class TestSwallowedExceptionRule:
    RULES = [SwallowedExceptionRule()]

    def test_silent_swallow_fires(self):
        src = """
        try:
            work()
        except Exception:
            pass
        """
        assert rule_ids(lint(src, self.RULES)) == ["UDA006"]

    def test_bare_except_fires(self):
        src = """
        try:
            work()
        except:
            return None
        """
        assert rule_ids(lint(src, self.RULES)) == ["UDA006"]

    def test_logged_passes(self):
        src = """
        try:
            work()
        except Exception as e:
            log.warn(f"best effort: {e}")
        """
        assert lint(src, self.RULES) == []

    def test_counted_passes(self):
        src = """
        try:
            work()
        except Exception:
            metrics.add("errors.swallowed")
        """
        assert lint(src, self.RULES) == []

    def test_reraise_passes(self):
        src = """
        try:
            work()
        except Exception:
            cleanup()
            raise
        """
        assert lint(src, self.RULES) == []

    def test_forwarded_exception_passes(self):
        src = """
        try:
            work()
        except Exception as e:
            on_complete(e)
        """
        assert lint(src, self.RULES) == []

    def test_narrow_handler_exempt(self):
        src = """
        try:
            work()
        except OSError:
            pass
        """
        assert lint(src, self.RULES) == []

    def test_suppression_silences(self):
        src = """
        try:
            work()
        except Exception:  # udalint: disable=UDA006
            pass
        """
        assert lint(src, self.RULES) == []


# -- UDA007: blocking under a lock -------------------------------------------


class TestBlockingInLockRule:
    RULES = [BlockingInLockRule()]

    def test_bare_result_under_lock_fires(self):
        src = """
        with self._lock:
            data = fut.result()
        """
        out = lint(src, self.RULES)
        assert rule_ids(out) == ["UDA007"]
        assert "result" in out[0].message

    def test_bounded_result_passes(self):
        src = """
        with self._lock:
            data = fut.result(timeout=5.0)
        """
        assert lint(src, self.RULES) == []

    def test_queue_get_under_lock_fires(self):
        src = """
        with done_lock:
            item = outq.get()
        """
        assert rule_ids(lint(src, self.RULES)) == ["UDA007"]

    def test_dict_get_not_confused(self):
        src = """
        with self._lock:
            v = table.get(key)
        """
        assert lint(src, self.RULES) == []

    def test_unbounded_wait_under_cv_fires(self):
        src = """
        with self._cv:
            while not ready:
                self._cv.wait()
        """
        assert rule_ids(lint(src, self.RULES)) == ["UDA007"]

    def test_bounded_wait_passes(self):
        src = """
        with self._cv:
            while not ready:
                self._cv.wait(timeout=0.25)
        """
        assert lint(src, self.RULES) == []

    def test_recv_under_lock_fires(self):
        src = """
        with self._wlock:
            data = sock.recv(4096)
        """
        assert rule_ids(lint(src, self.RULES)) == ["UDA007"]

    def test_non_lock_with_exempt(self):
        src = """
        with open(path) as f:
            data = fut.result()
        """
        assert lint(src, self.RULES) == []

    def test_deferred_code_exempt(self):
        # a callback DEFINED under the lock does not RUN under it
        src = """
        with self._lock:
            def cb(f):
                return f.result()
            fut.add_done_callback(cb)
        """
        assert lint(src, self.RULES) == []


# -- UDA008: blocking in event-loop callbacks --------------------------------


class TestEventLoopBlockingRule:
    RULES = [EventLoopBlockingRule()]
    NET = "uda_tpu/net/x.py"

    def test_sendall_in_callback_fires(self):
        src = """
        @loop_callback
        def _on_event(self, mask):
            self.sock.sendall(frame)
        """
        out = lint(src, self.RULES, rel=self.NET)
        assert rule_ids(out) == ["UDA008"]
        assert "sendall" in out[0].message

    def test_blocking_recv_in_callback_fires(self):
        src = """
        @loop_callback
        def _on_event(self, mask):
            data = self.sock.recv(4096)
        """
        assert rule_ids(lint(src, self.RULES, rel=self.NET)) == ["UDA008"]

    def test_unbounded_result_in_callback_fires(self):
        src = """
        @loop_callback
        def _on_engine_done(self, f):
            res = f.result()
        """
        assert rule_ids(lint(src, self.RULES, rel=self.NET)) == ["UDA008"]

    def test_unbounded_queue_get_in_callback_fires(self):
        src = """
        @loop_callback
        def _drain(self):
            item = self.outq.get()
        """
        assert rule_ids(lint(src, self.RULES, rel=self.NET)) == ["UDA008"]

    def test_nonblocking_forms_pass(self):
        src = """
        @loop_callback
        def _on_event(self, mask):
            n = self.sock.recv_into(self._rbuf)
            sent = self.sock.send(mv)
            sent2 = self.sock.sendmsg(bufs)
            res = f.result(timeout=0)
            item = self.outq.get(timeout=0.25)
            v = table.get(key)
        """
        assert lint(src, self.RULES, rel=self.NET) == []

    def test_loop_thread_itself_exempt(self):
        # the run loop is not a REGISTERED callback: parking in
        # select() (and blocking on its own queues) is its job
        src = """
        def _run(self):
            while True:
                events = self._sel.select(timeout=0.25)
                item = self._dispatchq.get()
        """
        assert lint(src, self.RULES, rel=self.NET) == []

    def test_outside_net_exempt(self):
        src = """
        @loop_callback
        def _on_event(self, mask):
            self.sock.sendall(frame)
        """
        assert lint(src, self.RULES, rel="uda_tpu/merger/x.py") == []

    def test_deferred_code_exempt(self):
        # a function DEFINED in a callback does not RUN on the loop
        src = """
        @loop_callback
        def _on_event(self, mask):
            def later(f):
                return f.result()
            fut.add_done_callback(later)
        """
        assert lint(src, self.RULES, rel=self.NET) == []

    def test_decorator_attribute_form_caught(self):
        src = """
        @evloop.loop_callback
        def _on_event(self, mask):
            self.sock.sendall(frame)
        """
        assert rule_ids(lint(src, self.RULES, rel=self.NET)) == ["UDA008"]


# -- UDA009: span names ------------------------------------------------------


class TestSpanNameRule:
    def rules(self):
        return [SpanNameRule(registry={"net.serve", "reduce_task"})]

    def test_registered_literal_passes(self):
        src = ('metrics.start_span("net.serve", map=m)\n'
               'with metrics.span("reduce_task", job=j):\n'
               '    pass\n')
        assert lint(src, self.rules()) == []

    def test_unregistered_name_fires(self):
        out = lint('metrics.start_span("net.sreve")\n', self.rules())
        assert rule_ids(out) == ["UDA009"]
        assert "net.sreve" in out[0].message

    def test_span_context_manager_checked_too(self):
        out = lint('with metrics.span("nope.span"):\n    pass\n',
                   self.rules())
        assert rule_ids(out) == ["UDA009"]

    def test_non_literal_name_fires(self):
        out = lint('metrics.start_span(some_name)\n', self.rules())
        assert rule_ids(out) == ["UDA009"]
        assert "string literal" in out[0].message

    def test_aliased_receiver_tracked(self):
        src = ('from uda_tpu.utils.metrics import metrics as m\n'
               'm.span("nope.span")\n')
        assert rule_ids(lint(src, self.rules())) == ["UDA009"]

    def test_unrelated_receivers_and_methods_pass(self):
        src = ('tracer.start_span("whatever")\n'  # not the hub
               'metrics.timer("merge")\n'         # timer names exempt
               'metrics.use_span(span)\n')        # takes a Span object
        assert lint(src, self.rules()) == []

    def test_suppression_silences(self):
        src = ('metrics.start_span("nope.span")  '
               '# udalint: disable=UDA009\n')
        assert lint(src, self.rules()) == []


# -- engine plumbing ---------------------------------------------------------


class TestEngine:
    def test_parse_error_is_a_finding(self):
        out = Engine([ConfigKeyRule(flags=set())]).lint_source(
            "def broken(:\n", "uda_tpu/broken.py")
        assert rule_ids(out) == ["UDA000"]

    def test_disable_all_silences_every_rule(self):
        src = ('metrics.add("nope.metric")  # udalint: disable=all\n')
        rules = [MetricsNameRule(registry=set(), prefixes=(),
                                 name_re=NAME_RE)]
        assert lint(src, rules) == []

    def test_findings_sorted_and_rendered(self):
        src = 'cfg.get("uda.tpu.zzz.bad")\ncfg.get("mapred.aaa.bad")\n'
        out = lint(src, [ConfigKeyRule(flags=set())])
        assert [f.line for f in out] == [1, 2]
        assert "uda_tpu/x.py:1:" in out[0].render()
        assert "[fix:" in out[0].render()


# -- the whole-tree clean gate (the same gate ci.sh runs) --------------------


def test_tree_clean():
    findings = Engine([cls() for cls in ALL_RULES], root=REPO).lint_paths(
        [os.path.join(REPO, "uda_tpu"), os.path.join(REPO, "scripts")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_check_metrics_names_wrapper_contract(tmp_path):
    """The old CLI's check() contract survives the AST port: tuples of
    (file, line, name, reason), aliased receivers now included."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_metrics_names",
        os.path.join(REPO, "scripts", "check_metrics_names.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    bad = tmp_path / "bad.py"
    bad.write_text("from uda_tpu.utils.metrics import metrics as m\n"
                   "m.add(\n    'not.registered')\n")
    violations = mod.check(root=str(tmp_path))
    assert len(violations) == 1
    _, line, name, reason = violations[0]
    assert (line, name) == (3, "not.registered")
    assert reason == "not listed in METRICS_REGISTRY"


# -- lockdep: the dynamic half -----------------------------------------------


@pytest.mark.faults
def test_lockdep_detects_seeded_ab_ba_inversion():
    """The seeded AB/BA fixture: two lock classes taken in opposite
    orders by two code paths. No actual deadlock is provoked (the
    acquisitions are sequential) — lockdep must flag the ORDER, which
    is exactly what makes it useful before the unlucky scheduling."""
    dep = LockDep(enabled=True)  # private: the global stays cycle-free
    a = TrackedLock("fixture.A", dep=dep)
    b = TrackedLock("fixture.B", dep=dep)

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t = threading.Thread(target=ab)
    t.start()
    t.join()
    ba()
    assert len(dep.cycles) == 1
    cyc = dep.cycles[0]
    assert cyc["kind"] == "order-inversion"
    assert {"fixture.A", "fixture.B"} <= set(cyc["path"])
    # both stacks present: the current acquire and the first-seen edge
    assert any("(now)" in k for k in cyc["stacks"])
    assert any(v for k, v in cyc["stacks"].items() if "(now)" not in k)
    # dedup: replaying the same inversion does not re-report
    ba()
    assert len(dep.cycles) == 1


def test_lockdep_consistent_order_is_clean():
    dep = LockDep(enabled=True)
    a = TrackedLock("x.outer", dep=dep)
    b = TrackedLock("x.inner", dep=dep)
    for _ in range(3):
        with a:
            with b:
                pass
    assert dep.cycles == []


def test_lockdep_same_class_nesting_not_an_edge():
    """Two INSTANCES of one class held together is legitimate (an
    instance hierarchy); only same-INSTANCE re-acquisition reports."""
    dep = LockDep(enabled=True)
    s1 = TrackedLock("seg", dep=dep)
    s2 = TrackedLock("seg", dep=dep)
    with s1:
        with s2:
            pass
    assert dep.cycles == []


def test_lockdep_self_deadlock_reported_before_blocking():
    dep = LockDep(enabled=True)
    s = TrackedLock("solo", dep=dep)
    assert s.acquire()
    try:
        # the re-acquire WILL fail (non-reentrant) — the report must be
        # written before the wait, or a real wedge would never log it
        assert s.acquire(timeout=0.05) is False
        assert len(dep.cycles) == 1
        assert dep.cycles[0]["kind"] == "self-deadlock"
    finally:
        s.release()


def test_tracked_condition_wait_releases_the_hold():
    """A waiter parked in cv.wait must NOT count as holding the lock:
    another thread can take it (that is what wait means), and lockdep's
    held stack must agree or every wake pattern would false-cycle."""
    dep = LockDep(enabled=True)
    lock = TrackedLock("cv.lock", dep=dep)
    cv = TrackedCondition(lock)
    entered = threading.Event()
    released = threading.Event()

    def waiter():
        with cv:
            entered.set()
            cv.wait(timeout=5.0)

    t = threading.Thread(target=waiter)
    t.start()
    assert entered.wait(timeout=5.0)
    # while the waiter sits in wait(), the lock is takeable...
    assert lock.acquire(timeout=2.0)
    # ...and the waiter's held table shows nothing held
    held = dep.held_by_thread()
    assert all("cv.lock" not in classes
               for who, classes in held.items()
               if str(t.ident) in who)
    cv.notify_all()  # legal: this thread holds the raw lock via `lock`
    lock.release()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert dep.cycles == []


def test_tracked_lock_disabled_is_a_plain_lock():
    dep = LockDep(enabled=False)
    a = TrackedLock("off.a", dep=dep)
    b = TrackedLock("off.b", dep=dep)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert dep.cycles == []  # not watching
    assert dep._edges == {}


@pytest.mark.faults
def test_lockdep_emit_metrics_and_json_report(tmp_path, monkeypatch):
    """The chaos rung's reporting channel: an emitting LockDep counts
    ``lockdep.cycles`` and appends the report to UDA_TPU_LOCKDEP_JSON
    (run_chaos.sh folds that file into CHAOS_TELEMETRY.json)."""
    import json

    from uda_tpu.utils.metrics import metrics

    out = tmp_path / "cycles.jsonl"
    monkeypatch.setenv("UDA_TPU_LOCKDEP_JSON", str(out))
    before = metrics.get("lockdep.cycles")
    dep = LockDep(enabled=True, emit_metrics=True)
    a = TrackedLock("emit.A", dep=dep)
    b = TrackedLock("emit.B", dep=dep)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    try:
        assert metrics.get("lockdep.cycles") == before + 1
        rep = json.loads(out.read_text().strip())
        assert rep["kind"] == "order-inversion"
        assert {"emit.A", "emit.B"} <= set(rep["path"])
    finally:
        # the fixture's synthetic cycle must not leak into the session
        # telemetry: the chaos rung's "cycles on real code" field sums
        # this very counter across the run (conftest accumulation)
        metrics.reset()


def test_watchdog_dump_includes_lock_table():
    from uda_tpu.utils.locks import lockdep
    from uda_tpu.utils.watchdog import dump_diagnostics

    was = lockdep.enabled
    lockdep.enabled = True
    try:
        hold = TrackedLock("dump.probe")
        with hold:
            dump = dump_diagnostics("test")
        assert "tracked locks held" in dump
        assert "dump.probe" in dump
    finally:
        lockdep.enabled = was
        lockdep.reset()
