"""Failpoint framework + retrying fetch path: injection grammar and
triggers, backoff/timeout/deadline policy, per-chunk CRC, the penalty
box, and the FallbackSignal contract — the failure scenarios the
reference could only reach on a broken cluster (SURVEY §4.5), now
reachable, injectable, and survived."""

import functools
import io
import threading
import time

import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.bridge import Cmd, UdaBridge, form_cmd
from uda_tpu.merger import (HostRoutingClient, LocalFetchClient,
                            MergeManager, PenaltyBox, Segment)
from uda_tpu.mofserver import DataEngine, DirIndexResolver, FetchResult
from uda_tpu.utils import comparators
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import (ConfigError, FallbackSignal, StorageError,
                                  TransportError, UdaError)
from uda_tpu.utils.failpoints import (FailpointRegistry, chaos_spec,
                                      failpoint, failpoints)
from uda_tpu.utils.ifile import IFileReader, write_records
from uda_tpu.utils.metrics import metrics
from uda_tpu.utils.retry import RetryPolicy


# -- spec grammar + triggers -------------------------------------------------


def test_spec_parse_and_every_trigger():
    r = FailpointRegistry()
    r.arm("s.site", "error:every:3")
    for i in range(1, 10):
        if i % 3 == 0:
            with pytest.raises(UdaError) as ei:
                r.evaluate("s.site", None, "")
            assert "s.site" in str(ei.value)
            assert ei.value.failpoint_site == "s.site"
        else:
            assert r.evaluate("s.site", None, "") is None
    assert r.hits["s.site"] == 3


def test_once_and_match_triggers():
    r = FailpointRegistry()
    r.arm("s", "error:once:match:m_0002")
    assert r.evaluate("s", None, "m_0001/0") is None  # key mismatch
    with pytest.raises(UdaError):
        r.evaluate("s", None, "m_0002/0")
    assert r.evaluate("s", None, "m_0002/0") is None  # one-shot spent
    assert r.hits["s"] == 1


def test_prob_trigger_is_seeded_deterministic():
    def fires(reg):
        out = []
        for _ in range(50):
            try:
                reg.evaluate("p", None, "")
                out.append(False)
            except UdaError:
                out.append(True)
        return out

    a, b = FailpointRegistry(), FailpointRegistry()
    a.arm("p", "error:prob:0.3:seed:7")
    b.arm("p", "error:prob:0.3:seed:7")
    pattern = fires(a)
    assert pattern == fires(b)
    assert 0 < sum(pattern) < 50


def test_truncate_and_corrupt_actions():
    r = FailpointRegistry()
    r.arm("t", "truncate:4")
    assert r.evaluate("t", b"abcdefgh", "") == b"abcd"
    assert r.evaluate("t", b"ab", "") == b"a"  # never truncates to empty
    r.arm("c", "corrupt:2:seed:5")
    data = b"x" * 64
    out = r.evaluate("c", data, "")
    assert len(out) == 64 and out != data
    # data-less sites pass truncate/corrupt through untouched
    assert r.evaluate("t", None, "") is None


def test_error_kind_override_and_delay():
    r = FailpointRegistry()
    r.arm("k", "error:transport")
    with pytest.raises(TransportError):
        r.evaluate("k", None, "")
    r.arm("d", "delay:30")
    t0 = time.monotonic()
    r.evaluate("d", None, "")
    assert time.monotonic() - t0 >= 0.02


def test_arm_spec_scoped_and_bad_specs():
    with failpoints.scoped("a.b=error:every:2,c.d=delay:1"):
        assert set(failpoints.active()) >= {"a.b", "c.d"}
        assert failpoints.active()["a.b"] == "error:every:2"
    assert "a.b" not in failpoints.active()
    for bad in ("a.b", "a.b=nonsense", "a.b=error:every",
                "a.b=delay", "a.b=error:bogus_tok"):
        with pytest.raises(ConfigError):
            failpoints.arm_spec(bad)


def test_chaos_spec_reproducible_and_parseable():
    assert chaos_spec(123) == chaos_spec(123)
    r = FailpointRegistry()
    for seed in range(20):
        r.arm_spec(chaos_spec(seed))  # every generated schedule parses


# -- retry policy ------------------------------------------------------------


def test_backoff_exponential_capped_and_jittered():
    p = RetryPolicy(backoff_ms=10, backoff_max_ms=50, jitter=0.0)
    assert [p.backoff(a) for a in (1, 2, 3, 4)] == \
        [0.010, 0.020, 0.040, 0.050]
    assert RetryPolicy().backoff(3) == 0.0  # default: immediate retry
    import random as _r
    pj = RetryPolicy(backoff_ms=100, jitter=0.5)
    vals = {pj.backoff(1, _r.Random(i)) for i in range(10)}
    assert len(vals) > 1
    assert all(0.05 <= v <= 0.15 for v in vals)


class _DropFirst:
    """Transport that never completes its first fetch (a wedged
    supplier), then serves normally."""

    def __init__(self, payload):
        self.payload = payload
        self.calls = 0
        self.dropped = []

    def start_fetch(self, req, on_complete):
        self.calls += 1
        if self.calls == 1:
            self.dropped.append(on_complete)  # black hole
            return
        n = len(self.payload)
        on_complete(FetchResult(self.payload, n, n, 0, "p", last=True))


def test_attempt_timeout_retries_and_drops_stale_completion():
    payload = write_records([(b"k1", b"v1"), (b"k2", b"v2")])
    client = _DropFirst(payload)
    seg = Segment(client, "j", "m", 0, 1 << 20,
                  policy=RetryPolicy(retries=2, attempt_timeout_ms=60))
    before = metrics.snapshot()
    seg.start()
    seg.wait(timeout=10)
    assert seg.num_records == 2 and client.calls == 2
    assert metrics.get("fetch.timeouts") > before.get("fetch.timeouts", 0)
    # the wedged attempt finally "completes": it must be dropped as
    # stale, not double-ingested into the finished segment
    n = len(payload)
    client.dropped[0](FetchResult(payload, n, n, 0, "p", last=True))
    assert seg.num_records == 2
    assert metrics.get("fetch.stale_completions") > \
        before.get("fetch.stale_completions", 0)


def test_deadline_gives_up_before_retry_budget():
    class AlwaysFail:
        calls = 0

        def start_fetch(self, req, on_complete):
            AlwaysFail.calls += 1
            on_complete(ConnectionError("down"))

    before = metrics.get("fetch.deadline_exceeded")
    seg = Segment(AlwaysFail(), "j", "m", 0, 1024,
                  policy=RetryPolicy(retries=10_000, backoff_ms=20,
                                     backoff_max_ms=40, jitter=0.0,
                                     deadline_ms=150))
    t0 = time.monotonic()
    seg.start()
    with pytest.raises(ConnectionError):
        seg.wait(timeout=10)
    assert time.monotonic() - t0 < 5.0
    assert AlwaysFail.calls < 100  # deadline cut the budget short
    assert metrics.get("fetch.deadline_exceeded") > before


def test_backoff_does_not_block_completion_thread():
    # the retry must be re-issued from a timer, so the thread that
    # delivered the failure is free immediately (a transport worker
    # blocked in a sleeping retry is the pool-deadlock shape)
    threads = []

    class FailOnce:
        calls = 0

        def __init__(self, payload):
            self.payload = payload

        def start_fetch(self, req, on_complete):
            FailOnce.calls += 1
            if FailOnce.calls == 1:
                on_complete(ConnectionError("transient"))
                return
            threads.append(threading.current_thread().name)
            n = len(self.payload)
            on_complete(FetchResult(self.payload, n, n, 0, "p", last=True))

    payload = write_records([(b"k", b"v")])
    seg = Segment(FailOnce(payload), "j", "m", 0, 1 << 20,
                  policy=RetryPolicy(retries=3, backoff_ms=20, jitter=0.0))
    t0 = time.monotonic()
    seg.start()
    assert time.monotonic() - t0 < 0.015  # start() returned pre-backoff
    seg.wait(timeout=10)
    assert seg.num_records == 1


# -- penalty box -------------------------------------------------------------


def test_penalty_box_threshold_expiry_forgive():
    box = PenaltyBox(threshold=2, penalty_s=0.05)
    assert not box.punish("h")          # first fault: under threshold
    assert not box.penalized("h")
    assert box.punish("h")              # second fault: boxed
    assert box.penalized("h") and box.boxed == ["h"]
    time.sleep(0.06)
    assert not box.penalized("h")       # parole
    assert box.punish("h")              # one more fault re-boxes
    box.forgive("h")
    # forgiveness DECAYS one step (faults 2 -> 1): unboxed, but one
    # more fault re-boxes immediately — a flapping supplier cannot
    # oscillate out of the box on a single lucky fetch
    assert not box.penalized("h") and box.faults("h") == 1
    assert box.punish("h") and box.penalized("h")


def test_penalty_box_decay_and_full_reset_after_streak():
    box = PenaltyBox(threshold=2, penalty_s=60.0, reset_successes=3)
    box.punish("h")
    box.punish("h")
    box.punish("h")                     # faults=3, boxed
    assert box.penalized("h")
    box.forgive("h")                    # decay -> 2: still >= threshold,
    assert box.faults("h") == 2        # but the active box is kept only
    box.forgive("h")                    # while over it...
    assert box.faults("h") == 1 and not box.penalized("h")
    box.forgive("h")                    # 3rd CONSECUTIVE success: clear
    assert box.faults("h") == 0 and not box.penalized("h")
    # a fault mid-streak restarts the streak
    box.punish("h")
    box.punish("h")
    box.forgive("h")
    box.punish("h")                     # streak broken at 1
    box.forgive("h")
    box.forgive("h")
    assert box.faults("h") == 0        # cleared by streak, not decay


def test_penalty_box_rank_orders_by_health():
    box = PenaltyBox(threshold=2, penalty_s=60.0)
    box.punish("sick")
    box.punish("sick")                  # boxed
    box.punish("meh")                   # one fault, unboxed
    assert box.rank(["sick", "meh", "ok"]) == ["ok", "meh", "sick"]
    # stable within a tier: caller preference breaks ties
    assert box.rank(["b", "a"]) == ["b", "a"]


def test_penalty_box_deprioritizes_sick_supplier(tmp_path):
    """A host whose fetches fault gets its remaining maps rotated to the
    back of the schedule; the run still completes correctly."""
    root = str(tmp_path)
    expected = make_mof_tree(root, "jobP", 6, 1, 30, seed=3)
    engine = DataEngine(DirIndexResolver(root), Config())
    faulted = []
    lock = threading.Lock()

    class FlakyB(LocalFetchClient):
        """Faults the first fetch of every map, inline (so the box is
        set before the scheduler's next pick), and delivers successful
        completions late (so forgiveness cannot race the scheduler out
        of ever observing a penalized head)."""

        def start_fetch(self, req, on_complete):
            with lock:
                first = req.map_id not in faulted
                if first:
                    faulted.append(req.map_id)
            if first:
                on_complete(TransportError(f"hostB flake {req.map_id}"))
                return

            def late(res):
                t = threading.Timer(0.05, on_complete, args=(res,))
                t.daemon = True
                t.start()

            super().start_fetch(req, late)

    hosts = {"hostA": LocalFetchClient(engine), "hostB": FlakyB(engine)}
    router = HostRoutingClient(lambda h: hosts[h])
    cfg = Config({"mapred.rdma.wqe.per.conn": 2,
                  "uda.tpu.fetch.penalty.threshold": 1,
                  "uda.tpu.fetch.penalty.ms": 60_000})
    mids = map_ids("jobP", 6)
    maps = [("hostA", m) for m in mids[:2]] + [("hostB", m) for m in mids[2:]]
    before = metrics.snapshot()
    try:
        mm = MergeManager(router, "uda.tpu.RawBytes", cfg)
        blocks = []
        mm.run("jobP", maps, 0, lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    assert metrics.get("fetch.penalties") > before.get("fetch.penalties", 0)
    assert metrics.get("fetch.deprioritized") > \
        before.get("fetch.deprioritized", 0)
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    want = sorted(expected[0], key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want


@pytest.mark.faults
def test_retry_and_penalty_counters_labeled_by_supplier(tmp_path):
    """Observability over the PR-1 recovery layer: retries and penalties
    appear as per-supplier labeled series (and the labeled series sum to
    the unlabeled totals the older tests assert on)."""
    root = str(tmp_path)
    make_mof_tree(root, "jobLab", 4, 1, 20, seed=5)
    engine = DataEngine(DirIndexResolver(root), Config())
    faulted = set()
    lock = threading.Lock()

    class FlakySick(LocalFetchClient):
        """hostSick faults every map's first fetch; hostOk never."""

        def start_fetch(self, req, on_complete):
            with lock:
                first = req.map_id not in faulted
                faulted.add(req.map_id)
            if first:
                on_complete(TransportError(f"sick {req.map_id}"))
                return
            super().start_fetch(req, on_complete)

    hosts = {"hostOk": LocalFetchClient(engine), "hostSick": FlakySick(engine)}
    router = HostRoutingClient(lambda h: hosts[h])
    cfg = Config({"mapred.rdma.wqe.per.conn": 2,
                  "uda.tpu.fetch.penalty.threshold": 1,
                  "uda.tpu.fetch.penalty.ms": 50})
    mids = map_ids("jobLab", 4)
    maps = [("hostOk", m) for m in mids[:2]] + \
           [("hostSick", m) for m in mids[2:]]
    blocks = []
    try:
        # the exact-zero hostOk assertions below are about THIS test's
        # own injected faults; an ambient chaos-rung pread error is
        # indistinguishable from supplier sickness, so the scope pins
        # that one site out (restored, trigger state intact, on exit)
        with failpoints.scoped(""):
            failpoints.disarm("data_engine.pread")
            mm = MergeManager(router, "uda.tpu.RawBytes", cfg)
            mm.run("jobLab", maps, 0, lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    assert blocks
    # the sick supplier's series carries its retries and penalties...
    assert metrics.get("fetch.retries", supplier="hostSick") >= 2
    assert metrics.get("fetch.penalties", supplier="hostSick") >= 1
    # ...the healthy one's carries none...
    assert metrics.get("fetch.retries", supplier="hostOk") == 0
    assert metrics.get("fetch.penalties", supplier="hostOk") == 0
    # ...and series sum to the totals the PR-1 assertions read
    snap = metrics.snapshot()
    for base in ("fetch.retries", "fetch.penalties"):
        series = [v for k, v in snap.items()
                  if k.startswith(base + "{")]
        assert sum(series) == snap[base]
    # labeled fetch.bytes exists for both suppliers (the data did move)
    assert metrics.get("fetch.bytes", supplier="hostOk") > 0
    assert metrics.get("fetch.bytes", supplier="hostSick") > 0


# -- acceptance: faulted runs survive or fall back cleanly -------------------


def _sorted_expected(expected, kt):
    return sorted(expected, key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))


def test_every_third_pread_fails_run_is_byte_identical(tmp_path):
    """The ISSUE acceptance scenario: data_engine.pread armed to fail
    every 3rd call, >= 8 segments, byte-identical output vs the
    unfaulted run, fetch.retries > 0."""
    root = str(tmp_path)
    make_mof_tree(root, "jobFp", 8, 1, 50, seed=21)
    cfg = Config({"uda.tpu.fetch.retries": 10,
                  "mapred.rdma.wqe.per.conn": 2})
    engine = DataEngine(DirIndexResolver(root), cfg)

    def run_once():
        mm = MergeManager(LocalFetchClient(engine), "uda.tpu.RawBytes", cfg)
        blocks = []
        mm.run("jobFp", map_ids("jobFp", 8), 0,
               lambda b: blocks.append(bytes(b)))
        return b"".join(blocks)

    try:
        clean = run_once()
        before = metrics.get("fetch.retries")
        hits0 = failpoints.hits["data_engine.pread"]
        with failpoints.scoped("data_engine.pread=error:every:3"):
            faulted = run_once()
            assert failpoints.hits["data_engine.pread"] > hits0
    finally:
        engine.stop()
    assert faulted == clean
    assert metrics.get("fetch.retries") > before


def test_permanent_supplier_fault_raises_fallback_signal(tmp_path):
    """Retries exhausted on one supplier: FallbackSignal whose cause
    names the failing site; no hang, no partial output."""
    root = str(tmp_path)
    make_mof_tree(root, "jobPerm", 8, 1, 30, seed=22)
    engine = DataEngine(DirIndexResolver(root), Config())
    blocks = []
    try:
        mm = MergeManager(LocalFetchClient(engine), "uda.tpu.RawBytes")
        with failpoints.scoped("data_engine.pread=error:match:m_000002"):
            t0 = time.monotonic()
            with pytest.raises(FallbackSignal) as ei:
                mm.run("jobPerm", map_ids("jobPerm", 8), 0,
                       lambda b: blocks.append(bytes(b)))
            assert time.monotonic() - t0 < 60
    finally:
        engine.stop()
    assert blocks == []  # no partial output reached the consumer
    assert isinstance(ei.value.cause, StorageError)
    assert "data_engine.pread" in str(ei.value.cause)
    assert ei.value.__cause__ is ei.value.cause  # backtrace chain intact
    assert ei.value.cause.backtrace


def test_crc_catches_corruption_and_refetches(tmp_path):
    root = str(tmp_path)
    expected = make_mof_tree(root, "jobCrc", 4, 1, 40, seed=23)
    cfg = Config({"uda.tpu.fetch.crc": True})
    engine = DataEngine(DirIndexResolver(root), cfg)
    before = metrics.snapshot()
    try:
        mm = MergeManager(LocalFetchClient(engine), "uda.tpu.RawBytes", cfg)
        blocks = []
        with failpoints.scoped("data_engine.pread=corrupt:8:once"):
            mm.run("jobCrc", map_ids("jobCrc", 4), 0,
                   lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    assert metrics.get("fetch.crc_refetch") > \
        before.get("fetch.crc_refetch", 0)
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    assert got == _sorted_expected(expected[0], kt)


def test_crc_persistent_corruption_falls_back(tmp_path):
    # corruption on EVERY read of one map: the one-refetch grace and the
    # whole-segment retry budget both exhaust -> FallbackSignal whose
    # cause is the CRC failure
    root = str(tmp_path)
    make_mof_tree(root, "jobCrc2", 3, 1, 20, seed=24)
    cfg = Config({"uda.tpu.fetch.crc": True})
    engine = DataEngine(DirIndexResolver(root), cfg)
    try:
        mm = MergeManager(LocalFetchClient(engine), "uda.tpu.RawBytes", cfg)
        with failpoints.scoped(
                "data_engine.pread=corrupt:4:match:m_000001"):
            with pytest.raises(FallbackSignal) as ei:
                mm.run("jobCrc2", map_ids("jobCrc2", 3), 0, lambda b: None)
    finally:
        engine.stop()
    assert "CRC mismatch" in str(ei.value.cause)


def test_crc_validates_compressed_wire_chunks(tmp_path):
    # with compression the CRC covers the COMPRESSED chunk, so the
    # DecompressingClient validates it at the wire layer; a corrupted
    # chunk becomes a transport error the whole-segment retry absorbs
    from uda_tpu.compress import DecompressingClient, get_codec
    from uda_tpu.mofserver.writer import MOFWriter

    import numpy as np
    codec = get_codec("zlib")
    rng = np.random.default_rng(55)
    expected = []
    writer = MOFWriter(str(tmp_path), "jobCz", codec=codec)
    for m in range(3):
        recs = sorted((rng.bytes(8), rng.bytes(40)) for _ in range(50))
        expected += recs
        writer.write(f"attempt_jobCz_m_{m:06d}_0", [recs])
    cfg = Config({"uda.tpu.fetch.crc": True})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    before = metrics.get("fetch.retries")
    try:
        client = DecompressingClient(LocalFetchClient(engine), codec)
        mm = MergeManager(client, "uda.tpu.RawBytes", cfg)
        blocks = []
        with failpoints.scoped("data_engine.pread=corrupt:4:once"):
            mm.run("jobCz", writer.map_ids, 0,
                   lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    assert metrics.get("fetch.retries") > before  # mismatch was caught
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    assert got == _sorted_expected(expected, kt)


def test_compressed_fetch_attempt_timeout_drops_stale_completion(tmp_path):
    # the finding-shaped race: a slow first chunk times out, the segment
    # re-issues from offset 0, and the LATE completion of the superseded
    # attempt must not mutate the DecompressingClient's stream state the
    # new attempt depends on (token guard) — output stays byte-correct
    from uda_tpu.compress import DecompressingClient, get_codec
    from uda_tpu.mofserver.writer import MOFWriter

    import numpy as np
    codec = get_codec("zlib")
    rng = np.random.default_rng(56)
    recs = sorted((rng.bytes(8), rng.bytes(40)) for _ in range(60))
    writer = MOFWriter(str(tmp_path), "jobSt", codec=codec)
    writer.write("attempt_jobSt_m_000000_0", [recs])
    # 2 reader threads: the retry must run WHILE the wedged read still
    # sleeps, so its late completion races the new attempt for real
    cfg = Config({"mapred.rdma.fetch.attempt.timeout.ms": 80,
                  "uda.tpu.fetch.retries": 4,
                  "mapred.uda.provider.blocked.threads.per.disk": 2})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    before = metrics.snapshot()
    try:
        client = DecompressingClient(LocalFetchClient(engine), codec)
        mm = MergeManager(client, "uda.tpu.RawBytes", cfg)
        blocks = []
        with failpoints.scoped("data_engine.pread=delay:500:once"):
            mm.run("jobSt", writer.map_ids, 0,
                   lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()  # waits out the wedged read's late delivery
    assert metrics.get("fetch.timeouts") > before.get("fetch.timeouts", 0)
    assert metrics.get("fetch.stale_completions") > \
        before.get("fetch.stale_completions", 0)
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    assert got == _sorted_expected(recs, kt)


def test_bridge_reports_root_cause_with_backtrace(tmp_path):
    """The fallback boundary: the embedder's failure_in_uda receives the
    root UdaError (unwrapped from FallbackSignal) with its captured
    backtrace — the original failure point survives the trip."""
    root = str(tmp_path)
    make_mof_tree(root, "jobBr", 2, 1, 10, seed=25)
    failures = []
    fell_back = threading.Event()

    class H:
        def get_conf_data(self, name, default):
            return ""

        def failure_in_uda(self, error):
            failures.append(error)
            fell_back.set()

    bridge = UdaBridge()
    bridge.start(True, [], H())
    with failpoints.scoped("data_engine.pread=error"):
        bridge.do_command(form_cmd(
            Cmd.INIT, ["jobBr", "0", "2", "uda.tpu.RawBytes", root]))
        for mid in map_ids("jobBr", 2):
            bridge.do_command(form_cmd(
                Cmd.FETCH, ["h", "jobBr", mid, "0"]))
        bridge.do_command(form_cmd(Cmd.FINAL, []))
        assert fell_back.wait(timeout=30)
    bridge.reduce_exit()
    assert bridge.failed
    (err,) = failures
    assert isinstance(err, StorageError)      # root cause, not the signal
    assert not isinstance(err, FallbackSignal)
    assert "data_engine.pread" in str(err)
    assert err.backtrace                      # origin backtrace preserved


def test_exchange_round_failpoint_site():
    # the exchange-plane site raises a TransportError without touching
    # any mesh machinery (disarmed evaluation is what the hot loop pays)
    with failpoints.scoped("exchange.round=error:once"):
        with pytest.raises(TransportError) as ei:
            failpoint("exchange.round", key="round0")
        assert "exchange.round" in str(ei.value)
    assert failpoint("exchange.round", key="round1") is None


# -- chaos tier (scripts/run_chaos.sh arms UDA_FAILPOINTS) -------------------


@pytest.mark.faults
def test_chaos_schedule_survives_end_to_end(tmp_path):
    """Runs under whatever failpoint schedule the environment armed
    (scripts/run_chaos.sh exports a seeded chaos_spec; disarmed in the
    plain tier this is a clean-run parity check). The merge must absorb
    every injected fault and produce exactly the expected sorted
    records."""
    active = failpoints.active()
    print(f"chaos schedule: {active or 'disarmed'}")
    root = str(tmp_path)
    expected = make_mof_tree(root, "jobChaos", 8, 2, 60, seed=31)
    cfg = Config({"uda.tpu.fetch.retries": 25,
                  "mapred.rdma.fetch.retry.backoff.ms": 1,
                  "mapred.rdma.fetch.retry.backoff.max.ms": 20,
                  "mapred.rdma.wqe.per.conn": 4})
    engine = DataEngine(DirIndexResolver(root), cfg)
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    try:
        for r in range(2):
            mm = MergeManager(LocalFetchClient(engine),
                              "uda.tpu.RawBytes", cfg)
            blocks = []
            mm.run("jobChaos", map_ids("jobChaos", 8), r,
                   lambda b: blocks.append(bytes(b)))
            got = list(IFileReader(io.BytesIO(b"".join(blocks))))
            assert got == _sorted_expected(expected[r], kt), \
                f"reducer {r} diverged under schedule {active}"
    finally:
        engine.stop()
    if active:
        print(f"failpoint hits: {dict(failpoints.hits)}")
