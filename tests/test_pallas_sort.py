"""Pallas lanes-layout full sort vs host oracle (interpret mode)."""

import numpy as np
import pytest

from uda_tpu.ops import pallas_sort

pytestmark = pytest.mark.slow  # interpret-mode Pallas kernels


def _gen(n, num_keys=3, dup_rate=0.0, seed=0, payload_rows=None):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 2**32, size=(pallas_sort.ROWS, n),
                     dtype=np.uint32)
    if dup_rate:
        # few distinct keys -> many ties to exercise stability
        x[:num_keys] = rng.integers(0, 3, size=(num_keys, n),
                                    dtype=np.uint32)
    return x


def _oracle(x, num_keys):
    # stable ascending sort by key rows (records are columns)
    keys = tuple(x[r] for r in reversed(range(num_keys)))
    perm = np.lexsort(keys)  # lexsort is stable
    return x[:, perm], perm


def _check(n, tile, num_keys=3, dup_rate=0.0, seed=0):
    x = _gen(n, num_keys, dup_rate, seed)
    out = np.asarray(pallas_sort.sort_lanes(x, num_keys, tile=tile,
                                            interpret=True))
    want, perm = _oracle(x, num_keys)
    tb = pallas_sort.TB_ROW_DEFAULT
    # keys + payload rows (all but tb) must match the stable oracle
    for r in range(pallas_sort.ROWS):
        if r == tb:
            continue
        np.testing.assert_array_equal(out[r], want[r], err_msg=f"row {r}")
    # the tie-break row must hold the (stable) source permutation
    np.testing.assert_array_equal(out[tb].astype(np.int64), perm,
                                  err_msg="tie-break row != stable perm")


def test_single_tile():
    _check(512, tile=512)


def test_two_tiles_one_merge():
    _check(1024, tile=512, seed=1)


def test_eight_tiles_three_merges():
    _check(2048, tile=256, seed=2)


def test_many_duplicates_stability():
    _check(2048, tile=256, dup_rate=1.0, seed=3)


def test_presorted_and_reversed():
    n, tile, k = 1024, 256, 3
    x = _gen(n, k, seed=4)
    order = np.lexsort(tuple(x[r] for r in reversed(range(k))))
    for variant in (order, order[::-1]):
        xs = x[:, variant]
        out = np.asarray(pallas_sort.sort_lanes(xs, k, tile=tile,
                                                interpret=True))
        want, _ = _oracle(xs, k)
        np.testing.assert_array_equal(out[:k], want[:k])


def test_single_key_word():
    _check(1024, tile=256, num_keys=1, seed=5)


def test_roundtrip_layout_helpers():
    rng = np.random.default_rng(6)
    words = rng.integers(0, 2**32, size=(640, 26), dtype=np.uint32)
    lanes = np.asarray(pallas_sort.rows_to_lanes(words))
    assert lanes.shape == (pallas_sort.ROWS, 640)
    assert (lanes[26:] == 0).all()
    back = np.asarray(pallas_sort.lanes_to_rows(lanes, 26))
    np.testing.assert_array_equal(back, words)


def test_shape_validation():
    x = np.zeros((pallas_sort.ROWS, 768), np.uint32)  # 3 tiles: not pow2
    with pytest.raises(ValueError):
        pallas_sort.sort_lanes(x, 3, tile=256, interpret=True)
    with pytest.raises(ValueError):
        pallas_sort.sort_lanes(np.zeros((pallas_sort.ROWS, 512), np.uint32),
                               3, tile=192, interpret=True)


def test_two_phase_engine_matches_default():
    # the keys-view + payload-gather engine must be byte-identical to
    # the full-width network, incl. duplicate keys (arrival stability
    # rides the tie-break row in both) and multi-pass merges
    rng = np.random.default_rng(77)
    for n, dup in ((1024, False), (4096, True)):
        words = rng.integers(0, 2**32, size=(n, 6), dtype=np.uint32)
        if dup:
            words[:, :2] = rng.integers(0, 3, size=(n, 2), dtype=np.uint32)
        x = pallas_sort.rows_to_lanes(words)
        a = np.asarray(pallas_sort.sort_lanes(x, num_keys=2, tile=1024,
                                              interpret=True))
        b = np.asarray(pallas_sort.sort_lanes(x, num_keys=2, tile=1024,
                                              interpret=True,
                                              two_phase=True))
        np.testing.assert_array_equal(a, b)
