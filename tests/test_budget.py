"""Memory admission control + pressure-adaptive degradation (ISSUE 3):
the MemoryBudget routing matrix, INIT validation, the arena's
total-deadline acquire + soft-pressure callback, the supplier read-pool
admission, the stall watchdog, and the stop-path drain."""

import io
import threading
import time

import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.merger import LocalFetchClient, MergeManager
from uda_tpu.merger.arena import BufferArena
from uda_tpu.merger.segment import InputClient
from uda_tpu.mofserver import DataEngine, DirIndexResolver
from uda_tpu.mofserver.data_engine import ShuffleRequest
from uda_tpu.utils import comparators
from uda_tpu.utils.budget import (MemoryBudget, WORKING_SET_FACTOR,
                                  device_bytes_estimate)
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import (FallbackSignal, MergeError, StorageError,
                                  UdaError)
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.ifile import IFileReader
from uda_tpu.utils.metrics import metrics
from uda_tpu.utils.watchdog import StallError, StallWatchdog

MB = 1 << 20
KT = comparators.get_key_type("uda.tpu.RawBytes")


# -- the device-bytes model --------------------------------------------------

def test_device_bytes_model_shape():
    # the VERDICT.md model: a 10 GB TeraSort partition's device working
    # set exceeds a v5e's 16 GB HBM (the OOM scenario this PR closes)
    dev = device_bytes_estimate(10 << 30, key_width=16)
    assert dev > 16 << 30
    # ... and is ~1.08x shuffle bytes x working-set factor at that shape
    assert dev == int((10 << 30) * 1.08 * WORKING_SET_FACTOR)
    # tiny keys still charge the row matrix (row bytes dominate when
    # records are smaller than a row)
    assert device_bytes_estimate(1000, key_width=16, record_bytes=10) \
        >= 100 * 28
    assert device_bytes_estimate(0, 16) == 0


def test_budget_defaults_resolve_lazily_and_from_config():
    b = MemoryBudget(hbm_budget_mb=123, host_budget_mb=456)
    assert b.hbm_budget_bytes == 123 * MB
    assert b.host_budget_bytes == 456 * MB
    # auto budgets resolve to something positive on any platform (CPU
    # backend: host memory stands in for HBM)
    auto = MemoryBudget()
    assert auto.host_budget_bytes > 0
    assert auto.hbm_budget_bytes > 0
    with pytest.raises(UdaError):
        MemoryBudget(enforce="panic")


# -- the routing matrix (estimate x budgets -> decision) ---------------------

@pytest.mark.parametrize(
    "est_mb,hbm_mb,hard_mb,want,counter",
    [
        # in budget, under the hybrid crossover -> hybrid
        (10, 4096, 0, "hybrid", "budget.admitted"),
        # in budget, over the crossover -> streaming (still admitted)
        (600, 4096, 0, "streaming", "budget.admitted"),
        # device working set over the HBM budget -> streaming reroute
        (1024, 512, 0, "streaming", "budget.rerouted"),
        # over the hard ceiling -> reject (FallbackSignal at the caller)
        (4096, 512, 2048, "reject", "budget.rejected"),
        # unknown estimate -> streaming
        (None, 4096, 0, "streaming", "budget.admitted"),
    ])
def test_routing_matrix(est_mb, hbm_mb, hard_mb, want, counter):
    before = metrics.get(counter)
    b = MemoryBudget(hbm_budget_mb=hbm_mb, host_budget_mb=64 * 1024,
                     hard_ceiling_mb=hard_mb)
    est = None if est_mb is None else est_mb * MB
    adm = b.route(est, threshold_bytes=512 * MB)
    assert adm.decision == want
    assert metrics.get(counter) == before + 1
    if want == "reject":
        assert adm.rejected
    if counter == "budget.rerouted":
        assert adm.rerouted


def test_route_host_budget_gates_hybrid():
    # fits HBM but not host RSS (hybrid holds fetched bytes host-
    # resident through the LPQ spill) -> streaming reroute
    b = MemoryBudget(hbm_budget_mb=64 * 1024, host_budget_mb=256)
    adm = b.route(1024 * MB, threshold_bytes=4096 * MB)
    assert adm.decision == "streaming" and adm.rerouted
    assert adm.cause == "host"


# -- MergeManager auto-approach consumes the routing -------------------------

class _FixedEstimateClient(LocalFetchClient):
    def __init__(self, engine, estimate):
        super().__init__(engine)
        self._estimate = estimate
        self.fetches = 0

    def estimate_partition_bytes(self, job_id, mids, reduce_id):
        return self._estimate

    def start_fetch(self, req, on_complete):
        self.fetches += 1
        super().start_fetch(req, on_complete)


def test_auto_approach_over_hbm_budget_reroutes_to_streaming(tmp_path):
    expected = make_mof_tree(str(tmp_path), "jobB1", 4, 1, 50, seed=7)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    # pretend the partition is 1 GB against a 64 MB HBM budget: the
    # fast path would OOM, so routing must land on streaming and the
    # merger must not stage any device run (bounded device)
    client = _FixedEstimateClient(engine, 1 << 30)
    cfg = Config({"mapred.netmerger.merge.approach": 0,
                  "uda.tpu.hbm.budget.mb": 64,
                  "uda.tpu.host.budget.mb": 64 * 1024})
    mm = MergeManager(client, KT, cfg)
    blocks = []
    try:
        mm.run("jobB1", map_ids("jobB1", 4), 0,
               lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    adm = mm.last_admission
    assert adm is not None and adm.decision == "streaming" and adm.rerouted
    om = mm._active_overlap
    assert om is not None and not om.device_runs
    assert om.stats["device_merges"] == 0  # nothing staged on device
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    assert got == sorted(expected[0])


def test_auto_approach_hard_ceiling_rejects_before_any_fetch(tmp_path):
    make_mof_tree(str(tmp_path), "jobB2", 3, 1, 30, seed=8)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    client = _FixedEstimateClient(engine, 100 << 30)  # 100 GB estimate
    cfg = Config({"mapred.netmerger.merge.approach": 0,
                  "uda.tpu.budget.hard.mb": 1024})
    mm = MergeManager(client, KT, cfg)
    try:
        with pytest.raises(FallbackSignal) as ei:
            mm.run("jobB2", map_ids("jobB2", 3), 0, lambda b: None)
    finally:
        engine.stop()
    # the admission gate fired BEFORE any allocation or fetch
    assert client.fetches == 0
    assert "admission" in str(ei.value.cause)
    assert mm.last_admission.rejected


def test_auto_approach_in_budget_keeps_measured_crossover(tmp_path):
    # generous budgets: the decision reduces to the measured hybrid/
    # streaming crossover (the pre-budget behavior, now via route())
    expected = make_mof_tree(str(tmp_path), "jobB3", 4, 1, 40, seed=9)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    try:
        for threshold_mb, want in ((1 << 10, "hybrid"), (0, "streaming")):
            cfg = Config({"mapred.netmerger.merge.approach": 0,
                          "uda.tpu.hbm.budget.mb": 64 * 1024,
                          "uda.tpu.host.budget.mb": 64 * 1024,
                          "uda.tpu.auto.approach.threshold.mb":
                          threshold_mb})
            mm = MergeManager(LocalFetchClient(engine), KT, cfg)
            blocks = []
            mm.run("jobB3", map_ids("jobB3", 4), 0,
                   lambda b: blocks.append(bytes(b)))
            assert mm.last_admission.decision == want, threshold_mb
            got = list(IFileReader(io.BytesIO(b"".join(blocks))))
            assert got == sorted(expected[0])
    finally:
        engine.stop()


# -- INIT validation (the reducer.cc:56-133 mirror) --------------------------

def test_validate_init_shrinks_window_to_fit_host_budget():
    cfg = Config({"uda.tpu.host.budget.mb": 64,
                  "mapred.rdma.buf.size": 1024,       # 1 MB chunks
                  "mapred.rdma.wqe.per.conn": 256})   # wants 256 MB
    before = metrics.get("budget.rerouted")
    adm = MemoryBudget.from_config(cfg).validate_init(cfg)
    new_window = cfg.get("mapred.rdma.wqe.per.conn")
    assert 1 <= new_window < 256
    # the shrunken working set actually fits
    slots = cfg.get("uda.tpu.arena.slots")
    assert (new_window + slots + 2) * MB <= 64 * MB
    assert adm.rerouted
    assert metrics.get("budget.rerouted") == before + 1


def test_validate_init_reject_mode_raises():
    cfg = Config({"uda.tpu.host.budget.mb": 64,
                  "mapred.rdma.buf.size": 1024,
                  "mapred.rdma.wqe.per.conn": 256,
                  "uda.tpu.budget.enforce": "reject"})
    with pytest.raises(UdaError):
        MemoryBudget.from_config(cfg).validate_init(cfg)
    assert cfg.get("mapred.rdma.wqe.per.conn") == 256  # untouched


def test_validate_init_unfittable_chunk_always_raises():
    cfg = Config({"uda.tpu.host.budget.mb": 8,
                  "mapred.rdma.buf.size": 1024})  # 18 MB fixed > 8 MB
    with pytest.raises(UdaError):
        MemoryBudget.from_config(cfg).validate_init(cfg)
    assert metrics.get("budget.rejected") >= 1


def test_bridge_init_over_budget_falls_back():
    """The bridge wires validate_init into INIT: enforce=reject + a
    tiny host budget -> failure_in_uda, inert bridge (the reference's
    'Not enough memory for rdma buffers' fallback)."""
    from uda_tpu.bridge import UdaBridge

    failures = []

    class CB:
        def failure_in_uda(self, e):
            failures.append(e)

        def get_conf_data(self, name, default):
            return {"uda.tpu.host.budget.mb": "8"}.get(name, default)

    from uda_tpu.bridge.protocol import Cmd, form_cmd

    br = UdaBridge()
    br.start(True, ["-s", "1024"], CB())
    br.do_command(form_cmd(Cmd.INIT, ["jobX", "0", "2",
                                      "uda.tpu.RawBytes"]))
    assert br.failed
    assert failures and isinstance(failures[0], UdaError)


def test_bridge_init_in_budget_proceeds(tmp_path):
    """A comfortable budget leaves INIT untouched (admitted, counted)."""
    from uda_tpu.bridge import UdaBridge

    before = metrics.get("budget.admitted")
    from uda_tpu.bridge.protocol import Cmd, form_cmd

    br = UdaBridge()
    br.start(True, [], None)
    br.do_command(form_cmd(Cmd.INIT, ["jobY", "0", "1",
                                      "uda.tpu.RawBytes", str(tmp_path)]))
    assert not br.failed
    assert metrics.get("budget.admitted") == before + 1
    br.do_command(form_cmd(Cmd.EXIT, []))


# -- arena: total deadline + soft pressure -----------------------------------

def test_arena_acquire_timeout_is_total_deadline():
    """Spurious/notify wakeups must not restart the clock: under a
    notify storm the acquire still fails at ~the requested deadline
    (pre-fix each wakeup re-armed the full timeout)."""
    arena = BufferArena(1, 64)
    held = arena.acquire()
    stop = threading.Event()

    def storm():
        while not stop.is_set():
            with arena._cv:
                arena._cv.notify()
            time.sleep(0.01)

    t = threading.Thread(target=storm, daemon=True)
    t.start()
    t0 = time.monotonic()
    try:
        with pytest.raises(MergeError):
            arena.acquire(timeout=0.3)
        waited = time.monotonic() - t0
        assert waited < 2.0, f"deadline restarted: waited {waited:.1f}s"
        assert waited >= 0.25
    finally:
        stop.set()
        t.join()
        arena.release(held)


def test_arena_pressure_callback_fires_once_per_starved_acquire():
    events = []
    arena = BufferArena(1, 64, on_pressure=events.append,
                        pressure_after_s=0.05)
    slot = arena.acquire()
    before = metrics.get("arena.pressure_events")
    threading.Timer(0.4, lambda: arena.release(slot)).start()
    got = arena.acquire(timeout=5.0)  # succeeds after the release
    assert len(events) == 1 and events[0] >= 0.05
    assert metrics.get("arena.pressure_events") == before + 1
    arena.release(got)


def test_arena_fast_acquire_no_pressure():
    events = []
    arena = BufferArena(2, 64, on_pressure=events.append,
                        pressure_after_s=0.05)
    arena.release(arena.acquire())
    assert events == []


# -- supplier read-pool admission --------------------------------------------

def test_supplier_admission_rejects_over_budget_nonblocking(tmp_path):
    make_mof_tree(str(tmp_path), "jobS", 1, 1, 50, seed=11)
    cfg = Config({"uda.tpu.supplier.read.budget.mb": 1,
                  "mapred.rdma.buf.size": 512})  # 512 KB chunks
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    mid = map_ids("jobS", 1)[0]
    try:
        # wedge the workers so admitted bytes stay claimed
        with failpoints.scoped("data_engine.pread=delay:300"):
            before = metrics.get("supplier.admission.rejections")
            futs = [engine.submit(ShuffleRequest("jobS", mid, 0, 0,
                                                 512 * 1024))
                    for _ in range(2)]  # 2 x 512 KB = the full budget
            t0 = time.monotonic()
            with pytest.raises(StorageError) as ei:
                engine.submit(ShuffleRequest("jobS", mid, 0, 0, 512 * 1024))
            # the rejection is immediate (non-blocking), never a wait
            assert time.monotonic() - t0 < 0.2
            assert "read pool exhausted" in str(ei.value)
            assert metrics.get("supplier.admission.rejections") \
                == before + 1
        for f in futs:
            f.result(timeout=10)
        # budget fully released -> admission works again
        assert engine.fetch(ShuffleRequest("jobS", mid, 0, 0,
                                           512 * 1024)).data
    finally:
        engine.stop()


def test_supplier_admission_oversized_single_request_admitted(tmp_path):
    # a request larger than the whole budget is served when the pool is
    # idle: push-back must never become a permanent dead end
    make_mof_tree(str(tmp_path), "jobS2", 1, 1, 10, seed=12)
    cfg = Config({"uda.tpu.supplier.read.budget.mb": 1})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    try:
        res = engine.fetch(ShuffleRequest("jobS2", map_ids("jobS2", 1)[0],
                                          0, 0, 8 * MB))
        assert res.data
    finally:
        engine.stop()


# -- stop-path drain (the fetch_all leak fix) --------------------------------

def test_fetch_all_stop_drains_inflight_segments():
    """stop() mid-window: fetch_all must fail+drain the started
    segments (credits released, on_done delivered) before raising —
    not abandon them mid-flight."""

    class WedgeClient(InputClient):
        def __init__(self):
            self.started = []

        def start_fetch(self, req, on_complete):
            self.started.append(req.map_id)  # never completes

    client = WedgeClient()
    cfg = Config({"mapred.rdma.wqe.per.conn": 2})
    mm = MergeManager(client, KT, cfg)
    fed = []
    err = []

    def run():
        try:
            mm.fetch_all("jobD", [f"m{i}" for i in range(4)], 0,
                         on_segment=lambda i, s: fed.append(i))
        except Exception as e:  # noqa: BLE001
            err.append(e)

    t = threading.Thread(target=run)
    t.start()
    deadline = time.monotonic() + 5
    while len(client.started) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(client.started) == 2  # window filled, loop blocked
    mm.stop()
    t.join(timeout=10)
    assert not t.is_alive(), "fetch_all did not return after stop()"
    assert err and isinstance(err[0], MergeError)
    # every started segment was administratively completed (drained)
    drained = [s for s in mm._live_segments if s._done.is_set()]
    assert len(drained) >= 2
    assert metrics.get("fetch.failed_admin") >= 2
    assert fed == []  # no half-delivered on_segment


def test_fetch_all_stop_breaks_all_notified_wait():
    """A completion thread wedged inside the on_segment consumer (the
    overlapped merger's bounded feed in real runs) blocks the
    all-callbacks-delivered wait — stop() must break that wait too,
    not only the credit wait."""
    from uda_tpu.mofserver.data_engine import FetchResult

    class AsyncEmpty(InputClient):
        def start_fetch(self, req, on_complete):
            threading.Thread(
                target=lambda: on_complete(
                    FetchResult(b"", 0, 0, 0, "p", last=True)),
                daemon=True).start()

    release = threading.Event()
    cfg = Config({"mapred.rdma.wqe.per.conn": 8})
    mm = MergeManager(AsyncEmpty(), KT, cfg)
    err = []

    def run():
        try:
            mm.fetch_all("jobN", [f"m{i}" for i in range(3)], 0,
                         on_segment=lambda i, s: release.wait())
        except Exception as e:  # noqa: BLE001
            err.append(e)

    t = threading.Thread(target=run)
    t.start()
    # all segments complete their fetch; callbacks wedge in on_segment
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and \
            sum(1 for s in mm._live_segments if s._done.is_set()) < 3:
        time.sleep(0.01)
    mm.stop()
    threading.Timer(0.3, release.set).start()  # the om.abort analogue
    t.join(timeout=10)
    release.set()
    assert not t.is_alive(), "fetch_all hung in all_notified despite stop"
    assert err and isinstance(err[0], MergeError)


# -- the stall watchdog ------------------------------------------------------

def test_watchdog_unit_fires_and_dumps():
    fired = []
    wd = StallWatchdog(0.15, lambda: 7, on_stall=fired.append,
                       name="wd-test").start()
    try:
        deadline = time.monotonic() + 5
        while not wd.fired and time.monotonic() < deadline:
            time.sleep(0.02)
        assert wd.fired and isinstance(fired[0], StallError)
        assert "thread stacks" in wd.last_dump
        assert "wd-test" in wd.last_dump  # its own stack is in there
    finally:
        wd.stop()


def test_watchdog_does_not_fire_while_progressing():
    token = [0]

    def progress():
        token[0] += 1
        return token[0]

    wd = StallWatchdog(0.2, progress).start()
    time.sleep(0.7)
    try:
        assert not wd.fired
    finally:
        wd.stop()


@pytest.mark.faults
def test_watchdog_rescues_wedged_fetch(tmp_path):
    """The acceptance scenario: a fetch wedged via the segment.fetch
    failpoint terminates through the watchdog within ~the stall
    deadline — stall dump + FallbackSignal(StallError) — instead of
    hanging forever."""
    # preload the overlap/pallas modules: the watchdog measures ENGINE
    # stalls, not cold-import latency
    import uda_tpu.merger.overlap  # noqa: F401

    make_mof_tree(str(tmp_path), "jobWd", 2, 1, 60, seed=13)
    cfg = Config({"mapred.rdma.buf.size": 1,  # 1 KB chunks: many issues
                  "uda.tpu.watchdog.stall.s": 0.5,
                  "mapred.rdma.fetch.attempt.timeout.ms": 0})
    engine = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    mm = MergeManager(LocalFetchClient(engine), KT, cfg)
    before = metrics.get("watchdog.stalls")
    t0 = time.monotonic()
    try:
        # every 4th issue wedges for 3 s >> the 0.5 s stall deadline
        # (pread pinned harmless: a chaos-armed error schedule there
        # would exhaust retries and mask the stall with a transport
        # failure — this test is about the WEDGE, not recoverable noise)
        with failpoints.scoped("data_engine.pread=delay:0,"
                               "segment.fetch=delay:3000:every:4"):
            with pytest.raises(FallbackSignal) as ei:
                mm.run("jobWd", map_ids("jobWd", 2), 0, lambda b: None)
        took = time.monotonic() - t0
        assert isinstance(ei.value.cause, StallError)
        assert took < 3.0, f"terminated by the delay, not the watchdog " \
                           f"({took:.1f}s)"
        assert metrics.get("watchdog.stalls") == before + 1
        assert mm._watchdog is None  # stopped by run()'s finally
    finally:
        engine.stop()  # blocks until the wedged worker's sleep ends


@pytest.mark.faults
def test_memory_pressure_schedule_reroutes_not_crashes(tmp_path):
    """The chaos memory-pressure rung (scripts/run_chaos.sh): a tiny
    HBM budget + armed failpoints must degrade to the bounded streaming
    path and still produce the exact sorted output — graceful reroute,
    never a crash."""
    expected = make_mof_tree(str(tmp_path), "jobMP", 6, 1, 50, seed=17)
    engine = DataEngine(DirIndexResolver(str(tmp_path)))
    client = _FixedEstimateClient(engine, 2 << 30)  # 2 GB claim
    cfg = Config({"mapred.netmerger.merge.approach": 0,
                  "uda.tpu.hbm.budget.mb": 32,      # tiny arena/HBM
                  "uda.tpu.host.budget.mb": 64 * 1024,
                  "uda.tpu.fetch.retries": 25,
                  "mapred.rdma.fetch.retry.backoff.ms": 1,
                  "mapred.rdma.fetch.retry.backoff.max.ms": 20})
    mm = MergeManager(client, KT, cfg)
    blocks = []
    try:
        mm.run("jobMP", map_ids("jobMP", 6), 0,
               lambda b: blocks.append(bytes(b)))
    finally:
        engine.stop()
    assert mm.last_admission.rerouted
    assert not mm._active_overlap.device_runs
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    import functools
    want = sorted(expected[0], key=functools.cmp_to_key(
        lambda a, b: KT.compare(a[0], b[0])))
    assert got == want
