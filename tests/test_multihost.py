"""Cross-process exchange: 2 processes x 4 virtual CPU devices run the
distributed sort step over one global mesh (the reference's cross-node
RDMA data plane, SURVEY §2.3; jax.distributed replaces the rdma_cm
connect dance of reference src/DataNet/RDMAClient.cc:215-356)."""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # spawns real multi-process meshes


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("nprocs", [2, 4])
def test_multiprocess_cpu_exchange(nprocs):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "multihost_worker.py")
    port = _free_port()
    env = dict(os.environ)
    # drop sitecustomize shim dirs (e.g. an accelerator relay hook) from
    # the path: their sitecustomize.py imports jax at interpreter start,
    # which forbids the later jax.distributed.initialize; workers are
    # pure-CPU. Only dirs that actually carry a sitecustomize.py go.
    extra = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and not os.path.exists(os.path.join(p,
                                                      "sitecustomize.py"))]
    env["PYTHONPATH"] = os.pathsep.join([root] + extra)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), str(nprocs), str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(nprocs)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"MULTIHOST-OK p{i}" in out, f"worker {i} output:\n{out}"
