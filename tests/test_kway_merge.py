"""Native loser-tree k-way merge (native/merge.cc) parity vs the Python
heap merge (ops/merge.merge_record_streams — the semantic oracle, the
reference MergeQueue.h:276-427 contract). Byte-identity is the bar: the
native block stream concatenated must equal the oracle's records
re-framed, EOF marker included."""

import functools
import io

import numpy as np
import pytest

from uda_tpu import native
from uda_tpu.ops import merge as merge_ops
from uda_tpu.utils import ifile
from uda_tpu.utils.comparators import get_key_type
from uda_tpu.utils.errors import StorageError

pytestmark = pytest.mark.skipif(
    not (native.available() or native.build()),
    reason="native library not built and build failed")


def _write_run(path, records):
    with open(path, "wb") as f:
        w = ifile.IFileWriter(f)
        for k, v in records:
            w.append(k, v)
        w.close()


def _sorted_runs(kt, n_runs, n_recs, keygen, seed=0):
    rng = np.random.default_rng(seed)
    runs = []
    for _ in range(n_runs):
        recs = [(keygen(rng), rng.bytes(int(rng.integers(0, 40))))
                for _ in range(n_recs)]
        recs.sort(key=functools.cmp_to_key(
            lambda a, b: kt.compare(a[0], b[0])))
        runs.append(recs)
    return runs


def _oracle_bytes(paths, kt):
    streams = [ifile.iter_file_records(p) for p in paths]
    return ifile.write_records(merge_ops.merge_record_streams(streams, kt))


def _native_bytes(paths, kt, **kw):
    return b"".join(native.kway_merge_paths(paths, kt, **kw))


def _spill(tmp_path, runs):
    paths = []
    for i, recs in enumerate(runs):
        p = str(tmp_path / f"run-{i:03d}")
        _write_run(p, recs)
        paths.append(p)
    return paths


def _text_key(rng):
    # Text framing: VInt(len) + bytes (comparator skips the VInt)
    content = rng.bytes(int(rng.integers(0, 12)))
    from uda_tpu.utils import vint
    return vint.encode_vlong(len(content)) + content


@pytest.mark.parametrize("name,keygen", [
    ("uda.tpu.RawBytes", lambda rng: rng.bytes(int(rng.integers(0, 10)))),
    ("org.apache.hadoop.io.Text", _text_key),
    ("org.apache.hadoop.io.IntWritable",
     lambda rng: int(rng.integers(-2**31, 2**31)).to_bytes(
         4, "big", signed=True)),
    ("org.apache.hadoop.io.BytesWritable",
     lambda rng: (lambda c: len(c).to_bytes(4, "big") + c)(
         rng.bytes(int(rng.integers(0, 8))))),
    ("uda.tpu.IntNumeric",
     lambda rng: int(rng.integers(-2**31, 2**31)).to_bytes(
         4, "big", signed=True)),
])
def test_kway_parity(tmp_path, name, keygen):
    kt = get_key_type(name)
    import zlib
    runs = _sorted_runs(kt, n_runs=5, n_recs=120, keygen=keygen,
                        seed=zlib.crc32(name.encode()))
    paths = _spill(tmp_path, runs)
    assert _native_bytes(paths, kt) == _oracle_bytes(paths, kt)


def test_kway_int_memcmp_quirk(tmp_path):
    """memcmp order puts negative IntWritables AFTER positive ones (the
    reference CompareFunc quirk) — both paths must agree on it."""
    kt = get_key_type("org.apache.hadoop.io.IntWritable")
    vals = [-5, -1, 0, 1, 7, 2**31 - 1, -2**31]
    keys = sorted((v.to_bytes(4, "big", signed=True) for v in vals))
    runs = [[(k, b"v%d" % i) for i, k in enumerate(keys)]]
    paths = _spill(tmp_path, runs)
    out = _native_bytes(paths, kt)
    assert out == _oracle_bytes(paths, kt)
    # and the first record is a non-negative key (high bit clear)
    batch = ifile.crack(out)
    assert batch.key(0)[0] < 0x80


def test_kway_tie_stability(tmp_path):
    """Equal keys come out in spill-file order (seq tiebreak)."""
    kt = get_key_type("uda.tpu.RawBytes")
    runs = [[(b"k", b"from-%d" % i)] for i in range(6)]
    paths = _spill(tmp_path, runs)
    out = _native_bytes(paths, kt)
    assert out == _oracle_bytes(paths, kt)
    batch = ifile.crack(out)
    assert [batch.value(i) for i in range(6)] == \
        [b"from-%d" % i for i in range(6)]


def test_kway_empty_and_single(tmp_path):
    kt = get_key_type("uda.tpu.RawBytes")
    # a run holding only the EOF marker merges as zero records
    empty = str(tmp_path / "empty")
    _write_run(empty, [])
    single = str(tmp_path / "single")
    _write_run(single, [(b"a", b"1"), (b"b", b"2")])
    for paths in ([empty], [single], [empty, single], [single, empty]):
        assert _native_bytes(paths, kt) == _oracle_bytes(paths, kt)
    # no paths at all -> just the EOF marker
    assert _native_bytes([], kt) == ifile.EOF_MARKER


def test_kway_small_buffers_span_records(tmp_path):
    """Records far larger than the cursor read buffer and the output
    block exercise the refill/grow paths."""
    kt = get_key_type("uda.tpu.RawBytes")
    rng = np.random.default_rng(3)
    runs = _sorted_runs(kt, n_runs=3, n_recs=40,
                        keygen=lambda r: r.bytes(int(r.integers(0, 6))),
                        seed=3)
    # add some jumbo values so single records exceed buffer_size=64
    for recs in runs:
        for j in range(0, len(recs), 7):
            recs[j] = (recs[j][0], rng.bytes(500))
    paths = _spill(tmp_path, runs)
    out = _native_bytes(paths, kt, block_bytes=128, buffer_size=64)
    assert out == _oracle_bytes(paths, kt)


def test_kway_many_cursors(tmp_path):
    """A deep non-power-of-two loser tree (k=67) stays byte-identical."""
    kt = get_key_type("uda.tpu.RawBytes")
    runs = _sorted_runs(kt, n_runs=67, n_recs=25,
                        keygen=lambda rng: rng.bytes(
                            int(rng.integers(0, 8))), seed=13)
    paths = _spill(tmp_path, runs)
    assert _native_bytes(paths, kt) == _oracle_bytes(paths, kt)


def test_kway_missing_eof_marker(tmp_path):
    kt = get_key_type("uda.tpu.RawBytes")
    p = str(tmp_path / "trunc")
    full = ifile.write_records([(b"a", b"1"), (b"b", b"2")])
    with open(p, "wb") as f:
        f.write(full[:-2])  # strip the marker
    with pytest.raises(StorageError):
        _native_bytes([p], kt)


def test_kway_unsupported_keytype_detection():
    from uda_tpu.utils.comparators import KeyType
    custom = KeyType("custom", lambda b: bytes(b))
    assert not native.kway_supported(custom)
    assert native.kway_supported(get_key_type("org.apache.hadoop.io.Text"))


def test_hybrid_rpq_native_vs_python_identical(tmp_path):
    """run_hybrid's consumer stream is byte-identical with the native
    RPQ on and off (the kill-switch contract)."""
    from tests.helpers import make_mof_tree, map_ids
    from uda_tpu.merger import LocalFetchClient, MergeManager
    from uda_tpu.mofserver import DataEngine, DirIndexResolver
    from uda_tpu.utils.config import Config

    def run(root, use_native):
        cfg = Config({"mapred.netmerger.merge.approach": 2,
                      "mapred.netmerger.hybrid.lpq.size": 2,
                      "uda.tpu.spill.dirs": str(root / "spill")})
        make_mof_tree(str(root), "jobK", 6, 1, 60, seed=11)
        engine = DataEngine(DirIndexResolver(str(root)), cfg)
        kt = get_key_type("uda.tpu.RawBytes")
        ifile.set_native_enabled(use_native)
        try:
            mm = MergeManager(LocalFetchClient(engine), kt, cfg)
            blocks = []
            mm.run("jobK", map_ids("jobK", 6), 0,
                   lambda b: blocks.append(bytes(b)))
            return b"".join(blocks)
        finally:
            ifile.set_native_enabled(True)
            engine.stop()

    a = run(tmp_path / "nat", True)
    b = run(tmp_path / "py", False)
    assert a == b and len(a) > 0
