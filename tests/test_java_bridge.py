"""JVM integration: a Java process completes a merge through the
C-ABI shim (reference UdaBridge.java:49-81 natives + up-calls,
re-bound via the JDK foreign-function API in java/com/mellanox/...).

Gated: skips unless a JDK 22+ (javac + java with java.lang.foreign)
is installed — the build image has no JDK; the artifact is exercised
wherever one exists."""

import functools
import io
import os
import shutil
import subprocess
import sys

import pytest

from tests.helpers import make_mof_tree
from uda_tpu.utils import comparators
from uda_tpu.utils.ifile import IFileReader

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _jdk_version() -> int:
    javac = shutil.which("javac")
    if not javac:
        return 0
    try:
        out = subprocess.run([javac, "-version"], capture_output=True,
                             text=True, timeout=60)
        ver = (out.stdout or out.stderr).split()[-1]
        return int(ver.split(".")[0])
    except Exception:  # noqa: BLE001 - any probe failure means "no JDK"
        return 0


@pytest.mark.skipif(_jdk_version() < 22,
                    reason="needs a JDK 22+ (java.lang.foreign)")
def test_jvm_drives_merge_through_shim(tmp_path):
    shim = os.path.join(ROOT, "uda_tpu", "native",
                        "libuda_tpu_bridge.so")
    if not os.path.exists(shim):
        rc = subprocess.run(["make", "-C",
                             os.path.join(ROOT, "uda_tpu", "native"),
                             "libuda_tpu_bridge.so"]).returncode
        assert rc == 0, "shim build failed"
    build = tmp_path / "classes"
    rc = subprocess.run(["make", "-C", os.path.join(ROOT, "java"),
                         f"BUILD={build}"]).returncode
    assert rc == 0, "javac build failed"

    job = "jobJvm"
    num_maps = 3
    expected = make_mof_tree(str(tmp_path), job, num_maps, 1, 30, seed=71)
    out_file = tmp_path / "merged.bin"
    env = dict(os.environ)
    # the embedded interpreter must find uda_tpu and stay off the TPU
    env["UDA_TPU_PY_BOOTSTRAP"] = (
        "import sys; sys.path.insert(0, %r); "
        "import os; os.environ['JAX_PLATFORMS']='cpu'" % ROOT)
    proc = subprocess.run(
        ["java", "--enable-native-access=ALL-UNNAMED", "-cp", str(build),
         "com.mellanox.hadoop.mapred.UdaBridgeDriver", shim,
         str(tmp_path), job, str(num_maps), str(out_file)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "JVM-MERGE-OK" in proc.stdout

    got = list(IFileReader(io.BytesIO(out_file.read_bytes())))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    want = sorted(expected[0], key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want


def test_java_sources_present_and_wellformed():
    """Always-on sanity: the Java artifact exists and matches the C ABI
    surface it binds (symbol names and the 7-pointer callback table) —
    catches drift even on images without a JDK."""
    src = open(os.path.join(ROOT, "java", "com", "mellanox", "hadoop",
                            "mapred", "UdaBridge.java")).read()
    for sym in ("uda_bridge_start", "uda_bridge_do_command",
                "uda_bridge_reduce_exit", "uda_bridge_set_log_level",
                "uda_bridge_failed"):
        assert sym in src, f"binding for {sym} missing"
    shim = open(os.path.join(ROOT, "uda_tpu", "native",
                             "bridge_shim.cc")).read()
    # the callback table the Java side lays out must match the C struct
    order = ["fetch_over_message", "data_from_uda", "get_path_uda",
             "get_conf_data", "log_to", "failure_in_uda"]
    pos = [shim.index(f"(*{name})") for name in order]
    assert pos == sorted(pos), "uda_callbacks_t member order changed; " \
        "update UdaBridge.buildCallbacks offsets"
    assert "7 * 8L" in src  # ctx + 6 function pointers
    # the supplier up-calls are BOUND, not NULL slots (getPathUda round
    # trip, reference UdaBridge.cc:352-438)
    assert "cbs.set(ADDRESS, 24, getPath)" in src
    assert "cbs.set(ADDRESS, 32, getConf)" in src
    # uda_index_record_t: char path[4096] + 3 long longs — the Java
    # writer must use the same offsets as the C struct
    assert "char path[4096]" in shim
    for offset in ("4096", "4104", "4112"):
        assert f"out.set(JAVA_LONG, {offset}," in src, \
            f"IndexRecord field offset {offset} drifted"


def test_java_tree_structurally_valid():
    """Always-on compiler-less gate (scripts/build/check_java.py): the
    whole Java tree passes the string-aware structural pass — balanced
    braces, terminated literals, package<->path and type<->file
    agreement, in-tree import resolution. The REAL compile gate arms in
    ci.sh whenever a javac exists; this image has none and zero egress
    (documented there)."""
    import subprocess
    import sys as _sys

    r = subprocess.run(
        [_sys.executable,
         os.path.join(ROOT, "scripts", "build", "check_java.py")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_java_checker_catches_damage(tmp_path):
    """The structural checker must actually fail on mechanical damage
    (truncation, brace loss, class rename) — otherwise it gates
    nothing."""
    import shutil
    import subprocess
    import sys as _sys

    dst = os.path.join(str(tmp_path), "java")
    shutil.copytree(os.path.join(ROOT, "java"), dst)
    victim = os.path.join(dst, "com", "mellanox", "hadoop", "mapred",
                          "UdaBridge.java")
    src = open(victim).read()
    open(victim, "w").write(src[: len(src) // 2])  # truncate mid-file
    r = subprocess.run(
        [_sys.executable,
         os.path.join(ROOT, "scripts", "build", "check_java.py"), dst],
        capture_output=True, text=True)
    assert r.returncode == 1, r.stdout + r.stderr


def test_plugin_layer_sources_present():
    """Always-on: the Hadoop plugin cluster exists with the
    reference-parity shapes (SURVEY §2.2 J2-J4) — the classes a Hadoop
    jar loads, not just the FFM binding."""
    jdir = os.path.join(ROOT, "java", "com", "mellanox", "hadoop",
                        "mapred")
    rt = open(os.path.join(jdir, "UdaPluginRT.java")).read()
    # J2: budget calc, INIT construction, KVBuf ring, J2CQueue
    assert "mapred.rdma.shuffle.total.size" in rt
    assert "mapred.job.shuffle.input.buffer.percent" in rt
    assert "KV_BUF_NUM" in rt and "RECV_READY" in rt
    assert "class J2CQueue implements RawKeyValueIterator" in rt
    assert "INIT_COMMAND" in rt
    # the 1 Hz log-level re-sync (reference UdaPlugin.java:99-143)
    assert "logLevelTimer.schedule" in rt and "1000, 1000" in rt
    # J3: shared fallback machinery
    shared = open(os.path.join(
        jdir, "UdaShuffleConsumerPluginShared.java")).read()
    assert "doFallbackInit" in shared
    assert "mapred.rdma.developer.mode" in shared
    assert "GetMapEventsThread" in shared
    assert "shouldReset" in shared
    # J4: provider plugins + the SPI adapter
    sh = open(os.path.join(jdir, "UdaPluginSH.java")).read()
    assert "UdaIndexResolver" in sh and "addJob" in sh
    handler = open(os.path.join(jdir, "UdaShuffleHandler.java")).read()
    assert "extends AuxiliaryService" in handler
    assert "initializeApplication" in handler
    resolver = open(os.path.join(jdir, "UdaIndexResolver.java")).read()
    assert "getPathIndex" in resolver and "file.out.index" in resolver
    spi = open(os.path.join(jdir, "UdaShuffleConsumerPlugin.java")).read()
    assert "implements ShuffleConsumerPlugin" in spi


def _build_java(tmp_path):
    shim = os.path.join(ROOT, "uda_tpu", "native", "libuda_tpu_bridge.so")
    if not os.path.exists(shim):
        rc = subprocess.run(["make", "-C",
                             os.path.join(ROOT, "uda_tpu", "native"),
                             "libuda_tpu_bridge.so"]).returncode
        assert rc == 0, "shim build failed"
    build = tmp_path / "classes"
    rc = subprocess.run(["make", "-C", os.path.join(ROOT, "java"),
                         f"BUILD={build}"]).returncode
    assert rc == 0, "javac build failed"
    return shim, build


@pytest.mark.skipif(_jdk_version() < 22,
                    reason="needs a JDK 22+ (java.lang.foreign)")
@pytest.mark.parametrize("mode", ["dirs", "upcall"])
def test_jvm_plugin_stack_drives_job(tmp_path, mode):
    """The FULL Hadoop plugin stack from the JVM: ShuffleConsumerPlugin
    SPI init/run/close, GetMapEventsThread dedupe + fetch, KVBuf ring +
    J2CQueue drain — and in 'upcall' mode the supplier-side getPathUda
    round trip through UdaIndexResolver."""
    shim, build = _build_java(tmp_path)
    job = "job_202607_0001"
    num_maps = 3
    # Hadoop-real ids: the tree's attempt infix omits the job_ prefix
    expected = make_mof_tree(str(tmp_path), "202607_0001", num_maps, 1,
                             30, seed=77)
    os.rename(tmp_path / "202607_0001", tmp_path / job)
    out_file = tmp_path / "merged.bin"
    env = dict(os.environ)
    env["UDA_TPU_PY_BOOTSTRAP"] = (
        "import sys; sys.path.insert(0, %r); "
        "import os; os.environ['JAX_PLATFORMS']='cpu'" % ROOT)
    proc = subprocess.run(
        ["java", "--enable-native-access=ALL-UNNAMED", "-cp", str(build),
         "com.mellanox.hadoop.mapred.UdaJobDriver", shim,
         str(tmp_path), job, str(num_maps), str(out_file), mode],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "JVM-PLUGIN-OK" in proc.stdout

    got = list(IFileReader(io.BytesIO(out_file.read_bytes())))
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    want = sorted(expected[0], key=functools.cmp_to_key(
        lambda a, b: kt.compare(a[0], b[0])))
    assert got == want
