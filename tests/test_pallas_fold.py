"""Folded keys cascade (ops.pallas_fold) vs the standard keys8 pipeline."""

import numpy as np
import pytest

from uda_tpu.ops import pallas_fold, pallas_sort

pytestmark = pytest.mark.slow  # interpret-mode Pallas kernels


def _keys(n, seed, dup=False):
    rng = np.random.default_rng(seed)
    x = np.zeros((8, n), np.uint32)
    x[:3] = rng.integers(0, 2 ** 32, (3, n), dtype=np.uint32)
    if dup:
        x[:3, : n // 4] = x[:3, n // 2: n // 2 + n // 4]
    return x


@pytest.mark.parametrize("n,tile", [(256, 256), (1024, 256), (2048, 512),
                                    (4096, 512)])
def test_folded_matches_standard(n, tile):
    x = _keys(n, seed=n, dup=True)
    a = np.asarray(pallas_sort.sort_lanes(x, num_keys=3, tb_row=7,
                                          tile=tile, interpret=True))
    b = np.asarray(pallas_fold.sort_lanes_folded(x, num_keys=3, tile=tile,
                                                 interpret=True))
    np.testing.assert_array_equal(a, b)


def test_folded_narrow_keys_and_guards():
    x = _keys(512, seed=9)
    # num_keys < 3: rows beyond the keys are zero filler, still exact
    a = np.asarray(pallas_sort.sort_lanes(x, num_keys=2, tb_row=7,
                                          tile=256, interpret=True))
    b = np.asarray(pallas_fold.sort_lanes_folded(x, num_keys=2, tile=256,
                                                 interpret=True))
    np.testing.assert_array_equal(a, b)
    with pytest.raises(ValueError, match="num_keys"):
        pallas_fold.sort_lanes_folded(x, num_keys=4, tile=256,
                                      interpret=True)
    with pytest.raises(ValueError, match="tile"):
        pallas_fold.sort_lanes_folded(x, num_keys=3, tile=128,
                                      interpret=True)
    with pytest.raises(ValueError, match="8-row"):
        pallas_fold.sort_lanes_folded(np.zeros((32, 512), np.uint32),
                                      num_keys=3, tile=256, interpret=True)


def test_keys8_sort_perm_folded_param():
    # the shared core routes to the folded cascade and falls back to
    # the standard one when the tile cannot fold — same results
    x = _keys(1024, seed=5, dup=True)
    sk0, p0 = pallas_sort.keys8_sort_perm(x[:3], tile=256, interpret=True)
    sk1, p1 = pallas_sort.keys8_sort_perm(x[:3], tile=256, interpret=True,
                                          folded=True)
    sk2, p2 = pallas_sort.keys8_sort_perm(x[:3], tile=128, interpret=True,
                                          folded=True)  # fallback
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p2))
    np.testing.assert_array_equal(np.asarray(sk0), np.asarray(sk1))
