"""Host round planner + mesh topology units (uda_tpu/parallel/planner,
uda_tpu/parallel/mesh): pure host-side logic — no device work, no mesh
construction beyond names. The device-facing halves (the round bodies
the plans drive) are pinned by tests/test_exchange_hier.py."""

import numpy as np
import pytest

from uda_tpu.parallel import MeshTopology, WindowPlan, plan_rounds
from uda_tpu.parallel.mesh import is_dcn_axis
from uda_tpu.parallel.planner import record_window_metrics
from uda_tpu.utils.metrics import metrics

TOPO_2x4 = MeshTopology("dcn", "ici", 2, 4)


def test_is_dcn_axis_tagging():
    assert is_dcn_axis("dcn")
    assert is_dcn_axis("dcn0") and is_dcn_axis("dcn_outer")
    assert not is_dcn_axis("shuffle")
    assert not is_dcn_axis("ici")
    assert not is_dcn_axis("data")


def test_topology_helpers_4x2():
    t = MeshTopology("dcn", "shuffle", 4, 2)
    assert t.num_devices == 8 and t.hierarchical
    assert [t.pod_of(i) for i in range(8)] == [0, 0, 1, 1, 2, 2, 3, 3]
    assert [t.chip_of(i) for i in range(8)] == [0, 1] * 4
    assert list(t.pod_members(2)) == [4, 5]
    # egress stays inside the pod's chip range and is pair-symmetric
    for g in range(4):
        for g2 in range(4):
            assert 0 <= t.egress_chip(g, g2) < 2
            assert t.egress_chip(g, g2) == t.egress_chip(g2, g)


def test_plan_single_window_when_capacity_covers():
    counts = np.zeros((8, 8), np.int64)
    counts[1, 2] = 7
    plan = plan_rounds(counts, 8, TOPO_2x4, record_bytes=12,
                       hierarchical=True)
    assert plan.planned == 1 and plan.skipped == 0
    assert len(plan.windows) == 1
    assert plan.windows[0].moved_rows == 7
    assert plan.record_bytes == 12 and plan.hierarchical


def test_plan_window_indices_and_draining_tail():
    # bucket of 5 at capacity 2: windows 0..2 move 2, 2, 1 rows
    counts = np.zeros((8, 8), np.int64)
    counts[0, 7] = 5                       # pod 0 -> pod 1
    plan = plan_rounds(counts, 2, TOPO_2x4, record_bytes=4,
                       hierarchical=True)
    assert [w.index for w in plan.windows] == [0, 1, 2]
    assert [w.moved_rows for w in plan.windows] == [2, 2, 1]
    assert [w.dcn_rows for w in plan.windows] == [2, 2, 1]
    assert all(w.dcn_messages == 1 for w in plan.windows)
    assert not any(w.empty for w in plan.windows)


def test_plan_self_delivery_is_not_wire_traffic():
    counts = np.zeros((8, 8), np.int64)
    counts[3, 3] = 4                       # device to itself
    plan = plan_rounds(counts, 4, TOPO_2x4, record_bytes=4,
                       hierarchical=True)
    w = plan.windows[0]
    assert w.moved_rows == 4
    assert (w.ici_rows, w.dcn_rows, w.dcn_messages) == (0, 0, 0)


def test_plan_hierarchical_staging_hops_exact():
    # pod pair (0 -> 1): egress chip = (0 + 1) % 4 = 1. A record from
    # chip 1 to dst chip 1 takes NO staging hops (src == egress ==
    # ingress == dst); from chip 0 to dst chip 0 it takes both.
    counts = np.zeros((8, 8), np.int64)
    counts[1, 5] = 10                      # (pod 0, chip 1) -> (1, 1)
    plan = plan_rounds(counts, 16, TOPO_2x4, record_bytes=4,
                       hierarchical=True)
    assert plan.windows[0].ici_rows == 0
    assert plan.windows[0].dcn_rows == 10
    counts2 = np.zeros((8, 8), np.int64)
    counts2[0, 4] = 10                     # (pod 0, chip 0) -> (1, 0)
    plan2 = plan_rounds(counts2, 16, TOPO_2x4, record_bytes=4,
                        hierarchical=True)
    assert plan2.windows[0].ici_rows == 20     # both hops, 10 rows each
    assert plan2.windows[0].dcn_rows == 10


def test_plan_flat_wire_on_pod_mesh_counts_device_pairs():
    counts = np.zeros((8, 8), np.int64)
    counts[0, 4] = 1
    counts[0, 5] = 1
    counts[1, 4] = 1                       # 3 cross device pairs, 1 pod pair
    counts[2, 3] = 6                       # intra-pod
    flat = plan_rounds(counts, 8, TOPO_2x4, record_bytes=4,
                       hierarchical=False)
    hier = plan_rounds(counts, 8, TOPO_2x4, record_bytes=4,
                       hierarchical=True)
    assert flat.windows[0].dcn_messages == 3
    assert hier.windows[0].dcn_messages == 1
    assert flat.windows[0].dcn_rows == hier.windows[0].dcn_rows == 3
    assert flat.windows[0].ici_rows == 6   # intra-pod off-device rows


def test_plan_per_pod_breakdown_sums_to_totals():
    rng = np.random.default_rng(3)
    counts = rng.integers(0, 9, size=(8, 8)).astype(np.int64)
    for hier in (False, True):
        plan = plan_rounds(counts, 3, TOPO_2x4, record_bytes=4,
                           hierarchical=hier)
        for w in plan.windows:
            assert sum(r for _, r, _ in w.per_pod) == w.dcn_rows
            assert sum(m for _, _, m in w.per_pod) == w.dcn_messages


def test_plan_flat_mesh_topology_none():
    counts = np.zeros((4, 4), np.int64)
    counts[0, 1] = 2
    plan = plan_rounds(counts, 2, None, record_bytes=4)
    w = plan.windows[0]
    assert (w.dcn_rows, w.dcn_messages, w.per_pod) == (0, 0, ())
    assert w.ici_rows == 2


def test_plan_empty_and_zero_capacity_guard():
    empty = plan_rounds(np.zeros((4, 4), np.int64), 5, None,
                        record_bytes=4)
    assert empty.planned == 1 and empty.skipped == 1
    assert empty.windows == ()
    none = plan_rounds(np.zeros((0, 0), np.int64), 5, None,
                       record_bytes=4)
    assert none.skipped == 1
    # non-positive capacity plans zero deliverable windows — refuse
    # loudly instead of silently dropping the shuffle
    counts = np.ones((4, 4), np.int64)
    for cap in (0, -3):
        with pytest.raises(ValueError, match="capacity"):
            plan_rounds(counts, cap, None, record_bytes=4)
    # hierarchical delivery tags are int32: P*capacity past 2^31 would
    # wrap and misdeliver — the planner rejects it up front
    with pytest.raises(ValueError, match="tag overflow"):
        plan_rounds(np.ones((8, 8), np.int64), 1 << 28, TOPO_2x4,
                    record_bytes=4, hierarchical=True)


def test_record_window_metrics_label_series():
    metrics.reset()
    win = WindowPlan(index=0, moved_rows=9, ici_rows=3, dcn_rows=6,
                     dcn_messages=2, per_pod=((0, 4, 1), (1, 2, 1)))
    record_window_metrics(win, 16)
    assert metrics.get("exchange.ici.bytes") == 3 * 16
    assert metrics.get("exchange.dcn.bytes") == 6 * 16
    assert metrics.get("exchange.dcn.bytes", pod=0) == 4 * 16
    assert metrics.get("exchange.dcn.bytes", pod=1) == 2 * 16
    assert metrics.get("exchange.dcn.messages") == 2
    assert metrics.get("exchange.dcn.messages", pod=0) == 1
    metrics.reset()


def test_record_window_metrics_zero_rows_is_silent():
    metrics.reset()
    win = WindowPlan(index=0, moved_rows=2, ici_rows=0, dcn_rows=0,
                     dcn_messages=0, per_pod=())
    record_window_metrics(win, 16)
    assert metrics.get("exchange.ici.bytes") == 0
    assert metrics.get("exchange.dcn.bytes") == 0
    assert "exchange.ici.bytes" not in metrics.counters
    metrics.reset()


def test_windowplan_empty_property():
    assert WindowPlan(0, 0, 0, 0, 0, ()).empty
    assert not WindowPlan(0, 1, 1, 0, 0, ()).empty
