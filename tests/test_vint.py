"""VInt/VLong codec conformance (reference src/CommUtils/IOUtility.cc:167-397)."""

import numpy as np
import pytest

from uda_tpu.utils import vint


# Known-good vectors computed from Hadoop WritableUtils.writeVLong semantics.
KNOWN = [
    (0, b"\x00"),
    (1, b"\x01"),
    (127, b"\x7f"),
    (-1, b"\xff"),
    (-112, b"\x90"),
    (128, b"\x8f\x80"),
    (255, b"\x8f\xff"),
    (256, b"\x8e\x01\x00"),
    (-113, b"\x87\x70"),
    (-256, b"\x87\xff"),
    (-257, b"\x86\x01\x00"),
    (65535, b"\x8e\xff\xff"),
    (2**31 - 1, b"\x8c\x7f\xff\xff\xff"),
    (-(2**31), b"\x84\x7f\xff\xff\xff"),
    (2**63 - 1, b"\x88" + b"\x7f" + b"\xff" * 7),
    (-(2**63), b"\x80" + b"\x7f" + b"\xff" * 7),
]


@pytest.mark.parametrize("value,encoded", KNOWN)
def test_known_vectors(value, encoded):
    assert vint.encode_vlong(value) == encoded
    got, off = vint.decode_vlong(encoded)
    assert got == value
    assert off == len(encoded)
    assert vint.vlong_size(value) == len(encoded)


def test_round_trip_random():
    rng = np.random.default_rng(0)
    vals = list(rng.integers(-(2**62), 2**62, size=500))
    vals += [0, -1, 1, -112, -113, 127, 128, 2**63 - 1, -(2**63)]
    buf = b"".join(vint.encode_vlong(int(v)) for v in vals)
    pos = 0
    for v in vals:
        got, pos = vint.decode_vlong(buf, pos)
        assert got == int(v)
    assert pos == len(buf)


def test_decode_vint_size_matches_encoding():
    for v in (-(2**63), -2**40, -5000, -113, -112, -1, 0, 5, 127, 128, 2**40):
        enc = vint.encode_vlong(v)
        first = enc[0] - 256 if enc[0] > 127 else enc[0]
        assert vint.decode_vint_size(first) == len(enc)


def test_truncated_raises():
    enc = vint.encode_vlong(100000)
    with pytest.raises(IndexError):
        vint.decode_vlong(enc[:-1])


def test_stream_decode():
    vals = [1, -1, 300, -300, 2**40, 0, 127, -112]
    buf = np.frombuffer(b"".join(vint.encode_vlong(v) for v in vals), np.uint8)
    got, offs = vint.decode_vlong_stream(buf)
    assert got.tolist() == vals
    assert offs[0] == 0 and len(offs) == len(vals)
    got2, _ = vint.decode_vlong_stream(buf, count=3)
    assert got2.tolist() == vals[:3]
