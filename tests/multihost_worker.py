"""One process of the multi-process CPU exchange test (run by
test_multihost.py as ``multihost_worker.py <pid> <nprocs> <port>``).

Each process serves 4 virtual CPU devices; together they form the
8-device global shuffle mesh, and the SAME SPMD program as the
single-host path runs across the process boundary — the cross-node
capability of the reference's RDMA data plane (reference
src/DataNet/RDMAClient.cc:498-527 per-host connections), minus any
per-host connection bookkeeping."""

import os
import sys

pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from uda_tpu.parallel import multihost  # noqa: E402
from uda_tpu.parallel.distributed import (distributed_sort_step,  # noqa: E402
                                          uniform_splitters)

multihost.initialize(f"localhost:{port}", nprocs, pid)
assert jax.process_count() == nprocs, jax.process_count()
mesh = multihost.global_mesh()
P = len(jax.devices())
assert P == 4 * nprocs, P


def rows(seed: int, n: int) -> np.ndarray:
    return np.random.default_rng(seed).integers(0, 1 << 32, size=(n, 4),
                                                dtype=np.uint32)


per_proc = 512
local = rows(100 + pid, per_proc)
words = multihost.shard_rows(local, mesh)
res = distributed_sort_step(words, uniform_splitters(P), mesh, "shuffle",
                            capacity=2 * per_proc * nprocs // P, num_keys=2)
res.check()
out = multihost.allgather(res.words)
nvalid = multihost.allgather(res.valid_counts).reshape(-1)
shard = out.reshape(P, -1, 4)
got = np.concatenate([shard[d][: nvalid[d]] for d in range(P)])
allwords = np.concatenate([rows(100 + i, per_proc) for i in range(nprocs)])
ref = allwords[np.lexsort((allwords[:, 1], allwords[:, 0]))]
assert got.shape == ref.shape, (got.shape, ref.shape)
assert np.array_equal(got[:, :2], ref[:, :2]), "global key order mismatch"
assert sorted(map(tuple, got)) == sorted(map(tuple, allwords)), \
    "record multiset changed crossing the process boundary"

# the keys8 Pallas engine (interpret mode on the CPU mesh) must be
# byte-identical to the carry path ACROSS the process boundary too
res3 = distributed_sort_step(words, uniform_splitters(P), mesh, "shuffle",
                             capacity=2 * per_proc * nprocs // P,
                             num_keys=2, payload_path="keys8")
res3.check()
assert np.array_equal(multihost.allgather(res3.words), out), \
    "keys8 engine diverges across the process boundary"

# skew: every record to partition 0, capacity << bucket -> the windowed
# multi-round backlog path, across processes
local2 = local.copy()
local2[:, 0] = 0
words2 = multihost.shard_rows(local2, mesh)
res2 = distributed_sort_step(words2, uniform_splitters(P), mesh, "shuffle",
                             capacity=32, num_keys=1)
res2.check()
nv2 = multihost.allgather(res2.valid_counts).reshape(-1)
assert nv2[0] == per_proc * nprocs and nv2[1:].sum() == 0, nv2.tolist()

# the deployment-shaped 2-axis mesh ACROSS processes: process boundary
# = DCN axis, local devices = ICI axis (the v5p-64 topology the
# PARITY.md roofline models). Must be byte-identical to the flat mesh.
mesh2ax = multihost.global_mesh_2axis()
assert mesh2ax.devices.shape == (nprocs, P // nprocs)
words_2ax = multihost.shard_rows(local, mesh2ax, axis=("dcn", "shuffle"))
res4 = distributed_sort_step(words_2ax, uniform_splitters(P), mesh2ax,
                             ("dcn", "shuffle"),
                             capacity=2 * per_proc * nprocs // P,
                             num_keys=2)
res4.check()
assert np.array_equal(multihost.allgather(res4.words), out), \
    "2-axis (dcn, ici) mesh diverges from the flat mesh across processes"
assert np.array_equal(multihost.allgather(res4.valid_counts).reshape(-1),
                      nvalid), "2-axis valid counts diverge"

print(f"MULTIHOST-OK p{pid}", flush=True)
