"""Comparator semantics (reference src/Merger/CompareFunc.cc:70-113)."""

import struct

import pytest

from uda_tpu.utils import comparators, vint
from uda_tpu.utils.errors import UdaError


def _text(s: bytes) -> bytes:
    return vint.encode_vlong(len(s)) + s


def test_text_skips_vint_prefix():
    kt = comparators.get_key_type("org.apache.hadoop.io.Text")
    assert kt.compare(_text(b"apple"), _text(b"banana")) < 0
    assert kt.compare(_text(b"b"), _text(b"apple" * 100)) > 0
    assert kt.compare(_text(b"same"), _text(b"same")) == 0
    # shorter prefix sorts first
    assert kt.compare(_text(b"ab"), _text(b"abc")) < 0


def test_fixed_width_memcmp_semantics():
    kt = comparators.get_key_type("org.apache.hadoop.io.IntWritable")
    a = struct.pack(">i", 3)
    b = struct.pack(">i", 1000)
    assert kt.compare(a, b) < 0
    # reference uses memcmp: negative ints (high bit set) sort AFTER
    # positive — reproduce exactly (CompareFunc.cc:70-78)
    neg = struct.pack(">i", -5)
    assert kt.compare(neg, b) > 0


def test_numeric_variant_fixes_sign():
    kt = comparators.get_key_type("uda.tpu.IntNumeric")
    neg = struct.pack(">i", -5)
    pos = struct.pack(">i", 3)
    assert kt.normalize(neg, 4)[0] < kt.normalize(pos, 4)[0]


def test_bytes_writable_skips_length():
    kt = comparators.get_key_type("org.apache.hadoop.io.BytesWritable")
    a = struct.pack(">i", 2) + b"aa"
    b = struct.pack(">i", 1) + b"b"
    assert kt.compare(a, b) < 0


def test_unsupported_key_class_raises():
    with pytest.raises(UdaError):
        comparators.get_key_type("org.example.Custom")


def test_normalize_order_preserving():
    # for keys whose content fits the width, the (prefix, length) pair
    # must order exactly like the comparator — including trailing-NUL
    # pairs like b"a" vs b"a\x00" and b"\x01" vs b"\x01\x00"
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    keys = [b"", b"a", b"a\x00", b"a\x00\x00", b"ab", b"abc", b"b",
            b"\x01", b"\x01\x00", b"\x00", b"\xff\xff"]
    W = 8
    norm = [kt.normalize(k, W) for k in keys]
    for i in range(len(keys)):
        for j in range(len(keys)):
            c_full = comparators.memcmp(keys[i], keys[j])
            a, b = norm[i], norm[j]
            c_norm = comparators.memcmp(a[0], b[0]) or (a[1] > b[1]) - (a[1] < b[1])
            assert c_norm == c_full, (keys[i], keys[j])


def test_normalize_overflow_needs_rank():
    # keys longer than the width with equal prefixes tie on both columns;
    # ops.sort.overflow_ranks provides the third tiebreak
    kt = comparators.get_key_type("uda.tpu.RawBytes")
    a = kt.normalize(b"prefix__AAAA", 8)
    b = kt.normalize(b"prefix__BBBB", 8)
    assert a == b
