"""Hierarchical (multi-pod) exchange: the two-stage ICI/DCN round body
vs the flat single-stage path — byte-identity, pod accounting, the host
round planner, and failure semantics.

Everything here runs on the conftest 8-virtual-device CPU mesh, shaped
(dcn=2, ici=4) and (dcn=4, ici=2); the 4x4 and 8x8 shapes ride the slow
subprocess rung at the bottom (the device count locks at backend init,
so bigger meshes need fresh interpreters — scripts/exchange_bench.py is
the shared driver)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from uda_tpu.parallel import (distributed_sort_step, make_mesh,
                              mesh_from_config, mesh_topology,
                              plan_rounds, shuffle_exchange,
                              uniform_splitters)
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import ConfigError, TransportError
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.metrics import metrics

AXIS = "shuffle"
AXIS2 = ("dcn", AXIS)


def _mesh2(p=2, c=4, ici=AXIS):
    devs = np.asarray(jax.devices()[:p * c])
    return Mesh(devs.reshape(p, c), ("dcn", ici))


def _random_words(n, w, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 2**32, size=(n, w), dtype=np.uint32)


def _assert_rounds_identical(a, b):
    assert len(a) == len(b)
    for r, ((aw, ac), (bw, bc)) in enumerate(zip(a, b)):
        np.testing.assert_array_equal(np.asarray(ac), np.asarray(bc),
                                      err_msg=f"counts, round {r}")
        np.testing.assert_array_equal(np.asarray(aw), np.asarray(bw),
                                      err_msg=f"words, round {r}")


# -- topology descriptor -----------------------------------------------------

def test_mesh_topology_classification():
    mesh1 = make_mesh(8, AXIS)
    t1 = mesh_topology(mesh1, AXIS)
    assert not t1.hierarchical and t1.num_pods == 1 and t1.pod_size == 8
    mesh2 = _mesh2(2, 4)
    t2 = mesh_topology(mesh2, AXIS2)
    assert t2.hierarchical
    assert (t2.dcn_axis, t2.ici_axis) == ("dcn", AXIS)
    assert (t2.num_pods, t2.pod_size, t2.num_devices) == (2, 4, 8)
    assert t2.pod_of(5) == 1 and t2.chip_of(5) == 1
    assert list(t2.pod_members(1)) == [4, 5, 6, 7]
    # egress rotation: symmetric per pair, within the pod, spread
    for g in range(2):
        for g2 in range(2):
            e = t2.egress_chip(g, g2)
            assert 0 <= e < 4
            assert e == t2.egress_chip(g2, g)
    # untagged 2-axis tuples carry no pod semantics -> one flat group
    mesh_u = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                  ("rows", "cols"))
    tu = mesh_topology(mesh_u, ("rows", "cols"))
    assert not tu.hierarchical and tu.pod_size == 8


def test_mesh_from_config_dcn_ici_spec():
    cfg = Config({"uda.tpu.mesh.shape": "dcn:2,ici:4"})
    mesh = mesh_from_config(cfg)
    assert tuple(mesh.axis_names) == ("dcn", "ici")
    topo = mesh_topology(mesh, ("dcn", "ici"))
    assert topo.hierarchical and topo.num_pods == 2 and topo.pod_size == 4


def test_exchange_mode_dispatch_errors():
    mesh1 = make_mesh(8, AXIS)
    words = _random_words(64, 2, seed=1)
    dest = (words[:, 0] % 8).astype(np.int32)
    with pytest.raises(ConfigError, match="hierarchical"):
        shuffle_exchange(words, dest, mesh1, AXIS, capacity=8,
                         mode="hierarchical")
    with pytest.raises(ConfigError, match="unknown exchange mode"):
        shuffle_exchange(words, dest, mesh1, AXIS, capacity=8,
                         mode="bogus")


# -- byte-identity vs the flat exchange --------------------------------------

@pytest.mark.parametrize("shape", [(2, 4), (4, 2)])
def test_hierarchical_matches_flat_uniform(shape):
    p, c = shape
    mesh = _mesh2(p, c)
    words = _random_words(8 * 32, 3, seed=2)
    words[: 64, 0] = words[64:128, 0]       # duplicate keys ride along
    dest = (words[:, 1] % 8).astype(np.int32)
    hier, lay = shuffle_exchange(words, dest, mesh, AXIS2, capacity=9)
    assert lay.hierarchical
    flat, layf = shuffle_exchange(words, dest, mesh, AXIS2, capacity=9,
                                  mode="flat")
    assert not layf.hierarchical
    _assert_rounds_identical(hier, flat)


def test_hierarchical_matches_flat_skew_multiround():
    # extreme skew: every record to device 0 -> multi-round backlog;
    # the staged body must drain it identically to the flat windows
    mesh = _mesh2(2, 4)
    words = _random_words(8 * 16, 2, seed=3)
    dest = np.zeros(8 * 16, np.int32)
    hier, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=4)
    flat, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=4,
                               mode="flat")
    assert len(hier) == 4                    # 16 per bucket / capacity 4
    _assert_rounds_identical(hier, flat)


def test_hierarchical_empty_pod_edge():
    # every record lands in pod 0: pod 1 receives NOTHING (its tiles
    # are all-zero) and sends everything — the empty-ingress edge
    mesh = _mesh2(2, 4)
    words = _random_words(8 * 24, 2, seed=4)
    dest = (words[:, 0] % 4).astype(np.int32)    # devices 0..3 = pod 0
    metrics.reset()
    hier, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=24)
    msgs = metrics.get("exchange.dcn.messages")
    assert msgs == 1.0                       # only pod1 -> pod0 traffic
    assert metrics.get("exchange.dcn.messages", pod=1) == 1.0
    assert metrics.get("exchange.dcn.messages", pod=0) == 0.0
    flat, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=24,
                               mode="flat")
    _assert_rounds_identical(hier, flat)


def test_intra_pod_traffic_has_zero_dcn():
    mesh = _mesh2(2, 4)
    n = 8 * 16
    words = _random_words(n, 2, seed=5)
    dest = np.zeros(n, np.int32)
    shard = n // 8
    for s in range(8):
        base = (s // 4) * 4                  # stay inside my own pod
        dest[s * shard:(s + 1) * shard] = \
            base + words[s * shard:(s + 1) * shard, 1] % 4
    metrics.reset()
    hier, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=16)
    assert metrics.get("exchange.dcn.messages") == 0.0
    assert metrics.get("exchange.dcn.bytes") == 0.0
    assert metrics.get("exchange.ici.bytes") > 0.0
    flat, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=16,
                               mode="flat")
    _assert_rounds_identical(hier, flat)


def test_dcn_accounting_pod_pair_coalescing():
    # the tentpole claim at test scale: same DCN bytes, messages drop
    # from cross-pod DEVICE pairs to POD pairs
    mesh = _mesh2(2, 4)
    words = _random_words(8 * 32, 3, seed=6)
    dest = (words[:, 1] % 8).astype(np.int32)
    metrics.reset()
    shuffle_exchange(words, dest, mesh, AXIS2, capacity=32)
    hier = {k: metrics.get(k) for k in
            ("exchange.dcn.bytes", "exchange.dcn.messages",
             "exchange.ici.bytes")}
    metrics.reset()
    shuffle_exchange(words, dest, mesh, AXIS2, capacity=32, mode="flat")
    flat = {k: metrics.get(k) for k in
            ("exchange.dcn.bytes", "exchange.dcn.messages",
             "exchange.ici.bytes")}
    assert hier["exchange.dcn.bytes"] == flat["exchange.dcn.bytes"] > 0
    assert hier["exchange.dcn.messages"] <= 2 * 1     # p*(p-1) pod pairs
    assert flat["exchange.dcn.messages"] > hier["exchange.dcn.messages"]
    # the coalescing price: staging hops add ICI traffic, bounded by 2x
    # the DCN rows
    assert hier["exchange.ici.bytes"] <= (flat["exchange.ici.bytes"]
                                          + 2 * flat["exchange.dcn.bytes"])


# -- host round planner ------------------------------------------------------

def test_empty_exchange_skips_round():
    mesh = _mesh2(2, 4)
    metrics.reset()
    results, _ = shuffle_exchange(np.zeros((0, 3), np.uint32),
                                  np.zeros(0, np.int32), mesh, AXIS2,
                                  capacity=4)
    assert results == []
    assert metrics.get("exchange.rounds") == 0.0
    assert metrics.get("exchange.rounds.skipped") == 1.0


def test_plan_rounds_accounting():
    mesh = _mesh2(2, 4)
    topo = mesh_topology(mesh, AXIS2)
    counts = np.zeros((8, 8), np.int64)
    counts[0, 5] = 5          # pod 0 -> pod 1, needs 3 windows at cap 2
    counts[1, 6] = 1          # pod 0 -> pod 1 (same pod pair)
    counts[4, 4] = 2          # self-delivery: no wire traffic
    counts[2, 3] = 4          # intra-pod 0
    plan = plan_rounds(counts, 2, topo, record_bytes=8,
                       hierarchical=True)
    assert plan.planned == 3 and plan.skipped == 0
    w0 = plan.windows[0]
    # window 0: 2+1 cross rows in ONE pod-pair message, 2 intra rows
    assert w0.dcn_rows == 3 and w0.dcn_messages == 1
    assert w0.per_pod == ((0, 3, 1),)
    assert w0.moved_rows == 2 + 1 + 2 + 2
    flat_plan = plan_rounds(counts, 2, topo, record_bytes=8,
                            hierarchical=False)
    # flat: each cross-pod device pair is its own DCN message
    assert flat_plan.windows[0].dcn_messages == 2
    assert flat_plan.windows[0].dcn_rows == 3
    # identical per-window DCN rows either way (coalescing moves the
    # same bytes in fewer messages)
    for wh, wf in zip(plan.windows, flat_plan.windows):
        assert wh.dcn_rows == wf.dcn_rows
    # all-empty counts: one planned window, skipped
    empty = plan_rounds(np.zeros((8, 8), np.int64), 2, topo,
                        record_bytes=8, hierarchical=True)
    assert empty.planned == 1 and empty.skipped == 1
    assert empty.windows == ()


# -- distributed step dispatch ----------------------------------------------

def test_fused_step_hier_matches_flat_mesh():
    mesh1 = make_mesh(8, AXIS)
    mesh2 = _mesh2(2, 4)
    words = _random_words(1024, 4, seed=7)
    spl = uniform_splitters(8)
    r1 = distributed_sort_step(words, spl, mesh1, AXIS, capacity=256,
                               num_keys=2)
    r1.check()
    r2 = distributed_sort_step(words, spl, mesh2, AXIS2, capacity=256,
                               num_keys=2)
    r2.check()
    np.testing.assert_array_equal(np.asarray(r1.words),
                                  np.asarray(r2.words))
    np.testing.assert_array_equal(np.asarray(r1.valid_counts),
                                  np.asarray(r2.valid_counts))
    # forced-flat on the same 2-axis mesh: also identical
    r3 = distributed_sort_step(words, spl, mesh2, AXIS2, capacity=256,
                               num_keys=2, exchange_mode="flat")
    r3.check()
    np.testing.assert_array_equal(np.asarray(r2.words),
                                  np.asarray(r3.words))


def test_multiround_scatter_on_staged_body():
    # skew far past the credit window: the multiround accumulator path
    # must produce identical shards through the two-stage body
    mesh1 = make_mesh(8, AXIS)
    mesh2 = _mesh2(2, 4)
    words = _random_words(512, 3, seed=8)
    words[:, 0] = 0                          # all records to device 0
    spl = uniform_splitters(8)
    a = distributed_sort_step(words, spl, mesh2, AXIS2, capacity=16,
                              num_keys=1, multiround="always")
    b = distributed_sort_step(words, spl, mesh1, AXIS, capacity=16,
                              num_keys=1, multiround="always")
    a.check()
    b.check()
    np.testing.assert_array_equal(np.asarray(a.words),
                                  np.asarray(b.words))
    nv = np.asarray(a.valid_counts).reshape(-1)
    assert nv[0] == 512 and nv[1:].sum() == 0


def test_auto_mode_pod_size_one_stays_flat():
    # dcn:8,ici:1 has a DCN axis but no intra-pod fan-out: nothing to
    # coalesce, auto keeps the single-stage path (and still works)
    devs = np.asarray(jax.devices()[:8])
    mesh = Mesh(devs.reshape(8, 1), ("dcn", AXIS))
    topo = mesh_topology(mesh, AXIS2)
    assert topo.num_pods == 8 and topo.pod_size == 1
    assert not topo.hierarchical
    words = _random_words(64, 2, seed=10)
    dest = (words[:, 0] % 8).astype(np.int32)
    results, lay = shuffle_exchange(words, dest, mesh, AXIS2, capacity=8)
    assert not lay.hierarchical and len(results) >= 1


def test_recv_counts_match_counts_matrix():
    # the staged body's recv_counts must equal the windowed counts
    # matrix column — the planner and the device program agree on what
    # moved
    mesh = _mesh2(2, 4)
    words = _random_words(8 * 20, 2, seed=11)
    dest = (words[:, 1] % 8).astype(np.int32)
    cap = 7
    results, lay = shuffle_exchange(words, dest, mesh, AXIS2,
                                    capacity=cap)
    counts = np.asarray(lay.counts)
    for r, (_, rc) in enumerate(results):
        got = np.asarray(rc).reshape(8, 8)      # [dst, src]
        want = np.clip(counts - r * cap, 0, cap).T
        np.testing.assert_array_equal(got, want, err_msg=f"round {r}")


def test_hierarchical_capacity_one_many_rounds():
    mesh = _mesh2(4, 2)
    words = _random_words(8 * 6, 2, seed=12)
    dest = (words[:, 0] % 8).astype(np.int32)
    hier, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=1)
    flat, _ = shuffle_exchange(words, dest, mesh, AXIS2, capacity=1,
                               mode="flat")
    assert len(hier) > 1
    _assert_rounds_identical(hier, flat)


def test_exchange_blobs_rides_hierarchical_mesh():
    # the opaque-bytes transport (bytes_exchange) runs on the same
    # shuffle_exchange: a hierarchical mesh must reassemble every blob
    # byte-exactly, same as the flat 1-axis mesh
    from uda_tpu.parallel import exchange_blobs

    mesh1 = make_mesh(8, AXIS)
    mesh2 = _mesh2(2, 4)
    rng = np.random.default_rng(13)
    blobs = [[(int(rng.integers(0, 8)),
               rng.bytes(int(rng.integers(0, 900))))
              for _ in range(3)] for _ in range(8)]
    out1 = exchange_blobs(blobs, mesh1, AXIS)
    out2 = exchange_blobs(blobs, mesh2, AXIS2)
    assert out1 == out2
    # spot-check contents against the send lists
    for s in range(8):
        for dst, payload in blobs[s]:
            assert payload in out2[dst][s]


def test_planner_flat_mesh_has_no_dcn_series():
    mesh = make_mesh(8, AXIS)
    topo = mesh_topology(mesh, AXIS)
    counts = np.zeros((8, 8), np.int64)
    counts[0, 1] = 3
    counts[2, 2] = 5                   # self rows: moved, not wired
    plan = plan_rounds(counts, 4, topo, record_bytes=8)
    assert plan.planned == 2 and plan.skipped == 0
    w0 = plan.windows[0]
    assert (w0.dcn_rows, w0.dcn_messages, w0.per_pod) == (0, 0, ())
    assert w0.ici_rows == 3 and w0.moved_rows == 7


def test_egress_rotation_is_balanced_on_square_meshes():
    # p == c: for any source pod, the egress map g' -> (g+g') % c is a
    # bijection — every chip relays exactly one peer-pod pair, no chip
    # is the pod's single DCN chokepoint
    from uda_tpu.parallel import MeshTopology

    topo = MeshTopology("dcn", "ici", 8, 8)
    for g in range(8):
        peers = [topo.egress_chip(g, g2) for g2 in range(8) if g2 != g]
        assert len(set(peers)) == len(peers)


# -- failure semantics -------------------------------------------------------

@pytest.mark.faults
def test_exchange_stage_b_failpoint_surfaces_transport_error():
    # a fault injected at the cross-pod (DCN) stage of a hierarchical
    # round must surface as TransportError, exactly like a whole-round
    # collective failure (the WC-error contract)
    mesh = _mesh2(2, 4)
    words = _random_words(8 * 16, 2, seed=9)
    dest = (words[:, 0] % 8).astype(np.int32)
    with failpoints.scoped("exchange.round=error:match:stageB"):
        with pytest.raises(TransportError) as ei:
            shuffle_exchange(words, dest, mesh, AXIS2, capacity=16)
        assert "exchange.round" in str(ei.value)
    # flat mode never reaches the stage-B rung: the armed match fires
    # nothing and the exchange completes
    with failpoints.scoped("exchange.round=error:match:stageB"):
        results, _ = shuffle_exchange(words, dest, mesh, AXIS2,
                                      capacity=16, mode="flat")
    assert len(results) == 1


@pytest.mark.faults
def test_exchange_mid_backlog_failpoint_round_key():
    # a fault keyed to a LATER window of a skewed multi-round exchange
    # fires only once the backlog reaches it — earlier rounds complete
    mesh = _mesh2(2, 4)
    words = _random_words(8 * 16, 2, seed=14)
    dest = np.zeros(8 * 16, np.int32)            # 4 rounds at capacity 4
    metrics.reset()
    with failpoints.scoped("exchange.round=error:match:round2"):
        with pytest.raises(TransportError):
            shuffle_exchange(words, dest, mesh, AXIS2, capacity=4)
    assert metrics.get("exchange.rounds") >= 2.0


# -- bigger shapes (fresh interpreters; the bench is the driver) -------------

@pytest.mark.slow
@pytest.mark.parametrize("spec", ["dcn:4,ici:4", "dcn:8,ici:8"])
def test_hier_byte_identity_subprocess_scale(spec, tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ndev = 1
    for part in spec.split(","):
        ndev *= int(part.split(":")[1])
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=f"--xla_force_host_platform_device_count={ndev}")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts",
                                      "exchange_bench.py"),
         "--child", spec, "--rows-per-device", "16"],
        capture_output=True, text=True, timeout=1200, env=env, cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:] or proc.stdout[-2000:]
    import json

    acct = None
    for line in proc.stdout.splitlines():
        if line.startswith("ACCT "):
            acct = json.loads(line[5:])
    assert acct is not None and acct["ok"]
    for case in acct["cases"]:
        assert all(case["checks"].values()), (spec, case)
        assert (case["hierarchical"]["dcn_messages_per_round_max"]
                <= case["pod_pair_bound"])
