"""scripts/stagelib.py: the budgeted-subprocess runner shared by the
staged pool drivers (tpu_return / sweep_carrychunk / pool_watch). The
kill discipline matters: a timed-out stage must die as a whole process
group (a surviving grandchild holding the pool's single device claim is
the documented wedge trigger)."""

import os
import sys
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts"))
from stagelib import run_stage  # noqa: E402


def test_ok_stage_writes_log(tmp_path):
    ok, timed_out = run_stage(
        "hello", [sys.executable, "-c", "print('from-stage')"],
        30, str(tmp_path))
    assert ok and not timed_out
    assert "from-stage" in (tmp_path / "hello.log").read_text()


def test_failing_stage_reports_not_ok(tmp_path):
    ok, timed_out = run_stage(
        "boom", [sys.executable, "-c", "raise SystemExit(3)"],
        30, str(tmp_path))
    assert not ok and not timed_out


def test_timeout_kills_whole_process_group(tmp_path):
    # the stage spawns a GRANDCHILD that would outlive a naive
    # child-only kill; both must be dead right after run_stage returns
    pidfile = tmp_path / "grandchild.pid"
    prog = (
        "import subprocess, sys, time\n"
        f"p = subprocess.Popen([sys.executable, '-c', "
        f"'import time; time.sleep(60)'])\n"
        f"open({str(pidfile)!r}, 'w').write(str(p.pid))\n"
        "time.sleep(60)\n"
    )
    t0 = time.perf_counter()
    ok, timed_out = run_stage("hang", [sys.executable, "-c", prog],
                              2, str(tmp_path))
    assert not ok and timed_out
    assert time.perf_counter() - t0 < 15
    assert "TIMEOUT" in (tmp_path / "hang.log").read_text()
    gc_pid = int(pidfile.read_text())
    # the grandchild shared the stage's session; killpg must have
    # reached it (allow a moment for reaping by init)
    for _ in range(50):
        try:
            os.kill(gc_pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.1)
    else:
        os.kill(gc_pid, 9)  # clean up before failing
        raise AssertionError("grandchild survived the process-group kill")
