"""Survivable shuffle (ISSUE 8): k-of-n erasure-coded map outputs
(uda_tpu.coding — GF(2^8) Reed-Solomon codec, striped layout, v2
index, stripe-aware recovery), speculative dual-source fetch, and
supplier warm-restart with fetch-epoch handoff.

The ``faults``-marked rungs double as the chaos COMPLETION tier
(scripts/run_chaos.sh): a seeded supplier kill or bounce must end in a
byte-correct finished job — recovery counters > 0 and zero
FallbackSignals — not merely a clean fallback.
"""

import io
import itertools
import os
import random
import threading
import time

import numpy as np
import pytest

from tests.helpers import make_mof_tree, map_ids
from uda_tpu.coding import (CodingScheme, parse_scheme, shard_map_id,
                            parse_shard_id, stripe_host)
from uda_tpu.coding import gf256, rs
from uda_tpu.coding.recovery import StripeContext
from uda_tpu.merger import (HostRoutingClient, LocalFetchClient,
                            MergeManager, PenaltyBox, RecoveryLedger,
                            Segment)
from uda_tpu.mofserver import (DataEngine, DirIndexResolver, FetchResult,
                               ShuffleRequest, read_index_file,
                               write_index_file)
from uda_tpu.mofserver.writer import (write_map_output,
                                      write_striped_map_output)
from uda_tpu.utils.config import Config
from uda_tpu.utils.errors import (ConfigError, FallbackSignal,
                                  StorageError, TransportError)
from uda_tpu.utils.failpoints import failpoints
from uda_tpu.utils.ifile import IFileReader
from uda_tpu.utils.metrics import metrics
from uda_tpu.utils.retry import RetryPolicy, SpeculationPolicy

JOB = "job_coding"


# -- GF(2^8) + RS codec ------------------------------------------------------

def test_gf256_field_properties():
    # alpha = 2 generates the full multiplicative group of 255 elements
    assert len(set(gf256.EXP[:255].tolist())) == 255
    rng = random.Random(0)
    for _ in range(500):
        a = rng.randrange(256)
        b = rng.randrange(1, 256)
        c = rng.randrange(256)
        assert gf256.gf_mul(gf256.gf_mul(a, b), gf256.gf_inv(b)) == a
        # distributivity over XOR (the field's addition)
        assert gf256.gf_mul(a, b ^ c) == \
            gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)
    with pytest.raises(ZeroDivisionError):
        gf256.gf_inv(0)


def test_gf256_matrix_inverse():
    for k in (1, 2, 4, 7):
        a = rs.parity_matrix(k, 2 * k)  # a k x k Cauchy minor
        inv = gf256.inv_matrix(a)
        prod = gf256.matmul(a, inv)
        assert np.array_equal(prod, np.eye(k, dtype=np.uint8))
    with pytest.raises(np.linalg.LinAlgError):
        gf256.inv_matrix(np.zeros((2, 2), dtype=np.uint8))


@pytest.mark.parametrize("k,n", [(1, 1), (1, 3), (2, 3), (4, 6), (3, 3),
                                 (2, 5)])
def test_rs_roundtrip_every_erasure_pattern(k, n):
    """Any k of the n stripe chunks reconstruct the blob — exhaustively
    over every k-subset (the MDS property), over sizes that exercise
    empty, sub-chunk, unaligned and multi-chunk stripes."""
    rng = random.Random(42)
    for size in (0, 1, 17, 256, 1025):
        blob = rng.randbytes(size)
        chunks = {i: c for i, c in enumerate(rs.split_data(blob, k))}
        chunks.update({k + j: p for j, p in
                       enumerate(rs.encode_parity(blob, k, n))})
        assert len(chunks) == n
        for subset in itertools.combinations(range(n), k):
            got = rs.decode({i: chunks[i] for i in subset}, k, n, size)
            assert got == blob, (k, n, size, subset)


def test_rs_systematic_identity_and_failure_modes():
    blob = bytes(range(256)) * 3
    # n == k: no parity, decode of the data chunks is pure concat
    assert rs.encode_parity(blob, 4, 4) == []
    data = {i: c for i, c in enumerate(rs.split_data(blob, 4))}
    assert rs.decode(data, 4, 4, len(blob)) == blob
    # fewer than k chunks is typed, loud, and names the shortfall
    with pytest.raises(StorageError, match="unrecoverable"):
        rs.decode({0: data[0]}, 4, 6, len(blob))
    with pytest.raises(StorageError):
        rs.decode({0: data[0], 9: b"x"}, 4, 6, len(blob))  # bad index


def test_scheme_parsing():
    assert parse_scheme("") is None and parse_scheme(None) is None
    s = parse_scheme("rs:4:6")
    assert s == CodingScheme(4, 6) and s.parity == 2
    assert str(s) == "rs:4:6"
    for bad in ("rs:0:4", "rs:5:4", "xor:2:3", "rs:4", "rs:a:b"):
        with pytest.raises(ConfigError):
            parse_scheme(bad)


def test_shard_ids_and_placement():
    assert parse_shard_id(shard_map_id("m_01", 3)) == ("m_01", 3)
    assert parse_shard_id("m_01") is None
    hosts = ["a", "b", "c"]
    assert [stripe_host(hosts, "b", i) for i in range(4)] == \
        ["b", "c", "a", "b"]
    assert stripe_host([], "x", 2) == "x"  # degenerate: no universe


# -- v2 index + striped layout ----------------------------------------------

def test_index_v2_roundtrip_and_v1_back_compat(tmp_path):
    idx = str(tmp_path / "file.out.index")
    triples = [(0, 100, 100), (100, 57, 57)]
    locators = [[(200, 25), (225, 25)], [(250, 15), (265, 15)]]
    write_index_file(idx, triples, stripe=(4, 6, locators))
    recs = read_index_file(idx, "/mof")
    assert [(r.start_offset, r.raw_length, r.part_length) for r in recs] \
        == triples
    assert recs[0].stripe.k == 4 and recs[0].stripe.n == 6
    assert recs[1].stripe.parity == ((250, 15), (265, 15))
    # v1 files keep reading exactly as before, stripe-less
    write_index_file(idx, triples)
    recs = read_index_file(idx, "/mof")
    assert recs[0].stripe is None and recs[1].part_length == 57


def _records(num, seed=0, val=24):
    rng = np.random.default_rng(seed)
    return sorted((rng.bytes(10), rng.bytes(val)) for _ in range(num))


def test_parity_section_keeps_data_region_byte_identical(tmp_path):
    recs = [_records(80, 1), _records(50, 2)]
    plain, coded, chunked = (str(tmp_path / d) for d in ("p", "c", "k"))
    t_plain = write_map_output(plain, recs)
    t_coded = write_map_output(coded, recs, scheme=parse_scheme("rs:4:6"))
    t_chunk = write_map_output(chunked, recs, scheme=parse_scheme("rs:4:4"))
    assert t_plain == t_coded == t_chunk  # data triples untouched
    raw_plain = open(os.path.join(plain, "file.out"), "rb").read()
    raw_coded = open(os.path.join(coded, "file.out"), "rb").read()
    raw_chunk = open(os.path.join(chunked, "file.out"), "rb").read()
    # the data region is byte-identical; parity is strictly appended
    assert raw_coded[:len(raw_plain)] == raw_plain
    assert len(raw_coded) > len(raw_plain)
    # rs:k:k has zero parity -> the whole file is byte-identical
    assert raw_chunk == raw_plain


def test_resolver_synthesizes_shards_from_primary(tmp_path):
    """On the full-stripe holder no shard bytes exist on disk: data
    chunks resolve as slices of the partition range, parity chunks as
    parity-section ranges, and the served bytes equal the codec's."""
    scheme = parse_scheme("rs:3:5")
    recs = [_records(60, 3)]
    write_map_output(str(tmp_path / JOB / "m0"), recs, scheme=scheme)
    eng = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    try:
        full = eng.fetch(ShuffleRequest(JOB, "m0", 0, 0, 1 << 20)).data
        data = rs.split_data(bytes(full), 3)
        parity = rs.encode_parity(bytes(full), 3, 5)
        for i in range(5):
            got = eng.fetch(ShuffleRequest(JOB, shard_map_id("m0", i),
                                           0, 0, 1 << 20))
            want = data[i] if i < 3 else parity[i - 3]
            assert bytes(got.data) == want, f"chunk {i}"
            assert got.raw_length == len(full)  # the decode-trim total
    finally:
        eng.stop()


def test_striped_fanout_places_chunks_on_peers(tmp_path):
    scheme = parse_scheme("rs:2:4")
    roots = [str(tmp_path / f"r{i}") for i in range(4)]
    recs = [_records(40, 4)]
    write_striped_map_output(roots, 1, JOB, "m7", recs, scheme)
    # primary root holds the full MOF (+ parity); peers hold shards
    assert os.path.exists(os.path.join(roots[1], JOB, "m7", "file.out"))
    blob = open(os.path.join(roots[1], JOB, "m7", "file.out"), "rb").read()
    data_len = read_index_file(
        os.path.join(roots[1], JOB, "m7", "file.out.index"),
        "x")[0].part_length
    data = rs.split_data(blob[:data_len], 2)
    parity = rs.encode_parity(blob[:data_len], 2, 4)
    # chunk i -> root (1 + i) % 4; chunk 0 stays on the primary
    # (synthesized, no shard dir)
    assert not os.path.exists(os.path.join(roots[1], JOB,
                                           shard_map_id("m7", 0)))
    for i, want in [(1, data[1]), (2, parity[0]), (3, parity[1])]:
        d = os.path.join(roots[(1 + i) % 4], JOB, shard_map_id("m7", i))
        got = open(os.path.join(d, "file.out"), "rb").read()
        assert got == want, f"chunk {i}"


# -- stripe-aware routing + reconstruction ----------------------------------

class _DeadClient(LocalFetchClient):
    """A supplier that answers every fetch with a transport fault (the
    dead-host shape, delivered async like a real dial failure)."""

    def start_fetch(self, req, on_complete):
        t = threading.Timer(0.002, on_complete, args=(
            TransportError(f"supplier down ({req.map_id})"),))
        t.daemon = True
        t.start()


def _striped_cluster(tmp_path, scheme_spec, num_maps, hosts):
    """num_maps maps striped over len(hosts) in-process suppliers ->
    (expected records, {host: engine}, [(host, map_id)] entries)."""
    scheme = parse_scheme(scheme_spec)
    roots = [str(tmp_path / f"root_{h}") for h in hosts]
    rng = np.random.default_rng(11)
    expected, maps = [], []
    for m in range(num_maps):
        mid = f"m_{m:04d}"
        recs = sorted((rng.bytes(10), rng.bytes(30)) for _ in range(90))
        expected += recs
        write_striped_map_output(roots, m % len(hosts), JOB, mid,
                                 [recs], scheme)
        maps.append((hosts[m % len(hosts)], mid))
    engines = {h: DataEngine(DirIndexResolver(r), Config())
               for h, r in zip(hosts, roots)}
    return expected, engines, maps


def test_stripe_aware_routing_reconstructs_through_dead_primary(tmp_path):
    """The acceptance shape in-process: rs:2:4 over 4 suppliers, one
    dead from the start — its maps reconstruct from any k shards on
    the survivors, the merge completes byte-correct, and the run never
    falls back."""
    hosts = ["h0", "h1", "h2", "h3"]  # sorted == canonical order
    expected, engines, maps = _striped_cluster(tmp_path, "rs:2:4", 4,
                                               hosts)
    clients = {h: LocalFetchClient(e) for h, e in engines.items()}
    clients["h2"] = _DeadClient(engines["h2"])  # dead supplier
    router = HostRoutingClient(lambda h: clients[h])
    cfg = Config({"uda.tpu.coding.scheme": "rs:2:4",
                  "uda.tpu.fetch.retries": 1})
    mm = MergeManager(router, "uda.tpu.RawBytes", cfg)
    blocks = []
    try:
        mm.run(JOB, maps, 0, lambda b: blocks.append(bytes(b)))
    finally:
        for e in engines.values():
            e.stop()
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    assert sorted(got) == sorted(expected)
    assert metrics.get("coding.reconstructed.partitions") >= 1
    assert metrics.get("coding.shard.fetches") >= 2
    assert metrics.get("fallback.signals") == 0
    # the ledger journaled the whole story, structurally
    kinds = {e["kind"] for e in mm.ledger.events()}
    assert "reconstructed" in kinds and "fault" in kinds


def test_decode_under_penalty_single_host(tmp_path):
    """Single-supplier degenerate: the plain fetch path fails, every
    shard synthesizes from the primary's own parity section — the
    partition still reconstructs locally (no peers at all)."""
    scheme = parse_scheme("rs:4:6")
    recs = [_records(70, 6)]
    write_map_output(str(tmp_path / JOB / "m0"), recs, scheme=scheme)
    eng = DataEngine(DirIndexResolver(str(tmp_path)), Config())

    class FailPlain(LocalFetchClient):
        """Faults direct partition fetches; shard fetches pass."""

        def start_fetch(self, req, on_complete):
            if parse_shard_id(req.map_id) is None:
                on_complete(TransportError("primary path penalized"))
                return
            super().start_fetch(req, on_complete)

    seg = Segment(FailPlain(eng), JOB, "m0", 0, 1 << 20,
                  policy=RetryPolicy(retries=1),
                  stripe=StripeContext(scheme, [""]))
    try:
        seg.start()
        seg.wait(10.0)
        got = list(seg.record_batch().iter_records())
    finally:
        eng.stop()
    assert sorted(got) == recs[0]
    assert metrics.get("coding.reconstructed.partitions") == 1


def test_reconstruction_slots_in_below_decompression(tmp_path):
    """The stripe codes the ON-DISK (compressed) bytes; a compressed
    job's reconstruction decodes the stripe first and decompresses the
    rebuilt partition on the way up — the segment sees the same
    uncompressed domain a fetched stream would (byte-agnostic
    contract)."""
    from uda_tpu.compress import DecompressingClient, get_codec

    scheme = parse_scheme("rs:3:5")
    codec = get_codec("zlib")
    recs = [_records(80, 17, val=64)]
    write_map_output(str(tmp_path / JOB / "m0"), recs, codec=codec,
                     scheme=scheme)
    eng = DataEngine(DirIndexResolver(str(tmp_path)), Config())

    class FailPlain(LocalFetchClient):
        def start_fetch(self, req, on_complete):
            if parse_shard_id(req.map_id) is None:
                on_complete(TransportError("primary path down"))
                return
            super().start_fetch(req, on_complete)

    client = DecompressingClient(FailPlain(eng), codec)
    assert not client.resume_ok()  # stream state is never resumable
    seg = Segment(client, JOB, "m0", 0, 1 << 20,
                  policy=RetryPolicy(retries=1),
                  stripe=StripeContext(scheme, [""]))
    try:
        seg.start()
        seg.wait(10.0)
        got = list(seg.record_batch().iter_records())
    finally:
        eng.stop()
    assert sorted(got) == recs[0]
    assert metrics.get("coding.reconstructed.partitions") == 1
    assert metrics.get("decompress.bytes") > 0


def test_stale_shard_cannot_poison_reconstruction(tmp_path):
    """A shard left over from a DIFFERENT map attempt (different
    full-partition length) must not define the stripe baseline just by
    completing first: chunks group by identity and whichever identity
    collects k wins — even when the stale shard is the fastest."""
    scheme = parse_scheme("rs:2:4")
    recs = [_records(40, 33)]
    write_map_output(str(tmp_path / JOB / "m0"), recs, scheme=scheme)
    eng = DataEngine(DirIndexResolver(str(tmp_path)), Config())

    class StaleShard1(LocalFetchClient):
        """Plain fetch fails; shard 1 answers INSTANTLY with a stale
        attempt's bytes (wrong identity); real shards answer late."""

        def start_fetch(self, req, on_complete):
            shard = parse_shard_id(req.map_id)
            if shard is None:
                on_complete(TransportError("primary down"))
                return
            if shard[1] == 1:
                on_complete(FetchResult(b"Z" * 9, 999, 9, 0,
                                        "/stale", last=True))
                return

            def late(res):
                t = threading.Timer(0.05, on_complete, args=(res,))
                t.daemon = True
                t.start()

            super().start_fetch(req, late)

    seg = Segment(StaleShard1(eng), JOB, "m0", 0, 1 << 20,
                  policy=RetryPolicy(retries=0),
                  stripe=StripeContext(scheme, [""]))
    try:
        seg.start()
        seg.wait(10.0)
        got = list(seg.record_batch().iter_records())
    finally:
        eng.stop()
    assert sorted(got) == recs[0]
    assert metrics.get("coding.reconstructed.partitions") == 1


@pytest.mark.faults
def test_coding_decode_failpoint_makes_recovery_injectable(tmp_path):
    """The coding.decode site: an injected decode fault turns a
    would-have-recovered segment into the terminal (typed) error —
    chaos can reach the new path from day one (UDA003)."""
    scheme = parse_scheme("rs:2:3")
    recs = [_records(30, 7)]
    write_map_output(str(tmp_path / JOB / "m0"), recs, scheme=scheme)
    eng = DataEngine(DirIndexResolver(str(tmp_path)), Config())

    class FailPlain(LocalFetchClient):
        def start_fetch(self, req, on_complete):
            if parse_shard_id(req.map_id) is None:
                on_complete(TransportError("down"))
                return
            super().start_fetch(req, on_complete)

    seg = Segment(FailPlain(eng), JOB, "m0", 0, 1 << 20,
                  policy=RetryPolicy(retries=0),
                  stripe=StripeContext(scheme, [""]))
    try:
        with failpoints.scoped("coding.decode=error"):
            seg.start()
            with pytest.raises(StorageError, match="coding.decode"):
                seg.wait(10.0)
    finally:
        eng.stop()
    assert metrics.get("coding.recover.failures") == 1


# -- speculative dual-source fetch ------------------------------------------

class _SlowClient(LocalFetchClient):
    def __init__(self, engine, delay_s):
        super().__init__(engine)
        self.delay_s = delay_s

    def start_fetch(self, req, on_complete):
        def late(res):
            t = threading.Timer(self.delay_s, on_complete, args=(res,))
            t.daemon = True
            t.start()

        super().start_fetch(req, late)


@pytest.mark.faults
def test_speculation_won_switches_to_faster_source(tmp_path):
    """The straggler detector: a fetch stuck on a slow replica gets a
    duplicate on the PenaltyBox-ranked alternate; the duplicate wins,
    the segment switches sources, and the slow completion is discarded
    by the epoch machinery."""
    expected = make_mof_tree(str(tmp_path), JOB, 1, 1, 150, seed=8)
    eng = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    clients = {"slow": _SlowClient(eng, 0.6),
               "fast": LocalFetchClient(eng)}
    router = HostRoutingClient(lambda h: clients[h])
    seg = Segment(router, JOB, map_ids(JOB, 1)[0], 0, 1 << 20,
                  host="slow", hosts=["slow", "fast"],
                  ledger=RecoveryLedger(PenaltyBox()),
                  speculation=SpeculationPolicy(pn=95, floor_ms=50),
                  policy=RetryPolicy(retries=1))
    t0 = time.perf_counter()
    try:
        seg.start()
        seg.wait(10.0)
    finally:
        eng.stop()
    assert seg.num_records == len(expected[0])
    assert seg.host == "fast"  # sticky win
    assert metrics.get("fetch.speculated") >= 1
    assert metrics.get("fetch.speculation.won") >= 1
    assert time.perf_counter() - t0 < 0.5  # did not wait out the slow path
    assert metrics.get_gauge("fetch.on_air") == 0  # loser fully settled


@pytest.mark.faults
def test_speculation_lost_late_completion_discarded(tmp_path):
    """The primary wins the race: the speculative duplicate's (slower)
    completion must be discarded as stale — exactly one ingest, no
    double-counted records, balanced on-air accounting."""
    expected = make_mof_tree(str(tmp_path), JOB, 1, 1, 120, seed=9)
    eng = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    clients = {"primary": _SlowClient(eng, 0.12),
               "alt": _SlowClient(eng, 1.0)}
    router = HostRoutingClient(lambda h: clients[h])
    seg = Segment(router, JOB, map_ids(JOB, 1)[0], 0, 1 << 20,
                  host="primary", hosts=["primary", "alt"],
                  ledger=RecoveryLedger(PenaltyBox()),
                  speculation=SpeculationPolicy(pn=95, floor_ms=30),
                  policy=RetryPolicy(retries=1))
    try:
        seg.start()
        seg.wait(10.0)
        assert seg.num_records == len(expected[0])
        assert seg.host == "primary"
        assert metrics.get("fetch.speculated") >= 1
        assert metrics.get("fetch.speculation.won") == 0
        assert metrics.get("fetch.speculation.lost") >= 1
        # the loser's completion lands AFTER the win: stale-dropped
        time.sleep(1.1)
        assert metrics.get("fetch.stale_completions") >= 1
        assert seg.num_records == len(expected[0])  # no double ingest
        assert metrics.get_gauge("fetch.on_air") == 0
    finally:
        eng.stop()


@pytest.mark.faults
def test_both_racing_attempts_failing_still_retries(tmp_path):
    """Primary AND speculative duplicate both fail: the second failure
    must settle the attempt group and drive the retry ladder — never
    strand the segment with zero live attempts (the racing-failures
    path of Segment._drop_attempt)."""
    make_mof_tree(str(tmp_path), JOB, 1, 1, 30, seed=10)
    eng = DataEngine(DirIndexResolver(str(tmp_path)), Config())

    class FailAfter(LocalFetchClient):
        def __init__(self, engine, delay_s):
            super().__init__(engine)
            self.delay_s = delay_s

        def start_fetch(self, req, on_complete):
            t = threading.Timer(self.delay_s, on_complete, args=(
                TransportError(f"down ({req.host})"),))
            t.daemon = True
            t.start()

    clients = {"a": FailAfter(eng, 0.2), "b": FailAfter(eng, 0.01)}
    router = HostRoutingClient(lambda h: clients[h])
    seg = Segment(router, JOB, map_ids(JOB, 1)[0], 0, 1 << 20,
                  host="a", hosts=["a", "b"],
                  ledger=RecoveryLedger(PenaltyBox()),
                  speculation=SpeculationPolicy(pn=95, floor_ms=20),
                  policy=RetryPolicy(retries=1))
    try:
        seg.start()
        with pytest.raises(TransportError):
            seg.wait(5.0)  # fails PROMPTLY after the retry — a stranded
            # attempt group would hang until this timeout
        assert metrics.get("fetch.retries") >= 1
        assert metrics.get_gauge("fetch.on_air") == 0
    finally:
        eng.stop()


def test_speculation_gated_off_for_stateful_decompressing_client(tmp_path):
    """DecompressingClient claims a per-partition sequential stream
    token in start_fetch — a speculative DUPLICATE would steal it and
    fail the healthy primary's completion as stale, fabricating a
    fault. The straggler detector must not fire through it."""
    from uda_tpu.compress import DecompressingClient, get_codec

    codec = get_codec("zlib")
    recs = [_records(100, 19, val=48)]
    write_map_output(str(tmp_path / JOB / "m0"), recs, codec=codec)
    eng = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    client = DecompressingClient(_SlowClient(eng, 0.1), codec)
    assert not client.speculate_ok()
    box = PenaltyBox(threshold=1, penalty_s=60.0)
    seg = Segment(client, JOB, "m0", 0, 1 << 20,
                  ledger=RecoveryLedger(box),
                  speculation=SpeculationPolicy(pn=95, floor_ms=10),
                  policy=RetryPolicy(retries=1))
    try:
        seg.start()
        seg.wait(10.0)
    finally:
        eng.stop()
    assert sorted(seg.record_batch().iter_records()) == recs[0]
    assert metrics.get("fetch.speculated") == 0  # gated, not raced
    assert metrics.get("fetch.penalties") == 0   # nobody punished


def test_handoff_record_survives_a_failed_start(tmp_path):
    """The handoff record is consumed by a SUCCESSFUL start only: a
    transient bind failure (port in use) must leave it in place so the
    supervisor's retry still comes up warm."""
    from uda_tpu.net import ShuffleServer

    eng, srv, cfg = _netted_supplier(tmp_path)
    port = srv.port
    srv.stop(drain=True)  # persists the record
    blocker = __import__("socket").socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    try:
        with pytest.raises(OSError):
            # same port as the blocker: bind fails BEFORE the record
            # would be consumed
            ShuffleServer(eng, cfg, host="127.0.0.1",
                          port=blocker.getsockname()[1]).start()
        srv2 = ShuffleServer(eng, cfg, host="127.0.0.1",
                             port=port).start()
        try:
            assert srv2.warm_restart  # the record was still there
        finally:
            srv2.stop()
    finally:
        blocker.close()
        eng.stop()


def test_speculation_policy_threshold_uses_histogram():
    pol = SpeculationPolicy(pn=95, floor_ms=40.0)
    assert pol.threshold_ms() == 40.0  # empty histogram -> floor
    metrics.enable_stats()
    for v in (10.0,) * 90 + (400.0,) * 10:
        metrics.observe("fetch.latency_ms", v)
    assert pol.threshold_ms() > 40.0  # p95 pulled it above the floor
    assert not SpeculationPolicy(pn=0).enabled


# -- structured cause + ledger ----------------------------------------------

def test_admin_fail_records_supplier_in_structured_cause(tmp_path):
    ledger = RecoveryLedger(PenaltyBox())
    eng = DataEngine(DirIndexResolver(str(tmp_path)), Config())
    seg = Segment(_SlowClient(eng, 5.0), JOB, "m9", 0, 1 << 20,
                  host="sick-host", ledger=ledger)
    try:
        seg.start()
        err = StorageError("watchdog rescue")
        assert seg.fail(err)
        assert err.supplier == "sick-host"  # structured, not a string
        events = ledger.events("admin_fail")
        assert events and events[0]["supplier"] == "sick-host"
        assert events[0]["error"] == "StorageError"
        # a SHARED stop-path error keeps its first attribution
        seg2 = Segment(_SlowClient(eng, 5.0), JOB, "m10", 0, 1 << 20,
                       host="other", ledger=ledger)
        seg2.start()
        assert seg2.fail(err)
        assert err.supplier == "sick-host"
        assert ledger.events("admin_fail")[1]["supplier"] == "other"
    finally:
        eng.stop()


def test_recovery_ledger_rank_and_snapshot():
    box = PenaltyBox(threshold=1, penalty_s=60.0)
    ledger = RecoveryLedger(box)
    box.punish("bad")
    assert ledger.rank(["bad", "good"]) == ["good", "bad"]
    v0 = ledger.version
    ledger.record("fault", supplier="bad", map_id="m",
                  error=TransportError("x"))
    assert ledger.version == v0 + 1
    snap = ledger.snapshot()
    assert snap["counts"]["fault"] == 1
    assert snap["events"][-1]["error"] == "TransportError"


# -- warm-restart + resume (the net handoff) --------------------------------

def _netted_supplier(tmp_path, handoff=True, port=0):
    cfg = Config({"uda.tpu.net.handoff.path":
                  str(tmp_path / "handoff.json") if handoff else ""})
    eng = DataEngine(DirIndexResolver(str(tmp_path)), cfg)
    from uda_tpu.net import ShuffleServer

    return eng, ShuffleServer(eng, cfg, host="127.0.0.1",
                              port=port).start(), cfg


@pytest.mark.faults
def test_completion_warm_restart_resumes_from_offset_ledger(tmp_path):
    """The bounced-supplier completion rung: stop(drain=True) persists
    the handoff, the restart advertises generation+1 warm, and the
    in-flight segment resumes from its own offset ledger — the job
    finishes without refetching already-served bytes and without a
    FallbackSignal."""
    expected = make_mof_tree(str(tmp_path), JOB, 1, 1, 2500, seed=12)
    eng, srv, cfg = _netted_supplier(tmp_path)
    port, gen1 = srv.port, srv.generation
    router = HostRoutingClient(config=Config())
    seg = Segment(router, JOB, map_ids(JOB, 1)[0], 0, 8192,
                  host=f"127.0.0.1:{port}",
                  policy=RetryPolicy(retries=8, backoff_ms=100),
                  resume=True)
    mid_fetch = threading.Event()
    orig_ingest = seg._ingest
    chunks = [0]

    def pacing_ingest(res):
        chunks[0] += 1
        if chunks[0] == 3:
            mid_fetch.set()
        if chunks[0] in (3, 4):
            time.sleep(0.15)  # hold the stream open across the bounce
        return orig_ingest(res)

    seg._ingest = pacing_ingest
    srv2 = None
    try:
        seg.start()
        assert mid_fetch.wait(10.0)
        srv.stop(drain=True)  # the graceful bounce: handoff persisted
        time.sleep(0.4)  # a real outage window: the segment's next
        # chunk fails against the down supplier and RETRIES (resume)
        from uda_tpu.net import ShuffleServer

        srv2 = ShuffleServer(eng, cfg, host="127.0.0.1",
                             port=port).start()
        assert srv2.generation == (gen1 + 1) & 0x7FFFFFFF
        assert srv2.warm_restart
        seg.wait(20.0)
    finally:
        if srv2 is not None:
            srv2.stop()
        router.stop()
        eng.stop()
    assert seg.num_records == len(expected[0])
    assert metrics.get("fetch.resumed") >= 1
    assert metrics.get("fetch.resumed.bytes") > 0  # bytes NOT refetched
    assert metrics.get("net.handoff.persisted") >= 1
    assert metrics.get("net.handoff.loaded") >= 1
    assert metrics.get("fallback.signals") == 0


@pytest.mark.faults
def test_remote_pread_error_resumes_mid_partition(tmp_path):
    """A transient REMOTE StorageError — a typed ERR frame on a healthy
    stream (structured remote_kind stamp, net/wire.py) — must not cost
    a full refetch: every chunk ingested before it is valid, so the
    segment keeps its offset ledger and resumes. Under a periodic
    per-call error schedule a refetch-from-zero retry loop re-hits the
    fault at the same phase every attempt and exhausts any retry
    budget deterministically (the chaos-rung livelock this pins); with
    resume each attempt banks its progress and the fetch converges."""
    expected = make_mof_tree(str(tmp_path), JOB, 1, 1, 2500, seed=21)
    eng, srv, _ = _netted_supplier(tmp_path)
    router = HostRoutingClient(config=Config())
    seg = Segment(router, JOB, map_ids(JOB, 1)[0], 0, 8192,
                  host=f"127.0.0.1:{srv.port}",
                  policy=RetryPolicy(retries=8, backoff_ms=20),
                  resume=True)
    try:
        # every 3rd pread errors: < the partition's chunk count, so
        # without resume NO attempt can ever finish (the livelock)
        with failpoints.scoped("data_engine.pread=error:every:3"):
            seg.start()
            seg.wait(20.0)
    finally:
        srv.stop()
        router.stop()
        eng.stop()
    assert seg.num_records == len(expected[0])
    assert metrics.get("fetch.resumed") >= 1
    assert metrics.get("fetch.resumed.bytes") > 0  # ground held


def test_cold_restart_revokes_resume(tmp_path):
    """Without a handoff record the restarted server mints a FRESH
    generation and advertises cold — the client revokes resume for
    retrying segments (their ledgers restart from zero)."""
    make_mof_tree(str(tmp_path), JOB, 1, 1, 20, seed=13)
    eng, srv, _ = _netted_supplier(tmp_path, handoff=False)
    port = srv.port
    from uda_tpu.net import RemoteFetchClient

    client = RemoteFetchClient("127.0.0.1", port, Config())
    try:
        res_box, done = [], threading.Event()
        client.start_fetch(
            ShuffleRequest(JOB, map_ids(JOB, 1)[0], 0, 0, 1 << 20),
            lambda r: (res_box.append(r), done.set()))
        assert done.wait(10.0) and isinstance(res_box[0], FetchResult)
        assert client.resume_ok()  # same generation so far
        srv.stop(drain=False)  # killed: no handoff record
        from uda_tpu.net import ShuffleServer

        srv = ShuffleServer(eng, Config(), host="127.0.0.1",
                            port=port).start()
        assert not srv.warm_restart
        done2, box2 = threading.Event(), []
        client.start_fetch(
            ShuffleRequest(JOB, map_ids(JOB, 1)[0], 0, 0, 1 << 20),
            lambda r: (box2.append(r), done2.set()))
        assert done2.wait(10.0)
        deadline = time.monotonic() + 5.0
        while client.resume_ok() and time.monotonic() < deadline:
            time.sleep(0.01)  # HELLO may trail the first data frame
        assert not client.resume_ok()  # cold restart observed
        assert metrics.get("net.generation.changes") >= 1
    finally:
        client.stop()
        srv.stop()
        eng.stop()


def _ifile_blob(records):
    from uda_tpu.utils.ifile import IFileWriter

    buf = io.BytesIO()
    w = IFileWriter(buf)
    for k, v in records:
        w.append(k, v)
    w.close()
    return buf.getvalue()


def test_resume_identity_check_restarts_on_changed_partition():
    """A resumed fetch whose first chunk reports a different partition
    identity (raw_length) must NOT splice two attempts' bytes: the
    identity check forces a full restart from zero, and the segment
    completes with the NEW attempt's records only."""
    recs_a = _records(12, 21)
    recs_b = _records(30, 22)
    part_a, part_b = _ifile_blob(recs_a), _ifile_blob(recs_b)
    assert len(part_a) != len(part_b)

    class SwappingClient(LocalFetchClient):
        """Serves 64-byte chunks of attempt A, faults once mid-stream,
        then serves attempt B (a different map attempt's output)."""

        def __init__(self):
            self.phase = 0

        def start_fetch(self, req, on_complete):
            blob = part_a if self.phase == 0 else part_b
            if self.phase == 0 and req.offset >= 64:
                self.phase = 1
                on_complete(TransportError("supplier bounced"))
                return
            chunk = blob[req.offset:req.offset + 64]
            on_complete(FetchResult(
                chunk, len(blob), len(blob), req.offset, "/x",
                last=req.offset + len(chunk) >= len(blob)))

    seg = Segment(SwappingClient(), JOB, "m0", 0, 64,
                  policy=RetryPolicy(retries=3), resume=True)
    seg.start()
    seg.wait(10.0)
    assert metrics.get("fetch.resumed") == 1
    assert metrics.get("fetch.resume.invalidated") == 1
    assert sorted(seg.record_batch().iter_records()) == recs_b


@pytest.mark.faults
def test_net_handoff_failpoint_degrades_to_cold(tmp_path):
    """An injected handoff-save fault must degrade the NEXT start to
    cold (counted, logged), never break the graceful stop itself."""
    make_mof_tree(str(tmp_path), JOB, 1, 1, 10, seed=14)
    eng, srv, cfg = _netted_supplier(tmp_path)
    port = srv.port
    with failpoints.scoped("net.handoff=error:match:save"):
        srv.stop(drain=True)  # save injected away; stop still clean
    from uda_tpu.net import ShuffleServer

    srv2 = ShuffleServer(eng, cfg, host="127.0.0.1", port=port).start()
    try:
        assert not srv2.warm_restart  # no record -> cold
        assert metrics.get("errors.swallowed") >= 1
    finally:
        srv2.stop()
        eng.stop()


# -- the chaos completion rung (sockets, seeded kill) ------------------------

@pytest.mark.faults
def test_completion_reconstruct_through_seeded_supplier_kill(tmp_path):
    """THE acceptance rung: rs:4:6 over six socket suppliers, a seeded
    supplier killed with no restart — the job completes with
    byte-correct merged output, coding.reconstructed.partitions > 0,
    and no FallbackSignal."""
    from uda_tpu.net import ShuffleServer

    seed = int(os.environ.get("UDA_TPU_CHAOS_SEED", "7"))
    num = 6
    scheme_spec = "rs:4:6"
    roots = [str(tmp_path / f"r{i}") for i in range(num)]
    engines = [DataEngine(DirIndexResolver(r), Config()) for r in roots]
    servers = [ShuffleServer(e, Config(), host="127.0.0.1", port=0).start()
               for e in engines]
    unsorted_hosts = [f"127.0.0.1:{s.port}" for s in servers]
    order = sorted(range(num), key=lambda i: unsorted_hosts[i])
    hosts = [unsorted_hosts[i] for i in order]       # canonical order
    roots_c = [roots[i] for i in order]
    servers_c = [servers[i] for i in order]
    scheme = parse_scheme(scheme_spec)
    rng = np.random.default_rng(seed)
    expected, maps = [], []
    for m in range(num):
        mid = f"m_{m:04d}"
        recs = sorted((rng.bytes(10), rng.bytes(30)) for _ in range(100))
        expected += recs
        write_striped_map_output(roots_c, m, JOB, mid, [recs], scheme)
        maps.append((hosts[m], mid))
    victim = seed % num
    cfg = Config({"uda.tpu.coding.scheme": scheme_spec,
                  "uda.tpu.fetch.retries": 1,
                  "mapred.rdma.fetch.retry.backoff.ms": 30,
                  "uda.tpu.net.connect.timeout.s": 2.0,
                  "mapred.rdma.buf.size": 16})
    router = HostRoutingClient(config=cfg)
    mm = MergeManager(router, "uda.tpu.RawBytes", cfg, seed=seed)
    blocks = []
    try:
        servers_c[victim].stop(drain=False)  # the kill: mid-shuffle
        # from the reducer's view (fetches racing the teardown)
        mm.run(JOB, maps, 0, lambda b: blocks.append(bytes(b)))
    finally:
        router.stop()
        for s in servers_c:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 - double-stop on the
                pass           # victim is part of the scenario
        for e in engines:
            e.stop()
    got = list(IFileReader(io.BytesIO(b"".join(blocks))))
    assert sorted(got) == sorted(expected), "merged output not byte-correct"
    assert metrics.get("coding.reconstructed.partitions") > 0
    assert metrics.get("fallback.signals") == 0


# -- failure-domain-aware stripe placement (ISSUE 15) ------------------------

def test_parse_domains():
    from uda_tpu.coding import parse_domains

    assert parse_domains("") == {} and parse_domains(None) == {}
    assert parse_domains("a=r0, b=r0 ,c=r1") == \
        {"a": "r0", "b": "r0", "c": "r1"}
    for bad in ("a", "a=", "=r0", "a=r0,b"):
        with pytest.raises(ConfigError):
            parse_domains(bad)


def test_stripe_order_rotation_and_domain_interleave():
    from uda_tpu.coding import stripe_order

    # no domains: the PR 8 positional rotation, unchanged
    assert stripe_order(4, 1) == [1, 2, 3, 0]
    # domains: round-robin across domains, primary (and its domain)
    # first, rotation order within each domain
    #   hosts 0,1 -> rack0; hosts 2,3 -> rack1
    order = stripe_order(4, 0, ["r0", "r0", "r1", "r1"])
    assert order[0] == 0                      # primary stays chunk 0
    assert order == [0, 2, 1, 3]              # r0, r1, r0, r1
    # consecutive chunks land in distinct domains while any remain
    doms = ["r0", "r0", "r1", "r1"]
    for a, b in zip(order, order[1:]):
        assert doms[a] != doms[b]
    with pytest.raises(ConfigError):
        stripe_order(4, 0, ["r0"])            # label/count mismatch


def test_stripe_host_domains_spread_no_domain_holds_too_many():
    # THE satellite invariant: with declared failure domains, no
    # domain holds >= n-k+1 shards of one stripe (losing a whole
    # domain never makes a stripe unrecoverable) — checked over every
    # primary and a spread of (k, n, domain) configurations
    hosts = ["h0", "h1", "h2", "h3", "h4", "h5"]
    domains = {"h0": "rackA", "h1": "rackA", "h2": "rackB",
               "h3": "rackB", "h4": "rackC", "h5": "rackC"}
    for k, n in ((2, 4), (4, 6), (3, 5)):
        for primary in hosts:
            placed = [stripe_host(hosts, primary, i, domains=domains)
                      for i in range(n)]
            per_dom: dict = {}
            for h in placed:
                per_dom[domains[h]] = per_dom.get(domains[h], 0) + 1
            assert max(per_dom.values()) < n - k + 1, \
                (k, n, primary, placed, per_dom)
            assert placed[0] == primary
    # rotation (undeclared) keeps the historical placement
    assert [stripe_host(hosts[:3], "h1", i) for i in range(4)] == \
        ["h1", "h2", "h0", "h1"]
    # partially-declared hosts fall back to singleton domains
    part = {"h0": "rackA", "h1": "rackA"}
    placed = [stripe_host(hosts[:4], "h0", i, domains=part)
              for i in range(4)]
    assert placed[0] == "h0" and len(set(placed)) == 4


def test_striped_writer_and_recovery_agree_on_domain_placement(tmp_path):
    # writer fan-out and reduce-side StripeContext must derive the
    # SAME placement from the same domain declaration (no metadata
    # travels) — shards land exactly where host_of says they are
    from uda_tpu.coding import stripe_order

    roots = [str(tmp_path / f"s{i}") for i in range(4)]
    domains = {r: f"rack{i % 2}" for i, r in enumerate(roots)}
    scheme = parse_scheme("rs:2:4")
    parts = [[(b"k%d" % i, b"v" * i)] for i in range(3)]
    write_striped_map_output(roots, 1, "job", "m_0", parts, scheme,
                             domains=domains)
    ctx = StripeContext(scheme, roots, domains=domains)
    order = stripe_order(4, 1, [domains[r] for r in roots])
    for i in range(scheme.n):
        expect = roots[order[i % 4]]
        assert ctx.host_of(roots[1], i) == expect
        sdir = os.path.join(expect, "job", shard_map_id("m_0", i))
        if expect == roots[1]:
            assert not os.path.exists(sdir)   # synthesized, no bytes
        else:
            assert os.path.exists(os.path.join(sdir, "file.out"))


# -- background stripe scrub (ISSUE 15) --------------------------------------

def _write_coded_tree(tmp_path, nroots=3, scheme_spec="rs:2:3"):
    roots = [str(tmp_path / f"r{i}") for i in range(nroots)]
    scheme = parse_scheme(scheme_spec)
    parts = [[(b"key%03d" % i, bytes(range(i % 7)) * 5)]
             for i in range(4)]
    write_striped_map_output(roots, 0, "jobS", "m_000", parts, scheme)
    return roots, scheme


def test_scrub_clean_tree_counts_stripes(tmp_path):
    from uda_tpu.coding.scrub import scrub_roots

    roots, scheme = _write_coded_tree(tmp_path)
    metrics.reset()
    rep = scrub_roots(roots)
    assert rep["maps"] == 1 and rep["stripes"] == 4
    assert rep["parity_mismatches"] == 0 and rep["shard_faults"] == 0
    assert metrics.get("coding.scrub.stripes") == 4.0
    assert metrics.get("coding.scrub.repairs") == 0.0


def test_scrub_detects_lost_shard_dump_only_then_repairs(tmp_path):
    from uda_tpu.coding.scrub import scrub_roots

    roots, scheme = _write_coded_tree(tmp_path)
    # find a peer shard and destroy it
    victim = None
    for root in roots[1:]:
        for dirpath, _dirs, files in os.walk(root):
            if "file.out" in files:
                victim = os.path.join(dirpath, "file.out")
    assert victim is not None
    with open(victim, "rb") as f:
        original = f.read()
    os.remove(victim)
    metrics.reset()
    rep = scrub_roots(roots)                   # dump-only default
    assert rep["shard_faults"] >= 1 and rep["repaired"] == 0
    assert not os.path.exists(victim)          # bytes never touched
    assert metrics.get("coding.scrub.repairs") >= 1.0
    rep2 = scrub_roots(roots, repair=True)     # proactive rebuild
    assert rep2["repaired"] >= 1
    with open(victim, "rb") as f:
        assert f.read() == original            # byte-exact rebuild
    rep3 = scrub_roots(roots)
    assert rep3["shard_faults"] == 0           # tree healthy again


def test_scrub_detects_corrupt_shard_and_parity(tmp_path):
    from uda_tpu.coding.scrub import scrub_roots

    roots, scheme = _write_coded_tree(tmp_path)
    victim = None
    for root in roots[1:]:
        for dirpath, _dirs, files in os.walk(root):
            if "file.out" in files:
                victim = os.path.join(dirpath, "file.out")
    with open(victim, "r+b") as f:
        f.seek(0)
        b = f.read(1)
        f.seek(0)
        f.write(bytes([b[0] ^ 0xFF]))
    rep = scrub_roots(roots)
    assert rep["shard_faults"] >= 1
    rep2 = scrub_roots(roots, repair=True)
    assert rep2["repaired"] >= 1
    assert scrub_roots(roots)["shard_faults"] == 0


def test_maybe_scrub_interval_and_single_flight(tmp_path):
    from uda_tpu.coding import scrub as scrub_mod

    roots, _ = _write_coded_tree(tmp_path)
    scrub_mod.scrub_state_reset()
    cfg = Config({"uda.tpu.coding.scheme": "rs:2:3",
                  "uda.tpu.coding.scrub.s": 3600})
    assert scrub_mod.maybe_scrub(cfg, roots) is True
    # within the interval (and/or in flight): no second pass
    assert scrub_mod.maybe_scrub(cfg, roots) is False
    deadline = time.time() + 5
    while scrub_mod._SCRUB_ACTIVE and time.time() < deadline:
        time.sleep(0.01)
    assert not scrub_mod._SCRUB_ACTIVE
    # off switch and coding-off both decline
    scrub_mod.scrub_state_reset()
    assert scrub_mod.maybe_scrub(
        Config({"uda.tpu.coding.scheme": "rs:2:3"}), roots) is False
    assert scrub_mod.maybe_scrub(
        Config({"uda.tpu.coding.scrub.s": 10}), roots) is False


# -- coded jobs through the models/ map phase (ISSUE 15) ---------------------

def test_map_phase_writes_coded_layout_behind_scheme_flag(tmp_path):
    # the full-workload wiring: a sort job with uda.tpu.coding.scheme
    # set writes parity sections + v2 indexes (single root) and the
    # striped fan-out (multi root), with output validity intact
    from uda_tpu.coding.scrub import scrub_roots
    from uda_tpu.models.sort_job import run_sort
    from uda_tpu.utils.comparators import memcmp

    rng = np.random.default_rng(31)
    records = [(rng.bytes(int(rng.integers(1, 16))),
                rng.bytes(int(rng.integers(0, 32)))) for _ in range(64)]
    roots = [str(tmp_path / "w")] + [str(tmp_path / f"p{i}")
                                     for i in (1, 2)]
    cfg = Config({"uda.tpu.coding.scheme": "rs:2:3"})
    out = run_sort(records, num_maps=3, num_reducers=2, config=cfg,
                   work_dir=roots[0], supplier_roots=roots)
    got = []
    for r, recs in sorted(out.items()):
        keys = [k for k, _ in recs]
        assert all(memcmp(a, b) <= 0 for a, b in zip(keys, keys[1:]))
        got.extend(recs)
    assert sorted(got) == sorted(records)
    # the layout really is coded: v2 stripes scrub clean, shards exist
    rep = scrub_roots(roots)
    assert rep["maps"] == 3 and rep["stripes"] > 0
    assert rep["parity_mismatches"] == 0 and rep["shard_faults"] == 0


def test_scrub_min_age_skips_fresh_maps(tmp_path):
    # review hardening: a pass racing a live (non-atomic) striped
    # write must not book phantom faults — fresh maps are skipped
    # until the quiesce window passes (the daemon rung always sets it)
    from uda_tpu.coding.scrub import scrub_roots

    roots, _ = _write_coded_tree(tmp_path)
    rep = scrub_roots(roots, min_age_s=3600)
    assert rep["maps"] == 0 and rep["stripes"] == 0
    rep2 = scrub_roots(roots, min_age_s=0)
    assert rep2["maps"] == 1 and rep2["shard_faults"] == 0


def test_scrub_survives_damaged_primary(tmp_path):
    # review hardening (round 5): one torn/lost PRIMARY must be a
    # counted finding, never an aborted pass — the neighbor maps still
    # get scrubbed
    from uda_tpu.coding.scrub import scrub_roots

    roots = [str(tmp_path / f"r{i}") for i in range(3)]
    scheme = parse_scheme("rs:2:3")
    for mid in ("m_000", "m_001"):
        parts = [[(b"k", b"v" * 9)] for _ in range(2)]
        write_striped_map_output(roots, 0, "jobP", mid, parts, scheme)
    os.remove(os.path.join(roots[0], "jobP", "m_000", "file.out"))
    rep = scrub_roots(roots)
    assert rep["primary_faults"] == 1
    assert rep["maps"] == 1 and rep["stripes"] == 2   # m_001 scrubbed
    assert rep["shard_faults"] == 0


def test_scrub_corrupt_primary_never_repairs_healthy_shards(tmp_path):
    # review hardening (round 6): a parity mismatch marks the PRIMARY
    # untrusted — the shard pass (and especially repair) is skipped so
    # corrupt primary bytes can never overwrite the last good copies
    from uda_tpu.coding.scrub import scrub_roots

    roots, _ = _write_coded_tree(tmp_path)
    # flip a byte inside the PRIMARY's file.out data region
    primary = os.path.join(roots[0], "jobS", "m_000", "file.out")
    with open(primary, "r+b") as f:
        b = f.read(1)
        f.seek(0)
        f.write(bytes([b[0] ^ 0xFF]))
    shard_files = {}
    for root in roots[1:]:
        for dirpath, _dirs, files in os.walk(root):
            if "file.out" in files:
                p = os.path.join(dirpath, "file.out")
                with open(p, "rb") as f:
                    shard_files[p] = f.read()
    rep = scrub_roots(roots, repair=True)
    assert rep["parity_mismatches"] >= 1
    assert rep["repaired"] == 0 and rep["shard_faults"] == 0
    for p, want in shard_files.items():      # peer bytes untouched
        with open(p, "rb") as f:
            assert f.read() == want
